"""Deterministic seeded chaos harness for the cluster resiliency layer.

Analogue of the reference's BaseFailureRecoveryTest matrix run as a
harness instead of hand-written cases: a fixed seed generates a fault
schedule (which partitions crash where, how many exchange fetches drop,
who stalls, who OOMs), the schedule is installed into the shared
FailureInjector, and TPC-H queries run through the fault-tolerant
scheduler. Because every random draw — schedule generation AND the
retry layer's backoff jitter (error_tracker seeds its RNG from the
destination) — is seeded, a failing run replays exactly from its seed.

Fault classes map onto distinct recovery paths:

- task_crash_start: task dies before producing output (clean re-run)
- task_crash_mid:   task dies AFTER its first output page (the
                    partially-spooled path; spool commit manifests keep
                    replayed attempts duplicate-free)
- fetch_loss:       exchange page pulls fail transiently (absorbed by
                    the RequestErrorTracker loop, no task retry at all)
- straggler:        a task stalls; FTE speculation races a duplicate
- oom:              a task raises ExceededMemoryLimitError (memory-
                    classed: the partition memory estimator doubles
                    before re-placement)

Lifecycle scenarios (LIFECYCLE_CLASSES) exercise the cluster-lifecycle
layer end to end: drain_mid_query / drain_all_but_one gracefully drain
workers while a query is in flight (oracle-equal result, zero accepted
launches on the drained node after the drain, drain completes), and
straggler_speculation demands a recorded speculative WIN, not just a
launched duplicate.
"""

from __future__ import annotations

import random
import threading
from trino_tpu.analysis import threadreg
import time
from typing import Dict, List, Optional, Tuple

FAULT_CLASSES = (
    "task_crash_start",
    "task_crash_mid",
    "fetch_loss",
    "straggler",
    "oom",
)

# cluster-lifecycle scenarios (PR 3): not injector schedules but whole-
# cluster maneuvers — graceful drains racing a live query, and a
# straggler that speculation must beat. Run via run_lifecycle_case.
LIFECYCLE_CLASSES = (
    "drain_mid_query",
    "drain_all_but_one",
    "straggler_speculation",
)

# time-bounding scenarios (PR 4): a hung operator the worker watchdog
# must interrupt (and FTE must retry elsewhere — query still correct),
# and a client that vanishes mid-query (reaper must cancel the query,
# free its resource-group slot, and drain its memory reservations to
# zero). Run via run_hung_operator_case / run_abandoned_client_case.
TIMEBOUND_CLASSES = (
    "hung_operator",
    "abandoned_client",
)

# serving scenarios (PR 8): faults injected while a POPULATION of
# concurrent HTTP clients is mid-traffic, not around one query in
# isolation — recovery must stay correct when retries contend with
# live load for workers, memory, and admission slots. Every query must
# end oracle-equal, shed (429), or as a TYPED failure, and every client
# thread must come back (no hangs). Run via run_loaded_cluster_case.
SERVING_CLASSES = (
    "loaded_cluster",
)

# adaptive scenarios (PR 13): the loaded-cluster fault burst + the
# mid-traffic drain, on a population whose session runs ADAPTIVELY — a
# query mix seeded with a misestimated join so the coordinator is
# re-planning mid-query while workers crash and drain out from under
# it. Re-planned queries must stay oracle-equal and the run must record
# at least one re-plan (otherwise the scenario proved nothing). Run via
# run_adaptive_drain_case.
ADAPTIVE_CLASSES = (
    "adaptive_loaded_drain",
)

# recovery scenarios (PR 14): chunk-granular checkpoint/resume for the
# mesh plane (trino_tpu/recovery/). The injector schedules above land
# on the page/FTE planes; these land INSIDE the mesh chunk loop via
# parallel.mesh_chunk.MESH_FAULT_HOOK — a seeded chunk boundary raises
# MeshStuck (the watchdog classification) or MeshDeviceLost (device
# loss), and the run must RESUME from its last checkpoint: oracle-equal
# rows AND strictly fewer re-executed chunks than restarting from chunk
# 0. Run via run_mesh_recovery_case.
RECOVERY_CLASSES = (
    "mesh_fault_mid_chunk",
    "device_lost_resume",
)

REPLICA_CLASSES = (
    "replica_down_mid_serve",
    "replica_drain_under_load",
)

# preemptive multi-tenancy scenarios (PR 18): the chunk-granular mesh
# scheduler (runtime/scheduler.py) under adversity. A fast-lane point
# lookup parks a streaming analytic at a seeded chunk boundary, then a
# device loss lands AFTER the resume — the checkpoint machinery must
# compose with parked state (park -> resume -> fault -> in-run resume,
# all in one run, byte-identical, nothing re-executed). And a replica
# drain surfacing while a query sits PARKED must raise out of the
# parked wait and resume the query from its parked host-portable
# snapshot on the sibling sub-mesh. Run via run_preempt_park_resume_case
# / run_preempt_under_drain_case.
PREEMPT_CLASSES = (
    "preempt_park_resume",
    "preempt_under_drain",
)

# multi-host fabric scenarios (PR 19): the checkpoint transport and
# membership tier (runtime/fabric.py) under adversity. host_lost_mid_
# chunk wipes the local checkpoint store at a seeded boundary (the
# whole "host" dies, not just a sub-mesh) — failover must PULL the last
# pushed snapshot from a fabric peer and resume with zero re-executed
# chunk-steps. membership_flap leaves-and-rejoins the sibling replica
# mid-fault — the membership epoch must advance, a second claim on an
# owned query must be refused (no double placement across epochs), and
# the query still completes oracle-equal. transport_corruption serves
# bit-flipped payloads from the peer — the digest check must reject
# them (fabric.digest_rejects) so failover degrades to a clean restart,
# never a resume from corrupt carries. Run via run_host_lost_case /
# run_membership_flap_case / run_transport_corruption_case.
FABRIC_CLASSES = (
    "host_lost_mid_chunk",
    "membership_flap",
    "transport_corruption",
)


def generate_schedule(
    seed: int,
    fault_class: str,
    n_partitions: int = 2,
    n_rules: int = 2,
    stall_s: float = 1.0,
) -> List[dict]:
    """Deterministic fault schedule: FailureRule kwargs drawn from
    random.Random(seed). Same (seed, fault_class) -> same schedule."""
    if fault_class not in FAULT_CLASSES:
        raise ValueError(f"unknown fault class: {fault_class}")
    rng = random.Random(seed)
    rules: List[dict] = []
    for _ in range(n_rules):
        p = rng.randrange(n_partitions)
        if fault_class == "task_crash_start":
            rules.append(dict(
                where="start", kind="crash", partition=p,
                attempts=(0,), max_hits=1,
            ))
        elif fault_class == "task_crash_mid":
            rules.append(dict(
                where="mid", kind="crash", partition=p,
                attempts=(0,), max_hits=1,
            ))
        elif fault_class == "fetch_loss":
            rules.append(dict(
                where="fetch", kind="fetch_loss", partition=p,
                attempts=(0, 1), max_hits=rng.randint(1, 3),
            ))
        elif fault_class == "straggler":
            # one stall is enough to drive speculation; more would just
            # serialize the test
            if not rules:
                rules.append(dict(
                    where="start", partition=p, attempts=(0,),
                    stall_s=stall_s + rng.random(), max_hits=1,
                ))
        elif fault_class == "oom":
            rules.append(dict(
                where="start", kind="oom", partition=p,
                attempts=(0,), max_hits=1,
            ))
    return rules


def schedule_max_failures(rules: List[dict]) -> int:
    """Upper bound on injected failures a schedule can cause — the
    bounded-attempt assertion compares observed retries against this."""
    return sum(r.get("max_hits", 0) for r in rules if r.get("stall_s", 0) == 0)


def run_mesh_recovery_case(
    sql: str, fault_class: str, seed: int,
    checkpoint_interval: int = 1, mesh_chunk_rows: int = 256,
) -> Tuple[List[list], dict]:
    """One seeded mesh fault mid-chunk against an in-process (mesh-
    colocated) runner with chunk checkpointing on. The fault chunk is
    drawn deterministically from the seed once the chunk count is known
    (same seed -> same boundary), fires exactly once, and the run must
    resume from its last checkpoint rather than restart: the report's
    executed_chunk_steps counts every chunk step across attempts, so
    `executed_chunk_steps - chunks` is the number of RE-executed chunks
    (a restart-from-zero re-executes all `fault_chunk` completed ones)."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.runtime.coordinator import DistributedQueryRunner

    if fault_class not in RECOVERY_CLASSES:
        raise ValueError(f"unknown recovery fault class: {fault_class}")
    exc = (
        mesh_chunk.MeshStuck
        if fault_class == "mesh_fault_mid_chunk"
        else mesh_chunk.MeshDeviceLost
    )
    runner = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            mesh_chunk_rows=mesh_chunk_rows,
            mesh_checkpoint_interval_chunks=checkpoint_interval,
        ),
        n_workers=2, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())
    expected = runner.execute(sql).rows  # warm run doubles as oracle
    mesh_clean = runner._last_data_plane == "mesh"
    rng = random.Random(seed)
    state = {"target": None, "fired": 0}

    def hook(k: int, K: int) -> None:
        if state["target"] is None:
            # any boundary but 0: chunk 0 never has a checkpoint below
            # it (tests/test_recovery.py covers the k=0 degenerate)
            state["target"] = 1 + rng.randrange(max(K - 1, 1))
        if k == state["target"] and not state["fired"]:
            state["fired"] = 1
            raise exc(f"chaos[{fault_class}]: injected at chunk {k}/{K}")

    mesh_chunk.MESH_FAULT_HOOK = hook
    try:
        rows = runner.execute(sql).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    info = mesh_chunk.last_run_info()
    report = {
        "mesh_clean_plane": mesh_clean,
        "mesh_fault_plane": runner._last_data_plane,
        "fault_chunk": state["target"],
        "fired": state["fired"],
        "chunks": info.get("chunks"),
        "executed_chunk_steps": info.get("executed_chunk_steps"),
        "resumes": info.get("resumes"),
        "resumed_from_chunk": info.get("resumed_from_chunk"),
        "expected": expected,
    }
    return rows, report


def run_preempt_park_resume_case(
    sql: str, seed: int, mesh_chunk_rows: int = 256,
) -> Tuple[List[list], dict]:
    """Park/resume composed with checkpoint recovery in ONE run: a
    fast-lane point lookup arrives at a seeded chunk boundary and parks
    the analytic (device carries snapshot to host, lookup runs, resume
    from chunk k warm); then a MeshDeviceLost lands at a later seeded
    boundary and the run must resume IN-RUN from its last checkpoint.
    Oracle-equal rows, exactly one park/unpark, at least one resume,
    and zero re-executed chunk-steps across the whole maneuver."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.runtime.coordinator import DistributedQueryRunner

    point = (
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey where n_nationkey = 3"
    )
    runner = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            mesh_chunk_rows=mesh_chunk_rows,
            mesh_checkpoint_interval_chunks=1,
            mesh_resume_attempts=1,
        ),
        n_workers=2, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())
    expected = runner.execute(sql).rows  # warm run doubles as oracle
    mesh_clean = runner._last_data_plane == "mesh"
    point_expected = runner.execute(point).rows
    rng = random.Random(seed)
    state = {
        "park_target": None, "fault_target": None,
        "parked": 0, "faulted": 0, "point_rows": None,
    }
    case_thread = threading.current_thread()

    def hook(k: int, K: int) -> None:
        if threading.current_thread() is not case_thread:
            return  # the point lookup's own chunk loop
        if state["park_target"] is None:
            # the park lands at park_target+1; the device loss lands
            # strictly after the resume so both maneuvers compose
            state["park_target"] = rng.randrange(max(K - 2, 1))
            state["fault_target"] = (
                state["park_target"] + 1
                + rng.randrange(max(K - state["park_target"] - 2, 1))
            )
        if k == state["park_target"] and not state["parked"]:
            state["parked"] = 1

            def run_point():
                state["point_rows"] = runner.execute(point).rows

            threadreg.spawn("chaos-point-query", run_point, owner="chaos")
            # hold this boundary until the fast seat is queued, so the
            # NEXT boundary deterministically parks
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                sched = runner._mesh_scheduler
                if sched is not None and sched.waiting_count(fast=True):
                    break
                time.sleep(0.002)
            return
        if (
            k == state["fault_target"]
            and state["parked"]
            and not state["faulted"]
        ):
            state["faulted"] = 1
            raise mesh_chunk.MeshDeviceLost(
                f"chaos[preempt_park_resume]: device loss at chunk "
                f"{k}/{K} after the park/resume cycle"
            )

    mesh_chunk.MESH_FAULT_HOOK = hook
    try:
        rows = runner.execute(sql).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    deadline = time.monotonic() + 10.0
    while state["point_rows"] is None and time.monotonic() < deadline:
        time.sleep(0.002)
    info = mesh_chunk.last_run_info()
    report = {
        "mesh_clean_plane": mesh_clean,
        "mesh_fault_plane": runner._last_data_plane,
        "park_chunk": (
            None if state["park_target"] is None
            else state["park_target"] + 1
        ),
        "fault_chunk": state["fault_target"],
        "parked": state["parked"],
        "faulted": state["faulted"],
        "chunks": info.get("chunks"),
        "executed_chunk_steps": info.get("executed_chunk_steps"),
        "parks": info.get("parks"),
        "unparks": info.get("unparks"),
        "resumes": info.get("resumes"),
        "point_ok": state["point_rows"] == point_expected,
        "expected": expected,
    }
    return rows, report


def run_preempt_under_drain_case(
    sql: str, seed: int, mesh_chunk_rows: int = 256,
) -> Tuple[List[list], dict]:
    """A replica drain surfacing while a query sits PARKED: a fast seat
    parks the analytic at a seeded boundary, then the victim replica is
    drained while the query is in the parked wait. The drain must raise
    MeshReplicaDraining OUT of the parked wait, keep the parked
    host-portable snapshot, and resume the query on the sibling
    sub-mesh from exactly the park boundary — oracle-equal, nothing
    re-executed, and the victim quiesces."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.runtime.coordinator import DistributedQueryRunner
    from trino_tpu.runtime.metrics import METRICS

    runner = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            mesh_replicas=2,
            mesh_chunk_rows=mesh_chunk_rows,
            mesh_checkpoint_interval_chunks=1,
            mesh_resume_attempts=0,
        ),
        n_workers=2, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())
    # sequential placements alternate replicas: two rounds warm both
    # sub-meshes, so the sibling resume mints no new lowerings
    expected = runner.execute(sql).rows
    runner.execute(sql)
    mesh_clean = runner._last_data_plane == "mesh"
    rm = runner._replicas
    rng = random.Random(seed)
    state = {
        "target": None, "victim": None, "fake": None,
        "parked": 0, "drained": 0,
    }

    def drain_when_parked(victim: int) -> None:
        vic = rm.replicas[victim]
        parks0 = vic.scheduler.parks
        state["fake"] = vic.scheduler.submit(
            "chaos-fast-seat", fast=True
        )
        # synthetic waiter: never calls acquire, so mark it ready by
        # hand — only ready waiters exert preemption pressure
        state["fake"].ready = True
        deadline = time.monotonic() + 10.0
        while (
            vic.scheduler.parks <= parks0
            and time.monotonic() < deadline
        ):
            time.sleep(0.002)
        if vic.scheduler.parks > parks0:
            state["parked"] = 1
            state["drained"] = 1
            rm.request_drain(victim)

    def hook(k: int, K: int) -> None:
        rep = mesh_chunk.active_replica()
        if rep is None:
            return
        if state["target"] is None:
            state["target"] = rng.randrange(max(K - 2, 1))
        if k == state["target"] and state["victim"] is None:
            state["victim"] = rep
            threadreg.spawn(
                "chaos-drain-when-parked", drain_when_parked, args=(rep,),
                owner="chaos",
            )
            # hold this boundary until the fast seat is queued: the
            # next boundary parks, and the side thread drains the
            # victim while the query sits parked
            vic = rm.replicas[rep]
            deadline = time.monotonic() + 10.0
            while (
                not vic.scheduler.waiting_count(fast=True)
                and time.monotonic() < deadline
            ):
                time.sleep(0.002)

    failovers0 = rm.failovers
    resumed0 = CHECKPOINTS.resumed
    steps0 = METRICS.snapshot().get("mesh.chunk_steps", 0.0)
    mesh_chunk.MESH_FAULT_HOOK = hook
    try:
        rows = runner.execute(sql).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
        if state["fake"] is not None and state["victim"] is not None:
            rm.replicas[state["victim"]].scheduler.finish(state["fake"])
    info = mesh_chunk.last_run_info()
    quiesced = bool(
        state["drained"]
        and state["victim"] is not None
        and rm.drain(state["victim"], timeout_s=30.0)
    )
    if quiesced:
        rm.undrain(state["victim"])
    report = {
        "mesh_clean_plane": mesh_clean,
        "mesh_fault_plane": runner._last_data_plane,
        "park_chunk": (
            None if state["target"] is None else state["target"] + 1
        ),
        "parked": state["parked"],
        "drain_requested": state["drained"],
        "replica_drained": quiesced,
        "failovers": rm.failovers - failovers0,
        "checkpoint_resumes": CHECKPOINTS.resumed - resumed0,
        "chunks": info.get("chunks"),
        "resumed_from_chunk": info.get("resumed_from_chunk"),
        "chunk_steps": int(
            METRICS.snapshot().get("mesh.chunk_steps", 0.0) - steps0
        ),
        "expected": expected,
    }
    return rows, report


def _fabric_case_runner(srv_uri: str, mesh_chunk_rows: int,
                        resume_attempts: int = 1):
    """Replicated runner whose session attaches the checkpoint fabric
    to one peer endpoint (the chaos cases' simulated surviving host)."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime.coordinator import DistributedQueryRunner

    runner = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            mesh_chunk_rows=mesh_chunk_rows,
            mesh_checkpoint_interval_chunks=1,
            mesh_replicas=2,
            mesh_resume_attempts=resume_attempts,
            fabric_peers=srv_uri,
        ),
        n_workers=2, hash_partitions=2,
    )
    runner.register_catalog("tpch", create_tpch_connector())
    return runner


def run_host_lost_case(
    sql: str, seed: int, mesh_chunk_rows: int = 256,
) -> Tuple[List[list], dict]:
    """Hard host loss mid-chunk with the fabric attached: at a seeded
    boundary the LOCAL checkpoint store is wiped (the host's memory
    died with it) and the active sub-mesh raises MeshDeviceLost. The
    coordinator's failover must find the local store empty, PULL the
    last pushed snapshot from the fabric peer, and resume the query on
    the sibling from exactly the fault boundary — oracle-equal with
    zero re-executed chunk-steps."""
    import os

    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery.checkpoint import (
        CHECKPOINTS,
        MeshCheckpointStore,
    )
    from trino_tpu.runtime.fabric import (
        HostFabric,
        active_fabric,
        stop_fabric,
    )
    from trino_tpu.runtime.http import FabricServer
    from trino_tpu.runtime.metrics import METRICS

    secret = os.environ.setdefault(
        "TRINO_TPU_INTERNAL_SECRET", "chaos-fabric"
    )
    peer_store = MeshCheckpointStore()
    peer = HostFabric(store=peer_store, host_id="chaos-peer")
    srv = FabricServer(peer, internal_secret=secret)
    stop_fabric()  # fresh attachment: the session below re-binds it
    runner = _fabric_case_runner(srv.uri, mesh_chunk_rows)
    try:
        expected = runner.execute(sql).rows  # warm run doubles as oracle
        mesh_clean = runner._last_data_plane == "mesh"
        rng = random.Random(seed)
        state = {"target": None, "fired": 0}

        def hook(k: int, K: int) -> None:
            if state["target"] is None:
                state["target"] = 1 + rng.randrange(max(K - 1, 1))
            if k == state["target"] and not state["fired"]:
                state["fired"] = 1
                fab = active_fabric()
                if fab is not None:
                    # the host's last push must be on the wire before
                    # it dies — the smoke's victim does the same flush
                    fab.pusher.flush(10.0)
                CHECKPOINTS.clear()  # the store dies with the host
                raise mesh_chunk.MeshDeviceLost(
                    f"chaos[host_lost_mid_chunk]: host lost at "
                    f"chunk {k}/{K}"
                )

        before = METRICS.snapshot()
        mesh_chunk.MESH_FAULT_HOOK = hook
        try:
            rows = runner.execute(sql).rows
        finally:
            mesh_chunk.MESH_FAULT_HOOK = None
        after = METRICS.snapshot()
        info = mesh_chunk.last_run_info()
        report = {
            "mesh_clean_plane": mesh_clean,
            "mesh_fault_plane": runner._last_data_plane,
            "fault_chunk": state["target"],
            "fired": state["fired"],
            "chunks": info.get("chunks"),
            "executed_chunk_steps": info.get("executed_chunk_steps"),
            "resumes": info.get("resumes"),
            "resumed_from_chunk": info.get("resumed_from_chunk"),
            "pushes": int(
                after.get("fabric.pushes", 0) - before.get("fabric.pushes", 0)
            ),
            "pulls": int(
                after.get("fabric.pulls", 0) - before.get("fabric.pulls", 0)
            ),
            "peer_served": peer.served,
            "expected": expected,
        }
        return rows, report
    finally:
        stop_fabric()
        srv.stop()


def run_membership_flap_case(
    sql: str, seed: int, mesh_chunk_rows: int = 256,
) -> Tuple[List[list], dict]:
    """A membership flap racing a failover: at a seeded boundary the
    SIBLING replica leaves and immediately rejoins (epoch advances
    twice), a second claim on the in-flight query is attempted and must
    be REFUSED (exactly one owner per query, across epochs), then the
    active sub-mesh dies. Failover lands on the freshly rejoined
    sibling — whose join epoch matches the post-flap fault epoch, so
    the resume proceeds from checkpoint — and the query completes
    oracle-equal with the ownership map drained."""
    from trino_tpu.parallel import mesh_chunk

    runner = _fabric_case_runner("", mesh_chunk_rows, resume_attempts=0)
    rm = runner._replica_manager()
    expected = runner.execute(sql).rows
    mesh_clean = runner._last_data_plane == "mesh"
    rng = random.Random(seed)
    epoch0 = rm.membership_epoch
    state = {
        "target": None, "fired": 0, "flapped": 0, "double_refused": -1,
    }

    def hook(k: int, K: int) -> None:
        if state["target"] is None:
            state["target"] = 1 + rng.randrange(max(K - 1, 1))
        if k == state["target"] and not state["fired"]:
            state["fired"] = 1
            owners = dict(rm._owners)
            if owners:
                qid, (rid, _ep) = next(iter(owners.items()))
                sib = rm.replicas[1 - rid]
                state["double_refused"] = int(not rm.claim(qid, sib))
            sib_id = 1 - (mesh_chunk.active_replica() or 0)
            rm.leave(sib_id)
            rm.join(sib_id)
            state["flapped"] = 1
            raise mesh_chunk.MeshDeviceLost(
                f"chaos[membership_flap]: sub-mesh lost at chunk {k}/{K} "
                f"with replica {sib_id} mid-flap"
            )

    mesh_chunk.MESH_FAULT_HOOK = hook
    try:
        rows = runner.execute(sql).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    info = mesh_chunk.last_run_info()
    report = {
        "mesh_clean_plane": mesh_clean,
        "mesh_fault_plane": runner._last_data_plane,
        "fault_chunk": state["target"],
        "fired": state["fired"],
        "flapped": state["flapped"],
        "double_refused": state["double_refused"],
        "epoch_delta": rm.membership_epoch - epoch0,
        "joins": rm.joins,
        "leaves": rm.leaves,
        "epoch_fences": rm.epoch_fences,
        "owners_at_end": len(rm._owners),
        "chunks": info.get("chunks"),
        "executed_chunk_steps": info.get("executed_chunk_steps"),
        "resumes": info.get("resumes"),
        "expected": expected,
    }
    return rows, report


def run_transport_corruption_case(
    sql: str, seed: int, mesh_chunk_rows: int = 256,
) -> Tuple[List[list], dict]:
    """Transport corruption on the failover pull: the peer serves a
    BIT-FLIPPED payload under the original digest (in-flight
    corruption). The digest check must reject it (fabric.digest_rejects
    grows, fabric.pulls does not), try_pull returns False, and the
    failover degrades to a CLEAN restart on the sibling — oracle-equal
    rows, never a resume from corrupt carries. A truncated payload with
    a matching digest is also pushed at the receive side and must come
    back `imported: False` (undecodable bytes never poison a store)."""
    import os

    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery.checkpoint import (
        CHECKPOINTS,
        MeshCheckpointStore,
    )
    from trino_tpu.runtime.fabric import (
        HostFabric,
        active_fabric,
        checkpoint_digest,
        encode_key,
        stop_fabric,
    )
    from trino_tpu.runtime.http import FabricServer
    from trino_tpu.runtime.metrics import METRICS

    class _CorruptingFabric(HostFabric):
        def serve_checkpoint(self, ekey):
            out = HostFabric.serve_checkpoint(self, ekey)
            if out is None:
                return None
            data, digest = out
            bad = bytearray(data)
            bad[len(bad) // 2] ^= 0xFF
            return bytes(bad), digest  # digest of the ORIGINAL bytes

    secret = os.environ.setdefault(
        "TRINO_TPU_INTERNAL_SECRET", "chaos-fabric"
    )
    peer_store = MeshCheckpointStore()
    peer = _CorruptingFabric(store=peer_store, host_id="chaos-corrupt")
    srv = FabricServer(peer, internal_secret=secret)
    stop_fabric()
    runner = _fabric_case_runner(srv.uri, mesh_chunk_rows)
    try:
        expected = runner.execute(sql).rows
        mesh_clean = runner._last_data_plane == "mesh"
        rng = random.Random(seed)
        state = {"target": None, "fired": 0, "truncated_import": None}

        def hook(k: int, K: int) -> None:
            if state["target"] is None:
                state["target"] = 1 + rng.randrange(max(K - 1, 1))
            if k == state["target"] and not state["fired"]:
                state["fired"] = 1
                fab = active_fabric()
                if fab is not None:
                    fab.pusher.flush(10.0)
                # receive-side truncation probe while the peer holds a
                # live entry: decodes to garbage -> imported False
                for key in list(peer_store._entries):
                    data = peer_store.export_bytes(key)
                    if data is None:
                        continue
                    cut = data[: len(data) // 2]
                    r = peer.receive_checkpoint(
                        encode_key(key), cut, checkpoint_digest(cut)
                    )
                    state["truncated_import"] = r.get("imported")
                    break
                CHECKPOINTS.clear()
                raise mesh_chunk.MeshDeviceLost(
                    f"chaos[transport_corruption]: host lost at "
                    f"chunk {k}/{K}; peer payloads corrupt"
                )

        before = METRICS.snapshot()
        mesh_chunk.MESH_FAULT_HOOK = hook
        try:
            rows = runner.execute(sql).rows
        finally:
            mesh_chunk.MESH_FAULT_HOOK = None
        after = METRICS.snapshot()
        info = mesh_chunk.last_run_info()
        report = {
            "mesh_clean_plane": mesh_clean,
            "mesh_fault_plane": runner._last_data_plane,
            "fault_chunk": state["target"],
            "fired": state["fired"],
            "truncated_import": state["truncated_import"],
            "chunks": info.get("chunks"),
            "executed_chunk_steps": info.get("executed_chunk_steps"),
            "resumes": info.get("resumes"),
            "digest_rejects": int(
                after.get("fabric.digest_rejects", 0)
                - before.get("fabric.digest_rejects", 0)
            ),
            "pulls": int(
                after.get("fabric.pulls", 0) - before.get("fabric.pulls", 0)
            ),
            "expected": expected,
        }
        return rows, report
    finally:
        stop_fabric()
        srv.stop()


class DownableWorker:
    """Proxy handle that can be taken down (every call raises
    ConnectionError) and counts launches — the graylist assertions need
    'zero create_task calls while the breaker is open', and the drain
    assertions need 'zero ACCEPTED launches after the drain landed'
    (accepted_creates is bumped only after the worker took the task, so
    it structurally cannot grow once the worker's state flipped to
    shutting_down — a racing create raises instead)."""

    def __init__(self, inner):
        self._inner = inner
        self.worker_id = inner.worker_id
        self.down = False
        self.create_calls = 0
        self.accepted_creates = 0

    def _check(self) -> None:
        if self.down:
            raise ConnectionError(f"worker {self.worker_id} is down")

    def create_task(self, spec):
        self.create_calls += 1
        self._check()
        out = self._inner.create_task(spec)
        self.accepted_creates += 1
        return out

    def task_state(self, task_id) -> dict:
        self._check()
        return self._inner.task_state(task_id)

    def get_results(self, task_id, partition, token,
                    max_pages=16, wait=0.0):
        self._check()
        return self._inner.get_results(
            task_id, partition, token, max_pages, wait
        )

    def remove_task(self, task_id) -> None:
        self._check()
        self._inner.remove_task(task_id)

    def results_location(self, task_id):
        return self._inner.results_location(task_id)

    def status(self) -> dict:
        self._check()
        return self._inner.status()

    def fail_query(self, query_id, message) -> None:
        self._check()
        self._inner.fail_query(query_id, message)

    def shutdown_gracefully(self) -> None:
        # drain must go through even on a flaky node — request_drain
        # treats delivery as best-effort anyway
        self._inner.shutdown_gracefully()

    # -- stuck-task watchdog passthrough (PR 4 timebound cases) --
    def watchdog_once(self, now=None):
        return self._inner.watchdog_once(now)

    def start_watchdog(self, poll_s: float = 0.01) -> None:
        self._inner.start_watchdog(poll_s)

    def stop_watchdog(self) -> None:
        self._inner.stop_watchdog()

    @property
    def watchdog_interrupts(self):
        return self._inner.watchdog_interrupts

    @property
    def state(self):
        return getattr(self._inner, "state", "active")

    @property
    def memory_pool(self):
        return getattr(self._inner, "memory_pool", None)


def _norm_rows(rows: List[list]) -> List[tuple]:
    """Comparable row form: floats rounded so recomputation noise (a
    retried attempt re-reduces in a different order) doesn't read as
    corruption."""
    out = []
    for r in rows:
        out.append(tuple(
            round(v, 6) if isinstance(v, float) else v for v in r
        ))
    return out


def rows_equal(a: List[list], b: List[list], ordered: bool = False) -> bool:
    na, nb = _norm_rows(a), _norm_rows(b)
    if ordered:
        return na == nb
    key = repr
    return sorted(na, key=key) == sorted(nb, key=key)


class ChaosHarness:
    """One FTE cluster with a shared FailureInjector: run queries under
    generated fault schedules and compare against a clean run.

    The harness owns N in-process workers behind the coordinator's
    worker_handles path (the FTE topology tests use), a NodeManager with
    circuit breakers, and the spooling exchange. `run_case` returns
    (rows, stats) where stats carries the FTE retry counters for
    bounded-attempt assertions.
    """

    def __init__(
        self,
        n_workers: int = 2,
        session=None,
        catalogs: Optional[Dict[str, object]] = None,
        hash_partitions: int = 2,
        memory_pool_bytes: Optional[int] = None,
        stuck_task_interrupt_s: Optional[float] = None,
        stuck_task_interrupt_warm_s: Optional[float] = None,
        in_process: bool = False,
    ):
        from trino_tpu.engine import Session
        from trino_tpu.runtime.coordinator import DistributedQueryRunner
        from trino_tpu.runtime.failure import FailureInjector
        from trino_tpu.runtime.worker import Worker

        self.injector = FailureInjector()
        self.session = session or Session(
            catalog="tpch", schema="tiny", retry_policy="task"
        )
        from trino_tpu.connectors.spi import CatalogManager

        self._catalogs = CatalogManager()
        self.in_process = in_process
        if in_process:
            # the mesh plane only engages on COLOCATED (engine-owned)
            # workers, so the recovery drain case builds the runner on
            # the n_workers path and exposes its Workers for the drain
            # bookkeeping. Injector schedules do not land here — mesh
            # faults arrive through MESH_FAULT_HOOK instead.
            self.stuck_task_interrupt_s = stuck_task_interrupt_s
            self.runner = DistributedQueryRunner(
                self.session,
                n_workers=n_workers,
                hash_partitions=hash_partitions,
            )
            self.workers = list(self.runner.workers)
            for name, conn in (catalogs or {}).items():
                self.register_catalog(name, conn)
            return
        # every worker sits behind a DownableWorker proxy so lifecycle
        # cases can count ACCEPTED launches (drain assertions) and take
        # nodes dark (graylist assertions) without touching the engine
        self.workers = [
            DownableWorker(Worker(
                f"chaos-w{i}", self._catalogs,
                failure_injector=self.injector,
                memory_pool_bytes=memory_pool_bytes,
                stuck_task_interrupt_s=stuck_task_interrupt_s,
                stuck_task_interrupt_warm_s=stuck_task_interrupt_warm_s,
            ))
            for i in range(n_workers)
        ]
        # NOTE: workers carry the watchdog threshold but it is NOT
        # armed here — run_hung_operator_case arms it around its own
        # execution, after a warm run has compiled every jit shape the
        # plan needs. Armed from birth, the watchdog would kill healthy
        # COLD tasks (first-use XLA compilation and connector data
        # generation happen inside one batch and dwarf any test-speed
        # threshold), and each retry would re-block on the same warm-up.
        self.stuck_task_interrupt_s = stuck_task_interrupt_s
        self.runner = DistributedQueryRunner(
            self.session,
            worker_handles=self.workers,
            hash_partitions=hash_partitions,
        )
        for name, conn in (catalogs or {}).items():
            self.register_catalog(name, conn)

    def register_catalog(self, name: str, connector) -> None:
        # planner-side AND worker-side (worker_handles topologies load
        # catalogs per node, as the reference does)
        self.runner.register_catalog(name, connector)
        self._catalogs.register(name, connector)

    def run_clean(self, sql: str) -> List[list]:
        self.injector.clear()
        return self.runner.execute(sql).rows

    def run_case(
        self, sql: str, fault_class: str, seed: int,
        n_partitions: int = 2,
    ) -> Tuple[List[list], dict]:
        """Run one query under one generated fault schedule."""
        rules = generate_schedule(seed, fault_class, n_partitions)
        self.injector.clear()
        for r in rules:
            self.injector.inject(**r)
        try:
            rows = self.runner.execute(sql).rows
        finally:
            self.injector.clear()
        stats = dict(self.runner.last_fte_stats or {})
        stats["max_injected_failures"] = schedule_max_failures(rules)
        stats["breakers"] = self.runner.node_manager.breaker_states()
        return rows, stats

    # -- cluster-lifecycle scenarios (graceful drain + speculation) --

    def run_lifecycle_case(
        self, sql: str, scenario: str, seed: int = 0,
    ) -> Tuple[List[list], dict]:
        """Drains are one-way (a drained node never rejoins), so run
        each lifecycle case on a FRESH harness."""
        if scenario == "drain_mid_query":
            return self.run_drain_case(sql, seed)
        if scenario == "drain_all_but_one":
            return self.run_drain_case(sql, seed, drain_all_but_one=True)
        if scenario == "straggler_speculation":
            return self.run_speculation_case(sql, seed)
        raise ValueError(f"unknown lifecycle scenario: {scenario}")

    def run_drain_case(
        self, sql: str, seed: int = 0, drain_all_but_one: bool = False,
        stall_s: float = 0.8, drain_timeout_s: float = 60.0,
    ) -> Tuple[List[list], dict]:
        """Gracefully drain worker(s) while `sql` is mid-flight.

        Every first attempt is stretched by `stall_s` so the drain is
        guaranteed to land on a node with running tasks. Returns (rows,
        report); report carries per-victim drain verdicts plus the
        accepted-launch counter at drain time vs end of query — equal
        counters prove the drained node took ZERO post-drain launches.
        """
        rng = random.Random(seed)
        self.injector.clear()
        self.injector.inject(
            where="start", attempts=(0,), stall_s=stall_s,
            max_hits=4 * len(self.workers),
        )
        result: dict = {}

        def run():
            try:
                result["rows"] = self.runner.execute(sql).rows
            except Exception as e:
                result["error"] = e

        t = threadreg.spawn("chaos-query-driver", run, owner="chaos")
        # drain a node that ACTUALLY hosts work: wait for launches
        deadline = time.monotonic() + 10.0
        busy: List[DownableWorker] = []
        while time.monotonic() < deadline and t.is_alive():
            busy = [w for w in self.workers if w.accepted_creates > 0]
            if busy:
                break
            time.sleep(0.002)
        if drain_all_but_one:
            victims = self.workers[:-1]
        else:
            victims = [busy[rng.randrange(len(busy))] if busy
                       else self.workers[0]]
        drained: Dict[str, bool] = {}
        at_drain: Dict[str, int] = {}
        for v in victims:
            drained[v.worker_id] = self.runner.drain(
                v.worker_id, timeout_s=drain_timeout_s
            )
            at_drain[v.worker_id] = v.accepted_creates
        t.join(120.0)
        self.injector.clear()
        if "error" in result:
            raise result["error"]
        report = dict(self.runner.last_fte_stats or {})
        report.update(
            drained=drained,
            launches_at_drain=at_drain,
            launches_at_end={
                v.worker_id: v.accepted_creates for v in victims
            },
            node_states=self.runner.node_manager.all_states(),
        )
        return result.get("rows"), report

    def run_speculation_case(
        self, sql: str, seed: int = 0, stall_s: float = 6.0,
    ) -> Tuple[List[list], dict]:
        """One partition's first attempt stalls hard; the speculative
        duplicate on a spare worker must commit first (stats carry
        speculation_wins/losses and attempts_per_partition).

        stall_s must comfortably exceed the query's REAL per-task wall
        time: the trigger is `age > speculation_quantile * median`, and
        a stalled attempt's age only reaches `stall + wall`, so a stall
        close to the task wall never crosses 2x median and the scenario
        silently degrades to a plain wait. The duplicate wins and
        cancels the stalled loser cooperatively, so a healthy run never
        waits out the full stall."""
        rng = random.Random(seed)
        self.injector.clear()
        # pin the stall to fragment 0 (the leaf stage, one task per
        # worker): speculation needs sibling attempts to commit first so
        # a median exists — a stall on a single-task fragment can never
        # speculate and the scenario would silently degrade to a wait
        self.injector.inject(
            where="start", fragment_id=0, partition=rng.randrange(2),
            attempts=(0,), stall_s=stall_s, max_hits=1,
        )
        try:
            rows = self.runner.execute(sql).rows
        finally:
            self.injector.clear()
        return rows, dict(self.runner.last_fte_stats or {})

    # -- time-bounding scenarios (watchdog + client-abandonment reaper) --

    def run_hung_operator_case(
        self, sql: str, seed: int = 0, stall_s: float = 8.0,
    ) -> Tuple[List[list], dict]:
        """One leaf task WEDGES mid-batch (a hung operator, not a slow
        one: its heartbeat goes stale, where a straggler's keeps
        ticking). The worker watchdog must interrupt it with a
        diagnostic naming the stuck operator; the failure is retryable,
        so FTE re-runs the partition (attempt 1 matches no rule) and the
        query completes correctly — in far less wall time than the
        stall, which is the no-query-may-hang-the-cluster property.

        The conservative threshold must comfortably exceed a cold
        task's honest silence: a fresh shape triggers an XLA lowering
        burst (~0.3s on CPU) INSIDE one operator call, and retries
        perturb batch capacities (dynamic-filter pruning differs per
        surviving attempt) so no warm run covers every shape. But
        operator-internal heartbeats (InstrumentedOperator._beat fires
        at entry AND exit of every add_input/get_output/finish, always
        on since exec/stats.py instrumentation became unconditional)
        mean a WARM task's longest honest silence is one operator call,
        not one batch — so stuck_task_interrupt_warm_s can run at a few
        hundred ms where the old batch-granular beats needed ~1s+."""
        rng = random.Random(seed)
        # warm run first: compiles every jit shape this plan touches, so
        # once the watchdog arms, the only task that can miss a
        # heartbeat for stuck_task_interrupt_s is the genuinely wedged
        # one (a cold compile inside one batch looks identical to a
        # hang at batch granularity). Its duration is the honest-work
        # baseline: the un-wedged proof is elapsed - warm < stall (the
        # injected stall abort-polls, so a killed task wakes early and
        # only a BROKEN watchdog ever waits out the full stall)
        t_warm = time.monotonic()
        self.run_clean(sql)
        warm_clean_s = time.monotonic() - t_warm
        self.injector.inject(
            where="batch", fragment_id=0, partition=rng.randrange(2),
            attempts=(0,), stall_s=stall_s, max_hits=1,
        )
        # speculation would race the watchdog to the rescue (a duplicate
        # attempt commits and cancels the wedged loser) — turn it off so
        # THIS case proves the watchdog path alone unhangs the query
        was_spec = getattr(self.session, "speculation_enabled", True)
        self.session.speculation_enabled = False
        for w in self.workers:
            w.start_watchdog()
        t0 = time.monotonic()
        try:
            rows = self.runner.execute(sql).rows
        finally:
            for w in self.workers:
                w.stop_watchdog()
            self.session.speculation_enabled = was_spec
            self.injector.clear()
        report = dict(self.runner.last_fte_stats or {})
        report["elapsed_s"] = time.monotonic() - t0
        report["warm_clean_s"] = warm_clean_s
        report["stall_s"] = stall_s
        report["watchdog_interrupts"] = [
            d for w in self.workers for _, d in w.watchdog_interrupts
        ]
        return rows, report

    def run_abandoned_client_case(
        self, sql: str, seed: int = 0, stall_s: float = 4.0,
        client_timeout_s: float = 0.2,
    ) -> Tuple[Optional[List[list]], dict]:
        """Submit through the HTTP server's job path, then VANISH —
        never poll the results page. The reaper must notice within
        client_timeout_s, cancel the query (the runner's `cancel` hook
        unwinds every running task), release the resource-group slot,
        and drain the query's memory reservations back to zero. The
        injected batch stall keeps the query mid-flight (with pages in
        memory) when abandonment lands; it abort-polls, so teardown
        never waits out the full stall."""
        from trino_tpu.runtime.resource_groups import (
            ResourceGroupManager,
            ResourceGroupSpec,
        )
        from trino_tpu.runtime.server import CoordinatorServer

        rg = ResourceGroupManager(
            ResourceGroupSpec("global", max_concurrency=4)
        )
        self.injector.clear()
        self.injector.inject(
            where="batch", attempts=(0,), stall_s=stall_s,
            max_hits=1_000,
        )
        server = CoordinatorServer(
            self.runner,
            resource_groups=rg,
            client_timeout_s=client_timeout_s,
            reap_interval_s=0.05,
        )

        def ledgers() -> Dict[str, Dict[str, int]]:
            return {
                w.worker_id: dict(w.memory_pool.query_reservations())
                for w in self.workers
                if w.memory_pool is not None
            }

        try:
            job = server._submit(sql)
            peak_reserved = 0
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                peak_reserved = max(
                    peak_reserved,
                    sum(sum(l.values()) for l in ledgers().values()),
                )
                if (
                    job.finished_at is not None
                    and rg.total_running() == 0
                    and all(not l for l in ledgers().values())
                ):
                    break
                time.sleep(0.01)
            report = {
                "reaped": job.state == "failed"
                and "abandoned" in (job.error or "").lower(),
                "error": job.error,
                "rg_running": rg.total_running(),
                "ledgers": ledgers(),
                "peak_reserved_bytes": peak_reserved,
            }
            return None, report
        finally:
            self.injector.clear()
            server.stop()

    def run_loaded_cluster_case(
        self, queries: Dict[str, str], seed: int = 0,
        n_clients: int = 6, duration_s: float = 3.0,
        join_timeout_s: float = 45.0,
    ) -> Tuple[None, dict]:
        """Faults under LIVE concurrent load, through the HTTP serving
        path end to end (admission lanes, plan cache, statement
        protocol). N client threads drive the query mix closed-loop
        while the fault schedule lands mid-traffic; every completion is
        checked against the clean-run oracle. Acceptable per-query
        outcomes: oracle-equal rows, an overload shed (HTTP 429), or a
        TYPED failure (a bracketed error code the client can act on).
        An untyped error or a client thread that never returns is a
        violation — under concurrency, a silent hang is the failure
        mode this case exists to catch."""
        import re
        import urllib.error

        from trino_tpu.client import Client, QueryError
        from trino_tpu.runtime.server import CoordinatorServer

        rng = random.Random(seed)
        self.injector.clear()
        oracle = {n: self.run_clean(sql) for n, sql in queries.items()}
        ordered = {
            n: "order by" in sql.lower() for n, sql in queries.items()
        }
        server = CoordinatorServer(self.runner, max_concurrent=n_clients)
        lock = threading.Lock()
        stats = {
            "completed": 0, "ok": 0, "mismatches": 0, "sheds": 0,
            "typed_failures": 0, "untyped_errors": [], "hung_threads": 0,
        }
        typed = re.compile(r"\[[A-Z][A-Z_]+\]")
        stop_at = time.monotonic() + duration_s

        def client_loop(i: int):
            r = random.Random(seed * 997 + i)
            c = Client(server.uri, timeout=30.0, poll_interval=0.005)
            names = list(queries)
            while time.monotonic() < stop_at:
                name = r.choice(names)
                try:
                    rows = c.execute(queries[name]).rows
                    with lock:
                        stats["completed"] += 1
                        if rows_equal(rows, oracle[name],
                                      ordered=ordered[name]):
                            stats["ok"] += 1
                        else:
                            stats["mismatches"] += 1
                except urllib.error.HTTPError as e:
                    with lock:
                        stats["completed"] += 1
                        if e.code == 429:
                            stats["sheds"] += 1
                        else:
                            stats["untyped_errors"].append(
                                f"{name}: HTTP {e.code}"
                            )
                except QueryError as e:
                    with lock:
                        stats["completed"] += 1
                        if typed.search(str(e)):
                            stats["typed_failures"] += 1
                        else:
                            stats["untyped_errors"].append(
                                f"{name}: {e}"
                            )
                except Exception as e:
                    with lock:
                        stats["completed"] += 1
                        stats["untyped_errors"].append(
                            f"{name}: {type(e).__name__}: {e}"
                        )

        threads = [
            threadreg.spawn(f"chaos-client-{i}", client_loop, args=(i,),
                            owner="chaos", start=False)
            for i in range(n_clients)
        ]
        try:
            for t in threads:
                t.start()
            # let traffic establish, then land a burst of every injector
            # fault class MID-FLIGHT; clear before the phase ends so the
            # tail of the run proves the cluster comes back clean
            time.sleep(min(0.4, duration_s / 4))
            for fc in ("task_crash_start", "task_crash_mid",
                       "fetch_loss", "oom"):
                for rule in generate_schedule(rng.randrange(1 << 20), fc):
                    self.injector.inject(**rule)
            # and a lifecycle maneuver on top: gracefully drain one
            # worker out from under the live population (one-way, so
            # the remaining nodes carry the tail of the run)
            drain_ok = self.runner.drain(
                self.workers[rng.randrange(len(self.workers))].worker_id,
                timeout_s=30.0,
            )
            time.sleep(min(1.0, duration_s / 2))
            self.injector.clear()
            deadline = time.monotonic() + duration_s + join_timeout_s
            for t in threads:
                t.join(max(0.1, deadline - time.monotonic()))
            stats["hung_threads"] = sum(t.is_alive() for t in threads)
        finally:
            self.injector.clear()
            server.stop()
        stats["drained"] = bool(drain_ok)
        stats["untyped_error_count"] = len(stats["untyped_errors"])
        stats["untyped_errors"] = stats["untyped_errors"][:5]
        return None, stats

    def run_adaptive_drain_case(
        self, queries: Dict[str, str], seed: int = 0, **kw,
    ) -> Tuple[None, dict]:
        """PR 13: loaded-cluster faults + mid-traffic drain against an
        ADAPTIVE session (construct the harness with adaptive_execution
        on and a permissive re-plan threshold). Delegates the population
        mechanics to run_loaded_cluster_case and adds the adaptive
        counters observed during the phase, so the caller can assert
        the drain actually landed on a cluster that was re-planning."""
        from trino_tpu.runtime.metrics import METRICS

        before = METRICS.snapshot()
        _, report = self.run_loaded_cluster_case(queries, seed, **kw)
        after = METRICS.snapshot()
        for counter in ("adaptive.replans", "adaptive.divergences",
                        "adaptive.spool_hits"):
            report[counter] = int(
                after.get(counter, 0) - before.get(counter, 0)
            )
        return None, report

    def run_recovery_drain_case(
        self, queries: Dict[str, str], seed: int = 0,
        n_faults: int = 3, **kw,
    ) -> Tuple[None, dict]:
        """PR 8 carry-forward, re-aimed (PR 14): the drain_mid_query /
        drain_all_but_one maneuvers now land on the loaded_cluster
        POPULATION instead of one isolated query — construct the
        harness with in_process=True and mesh checkpointing on, and
        mesh faults raise mid-chunk (MESH_FAULT_HOOK at the middle
        boundary, first n_faults hits) while run_loaded_cluster_case
        drains a worker out from under the live traffic. Faulted
        queries must RESUME from checkpoint on the surviving capacity
        (report carries checkpoint_resumes from the store's counters),
        and every completion still checks against the clean oracle."""
        from trino_tpu.parallel import mesh_chunk
        from trino_tpu.recovery import CHECKPOINTS

        if not self.in_process:
            raise ValueError(
                "run_recovery_drain_case needs in_process=True (the "
                "mesh plane only engages on colocated workers)"
            )
        lock = threading.Lock()
        state = {"fired": 0}

        def hook(k: int, K: int) -> None:
            # deterministic allowance, not a coin flip: the first
            # n_faults arrivals at a mid-run boundary fault; everything
            # after runs clean so the tail proves the cluster recovered
            with lock:
                if K >= 2 and k == max(1, K // 2) \
                        and state["fired"] < n_faults:
                    state["fired"] += 1
                    raise mesh_chunk.MeshDeviceLost(
                        f"chaos[recovery_drain]: injected device loss "
                        f"at chunk {k}/{K}"
                    )

        resumed0 = CHECKPOINTS.resumed
        mesh_chunk.MESH_FAULT_HOOK = hook
        try:
            _, report = self.run_loaded_cluster_case(queries, seed, **kw)
        finally:
            mesh_chunk.MESH_FAULT_HOOK = None
        report["mesh_faults_fired"] = state["fired"]
        report["checkpoint_resumes"] = CHECKPOINTS.resumed - resumed0
        return None, report

    def run_replica_down_case(
        self, queries: Dict[str, str], seed: int = 0, **kw,
    ) -> Tuple[None, dict]:
        """PR 17: hard-kill one replica's sub-mesh mid-chunk under live
        serving load. Construct the harness with in_process=True and a
        session with mesh_replicas >= 2 + chunking + checkpointing. A
        PERSISTENT fault hook kills every chunk loop that reaches a
        mid-run boundary on replica 0 — the coordinator must fail each
        one over to the sibling sub-mesh (resuming from the last
        host-portable checkpoint), replica 0's breaker trips after the
        configured consecutive failures, and placement routes the tail
        of the population around the dead sub-mesh. Zero queries may be
        lost: the delegated loaded-cluster case oracle-checks every
        completion. The hook ignores the case thread so the oracle
        pre-pass runs clean; only server-side executions fault."""
        from trino_tpu.parallel import mesh_chunk
        from trino_tpu.recovery import CHECKPOINTS
        from trino_tpu.runtime.metrics import METRICS

        if not self.in_process:
            raise ValueError(
                "run_replica_down_case needs in_process=True (the mesh "
                "plane only engages on colocated workers)"
            )
        lock = threading.Lock()
        state = {"fired": 0}
        case_thread = threading.current_thread()

        def hook(k: int, K: int) -> None:
            if threading.current_thread() is case_thread:
                return  # oracle pre-pass: the clean runs stay clean
            if mesh_chunk.active_replica() == 0 and K >= 2 \
                    and k >= max(1, K // 2):
                with lock:
                    state["fired"] += 1
                raise mesh_chunk.MeshDeviceLost(
                    f"chaos[replica_down]: replica 0 sub-mesh "
                    f"hard-killed at chunk {k}/{K}"
                )

        before = METRICS.snapshot()
        resumed0 = CHECKPOINTS.resumed
        mesh_chunk.MESH_FAULT_HOOK = hook
        try:
            _, report = self.run_loaded_cluster_case(queries, seed, **kw)
        finally:
            mesh_chunk.MESH_FAULT_HOOK = None
        after = METRICS.snapshot()
        report["mesh_faults_fired"] = state["fired"]
        report["checkpoint_resumes"] = CHECKPOINTS.resumed - resumed0
        for name in ("replica.failovers", "replica.breaker_opens"):
            report[name] = int(after.get(name, 0) - before.get(name, 0))
        return None, report

    def run_replica_drain_case(
        self, queries: Dict[str, str], seed: int = 0, **kw,
    ) -> Tuple[None, dict]:
        """PR 17: gracefully drain one replica with a chunked query in
        flight on it, under live serving load. The fault hook does not
        raise — the FIRST server-side chunk loop to reach a mid-run
        boundary on replica 0 triggers request_drain(0) synchronously,
        so that same run's next boundary hits the drain check, raises
        MeshReplicaDraining, and fails over to the sibling with a query
        deterministically in flight (no timer races). The drained
        replica takes no further placements; after the population
        finishes, drain() must confirm it quiesced to zero inflight."""
        from trino_tpu.parallel import mesh_chunk
        from trino_tpu.recovery import CHECKPOINTS
        from trino_tpu.runtime.metrics import METRICS

        if not self.in_process:
            raise ValueError(
                "run_replica_drain_case needs in_process=True (the mesh "
                "plane only engages on colocated workers)"
            )
        lock = threading.Lock()
        state = {"drain_requested": 0}
        case_thread = threading.current_thread()

        def hook(k: int, K: int) -> None:
            if threading.current_thread() is case_thread:
                return  # oracle pre-pass: don't drain before load starts
            if mesh_chunk.active_replica() == 0 and K >= 2 \
                    and k >= max(1, K // 2):
                rm = getattr(self.runner, "_replicas", None)
                if rm is None:
                    return
                with lock:
                    if state["drain_requested"]:
                        return
                    state["drain_requested"] = 1
                rm.request_drain(0)

        before = METRICS.snapshot()
        resumed0 = CHECKPOINTS.resumed
        mesh_chunk.MESH_FAULT_HOOK = hook
        try:
            _, report = self.run_loaded_cluster_case(queries, seed, **kw)
        finally:
            mesh_chunk.MESH_FAULT_HOOK = None
        rm = getattr(self.runner, "_replicas", None)
        report["drain_requested"] = bool(state["drain_requested"])
        report["replica_drained"] = bool(
            rm is not None and state["drain_requested"]
            and rm.drain(0, timeout_s=30.0)
        )
        after = METRICS.snapshot()
        report["checkpoint_resumes"] = CHECKPOINTS.resumed - resumed0
        for name in ("replica.failovers", "replica.drains"):
            report[name] = int(after.get(name, 0) - before.get(name, 0))
        return None, report


def chaos_smoke(
    seed: int,
    queries: Dict[str, str],
    fault_classes=FAULT_CLASSES,
    verbose: bool = True,
) -> List[str]:
    """bench.py --chaos-smoke entry: every (query, fault class) pair
    must be oracle-equal to the clean run and stay within its injected
    failure bound. Returns the list of violation descriptions (empty =
    pass)."""
    from trino_tpu.connectors.tpch import create_tpch_connector

    harness = ChaosHarness(n_workers=2)
    harness.register_catalog("tpch", create_tpch_connector())
    failures: List[str] = []
    for name, sql in queries.items():
        expected = harness.run_clean(sql)
        ordered = "order by" in sql.lower()
        for fc in fault_classes:
            try:
                rows, stats = harness.run_case(sql, fc, seed)
            except Exception as e:
                failures.append(f"{name}/{fc}: raised {type(e).__name__}: {e}")
                continue
            if not rows_equal(rows, expected, ordered=ordered):
                failures.append(
                    f"{name}/{fc}: rows diverged from clean run "
                    f"({len(rows)} vs {len(expected)})"
                )
            bound = stats.get("max_injected_failures", 0)
            if stats.get("retries", 0) > bound:
                failures.append(
                    f"{name}/{fc}: {stats['retries']} retries exceeds "
                    f"injected-failure bound {bound}"
                )
            if verbose:
                app = stats.get("attempts_per_partition") or {}
                print(
                    f"  chaos {name}/{fc}: ok rows={len(rows)} "
                    f"retries={stats.get('retries')} "
                    f"spec={stats.get('speculative_hits')} "
                    f"wins={stats.get('speculation_wins')} "
                    f"losses={stats.get('speculation_losses')} "
                    f"max_attempts={max(app.values(), default=0)}"
                )
    # lifecycle scenarios: drains are one-way, so each runs on a fresh
    # 3-worker harness (one spare survives drain_all_but_one)
    lifecycle_sql = next(iter(queries.values()))
    for scenario in LIFECYCLE_CLASSES:
        h = ChaosHarness(n_workers=3)
        h.register_catalog("tpch", create_tpch_connector())
        expected = h.run_clean(lifecycle_sql)
        try:
            rows, report = h.run_lifecycle_case(
                lifecycle_sql, scenario, seed
            )
        except Exception as e:
            failures.append(
                f"lifecycle/{scenario}: raised {type(e).__name__}: {e}"
            )
            continue
        ordered = "order by" in lifecycle_sql.lower()
        if not rows_equal(rows, expected, ordered=ordered):
            failures.append(
                f"lifecycle/{scenario}: rows diverged from clean run "
                f"({len(rows)} vs {len(expected)})"
            )
        if scenario.startswith("drain"):
            if not all(report["drained"].values()):
                failures.append(
                    f"lifecycle/{scenario}: drain timed out "
                    f"({report['drained']})"
                )
            if report["launches_at_end"] != report["launches_at_drain"]:
                failures.append(
                    f"lifecycle/{scenario}: drained worker accepted "
                    f"post-drain launches "
                    f"({report['launches_at_drain']} -> "
                    f"{report['launches_at_end']})"
                )
        if (
            scenario == "straggler_speculation"
            and not report.get("speculation_wins")
        ):
            failures.append(
                f"lifecycle/{scenario}: no speculative win recorded "
                f"({report})"
            )
        if verbose:
            app = report.get("attempts_per_partition") or {}
            print(
                f"  chaos lifecycle/{scenario}: ok rows={len(rows)} "
                f"retries={report.get('retries')} "
                f"wins={report.get('speculation_wins')} "
                f"losses={report.get('speculation_losses')} "
                f"max_attempts={max(app.values(), default=0)}"
            )
    # time-bounding scenarios (PR 4): watchdog + abandonment reaper;
    # fresh harnesses again (the abandoned case leaves a dead query in
    # its server, the hung case arms a watchdog). The agg shape is the
    # right query here: its batch capacities do not depend on which
    # attempt survives, so one warm run covers every jit shape a retry
    # can touch. The join's dynamic-filter pruning makes retry batch
    # capacities attempt-dependent — each retry hits a FRESH >1s XLA
    # lowering inside one batch, indistinguishable from a hang at any
    # test-speed threshold
    timebound_sql = lifecycle_sql
    for scenario in TIMEBOUND_CLASSES:
        h = ChaosHarness(
            n_workers=3,
            stuck_task_interrupt_s=1.0,
            memory_pool_bytes=256 << 20,
        )
        h.register_catalog("tpch", create_tpch_connector())
        if scenario == "hung_operator":
            expected = h.run_clean(timebound_sql)
            try:
                rows, report = h.run_hung_operator_case(
                    timebound_sql, seed
                )
            except Exception as e:
                failures.append(
                    f"timebound/{scenario}: raised "
                    f"{type(e).__name__}: {e}"
                )
                continue
            ordered = "order by" in timebound_sql.lower()
            if not rows_equal(rows, expected, ordered=ordered):
                failures.append(
                    f"timebound/{scenario}: rows diverged from clean "
                    f"run ({len(rows)} vs {len(expected)})"
                )
            interrupts = report.get("watchdog_interrupts") or []
            if not interrupts:
                failures.append(
                    f"timebound/{scenario}: watchdog never fired"
                )
            elif not any("in operator" in d for d in interrupts):
                failures.append(
                    f"timebound/{scenario}: diagnostic does not name "
                    f"the stuck operator ({interrupts[0]!r})"
                )
            overhead = report["elapsed_s"] - report["warm_clean_s"]
            if overhead >= report["stall_s"]:
                failures.append(
                    f"timebound/{scenario}: query waited out the full "
                    f"stall (recovery overhead {overhead:.2f}s >= "
                    f"{report['stall_s']}s) — the watchdog did not "
                    f"unwedge it"
                )
            if verbose:
                print(
                    f"  chaos timebound/{scenario}: ok rows={len(rows)} "
                    f"elapsed={report['elapsed_s']:.2f}s "
                    f"(warm clean {report['warm_clean_s']:.2f}s) "
                    f"interrupts={len(interrupts)}"
                )
        else:  # abandoned_client
            try:
                _, report = h.run_abandoned_client_case(
                    timebound_sql, seed
                )
            except Exception as e:
                failures.append(
                    f"timebound/{scenario}: raised "
                    f"{type(e).__name__}: {e}"
                )
                continue
            if not report["reaped"]:
                failures.append(
                    f"timebound/{scenario}: query was not reaped "
                    f"(error={report['error']!r})"
                )
            if report["rg_running"] != 0:
                failures.append(
                    f"timebound/{scenario}: resource-group slot leaked "
                    f"({report['rg_running']} still running)"
                )
            if any(report["ledgers"].values()):
                failures.append(
                    f"timebound/{scenario}: memory ledger not drained "
                    f"({report['ledgers']})"
                )
            if verbose:
                print(
                    f"  chaos timebound/{scenario}: ok "
                    f"peak_reserved={report['peak_reserved_bytes']} "
                    f"ledgers_drained=True rg_running=0"
                )
    # serving scenario (PR 8): the same fault classes, but landing on a
    # cluster that is actively serving a concurrent client population
    # through the HTTP path — fresh harness (faults + server leftovers)
    for scenario in SERVING_CLASSES:
        h = ChaosHarness(n_workers=3)
        h.register_catalog("tpch", create_tpch_connector())
        try:
            _, report = h.run_loaded_cluster_case(queries, seed)
        except Exception as e:
            failures.append(
                f"serving/{scenario}: raised {type(e).__name__}: {e}"
            )
            continue
        if report["completed"] == 0:
            failures.append(
                f"serving/{scenario}: no query completed under load"
            )
        if report["ok"] == 0:
            failures.append(
                f"serving/{scenario}: zero oracle-equal results "
                f"({report})"
            )
        if report["mismatches"]:
            failures.append(
                f"serving/{scenario}: {report['mismatches']} results "
                f"diverged from clean run under faults"
            )
        if report["untyped_error_count"]:
            failures.append(
                f"serving/{scenario}: {report['untyped_error_count']} "
                f"untyped errors (first: {report['untyped_errors'][:1]})"
            )
        if report["hung_threads"]:
            failures.append(
                f"serving/{scenario}: {report['hung_threads']} client "
                f"threads never returned"
            )
        if not report["drained"]:
            failures.append(
                f"serving/{scenario}: mid-traffic drain timed out"
            )
        if verbose:
            print(
                f"  chaos serving/{scenario}: ok "
                f"completed={report['completed']} ok={report['ok']} "
                f"sheds={report['sheds']} "
                f"typed_failures={report['typed_failures']} "
                f"drained={report['drained']} hung=0"
            )
    # adaptive scenario (PR 13): the same loaded-cluster burst + drain,
    # on a session that re-plans mid-query. The query mix adds a join
    # whose build-side filter the stats heuristics misestimate, so with
    # the permissive threshold every execution crosses the re-plan gate
    # — the drain and fault burst land while re-planned programs are in
    # flight, and each completion is still checked against the clean run
    from trino_tpu.engine import Session

    adaptive_queries = dict(queries)
    adaptive_queries["replan"] = (
        "select count(*) from supplier s "
        "join nation n on s_nationkey = n_nationkey "
        "where n_nationkey % 2 = 0"
    )
    for scenario in ADAPTIVE_CLASSES:
        h = ChaosHarness(
            n_workers=3,
            session=Session(
                catalog="tpch", schema="tiny", retry_policy="task",
                adaptive_execution=True,
                shared_subtree_materialization=True,
                adaptive_replan_threshold=1.3,
            ),
        )
        h.register_catalog("tpch", create_tpch_connector())
        try:
            _, report = h.run_adaptive_drain_case(adaptive_queries, seed)
        except Exception as e:
            failures.append(
                f"adaptive/{scenario}: raised {type(e).__name__}: {e}"
            )
            continue
        if report["ok"] == 0:
            failures.append(
                f"adaptive/{scenario}: zero oracle-equal results "
                f"({report})"
            )
        if report["mismatches"]:
            failures.append(
                f"adaptive/{scenario}: {report['mismatches']} re-planned "
                f"results diverged from clean run under faults"
            )
        if report["untyped_error_count"]:
            failures.append(
                f"adaptive/{scenario}: {report['untyped_error_count']} "
                f"untyped errors (first: {report['untyped_errors'][:1]})"
            )
        if report["hung_threads"]:
            failures.append(
                f"adaptive/{scenario}: {report['hung_threads']} client "
                f"threads never returned"
            )
        if not report["drained"]:
            failures.append(
                f"adaptive/{scenario}: mid-traffic drain timed out"
            )
        if report["adaptive.replans"] < 1:
            failures.append(
                f"adaptive/{scenario}: no re-plan happened during the "
                f"run — the drain never raced a re-planning query"
            )
        if verbose:
            print(
                f"  chaos adaptive/{scenario}: ok "
                f"completed={report['completed']} ok={report['ok']} "
                f"replans={report['adaptive.replans']} "
                f"spool_hits={report['adaptive.spool_hits']} "
                f"drained={report['drained']} hung=0"
            )
    # recovery scenarios (PR 14): seeded faults INSIDE the mesh chunk
    # loop must resume from the last checkpoint — oracle-equal rows and
    # strictly fewer re-executed chunks than restarting from chunk 0
    recovery_sql = (
        "select o_orderpriority, count(*) c from orders join customer "
        "on o_custkey = c_custkey group by o_orderpriority "
        "order by o_orderpriority"
    )
    for fc in RECOVERY_CLASSES:
        try:
            rows, rep = run_mesh_recovery_case(recovery_sql, fc, seed)
        except Exception as e:
            failures.append(
                f"recovery/{fc}: raised {type(e).__name__}: {e}"
            )
            continue
        if not rep["mesh_clean_plane"]:
            failures.append(
                f"recovery/{fc}: clean run did not take the mesh plane"
            )
            continue
        K = rep["chunks"] or 0
        steps = rep["executed_chunk_steps"] or 0
        fault_k = rep["fault_chunk"] or 0
        re_executed = steps - K
        if not rows_equal(rows, rep["expected"], ordered=True):
            failures.append(
                f"recovery/{fc}: rows diverged from clean run "
                f"({len(rows)} vs {len(rep['expected'])})"
            )
        if not rep["fired"]:
            failures.append(f"recovery/{fc}: fault never fired ({rep})")
        elif rep["mesh_fault_plane"] != "mesh":
            failures.append(
                f"recovery/{fc}: faulted run left the mesh plane "
                f"({rep['mesh_fault_plane']})"
            )
        elif not rep["resumes"]:
            failures.append(
                f"recovery/{fc}: no checkpoint resume recorded ({rep})"
            )
        elif re_executed >= max(fault_k, 1) or re_executed >= K:
            failures.append(
                f"recovery/{fc}: re-executed {re_executed} of {K} "
                f"chunks — a restart-from-zero re-executes {fault_k}; "
                f"the checkpoint saved nothing"
            )
        if verbose:
            print(
                f"  chaos recovery/{fc}: ok rows={len(rows)} "
                f"fault_chunk={fault_k}/{K} "
                f"resumed_from={rep['resumed_from_chunk']} "
                f"re_executed={re_executed}"
            )
    # carry-forward (PR 8 -> PR 14): the drain maneuvers aimed at the
    # loaded_cluster population, with mesh checkpointing on — device
    # losses land mid-chunk while a worker drains out from under the
    # live traffic, and the faulted queries must resume from checkpoint
    # on what survives
    h = ChaosHarness(
        n_workers=3, in_process=True,
        session=Session(
            catalog="tpch", schema="tiny",
            mesh_chunk_rows=256,
            mesh_checkpoint_interval_chunks=1,
        ),
    )
    h.register_catalog("tpch", create_tpch_connector())
    scenario = "recovery_loaded_drain"
    try:
        _, report = h.run_recovery_drain_case(queries, seed)
    except Exception as e:
        failures.append(
            f"recovery/{scenario}: raised {type(e).__name__}: {e}"
        )
        report = None
    if report is not None:
        if report["ok"] == 0:
            failures.append(
                f"recovery/{scenario}: zero oracle-equal results "
                f"({report})"
            )
        if report["mismatches"]:
            failures.append(
                f"recovery/{scenario}: {report['mismatches']} results "
                f"diverged from clean run under mesh faults"
            )
        if report["untyped_error_count"]:
            failures.append(
                f"recovery/{scenario}: {report['untyped_error_count']} "
                f"untyped errors (first: {report['untyped_errors'][:1]})"
            )
        if report["hung_threads"]:
            failures.append(
                f"recovery/{scenario}: {report['hung_threads']} client "
                f"threads never returned"
            )
        if not report["drained"]:
            failures.append(
                f"recovery/{scenario}: mid-traffic drain timed out"
            )
        if not report["mesh_faults_fired"]:
            failures.append(
                f"recovery/{scenario}: no mesh fault landed — the "
                f"drain never raced a resuming query"
            )
        elif not report["checkpoint_resumes"]:
            failures.append(
                f"recovery/{scenario}: faults fired but nothing "
                f"resumed from checkpoint ({report})"
            )
        if verbose:
            print(
                f"  chaos recovery/{scenario}: ok "
                f"completed={report['completed']} ok={report['ok']} "
                f"faults={report['mesh_faults_fired']} "
                f"resumes={report['checkpoint_resumes']} "
                f"drained={report['drained']} hung=0"
            )
    # replica scenarios (PR 17): the same live population against a
    # REPLICATED serving plane (two sub-meshes carved from the device
    # set) — one replica hard-killed mid-chunk, then (fresh harness)
    # gracefully drained with a query in flight. In-flight chunked
    # queries must fail over to the sibling sub-mesh and resume from
    # the host-portable checkpoint; zero queries lost either way.
    import jax

    if len(jax.devices()) < 2:
        if verbose:
            print(
                "  chaos replica/*: skipped (needs >= 2 devices to "
                "carve sub-meshes; run with "
                "--xla_force_host_platform_device_count)"
            )
    else:
        for scenario in REPLICA_CLASSES:
            h = ChaosHarness(
                n_workers=2, in_process=True,
                session=Session(
                    catalog="tpch", schema="tiny",
                    mesh_replicas=2,
                    mesh_chunk_rows=256,
                    mesh_checkpoint_interval_chunks=1,
                    mesh_resume_attempts=0,
                ),
            )
            h.register_catalog("tpch", create_tpch_connector())
            case = (
                h.run_replica_down_case
                if scenario == "replica_down_mid_serve"
                else h.run_replica_drain_case
            )
            try:
                _, report = case(queries, seed)
            except Exception as e:
                failures.append(
                    f"replica/{scenario}: raised {type(e).__name__}: {e}"
                )
                continue
            if report["ok"] == 0:
                failures.append(
                    f"replica/{scenario}: zero oracle-equal results "
                    f"({report})"
                )
            if report["mismatches"]:
                failures.append(
                    f"replica/{scenario}: {report['mismatches']} results "
                    f"diverged from clean run with a replica down"
                )
            if report["untyped_error_count"]:
                failures.append(
                    f"replica/{scenario}: {report['untyped_error_count']} "
                    f"untyped errors (first: {report['untyped_errors'][:1]})"
                )
            if report["hung_threads"]:
                failures.append(
                    f"replica/{scenario}: {report['hung_threads']} client "
                    f"threads never returned — a query was lost"
                )
            if scenario == "replica_down_mid_serve":
                if not report["mesh_faults_fired"]:
                    failures.append(
                        f"replica/{scenario}: the kill never landed on a "
                        f"mid-chunk boundary ({report})"
                    )
                elif not report["replica.failovers"]:
                    failures.append(
                        f"replica/{scenario}: replica 0 died but nothing "
                        f"failed over to the sibling ({report})"
                    )
            else:
                if not report["drain_requested"]:
                    failures.append(
                        f"replica/{scenario}: the drain never raced an "
                        f"in-flight chunked query ({report})"
                    )
                elif not report["replica_drained"]:
                    failures.append(
                        f"replica/{scenario}: replica 0 never quiesced "
                        f"to zero inflight ({report})"
                    )
                elif not report["replica.failovers"]:
                    failures.append(
                        f"replica/{scenario}: drained with a query in "
                        f"flight but nothing failed over ({report})"
                    )
            if verbose:
                print(
                    f"  chaos replica/{scenario}: ok "
                    f"completed={report['completed']} ok={report['ok']} "
                    f"failovers={report['replica.failovers']} "
                    f"resumes={report['checkpoint_resumes']} hung=0"
                )
    # preemptive multi-tenancy scenarios (PR 18): the chunk-granular
    # mesh scheduler's park/resume composed with checkpoint recovery
    # (device loss after a park) and with the replica drain lifecycle
    # (drain surfacing while parked -> sibling resumes the parked
    # snapshot). Same device gate as the replica scenarios.
    if len(jax.devices()) < 2:
        if verbose:
            print(
                "  chaos preempt/*: skipped (needs >= 2 devices; run "
                "with --xla_force_host_platform_device_count)"
            )
        return failures
    preempt_sql = recovery_sql
    for scenario in PREEMPT_CLASSES:
        case = (
            run_preempt_park_resume_case
            if scenario == "preempt_park_resume"
            else run_preempt_under_drain_case
        )
        # park_resume doubles as the lock-witness gate: the scheduler's
        # condition wait, the checkpoint store, and the fast-lane seat
        # all interleave here, so run it with order checking live and
        # require zero recorded violations.
        witness_case = scenario == "preempt_park_resume"
        if witness_case:
            from trino_tpu.analysis.witness import (
                enable_witness,
                violation_count,
                witness_enabled,
            )

            was_enabled = witness_enabled()
            violations_before = violation_count()
            enable_witness(True)
        try:
            rows, rep = case(preempt_sql, seed)
        except Exception as e:
            failures.append(
                f"preempt/{scenario}: raised {type(e).__name__}: {e}"
            )
            continue
        finally:
            if witness_case:
                enable_witness(was_enabled)
        if witness_case and violation_count() != violations_before:
            failures.append(
                f"preempt/{scenario}: "
                f"{violation_count() - violations_before} lock-witness "
                f"violation(s) recorded during the park/resume run"
            )
        if not rep["mesh_clean_plane"]:
            failures.append(
                f"preempt/{scenario}: clean run did not take the mesh "
                f"plane"
            )
            continue
        if not rows_equal(rows, rep["expected"], ordered=True):
            failures.append(
                f"preempt/{scenario}: rows diverged from clean run "
                f"({len(rows)} vs {len(rep['expected'])})"
            )
        if not rep["parked"]:
            failures.append(
                f"preempt/{scenario}: the fast-lane seat never parked "
                f"the analytic ({rep})"
            )
        if scenario == "preempt_park_resume":
            if not rep["faulted"]:
                failures.append(
                    f"preempt/{scenario}: the post-resume device loss "
                    f"never fired ({rep})"
                )
            elif rep["mesh_fault_plane"] != "mesh":
                failures.append(
                    f"preempt/{scenario}: faulted run left the mesh "
                    f"plane ({rep['mesh_fault_plane']})"
                )
            elif rep["parks"] != 1 or rep["unparks"] != 1:
                failures.append(
                    f"preempt/{scenario}: expected exactly one "
                    f"park/unpark cycle ({rep})"
                )
            elif not rep["resumes"]:
                failures.append(
                    f"preempt/{scenario}: no in-run checkpoint resume "
                    f"after the device loss ({rep})"
                )
            elif rep["executed_chunk_steps"] != rep["chunks"]:
                failures.append(
                    f"preempt/{scenario}: park+fault re-executed "
                    f"{rep['executed_chunk_steps'] - rep['chunks']} of "
                    f"{rep['chunks']} chunks"
                )
            if not rep["point_ok"]:
                failures.append(
                    f"preempt/{scenario}: the preempting point lookup "
                    f"answered wrong ({rep})"
                )
            if verbose and not any(
                f.startswith(f"preempt/{scenario}") for f in failures
            ):
                print(
                    f"  chaos preempt/{scenario}: ok rows={len(rows)} "
                    f"park_chunk={rep['park_chunk']} "
                    f"fault_chunk={rep['fault_chunk']}/{rep['chunks']} "
                    f"resumes={rep['resumes']} re_executed=0"
                )
        else:  # preempt_under_drain
            if not rep["drain_requested"]:
                failures.append(
                    f"preempt/{scenario}: the drain never landed while "
                    f"the query sat parked ({rep})"
                )
            elif not rep["failovers"]:
                failures.append(
                    f"preempt/{scenario}: drained while parked but "
                    f"nothing failed over to the sibling ({rep})"
                )
            elif not rep["checkpoint_resumes"]:
                failures.append(
                    f"preempt/{scenario}: sibling did not resume from "
                    f"the parked snapshot ({rep})"
                )
            elif rep["resumed_from_chunk"] != rep["park_chunk"]:
                failures.append(
                    f"preempt/{scenario}: sibling resumed from chunk "
                    f"{rep['resumed_from_chunk']}, expected the park "
                    f"boundary {rep['park_chunk']}"
                )
            elif rep["chunk_steps"] != rep["chunks"]:
                failures.append(
                    f"preempt/{scenario}: drain-while-parked "
                    f"re-executed "
                    f"{rep['chunk_steps'] - rep['chunks']} of "
                    f"{rep['chunks']} chunks"
                )
            if not rep["replica_drained"]:
                failures.append(
                    f"preempt/{scenario}: the victim replica never "
                    f"quiesced to zero inflight ({rep})"
                )
            if verbose and not any(
                f.startswith(f"preempt/{scenario}") for f in failures
            ):
                print(
                    f"  chaos preempt/{scenario}: ok rows={len(rows)} "
                    f"park_chunk={rep['park_chunk']}/{rep['chunks']} "
                    f"failovers={rep['failovers']} "
                    f"resumes={rep['checkpoint_resumes']} re_executed=0"
                )
    # multi-host fabric scenarios (PR 19): checkpoint transport +
    # membership under adversity. Same >= 2 device gate (replicated
    # sub-meshes) as above — reached only past the earlier early-return.
    fabric_sql = recovery_sql
    for scenario in FABRIC_CLASSES:
        case = {
            "host_lost_mid_chunk": run_host_lost_case,
            "membership_flap": run_membership_flap_case,
            "transport_corruption": run_transport_corruption_case,
        }[scenario]
        try:
            rows, rep = case(fabric_sql, seed)
        except Exception as e:
            failures.append(
                f"fabric/{scenario}: raised {type(e).__name__}: {e}"
            )
            continue
        if not rep["mesh_clean_plane"]:
            failures.append(
                f"fabric/{scenario}: clean run did not take the mesh plane"
            )
            continue
        if not rows_equal(rows, rep["expected"], ordered=True):
            failures.append(
                f"fabric/{scenario}: rows diverged from clean run "
                f"({len(rows)} vs {len(rep['expected'])})"
            )
        if not rep["fired"]:
            failures.append(
                f"fabric/{scenario}: fault never fired ({rep})"
            )
            continue
        K = rep["chunks"] or 0
        steps = rep["executed_chunk_steps"] or 0
        if scenario == "host_lost_mid_chunk":
            if not rep["pushes"]:
                failures.append(
                    f"fabric/{scenario}: nothing was ever pushed to the "
                    f"peer ({rep})"
                )
            elif not rep["pulls"]:
                failures.append(
                    f"fabric/{scenario}: local store wiped but failover "
                    f"never pulled from the peer ({rep})"
                )
            elif not rep["resumes"]:
                failures.append(
                    f"fabric/{scenario}: pulled a checkpoint but never "
                    f"resumed from it ({rep})"
                )
            elif steps != K - (rep["fault_chunk"] or 0):
                # the failover re-place runs a fresh attempt whose step
                # counter starts at the resume point: exactly the
                # not-yet-executed chunks remain
                failures.append(
                    f"fabric/{scenario}: re-executed "
                    f"{steps - (K - (rep['fault_chunk'] or 0))} chunk-steps "
                    f"after the fabric pull ({steps} steps for "
                    f"{K - (rep['fault_chunk'] or 0)} remaining chunks)"
                )
            if verbose and not any(
                f.startswith(f"fabric/{scenario}") for f in failures
            ):
                print(
                    f"  chaos fabric/{scenario}: ok rows={len(rows)} "
                    f"fault_chunk={rep['fault_chunk']}/{K} "
                    f"pushes={rep['pushes']} pulls={rep['pulls']} "
                    f"resumed_from={rep['resumed_from_chunk']} "
                    f"re_executed=0"
                )
        elif scenario == "membership_flap":
            if not rep["flapped"]:
                failures.append(
                    f"fabric/{scenario}: the flap never happened ({rep})"
                )
            elif rep["double_refused"] != 1:
                failures.append(
                    f"fabric/{scenario}: a second claim on an owned "
                    f"query was NOT refused — double placement across "
                    f"epochs ({rep})"
                )
            elif rep["epoch_delta"] < 2:
                failures.append(
                    f"fabric/{scenario}: membership epoch did not "
                    f"advance across the flap ({rep})"
                )
            elif rep["owners_at_end"] != 0:
                failures.append(
                    f"fabric/{scenario}: {rep['owners_at_end']} ownership "
                    f"claims leaked past query completion"
                )
            elif not rep["resumes"] and not rep["epoch_fences"]:
                failures.append(
                    f"fabric/{scenario}: neither a resume nor a typed "
                    f"epoch-fence restart happened after the flap ({rep})"
                )
            if verbose and not any(
                f.startswith(f"fabric/{scenario}") for f in failures
            ):
                print(
                    f"  chaos fabric/{scenario}: ok rows={len(rows)} "
                    f"fault_chunk={rep['fault_chunk']}/{K} "
                    f"epoch_delta={rep['epoch_delta']} "
                    f"double_refused=1 owners=0"
                )
        else:  # transport_corruption
            if not rep["digest_rejects"]:
                failures.append(
                    f"fabric/{scenario}: corrupted payload was never "
                    f"digest-rejected ({rep})"
                )
            elif rep["pulls"]:
                failures.append(
                    f"fabric/{scenario}: a corrupted payload was "
                    f"IMPORTED ({rep['pulls']} pulls landed)"
                )
            elif rep["truncated_import"] is not False:
                failures.append(
                    f"fabric/{scenario}: truncated payload import was "
                    f"not refused ({rep['truncated_import']!r})"
                )
            elif rep["resumes"]:
                failures.append(
                    f"fabric/{scenario}: resumed after a rejected "
                    f"transfer — restart expected ({rep})"
                )
            if verbose and not any(
                f.startswith(f"fabric/{scenario}") for f in failures
            ):
                print(
                    f"  chaos fabric/{scenario}: ok rows={len(rows)} "
                    f"fault_chunk={rep['fault_chunk']}/{K} "
                    f"digest_rejects={rep['digest_rejects']} "
                    f"pulls=0 clean_restart=True"
                )
    return failures
