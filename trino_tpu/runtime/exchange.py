"""Consumer-side exchange client: concurrent pullers over producer tasks.

Analogue of main/operator/DirectExchangeClient.java:57 +
HttpPageBufferClient.java:99 (SURVEY.md §3.4): one puller per producer
location long-polls pages with an advancing token (each request acks the
previous batch), feeding a memory-bounded shared queue the
RemoteSourceOperator drains. Backpressure: pullers pause while the local
queue is over budget (scheduleRequestIfNecessary's memory gate).
"""

from __future__ import annotations

import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Callable, List, Optional

from trino_tpu.exec.serde import Page
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.runtime.error_tracker import (
    REQUEST_STATS,
    RequestErrorTracker,
    RetryPolicy,
)

# fetch(partition, token, max_pages, wait) -> (pages, next_token, complete)
Fetch = Callable[[int, int, int, float], tuple]


class ExchangeLocation:
    """One producer task's result partition. `destination` labels the
    producer for error tracking (per-destination budgets/stats)."""

    def __init__(self, fetch: Fetch, partition: int,
                 destination: Optional[str] = None):
        self.fetch = fetch
        self.partition = partition
        self.destination = destination or f"exchange:{id(fetch):x}"


class DirectExchangeClient:
    """Pulls pages from every location into one queue. poll() never
    blocks; is_finished() is true once every location completed and the
    queue drained."""

    def __init__(
        self,
        locations: List[ExchangeLocation],
        max_buffered_pages: int = 64,
        long_poll_s: float = 0.5,
        retry_policy: Optional[RetryPolicy] = None,
        retry_seed: Optional[int] = None,
        failure_listener=None,
    ):
        self._locations = list(locations)
        self._retry_policy = retry_policy or RetryPolicy()
        self._retry_seed = retry_seed
        self._failure_listener = failure_listener
        self._queue: List[Page] = []
        self._lock = named_condition("DirectExchangeClient._lock")
        self._open = 0
        self._max_buffered = max_buffered_pages
        self._long_poll_s = long_poll_s
        self._failure: Optional[BaseException] = None
        self._closed = False
        self._threads: List[threading.Thread] = []
        for loc in self._locations:
            t = threadreg.spawn(
                f"exchange-pull-{loc.destination}", self._pull_loop, args=(loc,),
                owner="DirectExchangeClient", start=False,
            )
            self._open += 1
            self._threads.append(t)
        for t in self._threads:
            t.start()

    def _pull_loop(self, loc: ExchangeLocation) -> None:
        # Retrying here is safe because the token only advances on a
        # successful fetch: a replayed request re-reads un-acked pages,
        # so transient fetch loss never drops or duplicates a page. Once
        # the tracker's budget is spent, RequestFailedError surfaces via
        # poll() and the CONSUMING task fails (FTE re-places it).
        token = 0
        tracker = RequestErrorTracker(
            loc.destination, self._retry_policy, seed=self._retry_seed,
            listener=self._failure_listener,
        )
        try:
            while not self._closed:
                with self._lock:
                    while (
                        len(self._queue) >= self._max_buffered
                        and not self._closed
                    ):
                        self._lock.wait(timeout=0.1)
                    if self._closed:
                        return
                t_pull = time.monotonic()
                try:
                    pages, token, complete = loc.fetch(
                        loc.partition, token, 16, self._long_poll_s
                    )
                except BaseException as e:
                    REQUEST_STATS.record(loc.destination, ok=False)
                    tracker.on_failure(e)  # sleeps, or raises when spent
                    continue
                REQUEST_STATS.record(loc.destination, ok=True)
                tracker.on_success()
                if pages:
                    # data pulls only: an empty long-poll round measures
                    # the poll timeout, not exchange latency
                    METRICS.observe(
                        "exchange_page_pull_s", time.monotonic() - t_pull
                    )
                    with self._lock:
                        self._queue.extend(pages)
                        self._lock.notify_all()
                if complete:
                    return
        except BaseException as e:  # surfaced to the driver via poll()
            with self._lock:
                self._failure = e
        finally:
            with self._lock:
                self._open -= 1
                self._lock.notify_all()

    def poll(self) -> Optional[Page]:
        with self._lock:
            if self._failure is not None:
                raise RuntimeError("exchange pull failed") from self._failure
            if self._queue:
                page = self._queue.pop(0)
                self._lock.notify_all()
                return page
            return None

    def is_finished(self) -> bool:
        with self._lock:
            if self._failure is not None:
                raise RuntimeError("exchange pull failed") from self._failure
            return self._open == 0 and not self._queue

    def close(self) -> None:
        self._closed = True
        with self._lock:
            self._lock.notify_all()
