"""Memory accounting: pools, contexts, revocation.

Analogue of lib/trino-memory-context (LocalMemoryContext /
AggregatedMemoryContext), main/memory/ MemoryPool and the revocable-
memory protocol (Operator.startMemoryRevoke, Operator.java:60–81;
MemoryRevokingScheduler, main/execution/MemoryRevokingScheduler.java —
SURVEY.md §5.4). TPU mapping: "user memory" tracks HBM-resident batch
state (group tables, build sides, sort buffers); revoking moves state to
host/disk through the spiller, the HBM->DRAM/SSD eviction path.

Simplifications kept honest: reservation is synchronous (reserve either
fits, triggers revocation, or raises ExceededMemoryLimitError — the
blocked-future form arrives with async drivers).

PR2 adds the cluster dimension (ClusterMemoryManager.java +
LowMemoryKiller, SURVEY.md §5.4): pools keep a per-query reservation
ledger; on exhaustion — AFTER revocation/spill failed to make room — an
installed exhaustion handler may kill the single query with the largest
cluster-wide reservation (doomed queries fail their next reservation
with the kill message) so one runaway query dies with a query-level
ExceededMemoryLimitError instead of the worker failing everyone."""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Callable, Dict, List, Optional


class ExceededMemoryLimitError(RuntimeError):
    pass


class MemoryPool:
    """A byte budget shared by all operators of a query/worker
    (main/memory/MemoryPool.java). Revocation targets registered
    revocable contexts largest-first until the reservation fits."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._reserved = 0
        self._lock = named_lock("MemoryPool._lock")
        # context id -> (revocable bytes, revoke callback)
        self._revocable: Dict[int, tuple] = {}
        self._next_id = 0
        # per-query ledger (query_id -> bytes) for the low-memory killer
        self._by_query: Dict[str, int] = {}
        # per-query high-water mark (never decremented; survives context
        # close so the final QueryInfo can report peak memory)
        self._query_peak: Dict[str, int] = {}
        # query_id -> kill message; doomed queries fail reservations
        self._doomed: Dict[str, str] = {}
        # ClusterMemoryManager hook: handler(pool, bytes_, query_id) ->
        # bool (True = a kill was issued, retry the reservation)
        self.exhaustion_handler = None

    @property
    def reserved_bytes(self) -> int:
        return self._reserved

    def free_bytes(self) -> int:
        return self.max_bytes - self._reserved

    def query_reservations(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._by_query)

    def query_reserved_bytes(self, query_id: str) -> int:
        """One query's live reservation in this pool (0 once every task
        memory context closed). The abandonment reaper's ledger check —
        a reaped query must drain to zero here, or its bytes poison the
        shared pool for every later query."""
        with self._lock:
            return self._by_query.get(query_id, 0)

    def doom_query(self, query_id: str, message: str) -> None:
        """Mark a query dead-on-next-reservation: its operator threads
        unwind with ExceededMemoryLimitError(message) at their next
        set_bytes, freeing their reservations on context close."""
        with self._lock:
            self._doomed[query_id] = message

    def _check_doomed(self, query_id: Optional[str]) -> None:
        if query_id is None:
            return
        with self._lock:
            msg = self._doomed.get(query_id)
        if msg is not None:
            raise ExceededMemoryLimitError(msg)

    def try_reserve(self, bytes_: int, query_id: Optional[str] = None) -> bool:
        with self._lock:
            if self._reserved + bytes_ > self.max_bytes:
                return False
            self._reserved += bytes_
            if query_id is not None:
                now = self._by_query.get(query_id, 0) + bytes_
                self._by_query[query_id] = now
                if now > self._query_peak.get(query_id, 0):
                    self._query_peak[query_id] = now
            return True

    def query_peak_bytes(self, query_id: str) -> int:
        """High-water mark of one query's reservation in this pool
        (retained after the query drains; pruned by drop_query_peak so
        the dict stays bounded across a long-lived worker)."""
        with self._lock:
            return self._query_peak.get(query_id, 0)

    def query_peaks(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._query_peak)

    def drop_query_peak(self, query_id: str) -> int:
        """Retire a completed query's watermark, returning it."""
        with self._lock:
            return self._query_peak.pop(query_id, 0)

    def reserve(self, bytes_: int, for_ctx: Optional[int] = None,
                query_id: Optional[str] = None) -> None:
        """Reserve, revoking others' revocable memory if needed
        (MemoryRevokingScheduler's revoke-largest-first policy). A victim
        whose callback does not actually lower its registered revocable
        bytes is skipped on later rounds — re-picking it would spin
        forever (a revoke can legitimately no-op, e.g. an operator whose
        state just became non-spillable)."""
        self._check_doomed(query_id)
        if self.try_reserve(bytes_, query_id):
            return
        # revoke largest revocable contexts until it fits
        unhelpful: set = set()
        while True:
            with self._lock:
                candidates = [
                    (cid, rb, cb)
                    for cid, (rb, cb) in self._revocable.items()
                    if rb > 0 and cid != for_ctx and cid not in unhelpful
                ]
            if not candidates:
                break
            cid, rb, cb = max(candidates, key=lambda t: t[1])
            cb()  # operator spills and releases its revocable bytes
            if self.try_reserve(bytes_, query_id):
                return
            with self._lock:
                rb_after = self._revocable.get(cid, (0, None))[0]
            if rb_after >= rb:
                unhelpful.add(cid)
        if self.try_reserve(bytes_, query_id):
            return
        # revocation could not make room: escalate to the cluster
        # manager (kill-largest), which may doom THIS query
        handler = self.exhaustion_handler
        if handler is not None and handler(self, bytes_, query_id):
            self._check_doomed(query_id)
            if self.try_reserve(bytes_, query_id):
                return
        raise ExceededMemoryLimitError(
            f"cannot reserve {bytes_} bytes "
            f"(reserved {self._reserved}/{self.max_bytes})"
        )

    def free(self, bytes_: int, query_id: Optional[str] = None) -> None:
        with self._lock:
            self._reserved -= bytes_
            assert self._reserved >= 0, "double free in memory pool"
            if query_id is not None:
                left = self._by_query.get(query_id, 0) - bytes_
                if left > 0:
                    self._by_query[query_id] = left
                else:
                    self._by_query.pop(query_id, None)

    # -- revocable registry --
    def register_revocable(self, revoke: Callable[[], None]) -> int:
        with self._lock:
            cid = self._next_id
            self._next_id += 1
            self._revocable[cid] = (0, revoke)
            return cid

    def set_revocable(self, cid: int, bytes_: int) -> None:
        with self._lock:
            _, cb = self._revocable[cid]
            self._revocable[cid] = (bytes_, cb)

    def unregister_revocable(self, cid: int) -> None:
        with self._lock:
            self._revocable.pop(cid, None)


class MemoryContext:
    """Per-operator accounting handle (LocalMemoryContext analogue):
    setBytes semantics — the operator reports its current footprint and
    the delta hits the pool."""

    def __init__(self, pool: MemoryPool, revoke: Optional[Callable[[], None]] = None,
                 query_id: Optional[str] = None):
        self.pool = pool
        self.query_id = query_id
        self._bytes = 0
        self._revocable_bytes = 0
        self._cid = (
            pool.register_revocable(revoke) if revoke is not None else None
        )

    def set_revoker(self, revoke: Callable[[], None]) -> None:
        """Late-bind the revoke callback (operators register themselves
        after construction — Operator.startMemoryRevoke wiring)."""
        assert self._cid is None, "revoker already set"
        self._cid = self.pool.register_revocable(revoke)

    @property
    def reserved_bytes(self) -> int:
        return self._bytes

    def set_bytes(self, bytes_: int) -> None:
        delta = bytes_ - self._bytes
        if delta > 0:
            self.pool.reserve(delta, for_ctx=self._cid, query_id=self.query_id)
        elif delta < 0:
            self.pool.free(-delta, query_id=self.query_id)
        self._bytes = bytes_

    def set_revocable_bytes(self, bytes_: int) -> None:
        """The portion of this context's footprint a revoke() can free
        (spillable state)."""
        assert self._cid is not None, "context registered without revoke"
        self._revocable_bytes = bytes_
        self.pool.set_revocable(self._cid, bytes_)

    def close(self) -> None:
        self.set_bytes(0)
        if self._cid is not None:
            self.pool.unregister_revocable(self._cid)


class LowMemoryKiller:
    """Victim-selection policy under cluster memory exhaustion: kill the
    query with the LARGEST total reservation across all pools (the
    reference's TotalReservationLowMemoryKiller — predictable, and the
    biggest query is the one whose death frees the most room). Ties
    break on query id for determinism."""

    def pick_victim(self, totals: Dict[str, int]) -> Optional[str]:
        if not totals:
            return None
        return max(totals.items(), key=lambda kv: (kv[1], kv[0]))[0]


class ClusterMemoryManager:
    """Coordinator-side memory arbiter (ClusterMemoryManager.java:103).

    Installed as the exhaustion_handler on every worker pool. When a
    reservation still cannot fit after revocation/spill, it aggregates
    the per-query ledgers across pools, picks ONE victim via the
    LowMemoryKiller, dooms it in every pool (so all its operator threads
    unwind with the kill message), tells the coordinator to fail the
    query, then waits a bounded time for the victim's frees before the
    requester retries. Only the victim dies; every other query — and the
    worker itself — keeps running."""

    def __init__(self, pools: List[MemoryPool], fail_query=None,
                 killer: Optional[LowMemoryKiller] = None,
                 wait_s: float = 5.0, poll_s: float = 0.01):
        self.pools = list(pools)
        self._fail_query = fail_query  # fail_query(query_id, message)
        self.killer = killer or LowMemoryKiller()
        self.wait_s = wait_s
        self.poll_s = poll_s
        self._lock = named_lock("ClusterMemoryManager._lock")
        self.kills: List[str] = []  # observability / chaos assertions

    def install(self) -> None:
        for p in self.pools:
            p.exhaustion_handler = self._on_exhaustion

    def cluster_reservations(self) -> Dict[str, int]:
        totals: Dict[str, int] = {}
        for p in self.pools:
            for q, b in p.query_reservations().items():
                totals[q] = totals.get(q, 0) + b
        return totals

    def _on_exhaustion(self, pool: MemoryPool, bytes_: int,
                       query_id: Optional[str]) -> bool:
        with self._lock:  # one kill decision at a time
            totals = self.cluster_reservations()
            victim = self.killer.pick_victim(totals)
            if victim is None:
                return False
            message = (
                f"Query {victim} killed by the low-memory killer: cluster "
                f"out of memory (victim held {totals[victim]} bytes, "
                f"request was {bytes_} bytes)"
            )
            for p in self.pools:
                p.doom_query(victim, message)
            self.kills.append(victim)
            if self._fail_query is not None:
                try:
                    self._fail_query(victim, message)
                except Exception:
                    pass  # the doom marks still unwind the victim
        if victim == query_id:
            return True  # requester IS the victim: retry raises the kill
        deadline = time.monotonic() + self.wait_s
        while time.monotonic() < deadline:
            if pool.free_bytes() >= bytes_:
                break
            time.sleep(self.poll_s)
        return True


def batch_bytes(batch) -> int:
    """Device footprint of a RelBatch (capacity x dtype widths +
    masks)."""
    n = batch.capacity
    total = n  # live mask (bool)
    for c in batch.columns:
        total += n * c.data.dtype.itemsize
        if c.valid is not None:
            total += n
    return total
