"""Memory accounting: pools, contexts, revocation.

Analogue of lib/trino-memory-context (LocalMemoryContext /
AggregatedMemoryContext), main/memory/ MemoryPool and the revocable-
memory protocol (Operator.startMemoryRevoke, Operator.java:60–81;
MemoryRevokingScheduler, main/execution/MemoryRevokingScheduler.java —
SURVEY.md §5.4). TPU mapping: "user memory" tracks HBM-resident batch
state (group tables, build sides, sort buffers); revoking moves state to
host/disk through the spiller, the HBM->DRAM/SSD eviction path.

Simplifications kept honest: reservation is synchronous (reserve either
fits, triggers revocation, or raises ExceededMemoryLimitError — the
blocked-future form arrives with async drivers)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional


class ExceededMemoryLimitError(RuntimeError):
    pass


class MemoryPool:
    """A byte budget shared by all operators of a query/worker
    (main/memory/MemoryPool.java). Revocation targets registered
    revocable contexts largest-first until the reservation fits."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._reserved = 0
        self._lock = threading.Lock()
        # context id -> (revocable bytes, revoke callback)
        self._revocable: Dict[int, tuple] = {}
        self._next_id = 0

    @property
    def reserved_bytes(self) -> int:
        return self._reserved

    def free_bytes(self) -> int:
        return self.max_bytes - self._reserved

    def try_reserve(self, bytes_: int) -> bool:
        with self._lock:
            if self._reserved + bytes_ > self.max_bytes:
                return False
            self._reserved += bytes_
            return True

    def reserve(self, bytes_: int, for_ctx: Optional[int] = None) -> None:
        """Reserve, revoking others' revocable memory if needed
        (MemoryRevokingScheduler's revoke-largest-first policy). A victim
        whose callback does not actually lower its registered revocable
        bytes is skipped on later rounds — re-picking it would spin
        forever (a revoke can legitimately no-op, e.g. an operator whose
        state just became non-spillable)."""
        if self.try_reserve(bytes_):
            return
        # revoke largest revocable contexts until it fits
        unhelpful: set = set()
        while True:
            with self._lock:
                candidates = [
                    (cid, rb, cb)
                    for cid, (rb, cb) in self._revocable.items()
                    if rb > 0 and cid != for_ctx and cid not in unhelpful
                ]
            if not candidates:
                break
            cid, rb, cb = max(candidates, key=lambda t: t[1])
            cb()  # operator spills and releases its revocable bytes
            if self.try_reserve(bytes_):
                return
            with self._lock:
                rb_after = self._revocable.get(cid, (0, None))[0]
            if rb_after >= rb:
                unhelpful.add(cid)
        if self.try_reserve(bytes_):
            return
        raise ExceededMemoryLimitError(
            f"cannot reserve {bytes_} bytes "
            f"(reserved {self._reserved}/{self.max_bytes})"
        )

    def free(self, bytes_: int) -> None:
        with self._lock:
            self._reserved -= bytes_
            assert self._reserved >= 0, "double free in memory pool"

    # -- revocable registry --
    def register_revocable(self, revoke: Callable[[], None]) -> int:
        with self._lock:
            cid = self._next_id
            self._next_id += 1
            self._revocable[cid] = (0, revoke)
            return cid

    def set_revocable(self, cid: int, bytes_: int) -> None:
        with self._lock:
            _, cb = self._revocable[cid]
            self._revocable[cid] = (bytes_, cb)

    def unregister_revocable(self, cid: int) -> None:
        with self._lock:
            self._revocable.pop(cid, None)


class MemoryContext:
    """Per-operator accounting handle (LocalMemoryContext analogue):
    setBytes semantics — the operator reports its current footprint and
    the delta hits the pool."""

    def __init__(self, pool: MemoryPool, revoke: Optional[Callable[[], None]] = None):
        self.pool = pool
        self._bytes = 0
        self._revocable_bytes = 0
        self._cid = (
            pool.register_revocable(revoke) if revoke is not None else None
        )

    def set_revoker(self, revoke: Callable[[], None]) -> None:
        """Late-bind the revoke callback (operators register themselves
        after construction — Operator.startMemoryRevoke wiring)."""
        assert self._cid is None, "revoker already set"
        self._cid = self.pool.register_revocable(revoke)

    @property
    def reserved_bytes(self) -> int:
        return self._bytes

    def set_bytes(self, bytes_: int) -> None:
        delta = bytes_ - self._bytes
        if delta > 0:
            self.pool.reserve(delta, for_ctx=self._cid)
        elif delta < 0:
            self.pool.free(-delta)
        self._bytes = bytes_

    def set_revocable_bytes(self, bytes_: int) -> None:
        """The portion of this context's footprint a revoke() can free
        (spillable state)."""
        assert self._cid is not None, "context registered without revoke"
        self._revocable_bytes = bytes_
        self.pool.set_revocable(self._cid, bytes_)

    def close(self) -> None:
        self.set_bytes(0)
        if self._cid is not None:
            self.pool.unregister_revocable(self._cid)


def batch_bytes(batch) -> int:
    """Device footprint of a RelBatch (capacity x dtype widths +
    masks)."""
    n = batch.capacity
    total = n  # live mask (bool)
    for c in batch.columns:
        total += n * c.data.dtype.itemsize
        if c.valid is not None:
            total += n
    return total
