"""Failure injection for fault-tolerance tests.

Analogue of main/execution/FailureInjector.java:40 (injected per
(stage, partition, attempt); types incl. TASK_FAILURE and request
failures — SURVEY.md §5.3, BaseFailureRecoveryTest.java:53). The
injector lives on the Worker; TaskExecution consults it at task start
("start"), after the first output page ("mid"), and per exchange page
pull ("fetch") so retries exercise the nothing-produced, partially-
produced, and lost-fetch paths. Rules carry a failure KIND so the chaos
harness (runtime/chaos.py) can map fault classes onto the error surface
each one exercises: a crash is a generic task failure, fetch loss is a
transient network error the retry layer absorbs, an OOM is a memory-
classed failure that grows the partition memory estimate on retry.
"""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, List, Optional, Tuple


class InjectedFailure(RuntimeError):
    pass


@dataclasses.dataclass
class FailureRule:
    fragment_id: Optional[int] = None  # None = any
    partition: Optional[int] = None
    attempts: Tuple[int, ...] = (0,)  # which attempt numbers fail
    # "start" | "mid" | "fetch" | "batch" — "batch" fires at a driver
    # batch boundary (TaskExecution._on_batch), where a stall models a
    # HUNG OPERATOR the stuck-task watchdog must interrupt
    where: str = "start"
    max_hits: int = 1_000_000
    # straggler simulation: sleep this long instead of raising
    # (drives the speculative-execution path in tests)
    stall_s: float = 0.0
    # failure surface: "crash" raises InjectedFailure (task failure),
    # "fetch_loss" raises ConnectionError (transient, absorbed by the
    # exchange retry loop), "oom" raises ExceededMemoryLimitError
    # (memory-classed: the FTE estimator doubles before the retry)
    kind: str = "crash"

    def raise_failure(self, task_id, where: str) -> None:
        if self.kind == "fetch_loss":
            raise ConnectionError(
                f"injected fetch loss at {task_id}"
            )
        if self.kind == "oom":
            from trino_tpu.runtime.memory import ExceededMemoryLimitError

            raise ExceededMemoryLimitError(
                f"injected out-of-memory at {task_id}"
            )
        raise InjectedFailure(f"injected {where} failure at {task_id}")


class FailureInjector:
    def __init__(self):
        self._rules: List[FailureRule] = []
        self._hits: Dict[int, int] = {}
        self._lock = named_lock("FailureInjector._lock")

    def inject(self, **kw) -> FailureRule:
        rule = FailureRule(**kw)
        with self._lock:
            self._rules.append(rule)
        return rule

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
            self._hits.clear()

    def check(self, task_id, where: str, abort=None) -> None:
        """Raise InjectedFailure if a rule matches (task_id carries
        fragment/partition/attempt). A matching STALL sleeps in small
        chunks polling `abort` (zero-arg callable): a stalled task the
        watchdog already failed wakes promptly instead of pinning its
        thread for the full stall."""
        with self._lock:
            for i, r in enumerate(self._rules):
                if r.where != where:
                    continue
                if r.fragment_id is not None and r.fragment_id != task_id.fragment_id:
                    continue
                if r.partition is not None and r.partition != task_id.partition:
                    continue
                if getattr(task_id, "attempt", 0) not in r.attempts:
                    continue
                if self._hits.get(i, 0) >= r.max_hits:
                    continue
                self._hits[i] = self._hits.get(i, 0) + 1
                if r.stall_s > 0:
                    stall = r.stall_s
                    break  # sleep outside the lock
                r.raise_failure(task_id, where)
            else:
                return
        import time

        deadline = time.monotonic() + stall
        while True:
            if abort is not None and abort():
                return
            left = deadline - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(0.01, left))
