"""Listener-driven state machine.

Analogue of io.airlift-style StateMachine (main/execution/
StateMachine.java:44 — SURVEY.md §2.3): a thread-safe typed state
holder with terminal-state latching, change listeners fired OUTSIDE the
lock (the reference dispatches on an executor for the same reason:
a listener calling back into the machine must not deadlock), and
`wait_for` used by pollers instead of busy loops.

Query/task lifecycles (runtime/task.py, runtime/server.py) hold one of
these; the event-listener surface (runtime/events.py) subscribes query
transitions through it.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Callable, List, Optional, Sequence


class StateMachine:
    def __init__(
        self,
        name: str,
        initial: str,
        terminal_states: Sequence[str] = (),
    ):
        self.name = name
        self._state = initial
        self._terminal = frozenset(terminal_states)
        self._listeners: List[Callable[[str], None]] = []
        self._lock = named_lock("StateMachine._lock")
        self._changed = threading.Condition(self._lock)
        # serializes listener delivery so states arrive in transition
        # order; reentrant because a listener may transition the machine
        # from inside its callback
        self._dispatch = named_rlock("StateMachine._dispatch")

    def get(self) -> str:
        with self._lock:
            return self._state

    def is_terminal(self, state: Optional[str] = None) -> bool:
        s = self.get() if state is None else state
        return s in self._terminal

    def add_listener(self, fn: Callable[[str], None]) -> None:
        """fn(new_state) on every transition; fires immediately with the
        current state (fireOnceStateChangeListener semantics: a listener
        added after a transition still observes it). The dispatch lock
        spans the append + initial fire so a concurrent set() cannot
        deliver a NEWER state before the initial one (stale-last-state
        would wedge consumers waiting on a terminal state)."""
        with self._dispatch:
            with self._lock:
                self._listeners.append(fn)
                current = self._state
            fn(current)

    def set(self, new_state: str) -> bool:
        """Unconditional transition; returns False if already terminal
        (terminal states latch, StateMachine.setIf contract). The
        dispatch lock is held ACROSS transition + delivery so two
        concurrent set() calls cannot deliver their states to listeners
        out of transition order."""
        with self._dispatch:
            with self._lock:
                if self._state in self._terminal or new_state == self._state:
                    return False
                self._state = new_state
                listeners = list(self._listeners)
                self._changed.notify_all()
            for fn in listeners:
                fn(new_state)
        return True

    def compare_and_set(self, expected: str, new_state: str) -> bool:
        with self._dispatch:
            with self._lock:
                if self._state != expected or self._state in self._terminal:
                    return False
                self._state = new_state
                listeners = list(self._listeners)
                self._changed.notify_all()
            for fn in listeners:
                fn(new_state)
        return True

    def wait_for(
        self, predicate: Callable[[str], bool], timeout: Optional[float] = None
    ) -> str:
        """Block until predicate(state) or timeout; returns the state
        observed (StateMachine.waitForStateChange)."""
        with self._lock:
            if timeout is None:
                while not predicate(self._state):
                    self._changed.wait()
            else:
                deadline = time.monotonic() + timeout
                while not predicate(self._state):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._changed.wait(remaining):
                        break
            return self._state


# canonical lifecycles (QueryState / TaskState enums)
QUERY_STATES = (
    "queued", "planning", "running", "finishing", "finished", "failed",
)
QUERY_TERMINAL = ("finished", "failed")
TASK_STATES = ("planned", "running", "finished", "failed", "aborted")
TASK_TERMINAL = ("finished", "failed", "aborted")


def query_state_machine(query_id: str) -> StateMachine:
    return StateMachine(query_id, "queued", QUERY_TERMINAL)


def task_state_machine(task_id: str) -> StateMachine:
    return StateMachine(task_id, "planned", TASK_TERMINAL)
