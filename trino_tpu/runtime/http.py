"""HTTP task protocol: worker server + coordinator-side remote client.

Analogue of the reference's internal communication (SURVEY.md §5.8):
control plane = task create/status/delete (main/server/TaskResource.java:92,
HttpRemoteTask §3.2), data plane = pull-based binary page streams with
token/ack semantics (GET /v1/task/{id}/results/{partition}/{token},
TaskResource.java:321). JSON for control, the serde wire format for
pages (a typed binary layout — no object deserialization on wire
bytes). Task specs travel as typed, allowlist-decoded JSON
(runtime/codec.py — the TaskUpdateRequest Jackson-codec analogue; a
request body can only instantiate registered plan/task dataclasses,
never arbitrary objects). Internal authentication additionally gates
EVERY endpoint (TRINO_TPU_INTERNAL_SECRET;
InternalAuthenticationManager analogue), and a NETWORKED worker
refuses to start without a secret — require_secret=False is for
single-process embedding and tests only.

Endpoints served by WorkerServer:
  POST   /v1/task/{taskId}                     create/update task
  GET    /v1/task/{taskId}/status              task state JSON
  GET    /v1/task/{taskId}/results/{p}/{tok}   pull pages (long-poll)
  DELETE /v1/task/{taskId}                     abort + remove
  DELETE /v1/query/{queryId}?reason=...        fail every task of a query
                                               (low-memory killer /
                                               speculation-loser kill)
  GET    /v1/status                            worker heartbeat/info
  PUT    /v1/shutdown                          graceful shutdown (drain)
  PUT    /v1/info/state                        body "SHUTTING_DOWN" ->
                                               drain (reference API)

A draining worker answers task creation with 409 — deliberately NOT a
retryable status (503 would spin the RequestErrorTracker loop for the
full error budget): the refusal is permanent, the scheduler must
re-place the task elsewhere immediately.
"""

from __future__ import annotations

import json
import struct
import threading
from trino_tpu.analysis import threadreg
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from trino_tpu.exec.serde import Page, deserialize_page, serialize_page
from trino_tpu.runtime import codec
from trino_tpu.runtime.worker import Worker, WorkerShuttingDownError

_U32 = struct.Struct("<I")


def default_internal_secret() -> Optional[str]:
    """Cluster-wide shared secret for engine-internal HTTP, from the
    environment (the config.properties internal-communication.shared-secret
    analogue). None disables internal auth (single-process embedding)."""
    import os

    return os.environ.get("TRINO_TPU_INTERNAL_SECRET") or None


def pack_pages(pages: List[Page]) -> bytes:
    out = [_U32.pack(len(pages))]
    for p in pages:
        body = serialize_page(p)
        out.append(_U32.pack(len(body)))
        out.append(body)
    return b"".join(out)


def unpack_pages(data: bytes) -> List[Page]:
    (n,) = _U32.unpack_from(data, 0)
    off = _U32.size
    pages = []
    for _ in range(n):
        (ln,) = _U32.unpack_from(data, off)
        off += _U32.size
        pages.append(deserialize_page(data[off : off + ln]))
        off += ln
    return pages


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    worker: Worker = None  # set by server factory
    server_ref = None

    def log_message(self, *args):  # quiet
        pass

    def _json(self, code: int, obj) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _bytes(self, code: int, body: bytes, headers=()) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _authorized(self) -> bool:
        """Internal-comms gate (InternalAuthenticationManager analogue):
        when the server carries a shared secret, every request must
        present a valid X-Trino-Internal-Bearer."""
        auth = self.server_ref.internal_auth
        if auth is None:
            return True
        from trino_tpu.security import AuthenticationError

        try:
            auth.verify(self.headers)
            return True
        except AuthenticationError as ex:
            ln = int(self.headers.get("Content-Length", "0") or 0)
            if ln:
                self.rfile.read(ln)
            self._json(401, {"error": f"Unauthorized: {ex}"})
            return False

    # -- routes --
    def do_GET(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        try:
            if parts[:2] == ["v1", "status"]:
                # the worker's own status() carries lifecycle state +
                # running-task count — the drain waiter reads both
                self._json(200, self.worker.status())
                return
            if parts[:2] == ["v1", "task"] and len(parts) >= 4:
                task_id = parts[2]
                if parts[3] == "status":
                    self._json(200, self.worker.task_state(task_id))
                    return
                if parts[3] == "results" and len(parts) == 6:
                    partition, token = int(parts[4]), int(parts[5])
                    wait = 0.0
                    if "?" in self.path and "wait=" in self.path:
                        wait = float(self.path.split("wait=")[1].split("&")[0])
                    pages, next_token, complete = self.worker.get_results(
                        task_id, partition, token, wait=wait
                    )
                    self._bytes(
                        200,
                        pack_pages(pages),
                        [
                            ("X-Next-Token", str(next_token)),
                            ("X-Complete", "1" if complete else "0"),
                        ],
                    )
                    return
            self._json(404, {"error": f"no route {self.path}"})
        except KeyError:
            self._json(404, {"error": f"unknown task {self.path}"})
        except Exception as e:  # engine-internal; report upstream
            self._json(500, {"error": repr(e)})

    def do_POST(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("/") if p]
        try:
            if parts[:2] == ["v1", "task"] and len(parts) == 3:
                if self.worker.state != "active":
                    # 409, not 503: a drain refusal is permanent for
                    # this worker — the client must re-place, not retry
                    self._json(409, {"error": "worker shutting down"})
                    return
                ln = int(self.headers.get("Content-Length", "0"))
                spec = codec.loads(self.rfile.read(ln))
                task = self.worker.create_task(spec)
                self._json(200, {"task_id": str(task.spec.task_id), "state": task.state})
                return
            self._json(404, {"error": f"no route {self.path}"})
        except WorkerShuttingDownError as e:
            self._json(409, {"error": str(e)})
        except Exception as e:
            self._json(500, {"error": repr(e)})

    def do_DELETE(self):
        if not self._authorized():
            return
        path, _, query = self.path.partition("?")
        parts = [p for p in path.split("/") if p]
        try:
            if parts[:2] == ["v1", "task"] and len(parts) == 3:
                self.worker.remove_task(parts[2])
                self._json(200, {})
                return
            if parts[:2] == ["v1", "query"] and len(parts) == 3:
                # kill every task of a query with a reason (the
                # low-memory killer / speculation-loser cancel path on
                # HTTP topologies — Worker.fail_query over the wire)
                import urllib.parse as _up

                reason = _up.parse_qs(query).get("reason", [""])[0] or (
                    "Query killed via DELETE /v1/query"
                )
                self.worker.fail_query(parts[2], reason)
                self._json(200, {})
                return
            self._json(404, {"error": f"no route {self.path}"})
        except Exception as e:
            self._json(500, {"error": repr(e)})

    def do_PUT(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("/") if p]
        if parts[:2] == ["v1", "shutdown"]:
            # graceful shutdown (GracefulShutdownHandler.java:43): stop
            # accepting tasks; running tasks drain
            self.worker.shutdown_gracefully()
            self._json(200, {"state": "shutting_down"})
            return
        if parts[:3] == ["v1", "info", "state"]:
            # the reference's worker-state API: PUT /v1/info/state with
            # body "SHUTTING_DOWN" (JSON string) starts the drain
            ln = int(self.headers.get("Content-Length", "0") or 0)
            body = self.rfile.read(ln).decode("utf-8", "replace").strip()
            want = body.strip('"').upper()
            if want != "SHUTTING_DOWN":
                self._json(
                    400,
                    {"error": f"unsupported state {body!r}: only "
                              "SHUTTING_DOWN may be requested"},
                )
                return
            self.worker.shutdown_gracefully()
            self._json(200, {"state": "shutting_down"})
            return
        self._json(404, {"error": f"no route {self.path}"})


class WorkerServer:
    """HTTP front of one Worker (TrinoServer worker bootstrap analogue).
    `internal_secret` turns on shared-secret authentication of every
    endpoint (InternalAuthenticationManager analogue)."""

    def __init__(self, worker: Worker, port: int = 0,
                 internal_secret: Optional[str] = "__env__",
                 require_secret: bool = True):
        self.worker = worker
        self.internal_auth = None
        if internal_secret == "__env__":
            internal_secret = default_internal_secret()
        if internal_secret is None and require_secret:
            # a worker port without auth accepts task specs from anyone
            # who can reach it; default-config deployments must not be
            # open. Single-process embeddings/tests opt out explicitly.
            raise RuntimeError(
                "refusing to start a networked worker without an internal "
                "secret: set TRINO_TPU_INTERNAL_SECRET (or pass "
                "internal_secret=...), or pass require_secret=False for "
                "single-process embedding"
            )
        if internal_secret is not None:
            from trino_tpu.security import InternalAuthenticator

            self.internal_auth = InternalAuthenticator(internal_secret)
        handler = type("BoundHandler", (_Handler,), {"worker": worker, "server_ref": self})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_port
        self.uri = f"http://127.0.0.1:{self.port}"
        self._thread = threadreg.spawn(
            f"worker-http-{self.port}", self._httpd.serve_forever,
            owner="WorkerServer",
        )

    @property
    def state(self) -> str:
        """Lifecycle lives on the Worker (single source of truth shared
        by the in-process and HTTP surfaces)."""
        return self.worker.state

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class HttpWorkerClient:
    """Coordinator-side proxy for a remote worker (HttpRemoteTask +
    ContinuousTaskStatusFetcher collapsed into synchronous calls).

    Every call runs a RequestErrorTracker retry loop
    (runtime/error_tracker.py): transient failures back off with jitter
    until the per-destination error budget or hard deadline is spent,
    then the call raises RequestFailedError — the caller fails the TASK
    (FTE re-places it), never the query. The tracker is safe here
    because every endpoint is idempotent: create_task re-delivers by
    task id, results are pulled with an advancing ack token, and DELETE
    is a no-op on a missing task. `failure_listener` (e.g. a
    NodeManager) hears every success/failure for circuit-breaker
    accounting."""

    def __init__(self, uri: str, timeout: float = 30.0,
                 internal_secret: Optional[str] = "__env__",
                 retry_policy=None, failure_listener=None):
        self.uri = uri.rstrip("/")
        self.timeout = timeout
        self.worker_id = uri
        # None = "not explicitly chosen": the coordinator may bind the
        # session's request_max_error_duration_s onto it at registration
        self.retry_policy = retry_policy
        self.failure_listener = failure_listener
        self._auth = None
        if internal_secret == "__env__":
            internal_secret = default_internal_secret()
        if internal_secret is not None:
            from trino_tpu.security import InternalAuthenticator

            self._auth = InternalAuthenticator(internal_secret)

    def _req(self, method: str, path: str, body: Optional[bytes] = None):
        headers = {}
        if self._auth is not None:
            headers[self._auth.HEADER] = self._auth.token()
        req = urllib.request.Request(
            self.uri + path, data=body, method=method, headers=headers
        )
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _retrying(self, fn):
        from trino_tpu.runtime.error_tracker import (
            RetryPolicy,
            run_with_retry,
        )

        return run_with_retry(
            self.uri, fn, policy=self.retry_policy or RetryPolicy(),
            listener=self.failure_listener,
        )

    def create_task(self, spec) -> str:
        body = codec.dumps(spec)

        def go():
            try:
                with self._req("POST", f"/v1/task/{spec.task_id}", body) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    # drain refusal: permanent for this worker, typed so
                    # the scheduler re-places instead of retrying
                    raise WorkerShuttingDownError(
                        f"worker {self.uri} is shutting down"
                    ) from e
                raise

        out = self._retrying(go)
        if "error" in out:
            raise RuntimeError(out["error"])
        return out["task_id"]

    def task_state(self, task_id) -> dict:
        def go():
            with self._req("GET", f"/v1/task/{task_id}/status") as r:
                return json.loads(r.read())

        return self._retrying(go)

    def get_results(
        self, task_id, partition: int, token: int,
        max_pages: int = 16, wait: float = 0.0,
    ) -> Tuple[List[Page], int, bool]:
        path = f"/v1/task/{task_id}/results/{partition}/{token}?wait={wait}"

        def go():
            with self._req("GET", path) as r:
                data = r.read()
                next_token = int(r.headers["X-Next-Token"])
                complete = r.headers["X-Complete"] == "1"
            return unpack_pages(data), next_token, complete

        return self._retrying(go)

    def remove_task(self, task_id) -> None:
        try:
            self._req("DELETE", f"/v1/task/{task_id}").close()
        except (urllib.error.URLError, OSError):
            pass

    def fail_query(self, query_id: str, message: str) -> None:
        """DELETE /v1/query/{id}?reason=...: fail every task of the
        query on this worker with the kill reason (low-memory killer /
        speculation-loser cancellation over the wire)."""
        import urllib.parse as _up

        try:
            self._req(
                "DELETE",
                f"/v1/query/{query_id}?reason={_up.quote(message)}",
            ).close()
        except (urllib.error.URLError, OSError):
            pass  # a vanished worker has nothing left to kill

    def results_location(self, task_id):
        """Picklable location descriptor for TaskSpec.input_locations
        (resolved worker-side by task._resolve_fetch)."""
        return ("http", self.uri, str(task_id))

    def status(self) -> dict:
        # heartbeat probe: NO retry loop — the failure detector wants to
        # see every miss, and a probe that silently retries for 30s
        # would stall the ping loop behind one dead node
        with self._req("GET", "/v1/status") as r:
            return json.loads(r.read())

    def shutdown_gracefully(self) -> None:
        self._req("PUT", "/v1/shutdown").close()

    def set_state(self, state: str) -> None:
        """PUT /v1/info/state (the reference's worker-state API); only
        "SHUTTING_DOWN" is accepted by the server."""
        self._req(
            "PUT", "/v1/info/state", json.dumps(state).encode()
        ).close()


def frame_fabric_body(ekey: str, payload: bytes) -> bytes:
    """Length-prefix framing for fabric POST bodies. The encoded mesh
    record key is a pickled program identity and routinely exceeds the
    64 KiB request-line limit of http.server, so it rides in the BODY
    (never the URI or a header): 8-byte big-endian key length, the
    ascii key, then the checkpoint payload."""
    kb = ekey.encode("ascii")
    return struct.pack(">Q", len(kb)) + kb + payload


def unframe_fabric_body(body: bytes) -> Tuple[str, bytes]:
    if len(body) < 8:
        raise ValueError("fabric body too short for key frame")
    (klen,) = struct.unpack(">Q", body[:8])
    if klen > len(body) - 8:
        raise ValueError("fabric body key frame overruns body")
    return body[8 : 8 + klen].decode("ascii"), body[8 + klen :]


class _FabricHandler(_Handler):
    """Routes of the coordinator-to-coordinator checkpoint fabric
    (runtime/fabric.py HostFabric behind them):

      POST /v1/fabric/checkpoint        receive pushed bytes; framed
                                        body (key + payload), the
                                        X-Fabric-Digest header covers
                                        the payload and is verified
                                        before import_bytes
      POST /v1/fabric/checkpoint/pull   body is the encoded key; serve
                                        bytes + digest (404 when
                                        absent/stale)
      GET  /v1/fabric/status            endpoint state JSON

    Inherits _Handler's responders and the internal-auth gate; the
    worker task routes 404 here (no worker is bound)."""

    fabric = None  # set by server factory

    def do_GET(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("?")[0].split("/") if p]
        try:
            if parts == ["v1", "fabric", "status"]:
                self._json(200, self.fabric.status())
                return
            self._json(404, {"error": f"no route {self.path}"})
        except Exception as e:
            self._json(500, {"error": repr(e)})

    def do_POST(self):
        if not self._authorized():
            return
        parts = [p for p in self.path.split("/") if p]
        try:
            ln = int(self.headers.get("Content-Length", "0") or 0)
            body = self.rfile.read(ln)
            if parts == ["v1", "fabric", "checkpoint"]:
                ekey, data = unframe_fabric_body(body)
                digest = self.headers.get(FabricClient.HEADER_DIGEST, "")
                self._json(
                    200, self.fabric.receive_checkpoint(ekey, data, digest)
                )
                return
            if parts == ["v1", "fabric", "checkpoint", "pull"]:
                out = self.fabric.serve_checkpoint(body.decode("ascii"))
                if out is None:
                    self._json(404, {"error": "no checkpoint"})
                    return
                data, digest = out
                self._bytes(
                    200, data, [(FabricClient.HEADER_DIGEST, digest)]
                )
                return
            self._json(404, {"error": f"no route {self.path}"})
        except Exception as e:
            self._json(500, {"error": repr(e)})


class FabricServer:
    """HTTP front of one HostFabric — a coordinator's checkpoint-
    transport endpoint. Same auth posture as WorkerServer: a fabric
    port without a secret accepts (and serves) checkpoint bytes from
    anyone who can reach it, so a networked fabric refuses to start
    without one; require_secret=False is for single-process tests."""

    def __init__(self, fabric, port: int = 0,
                 internal_secret: Optional[str] = "__env__",
                 require_secret: bool = True):
        self.fabric = fabric
        self.internal_auth = None
        if internal_secret == "__env__":
            internal_secret = default_internal_secret()
        if internal_secret is None and require_secret:
            raise RuntimeError(
                "refusing to start a networked fabric endpoint without an "
                "internal secret: set TRINO_TPU_INTERNAL_SECRET (or pass "
                "internal_secret=...), or pass require_secret=False for "
                "single-process embedding"
            )
        if internal_secret is not None:
            from trino_tpu.security import InternalAuthenticator

            self.internal_auth = InternalAuthenticator(internal_secret)
        handler = type(
            "BoundFabricHandler", (_FabricHandler,),
            {"fabric": fabric, "server_ref": self},
        )
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self._httpd.server_port
        self.uri = f"http://127.0.0.1:{self.port}"
        self._thread = threadreg.spawn(
            f"fabric-http-{self.port}", self._httpd.serve_forever,
            owner="FabricServer",
        )

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class FabricClient:
    """Peer-coordinator side of the checkpoint fabric: push/pull
    MeshCheckpoint bytes with content digests, every call inside the
    RequestErrorTracker backoff/budget loop (same discipline as
    HttpWorkerClient — a spent budget raises RequestFailedError and
    the fabric degrades to pull-on-demand or a cold restart, never a
    blocked chunk loop)."""

    HEADER_DIGEST = "X-Fabric-Digest"

    def __init__(self, uri: str, timeout: float = 10.0,
                 internal_secret: Optional[str] = "__env__",
                 retry_policy=None, failure_listener=None):
        self.uri = uri.rstrip("/")
        self.timeout = timeout
        self.retry_policy = retry_policy
        self.failure_listener = failure_listener
        self._auth = None
        if internal_secret == "__env__":
            internal_secret = default_internal_secret()
        if internal_secret is not None:
            from trino_tpu.security import InternalAuthenticator

            self._auth = InternalAuthenticator(internal_secret)

    def _req(self, method: str, path: str, body: Optional[bytes] = None,
             headers: Optional[dict] = None):
        hdrs = dict(headers or {})
        if self._auth is not None:
            hdrs[self._auth.HEADER] = self._auth.token()
        req = urllib.request.Request(
            self.uri + path, data=body, method=method, headers=hdrs
        )
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _retrying(self, fn):
        from trino_tpu.runtime.error_tracker import (
            RetryPolicy,
            run_with_retry,
        )

        return run_with_retry(
            self.uri, fn, policy=self.retry_policy or RetryPolicy(),
            listener=self.failure_listener,
        )

    def push_checkpoint(self, key: tuple, data: bytes,
                        digest: Optional[str] = None) -> dict:
        from trino_tpu.runtime.fabric import checkpoint_digest, encode_key

        digest = digest or checkpoint_digest(data)
        body = frame_fabric_body(encode_key(key), data)

        def go():
            with self._req(
                "POST", "/v1/fabric/checkpoint", body=body,
                headers={self.HEADER_DIGEST: digest},
            ) as r:
                return json.loads(r.read())

        return self._retrying(go)

    def pull_checkpoint(
        self, key: tuple
    ) -> Tuple[Optional[bytes], Optional[str]]:
        """(bytes, digest) of the peer's live entry, or (None, None)
        when the peer has no (non-stale) checkpoint under the key."""
        from trino_tpu.runtime.fabric import encode_key

        body = encode_key(key).encode("ascii")

        def go():
            try:
                with self._req(
                    "POST", "/v1/fabric/checkpoint/pull", body=body
                ) as r:
                    return r.read(), r.headers.get(self.HEADER_DIGEST)
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return None, None  # absent is an answer, not an error
                raise

        return self._retrying(go)

    def status(self) -> dict:
        def go():
            with self._req("GET", "/v1/fabric/status") as r:
                return json.loads(r.read())

        return self._retrying(go)


def http_fetch(uri: str, task_id: str, retry_policy=None):
    """Location descriptor -> fetch callable for TaskSpec.input_locations
    (the HttpPageBufferClient pull side). Worker-to-worker page pulls
    carry the same retry/backoff discipline as coordinator calls."""
    client = HttpWorkerClient(uri, retry_policy=retry_policy)

    def fetch(partition: int, token: int, max_pages: int, wait: float):
        return client.get_results(task_id, partition, token, max_pages, wait)

    return fetch
