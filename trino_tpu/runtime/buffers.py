"""Task output buffers with token-based pull+ack semantics.

Analogue of main/execution/buffer/OutputBuffer.java:24 and TaskResource's
results protocol (GET /v1/task/{id}/results/{buffer}/{token} :321,
acknowledge :364 — SURVEY.md §3.4): the consumer pulls pages starting at
a token; requesting token T acknowledges everything below T (at-least-
once delivery with resume). Producer-side backpressure: enqueue blocks
once buffered bytes exceed the limit until consumers drain
(OutputBufferMemoryManager's blocked future, collapsed to a wait).
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import List, Optional, Tuple

from trino_tpu.exec.serde import Page


class OutputBuffer:
    """Per-task producer buffer, one logical queue per output partition."""

    def __init__(self, n_partitions: int, max_bytes: int = 128 << 20):
        self._n = n_partitions
        self._max_bytes = max_bytes
        self._lock = named_condition("OutputBuffer._lock")
        # per partition: pages kept from first_token onward
        self._pages: List[List[Page]] = [[] for _ in range(n_partitions)]
        self._first_token: List[int] = [0] * n_partitions
        self._bytes = 0
        self._no_more = False
        self._aborted = False

    @property
    def n_partitions(self) -> int:
        return self._n

    # -- producer side --
    def enqueue(self, partition: int, page: Page) -> None:
        with self._lock:
            while (
                self._bytes >= self._max_bytes
                and not self._aborted
            ):
                self._lock.wait(timeout=0.1)
            if self._aborted:
                return
            self._pages[partition].append(page)
            self._bytes += page.size_bytes()
            self._lock.notify_all()

    def set_no_more_pages(self) -> None:
        with self._lock:
            self._no_more = True
            self._lock.notify_all()

    def abort(self) -> None:
        """Tear down (query failure/cancel): unblock producers, drop data."""
        with self._lock:
            self._aborted = True
            self._pages = [[] for _ in range(self._n)]
            self._bytes = 0
            self._lock.notify_all()

    # -- consumer side (the /results/{partition}/{token} protocol) --
    def get_pages(
        self,
        partition: int,
        token: int,
        max_pages: int = 16,
        wait: float = 0.0,
    ) -> Tuple[List[Page], int, bool]:
        """Pages starting at `token`; requesting token T acks (drops)
        every page below T. Returns (pages, next_token, complete).
        `wait` > 0 long-polls until data/finish/timeout."""
        deadline = None
        with self._lock:
            while True:
                if self._aborted:
                    # consumers must fail fast, not drain silence
                    raise RuntimeError("output buffer aborted (task failed)")
                q = self._pages[partition]
                first = self._first_token[partition]
                if token < first:
                    # below the acked watermark: the data is gone; spinning
                    # would hang the consumer (Trino's results protocol
                    # rejects rewinds past the acknowledged token)
                    raise RuntimeError(
                        f"token {token} below acknowledged watermark {first}"
                    )
                # ack: drop pages below the requested token
                if token > first:
                    drop = min(token - first, len(q))
                    for pg in q[:drop]:
                        self._bytes -= pg.size_bytes()
                    del q[:drop]
                    self._first_token[partition] = first = first + drop
                    self._lock.notify_all()
                start = token - first
                available = q[start : start + max_pages] if start >= 0 else []
                end_token = first + len(q)
                complete = self._no_more and token >= end_token
                if available or complete or wait <= 0:
                    return list(available), token + len(available), complete
                import time

                if deadline is None:
                    deadline = time.monotonic() + wait
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], token, False
                self._lock.wait(timeout=remaining)

    def is_fully_consumed(self) -> bool:
        with self._lock:
            return self._no_more and all(not q for q in self._pages)
