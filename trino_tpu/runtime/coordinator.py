"""Coordinator: distributed planning + pipelined all-at-once scheduling.

Analogue of SqlQueryExecution (planQuery/planDistribution,
SqlQueryExecution.java:457/503) + PipelinedQueryScheduler.java:155
(StageManager creating every stage up front, tasks streaming pages
between stages through pull+ack buffers — SURVEY.md §3.1–§3.4).
The DistributedQueryRunner facade mirrors
testing/trino-testing/DistributedQueryRunner.java:84: one coordinator +
N workers in one process, real exchange data plane between tasks.
"""

from __future__ import annotations

import itertools
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import Dict, List, Optional

from trino_tpu import types as T
from trino_tpu.connectors.spi import CatalogManager, Connector
from trino_tpu.engine import MaterializedResult, Session
from trino_tpu.runtime.task import TaskId, TaskSpec
from trino_tpu.runtime.worker import Worker
from trino_tpu.sql import ast
from trino_tpu.sql.analyzer import Analyzer
from trino_tpu.sql.fragmenter import SubPlan, explain_distributed, plan_distributed
from trino_tpu.sql.local_planner import LocalPlanner
from trino_tpu.sql.parser import parse
from trino_tpu.exec.serde import Page

_query_counter = itertools.count(1)


class QueryScheduler:
    """Schedules one query's SubPlan over the workers (pipelined mode:
    every stage starts immediately; pages stream between running stages)."""

    def __init__(
        self,
        query_id: str,
        subplan: SubPlan,
        workers: List[Worker],
        catalogs: CatalogManager,
        session: Session,
        hash_partitions: Optional[int] = None,
        collect_stats: bool = False,
        trace=None,
        query_span=None,
        deadline_epoch_s: Optional[float] = None,
    ):
        self.query_id = query_id
        self.subplan = subplan
        self.workers = workers
        self.catalogs = catalogs
        self.session = session
        self.collect_stats = collect_stats
        self.deadline_epoch_s = deadline_epoch_s
        self.hash_partitions = hash_partitions or min(
            len(workers), session.hash_partition_count
        )
        # fragment id -> [(worker handle, task id string)]
        self.tasks: Dict[int, List] = {}
        self._schemas: Dict[int, list] = {}
        # tracing (runtime/tracing.py): one stage span per fragment and
        # one task span per launch, all hanging off `query_span`; tasks
        # get wire_context on TaskSpec so worker operator spans graft in
        self.trace = trace
        self.query_span = query_span
        self.stage_spans: Dict[int, object] = {}
        self.task_spans: Dict[str, object] = {}

    def start(self):
        """Create all tasks bottom-up (producers first so consumers can
        reference their buffers); returns the root task."""
        from trino_tpu.runtime.stages import (
            fragment_schema,
            stage_task_count,
            topo_order,
        )

        order = topo_order(self.subplan)
        task_counts: Dict[int, int] = {}
        consumer_counts: Dict[int, int] = {}
        # first pass: task counts; consumer partition counts per producer
        for sp in order:
            task_counts[sp.fragment.id] = stage_task_count(
                sp, len(self.workers), self.hash_partitions
            )
        for sp in order:
            for c in sp.children:
                consumer_counts[c.fragment.id] = task_counts[sp.fragment.id]
        from trino_tpu.runtime.node_scheduler import (
            TopologyAwareNodeSelector,
            UniformNodeSelector,
        )

        # least-loaded placement with a per-node cap (NodeScheduler /
        # UniformNodeSelector analogue; replaces blind round-robin).
        # Workers carrying a `location` ("rack/host" — the ICI-island
        # coordinate on a TPU pod) upgrade to tiered topology-aware
        # selection (TopologyAwareNodeSelector.java)
        cap = max(
            2,
            (sum(task_counts.values()) + len(self.workers) - 1)
            // max(len(self.workers), 1),
        )
        locations = {
            id(w): getattr(w, "location")
            for w in self.workers
            if getattr(w, "location", None)
        }
        selector = (
            TopologyAwareNodeSelector(locations, max_tasks_per_node=cap)
            if locations
            else UniformNodeSelector(max_tasks_per_node=cap)
        )
        tracing = self.trace is not None and self.query_span is not None
        if tracing:
            from trino_tpu.runtime.tracing import (
                KIND_STAGE,
                KIND_TASK,
                wire_context,
            )
        record_stages = bool(
            getattr(self.session, "recovery_spool_stages", False)
        )
        if record_stages:
            from trino_tpu.recovery import RECORDER, fragment_recordable
        root_fid = self.subplan.fragment.id
        for sp in order:
            f = sp.fragment
            tc = task_counts[f.id]
            record_this = (
                record_stages
                and fragment_recordable(sp, f.id == root_fid)
            )
            if record_this:
                RECORDER.expect(self.query_id, f.id, tc)
            n_out = consumer_counts.get(f.id, 1)
            if tracing:
                self.stage_spans[f.id] = self.query_span.child(
                    f"stage {f.id}", KIND_STAGE, fragment_id=f.id, tasks=tc
                )
            remote = {
                c.fragment.id: self._schemas[c.fragment.id]
                for c in sp.children
            }
            self._schemas[f.id] = fragment_schema(
                self.catalogs, self.session, sp, remote
            )
            input_locations = {
                c.fragment.id: [
                    handle.results_location(tid)
                    for handle, tid in self.tasks[c.fragment.id]
                ]
                for c in sp.children
            }
            created = []
            for p in range(tc):
                task_id = TaskId(self.query_id, f.id, p)
                spec = TaskSpec(
                    task_id=task_id,
                    fragment=f,
                    n_output_partitions=n_out,
                    remote_schemas=remote,
                    scan_slice=(p, tc) if f.partitioning == "source" else None,
                    input_locations=input_locations,
                    batch_rows=self.session.batch_rows,
                    target_splits=max(self.session.target_splits, tc),
                    dynamic_filtering=self.session.enable_dynamic_filtering,
                    collect_stats=self.collect_stats,
                    task_concurrency=self.session.task_concurrency,
                    shape_stabilization=getattr(
                        self.session, "shape_stabilization", True
                    ),
                    capacity_ladder_base=getattr(
                        self.session, "capacity_ladder_base", 2
                    ),
                    deadline_epoch_s=self.deadline_epoch_s,
                    record_output=record_this,
                )
                if tracing:
                    tspan = self.stage_spans[f.id].child(
                        f"task {task_id}", KIND_TASK, partition=p
                    )
                    self.task_spans[str(task_id)] = tspan
                    if self.collect_stats:
                        # operator spans only under query_trace=on —
                        # the traced-off run stays an honest baseline
                        spec.trace_ctx = wire_context(tspan)
                first_loc = (
                    locations.get(id(created[0][0]))
                    if locations and created else None
                )
                if first_loc is not None:
                    # co-schedule a fragment's tasks on the FIRST
                    # task's ISLAND (rack tier, not the host — stacking
                    # a fragment on one host would serialize it): its
                    # exchanges then ride ICI, not DCN. A location-less
                    # first task keeps uniform selection.
                    worker = selector.select(
                        self.workers,
                        location=TopologyAwareNodeSelector._rack(
                            first_loc
                        ),
                    )
                else:
                    worker = selector.select(self.workers)
                worker.create_task(spec)
                created.append((worker, str(task_id)))
            self.tasks[f.id] = created
        return self.tasks[self.subplan.fragment.id][0]

    def failed_tasks(self) -> List[str]:
        out = []
        for ts in self.tasks.values():
            for handle, tid in ts:
                try:
                    st = handle.task_state(tid)
                except Exception as e:
                    out.append(f"{tid}: status fetch failed ({e})")
                    continue
                if st["state"] == "failed":
                    out.append(f"{tid}: {st.get('failure')}")
        return out

    def finalize(self) -> Dict[int, List]:
        """Terminal status sweep, run BEFORE abort() (remove_task
        destroys the span/stats data): pull each task's final status,
        graft its operator spans into the trace, and close the task and
        stage spans with worker-reported wall bounds. Returns
        fragment id -> [(task id, status dict)] for QueryInfo."""
        # settle: draining the root output races the root task's own
        # state flip by a few ms — wait for every task to go terminal
        # so QueryInfo/EXPLAIN ANALYZE never snapshot a "running" task
        # with half-flushed stats (bounded: failure paths have already
        # flipped their tasks to failed before finalize runs)
        deadline = time.time() + 2.0
        while time.time() < deadline:
            settled = True
            for ts in self.tasks.values():
                for handle, tid in ts:
                    try:
                        st = handle.task_state(tid)
                    except Exception:
                        continue
                    if st.get("state") == "running":
                        settled = False
            if settled:
                break
            time.sleep(0.005)
        states: Dict[int, List] = {}
        for fid, ts in self.tasks.items():
            lst = []
            for handle, tid in ts:
                try:
                    st = handle.task_state(tid)
                except Exception as e:
                    st = {"state": "unknown",
                          "failure": f"status fetch failed ({e})",
                          "cpu_s": 0.0}
                lst.append((tid, st))
                span = self.task_spans.get(tid)
                if span is not None:
                    if st.get("start_time"):
                        span.start_s = st["start_time"]
                    span.set(state=st.get("state"),
                             cpu_s=st.get("cpu_s") or 0.0)
                    if st.get("failure"):
                        span.set(error=True)
                        span.event("task_failed",
                                   message=str(st["failure"])[:500])
                    span.end(st.get("end_time"))
                if self.trace is not None:
                    self.trace.graft(st.get("spans") or [])
            states[fid] = lst
        for span in self.stage_spans.values():
            span.end()
        return states

    def abort(self) -> None:
        for ts in self.tasks.values():
            for handle, tid in ts:
                try:
                    handle.remove_task(tid)
                except Exception:
                    pass


class DistributedQueryRunner:
    """Multi-worker engine in one process (DistributedQueryRunner.java:84
    analogue): same SQL surface as LocalQueryRunner, but every query runs
    through fragments, tasks and the page exchange."""

    def __init__(
        self,
        session: Optional[Session] = None,
        n_workers: int = 2,
        hash_partitions: Optional[int] = None,
        worker_handles: Optional[List] = None,
        access_control=None,
    ):
        """Default topology: N in-process Workers sharing the coordinator
        CatalogManager. Pass `worker_handles` (e.g. HttpWorkerClient
        instances) to schedule over remote workers instead — catalogs
        must then be registered on each worker process separately, as in
        the reference's per-node catalog loading. `access_control` guards
        distributed Query statements AND the embedded single-node runner
        (same policy object on both paths)."""
        from trino_tpu.security import AllowAllAccessControl

        self.session = session or Session()
        self.access_control = access_control or AllowAllAccessControl()
        self.catalogs = CatalogManager()
        if worker_handles is not None:
            self.workers = list(worker_handles)
            self._in_process_workers = False
        else:
            self.workers = [
                Worker(
                    f"worker-{i}", self.catalogs,
                    memory_pool_bytes=self.session.memory_pool_bytes,
                    stuck_task_interrupt_s=getattr(
                        self.session, "stuck_task_interrupt_s", 0.0
                    ) or None,
                    stuck_task_interrupt_warm_s=getattr(
                        self.session, "stuck_task_interrupt_warm_s", 0.0
                    ) or None,
                )
                for i in range(n_workers)
            ]
            self._in_process_workers = True
        self.hash_partitions = hash_partitions
        # recovery tier: surface the recovery.* counters in /v1/metrics
        # at zero from process start (a counter only materializes on
        # first bump otherwise)
        from trino_tpu.recovery import register_recovery_metrics

        register_recovery_metrics()
        # why the last query left the mesh plane (None = it didn't)
        self.last_mesh_fallback: Optional[str] = None
        # resiliency plane: every worker is registered with a
        # NodeManager whose per-node circuit breakers graylist
        # misbehaving workers (ping loop NOT started here — call
        # .node_manager.start() for live heartbeats, or ping_once() for
        # deterministic tests)
        from trino_tpu.runtime.discovery import NodeManager

        self.node_manager = NodeManager(
            breaker_threshold=self.session.node_breaker_threshold,
            breaker_cooldown_s=self.session.node_breaker_cooldown_s,
        )
        for w in self.workers:
            self.node_manager.register(w)
            # remote handles (HttpWorkerClient): bind the session's
            # retry budget and the breaker listener unless the caller
            # already chose them explicitly
            if getattr(w, "retry_policy", False) is None:
                from trino_tpu.runtime.error_tracker import RetryPolicy

                w.retry_policy = RetryPolicy(
                    max_error_duration_s=(
                        self.session.request_max_error_duration_s
                    ),
                )
            if (
                hasattr(w, "failure_listener")
                and w.failure_listener is None
            ):
                w.failure_listener = self.node_manager
        # FTE observability for bounded-attempt assertions
        self.last_fte_stats: Optional[dict] = None
        # how many whole-query attempts the last statement took
        # (retry_policy=QUERY observability; 1 = no retry happened)
        self.last_query_attempts: int = 0
        # cluster memory arbiter over the in-process workers' SHARED
        # pools: on exhaustion kill the largest query, not the worker
        self.memory_manager = None
        if (
            self._in_process_workers
            and self.session.memory_pool_bytes
            and self.session.low_memory_killer_enabled
        ):
            from trino_tpu.runtime.memory import ClusterMemoryManager

            self.memory_manager = ClusterMemoryManager(
                [w.memory_pool for w in self.workers],
                fail_query=self._fail_query_on_workers,
            )
            self.memory_manager.install()
        # deadline hierarchy (runtime/query_tracker.py): every Query
        # statement registers here; the enforcement tick thread starts
        # lazily, on the first query that actually carries limits
        from trino_tpu.runtime.query_tracker import QueryTracker

        self.query_tracker = QueryTracker()
        # observability plane: event listener SPI (QueryCreated/
        # QueryCompleted with resource enrichment), the bounded
        # completed-query registry behind GET /v1/query/{id} and
        # /v1/query/{id}/trace, and in-flight traces for live lookups
        from trino_tpu.runtime.events import EventListenerManager

        self.event_listeners = EventListenerManager()
        self.event_listeners.register_metrics()
        # compile-attribution counters (xla_compiles_by_query.{qid} ->
        # QueryInfo.compile_count) and the compile-duration histogram
        # require the process-wide jax.monitoring listener
        from trino_tpu.runtime.metrics import install_xla_compile_listener

        install_xla_compile_listener()
        # mesh data-plane counters (queries / all_to_all / all_gather /
        # fallbacks) ride the same registry as gauges -> /v1/metrics
        from trino_tpu.parallel.mesh_plan import register_mesh_metrics

        register_mesh_metrics()
        # concurrency soundness plane gauges (analysis.locks /
        # analysis.threads_live / analysis.witness_violations)
        from trino_tpu.analysis import register_analysis_metrics

        register_analysis_metrics()
        # serving tier: canonical-text plan cache over the distributed
        # planning pipeline (analyze -> optimize -> fragment). DDL/DML
        # through the embedded runner and catalog registration
        # invalidate wholesale — fragments capture table handles whose
        # split listings describe a data snapshot.
        from trino_tpu.serving.plan_cache import PlanCache

        self._plan_cache = PlanCache(
            max_entries=getattr(self.session, "plan_cache_entries", 256)
        )
        # replicated serving meshes (runtime/replicas.py): carved
        # lazily on the first mesh dispatch with mesh_replicas >= 2
        # (device carving needs jax initialized, which query execution
        # guarantees and construction must not force)
        self._replicas = None
        # serializes mesh runs on the single full-width mesh: a mesh is
        # a single-program resource (two programs interleaving
        # collectives on one device set deadlock their rendezvous).
        # With a replica plane, the per-replica exec_lock takes over —
        # replicas are the units of mesh concurrency.
        self._mesh_exec_lock = named_lock("DistributedQueryRunner._mesh_exec_lock")
        # preemptive multi-tenancy (runtime/scheduler.py): the single
        # full-width mesh's chunk-granular run queue, built lazily on
        # first scheduled dispatch (replica planes carry one scheduler
        # per Replica instead); _sched_steals counts completed
        # work-stealing dispatches, instance-scoped for the EXPLAIN
        # `scheduler=` line
        self._mesh_scheduler = None
        self._sched_steals = 0
        import collections

        self._completed_queries = collections.OrderedDict()
        self._completed_queries_cap = 200
        self.last_query_id: Optional[str] = None
        self._active_traces: Dict[str, tuple] = {}
        self._lock = named_lock("DistributedQueryRunner._lock")

    def _fail_query_on_workers(self, query_id: str, message: str) -> None:
        for w in self.workers:
            try:
                w.fail_query(query_id, message)
            except Exception:
                pass

    def drain(self, worker_id: str, timeout_s: float = 30.0) -> bool:
        """Gracefully drain a worker: it leaves the placement pool
        immediately, refuses new task launches, and this call returns
        True once everything running on it reached a terminal state
        (committed, or re-placed elsewhere by the scheduler). False on
        timeout — the worker stays out of rotation, still serving its
        spooled output."""
        return self.node_manager.drain(worker_id, timeout_s=timeout_s)

    def _schedulable_workers(self) -> List:
        """Placement pool for new launches: breaker-closed active nodes,
        degrading to the full set rather than refusing to run."""
        nm = self.node_manager
        return (
            nm.schedulable_workers() or nm.active_workers() or self.workers
        )

    def _mesh_colocated(self) -> bool:
        """Mesh execution applies when every task would run in THIS
        process (tasks then share the host's device mesh). Remote worker
        handles mean cross-host scheduling — keep the page exchange."""
        return self._in_process_workers

    def register_catalog(self, name: str, connector: Connector) -> None:
        self.catalogs.register(name, connector)
        self._plan_cache.invalidate()
        # a new catalog can shadow names any cached state resolved
        # against — wholesale epoch bump, not table-granular
        from trino_tpu.resident import GENERATIONS, RESIDENT

        GENERATIONS.bump_all()
        RESIDENT.evict_all()

    def _dml_target(self, stmt):
        """(catalog, schema, table) a non-Query statement writes, via
        the session defaults (the embedded runner's _resolve_target
        rule); None = cannot name one (COMMIT/ROLLBACK — wholesale)."""
        parts = getattr(stmt, "table", None)
        if not parts or not isinstance(parts, (tuple, list)):
            return None
        cat, schema = self.session.catalog, self.session.schema
        if len(parts) == 2:
            schema = parts[0]
        elif len(parts) == 3:
            cat, schema = parts[0], parts[1]
        from trino_tpu.resident.manager import table_key

        return table_key(cat, schema, parts[-1])

    def _embedded_runner(self):
        if getattr(self, "_embedded", None) is None:
            from trino_tpu.engine import LocalQueryRunner

            lqr = LocalQueryRunner(
                self.session, access_control=self.access_control
            )
            lqr.catalogs = self.catalogs
            self._embedded = lqr
        return self._embedded

    def _check_access(self, output, identity) -> None:
        """AccessControl for distributed Query statements (the
        LocalQueryRunner._check_scans policy applied to the same plan
        the fragmenter will cut)."""
        from trino_tpu.security import Identity
        from trino_tpu.sql.plan import ScanNode

        ident = identity or Identity(self.session.user)
        self.access_control.check_can_execute_query(ident)

        def walk(node):
            if isinstance(node, ScanNode):
                h = node.handle
                self.access_control.check_can_select(
                    ident, h.catalog, h.schema, h.table, node.columns
                )
            for c in node.children():
                walk(c)

        walk(output)

    # -- entry point --
    def execute(
        self, sql: str, identity=None, transaction_id=None,
        prepared=None, cancel=None,
    ) -> MaterializedResult:
        """`cancel` is a zero-arg callable polled while the query runs
        (the client-abandonment reaper's hook): once it returns True the
        query is torn down — tasks aborted, memory released — instead of
        computing a result nobody will read."""
        import time as _time

        t_parse0 = _time.time()
        stmt = parse(sql)
        t_parse1 = _time.time()
        if isinstance(stmt, ast.ExplainStatement):
            output = self._analyze(stmt.query)
            self._check_access(output, identity)
            # EXPLAIN ANALYZE runs the adaptive controller exactly like
            # execute would, so the rendered plan/adaptive section shows
            # what a plain run of the statement does
            from trino_tpu.adaptive import AdaptiveController

            self._last_adaptive_report = None
            controller = AdaptiveController(self.catalogs, self.session)
            if stmt.analyze and controller.enabled():
                output = controller.prepare(output)
                self._last_adaptive_report = controller.report
            subplan = plan_distributed(
                output, self.catalogs,
                broadcast_threshold=self.session.broadcast_join_threshold,
                target_splits=self.session.target_splits,
                validation=getattr(self.session, "plan_validation", "passes"),
            )
            if stmt.analyze:
                return self._explain_analyze(subplan)
            return MaterializedResult(
                [[self._explain_text(subplan)]], ["Query Plan"], [T.VARCHAR]
            )
        param_dtypes: tuple = ()
        if isinstance(stmt, ast.ExecuteStmt):
            # EXECUTE of a prepared Query runs DISTRIBUTED: resolve the
            # text (request-carried headers take precedence over the
            # shared embedded store, mirroring LocalQueryRunner), check
            # the binding up front (typed arity/dtype errors instead of
            # analyzer failures deep in the substituted tree), then fall
            # through with the bound statement and its dtype vector as a
            # plan-cache key component
            text = (prepared or {}).get(stmt.name)
            if text is None:
                hit = self._embedded_runner()._prepared.get(stmt.name)
                text = hit[1] if hit else None
            if text is not None:
                from trino_tpu.serving.params import check_parameters

                body = parse(text)
                dtypes = check_parameters(
                    body, stmt.parameters, self.catalogs,
                    self.session.catalog, self.session.schema,
                )
                bound = ast.substitute_parameters(body, stmt.parameters)
                if isinstance(bound, ast.Query):
                    stmt = bound
                    param_dtypes = tuple(dtypes)
            # unknown name / non-Query body: the embedded path below
            # reports or runs it
        if not isinstance(stmt, ast.Query):
            # metadata/DML/transaction statements take the single-node
            # path — through ONE persistent embedded runner, so
            # transaction state survives across statements (a throwaway
            # runner per statement would silently autocommit)
            result = self._embedded_runner().execute(
                sql, identity=identity,
                transaction_id=transaction_id, prepared=prepared,
            )
            if isinstance(stmt, (
                ast.CreateTable, ast.CreateTableAs, ast.Insert,
                ast.Delete, ast.Update, ast.Merge, ast.DropTable,
                ast.Commit, ast.Rollback,
            )):
                # cached plans captured split listings over data this
                # statement may have changed. The embedded runner already
                # drove the resident-tier protocol (generation bump /
                # delta re-key) — here only the DISTRIBUTED plan cache
                # needs dropping, table-granular when the statement names
                # its target
                tkey = self._dml_target(stmt)
                if tkey is not None:
                    self._plan_cache.invalidate_tables([tkey])
                else:
                    self._plan_cache.invalidate()
            return result
        from trino_tpu.runtime.query_tracker import DeadlineLimits, PLANNING

        limits = DeadlineLimits.from_session(self.session)
        # retry_policy=QUERY deterministic replay: every attempt re-runs
        # the SAME plan under a fresh internal task namespace (qN, qNr1,
        # qNr2, ...) — create_task is idempotent BY ID, so reusing the
        # first attempt's ids would hand back its dead TaskExecutions.
        # No dot in the suffix: task keys are matched by the
        # `query_id + "."` prefix and attempts must never cross-match.
        base_qid = f"q{next(_query_counter)}"
        tracker = self.query_tracker
        tq = tracker.register(base_qid, limits, phase=PLANNING)
        # bound late: the kill must target whichever ATTEMPT namespace is
        # live when the tick fires (live_query_id tracks qN/qNr1/...)
        tq.kill = lambda msg: self._fail_query_on_workers(
            tq.live_query_id, msg
        )
        if limits.any():
            tracker.start()
        # every distributed query gets a coordinator-side span tree
        # (query/phases/stages/tasks — a handful of spans); worker
        # OPERATOR spans and row counting only under query_trace=on
        from trino_tpu.runtime.events import QueryCreatedEvent
        from trino_tpu.runtime.metrics import METRICS
        from trino_tpu.runtime.tracing import (
            KIND_PHASE,
            KIND_QUERY,
            QueryTrace,
        )

        trace = QueryTrace(base_qid)
        qspan = trace.span(f"query {base_qid}", KIND_QUERY, sql=sql[:500])
        qspan.start_s = t_parse0
        pspan = qspan.child("parse", KIND_PHASE)
        pspan.start_s = t_parse0
        pspan.end(t_parse1)
        with self._lock:
            self._active_traces[base_qid] = trace
        counters_before = METRICS.snapshot()
        self.event_listeners.query_created(
            QueryCreatedEvent(base_qid, sql, _time.time())
        )
        self._last_stage_infos = None
        self._last_data_plane = "http"
        status, failure_txt, rows_n = "finished", None, 0
        try:
            result = self._execute_query(
                stmt, identity, base_qid, tq, limits, cancel,
                trace=trace, query_span=qspan, param_dtypes=param_dtypes,
            )
            rows_n = len(result.rows)
            return result
        except BaseException as e:
            status, failure_txt = "failed", repr(e)
            if not qspan.ended:
                qspan.event("exception", type=type(e).__name__,
                            message=str(e)[:500])
                qspan.set(error=True)
            raise
        finally:
            tracker.complete(base_qid)
            self._finalize_query(
                base_qid, sql, trace, qspan, status, failure_txt,
                rows_n, counters_before,
            )

    def _execute_query(
        self, stmt, identity, base_qid, tq, limits, cancel,
        trace=None, query_span=None, param_dtypes=(),
    ) -> MaterializedResult:
        from trino_tpu.runtime.query_tracker import (
            EXECUTING,
            QueryDeadlineError,
            deadline_code,
            deadline_error,
        )
        from trino_tpu.runtime.tracing import KIND_PHASE

        def phase(name):
            if query_span is None:
                import contextlib

                return contextlib.nullcontext()
            return query_span.child(name, KIND_PHASE)

        tracker = self.query_tracker
        # reset BEFORE any plane decision: a stale reason from an earlier
        # query must not read as applying to this one
        self.last_mesh_fallback = None
        self._last_adaptive_report = None
        cache_key = None
        try:
            from trino_tpu.sql.formatter import format_statement

            cache_key = self._plan_cache.key(
                format_statement(stmt), self.session, param_dtypes
            )
        except Exception:
            pass  # unformattable statement: plan uncached
        cached = self._plan_cache.lookup(cache_key) if cache_key else None
        if cached is not None:
            output, subplan = cached
            # access control is NOT part of the key: the cached logical
            # plan is re-checked under THIS caller's identity
            self._check_access(output, identity)
            if query_span is not None:
                query_span.event("plan_cache_hit")
        else:
            from trino_tpu.sql.analyzer import (
                plan_is_volatile,
                reset_volatile_plan,
            )

            # snapshot BEFORE planning: a catalog change racing the
            # analyze/optimize/fragment work below must void this store
            cache_generation = self._plan_cache.generation
            reset_volatile_plan()
            output = self._analyze(stmt, query_span=query_span)
            self._check_access(output, identity)
            # adaptive execution: materialize barriers on the
            # coordinator's catalogs and re-plan the remainder before
            # fragmenting. tracker.check at every barrier keeps a kill
            # latched mid-re-plan typed (EXCEEDED_TIME_LIMIT, not a
            # retryable transport error).
            adaptive_report = None
            from trino_tpu.adaptive import AdaptiveController

            controller = AdaptiveController(
                self.catalogs, self.session, span=query_span,
                preempt=lambda: tracker.check(base_qid),
            )
            if controller.enabled():
                with phase("adaptive"):
                    output = controller.prepare(output)
                adaptive_report = controller.report
            self._last_adaptive_report = adaptive_report
            with phase("fragment"):
                subplan = plan_distributed(
                    output,
                    self.catalogs,
                    broadcast_threshold=self.session.broadcast_join_threshold,
                    target_splits=self.session.target_splits,
                    validation=getattr(
                        self.session, "plan_validation", "passes"
                    ),
                )
            if (
                cache_key is not None
                and not plan_is_volatile()
                and not (
                    adaptive_report is not None
                    and adaptive_report.transformed
                )
            ):
                from trino_tpu.serving.plan_cache import plan_tables

                self._plan_cache.store(
                    cache_key, (output, subplan),
                    generation=cache_generation,
                    tables=plan_tables(output),
                )
        # planning is over: surface a planning-limit kill latched during
        # the analyze/optimize/fragment work before any task launches.
        # Enforce synchronously first — a planning phase that finishes
        # between background ticks must not outrun its own budget
        tracker.enforce_now(base_qid)
        tracker.check(base_qid)
        tracker.transition(base_qid, EXECUTING)
        # worker-local deadline: translate the query's remaining wall
        # budget into the epoch-seconds deadline every TaskSpec carries,
        # so workers self-terminate between batches instead of waiting
        # for the coordinator's enforcement tick to reach them
        deadline_epoch_s = None
        if limits is not None:
            import time as _time

            budgets = []
            if limits.max_execution_time_s:
                budgets.append(limits.max_execution_time_s)
            if limits.max_run_time_s:
                budgets.append(max(
                    0.0,
                    limits.max_run_time_s
                    - (_time.monotonic() - tq.created_at),
                ))
            if budgets:
                deadline_epoch_s = _time.time() + min(budgets)
        result_meta = (list(output.names), [f.type for f in output.fields])
        if self.session.retry_policy == "task":
            self._last_data_plane = "fte"
            rows = self._execute_fte(
                subplan, query_id=base_qid, cancel=cancel, tq=tq,
                trace=trace, query_span=query_span,
                deadline_epoch_s=deadline_epoch_s,
            )
            return MaterializedResult(rows, *result_meta, data_plane="fte")
        if self.session.mesh_execution and self._mesh_colocated():
            # tasks share one host's device mesh: exchanges ride ICI
            # collectives in chunked SPMD programs (parallel/mesh_chunk)
            # with host preemption checks at every chunk boundary — so
            # deadline-bearing queries run here too, killed between
            # chunks with the same typed errors the page plane raises.
            # Unsupported plan shapes fall back to the page exchange.
            from trino_tpu.parallel.mesh_plan import MeshUnsupported
            from trino_tpu.parallel.mesh_chunk import (
                MeshDeviceLost,
                MeshStuck,
            )
            from trino_tpu.runtime.metrics import set_compile_attribution
            from trino_tpu.runtime.query_tracker import (
                QueryAbandonedError,
                preemption_check,
            )

            preempt = preemption_check(
                tracker, base_qid, cancel=cancel,
                deadline_epoch_s=deadline_epoch_s,
            )
            # fast-lane classification for the mesh scheduler: point
            # lookups (possibly dimension-decorated) preempt a running
            # analytic at its next chunk boundary instead of queueing
            # behind the whole run
            try:
                from trino_tpu.serving.admission import is_fast_lane

                fast_lane = is_fast_lane(stmt)
            except Exception:
                fast_lane = False
            prev = set_compile_attribution(base_qid)
            try:
                rows = self._execute_mesh(
                    subplan, preempt, query_span,
                    fast=fast_lane, query_id=base_qid,
                )
                self._last_data_plane = "mesh"
                return MaterializedResult(
                    rows, *result_meta, data_plane="mesh"
                )
            except MeshUnsupported as ex:
                # fallback must be OBSERVABLE, not silent: count it and
                # record why (EXPLAIN ANALYZE / QueryInfo / metrics
                # surface it) — whether raised statically or mid-run
                self._record_mesh_fallback(str(ex), query_span)
            except (QueryDeadlineError, QueryAbandonedError):
                raise  # the preemption hook fired: typed, no fallback
            except (MeshStuck, MeshDeviceLost) as ex:
                # retryable by classification: a program hung (or lost
                # its device) after exhausting in-run checkpoint
                # resumes may succeed on the page plane, so fall back
                # observably. The mesh checkpoint survives — the next
                # mesh execution of this plan resumes from it.
                self._record_mesh_fallback(str(ex), query_span)
            except Exception as e:
                if deadline_code(str(e)) is not None:
                    # a latched kill that travelled as a failure string:
                    # re-type it so it stays non-retryable, no fallback
                    raise deadline_error(str(e)) from e
                # unexpected mesh runtime failure: the page-exchange
                # path below re-executes from scratch (correctness
                # preserved), but surface the regression
                import logging

                logging.getLogger(__name__).warning(
                    "mesh execution failed; falling back to page "
                    "exchange",
                    exc_info=True,
                )
                self._record_mesh_fallback(f"error: {e}", query_span)
            finally:
                set_compile_attribution(prev)
        attempts = (
            1 + self.session.query_retry_count
            if self.session.retry_policy == "query"
            else 1
        )
        # recovery tier: with recovery_spool_stages on, every non-root
        # task tees its wire pages into the stage-output recorder; a
        # failed attempt's fully-finished fragments are harvested into
        # the subtree spool and the NEXT attempt substitutes them as
        # literal sources (only the work that failed is recomputed)
        spool_stages = attempts > 1 and bool(
            getattr(self.session, "recovery_spool_stages", False)
        )
        last_error: Optional[BaseException] = None
        accrued_cpu = 0.0  # CPU spent by completed attempts
        for attempt in range(attempts):
            query_id = base_qid if attempt == 0 else f"{base_qid}r{attempt}"
            self.last_query_attempts = attempt + 1
            tracker.set_live_query_id(base_qid, query_id)
            # a deadline kill latched between attempts ends the query
            # here — resubmitting a spent budget can only spend it again
            tracker.check(base_qid)
            if cancel is not None and cancel():
                # nobody is waiting for this result: don't launch (or
                # re-launch) tasks for it
                from trino_tpu.runtime.query_tracker import (
                    QueryAbandonedError,
                )

                raise QueryAbandonedError(
                    f"Query {base_qid} abandoned: client stopped "
                    "polling results"
                )
            attempt_subplan = subplan
            if attempt > 0:
                # a stale cached split listing may be WHY the last
                # attempt died (files compacted/deleted under it):
                # re-list before replaying
                self.catalogs.invalidate_split_listings()
                if query_span is not None:
                    query_span.event(
                        "query_retry", attempt=attempt,
                        error=str(last_error)[:300],
                    )
                if spool_stages:
                    from trino_tpu.recovery import (
                        harvest_recorded_stages,
                        substitute_spooled_fragments,
                    )

                    prev_qid = (
                        base_qid if attempt == 1
                        else f"{base_qid}r{attempt - 1}"
                    )
                    banked = harvest_recorded_stages(prev_qid, subplan)
                    attempt_subplan, spooled = (
                        substitute_spooled_fragments(
                            subplan, span=query_span
                        )
                    )
                    if query_span is not None and (banked or spooled):
                        query_span.event(
                            "stage_recovery", banked=banked,
                            substituted=spooled,
                        )
            scheduler = QueryScheduler(
                query_id,
                attempt_subplan,
                self._schedulable_workers(),
                self.catalogs,
                self.session,
                self.hash_partitions,
                collect_stats=(
                    getattr(self.session, "query_trace", "off") == "on"
                ),
                trace=trace,
                query_span=query_span,
                deadline_epoch_s=deadline_epoch_s,
            )
            # the CPU budget reads the live attempt's task ledgers on
            # top of what earlier attempts already burned
            tq.cpu_time_fn = (
                lambda s=scheduler, base=accrued_cpu:
                base + _scheduler_cpu_s(s)
            )
            try:
                # start() inside the try: a mid-launch failure must still
                # abort the tasks already created, and counts as a
                # retryable attempt under retry_policy=QUERY. Worker
                # crashes surface as OSError/URLError, not RuntimeError,
                # so catch broadly here — analysis errors were raised
                # before this loop.
                with phase("schedule"):
                    root_handle, root_tid = scheduler.start()
                rows = self._collect(
                    scheduler, root_handle, root_tid,
                    cancel=cancel, base_qid=base_qid,
                )
                return MaterializedResult(
                    rows, *result_meta, data_plane="http"
                )
            except QueryDeadlineError:
                raise  # non-retryable by classification
            except Exception as e:
                if deadline_code(str(e)) is not None:
                    # a deadline kill that travelled as a task-failure
                    # string (HTTP 500 body, buffer-abort unwind):
                    # re-type it so it stays non-retryable
                    raise deadline_error(str(e)) from e
                # retry_policy=QUERY: whole-query re-run
                accrued_cpu += _scheduler_cpu_s(scheduler)
                last_error = e
            finally:
                # terminal sweep BEFORE abort (remove_task destroys the
                # span/stats data): grafts worker spans, closes stage/
                # task spans, snapshots task states for QueryInfo
                try:
                    self._last_stage_infos = self._stage_infos(
                        scheduler.finalize()
                    )
                    self._record_stage_divergences(
                        attempt_subplan, self._last_stage_infos,
                        query_span,
                    )
                except Exception:
                    pass  # observability must never mask the verdict
                scheduler.abort()
        raise last_error

    def _replica_manager(self):
        """The replica plane, carved lazily on first mesh dispatch:
        session.mesh_replicas >= 2 splits the device set into that many
        identical sub-meshes (runtime/replicas.py). None — the single
        full-width mesh — when replication is off or the device set is
        too small to carve."""
        n = int(getattr(self.session, "mesh_replicas", 1) or 1)
        if n < 2:
            return None
        rm = self._replicas
        if rm is not None and rm.n_replicas == n:
            return rm
        from trino_tpu.runtime.replicas import ReplicaManager

        try:
            rm = ReplicaManager(
                n,
                breaker_threshold=int(getattr(
                    self.session, "replica_breaker_threshold", 3
                )),
                breaker_cooldown_s=float(getattr(
                    self.session, "replica_breaker_cooldown_s", 1.0
                )),
                scheduler_kw=self._scheduler_kw(),
            )
        except ValueError:
            rm = None  # fewer devices than replicas: keep one mesh
        self._replicas = rm
        return rm

    def _scheduler_kw(self) -> dict:
        from trino_tpu.runtime.scheduler import parse_group_weights

        return {
            "min_slice_chunks": int(getattr(
                self.session, "mesh_scheduler_min_slice_chunks", 1
            ) or 1),
            "preemption_enabled": bool(getattr(
                self.session, "preemption_enabled", True
            )),
            "weights": parse_group_weights(str(getattr(
                self.session, "mesh_scheduler_weights", ""
            ) or "")),
        }

    def _tune_scheduler(self, sched) -> None:
        """Refresh a live scheduler's knobs from the current session —
        SET SESSION between queries must take effect without rebuilding
        the run queue (waiting jobs keep their seats)."""
        kw = self._scheduler_kw()
        sched.min_slice_chunks = max(1, int(kw["min_slice_chunks"]))
        sched.preemption_enabled = bool(kw["preemption_enabled"])
        sched.weights = dict(kw["weights"])

    def _mesh_scheduler_for(self):
        if self._mesh_scheduler is None:
            from trino_tpu.runtime.scheduler import MeshScheduler

            self._mesh_scheduler = MeshScheduler(
                name="mesh", **self._scheduler_kw()
            )
        else:
            self._tune_scheduler(self._mesh_scheduler)
        return self._mesh_scheduler

    def _sched_group(self) -> str:
        return str(getattr(
            self.session, "mesh_scheduler_group", ""
        ) or "") or "default"

    def _execute_mesh(self, subplan, preempt, query_span, fast=False,
                      query_id=""):
        """Mesh dispatch with replica placement and chunk-granular
        failover. Single-replica sessions run the full-width mesh
        directly. With a replica plane: place the least-loaded healthy
        sub-mesh; when it dies (MeshStuck/MeshDeviceLost) or drains
        mid-query, re-place onto a sibling — the sibling's chunk runner
        finds the host-portable checkpoint under the device-independent
        key and continues from chunk k on its own warm programs. Only
        when no sibling remains (or failover is off) does the fault
        re-raise into the caller's page-plane fallback.

        With mesh_scheduler on (the default), the serialization point
        is the weighted-fair run queue (runtime/scheduler.py) instead
        of a bare lock: the holder's chunk loop consults the scheduler
        at every boundary, `fast` submissions ride the preempting fast
        lane, and a drain fault whose unstarted chunk range is large
        enough may be SPLIT across two sibling replicas (work
        stealing) instead of resuming wholesale on one."""
        from trino_tpu.parallel.mesh_chunk import (
            MeshDeviceLost,
            MeshReplicaDraining,
            MeshStuck,
        )
        from trino_tpu.parallel.mesh_plan import MeshExecutor

        import contextlib

        use_sched = bool(getattr(self.session, "mesh_scheduler", True))
        group = self._sched_group()
        # multi-host fabric attach (no-op unless fabric_peers is set):
        # checkpoints taken by this run stream asynchronously to peer
        # coordinators, and failover below can pull the last pushed
        # snapshot on demand. Attached before the single-mesh branch so
        # a single-mesh coordinator pushes too.
        from trino_tpu.runtime.fabric import (
            MembershipEpochError,
            active_fabric,
            maybe_start_fabric,
        )

        maybe_start_fabric(self.session)
        rm = self._replica_manager()
        if rm is None:
            ex = MeshExecutor(self.catalogs, self.session)
            # width-1 meshes run no collectives and keep their historic
            # concurrency; wider meshes serialize — through the
            # scheduler's run queue when it is on, else the bare lock
            if getattr(ex, "n", 1) > 1 and use_sched:
                sched = self._mesh_scheduler_for()
                job = sched.submit(
                    query_id or "q?", group=group, fast=fast,
                    poll=preempt,
                )
                # the chunk runner acquires the seat itself, at device-
                # phase entry — host planning and feed builds for this
                # query run before the grant, outside the seat
                ex.sched_job = job
                try:
                    return ex.execute(
                        subplan, preempt=preempt, query_span=query_span
                    )
                finally:
                    sched.finish(job)
            guard = (
                self._mesh_exec_lock if getattr(ex, "n", 1) > 1
                else contextlib.nullcontext()
            )
            with guard:
                return ex.execute(
                    subplan, preempt=preempt, query_span=query_span
                )
        failover_on = bool(
            getattr(self.session, "replica_failover_enabled", True)
        )
        steal_on = use_sched and bool(
            getattr(self.session, "mesh_steal_enabled", True)
        )
        tried: set = set()
        # membership-epoch fencing: a failover remembers the epoch it
        # faulted under; a resume target whose join_epoch moved past it
        # (the host left and rejoined — effectively a new host) is
        # refused typed and the query restarts fresh instead
        fault_key = None
        fault_epoch = rm.membership_epoch
        while True:
            rep = rm.place(exclude=tried)
            if rep is None:
                raise MeshDeviceLost(
                    "no schedulable replica "
                    f"(tried {sorted(tried)} of {rm.n_replicas})"
                )
            # exactly-one-owner: a query may never run on two replicas
            # at once, even across a membership flap — the claim stays
            # latched until the owning loop fully unwinds
            if not rm.claim(query_id, rep):
                rm.release(rep)
                raise MeshDeviceLost(
                    f"query {query_id!r} already owned by another "
                    "replica; refusing double placement"
                )
            if fault_key is not None:
                try:
                    rm.require_epoch(rep, fault_epoch)
                except MembershipEpochError:
                    # typed refusal consumed here: drop the stale
                    # checkpoint so the runner starts this replica's
                    # attempt from chunk 0 (restart, not resume)
                    from trino_tpu.recovery.checkpoint import CHECKPOINTS

                    CHECKPOINTS.discard(fault_key)
                    fault_key = None
            try:
                ex = MeshExecutor(
                    self.catalogs, self.session,
                    devices=rep.devices, replica_id=rep.replica_id,
                    drain_check=rm.drain_check(rep),
                )
                # one mesh program at a time per sub-mesh (see
                # Replica.exec_lock / Replica.scheduler); concurrent
                # queries spread across replicas via place() and queue
                # only when all are busy
                if use_sched:
                    sched = rep.scheduler
                    self._tune_scheduler(sched)
                    job = sched.submit(
                        query_id or "q?", group=group, fast=fast,
                        poll=preempt,
                    )
                    # a drain surfacing while queued (or parked) raises
                    # MeshReplicaDraining out of the wait — failover,
                    # not a grant on decommissioned capacity. The chunk
                    # runner acquires the seat at device-phase entry;
                    # host feed builds run before the grant
                    job.aux_check = rm.drain_check(rep)
                    ex.sched_job = job
                    try:
                        rows = ex.execute(
                            subplan, preempt=preempt,
                            query_span=query_span,
                        )
                    finally:
                        sched.finish(job)
                else:
                    with rep.exec_lock:
                        rows = ex.execute(
                            subplan, preempt=preempt,
                            query_span=query_span,
                        )
                rm.report_success(rep)
                return rows
            except (MeshStuck, MeshDeviceLost) as e:
                # a drain is a deliberate lifecycle maneuver, not a
                # health signal — it must not push the breaker open
                if not isinstance(e, MeshReplicaDraining):
                    rm.report_failure(rep)
                tried.add(rep.replica_id)
                fault_key = getattr(e, "ckpt_key", None)
                fault_epoch = rm.membership_epoch
                # host-loss failover: when the faulted replica's
                # checkpoint is not in the local store (the whole host
                # died), pull the last pushed snapshot from a fabric
                # peer before resuming
                from trino_tpu.recovery.checkpoint import CHECKPOINTS

                fab = active_fabric()
                if (
                    fault_key is not None
                    and fab is not None
                    and CHECKPOINTS.get(fault_key) is None
                ):
                    fab.try_pull(fault_key)
                have_sibling = any(
                    r.state == "active" and r.replica_id not in tried
                    for r in rm.replicas
                )
                if not failover_on or not have_sibling:
                    raise
                rm.note_failover(rep)
                if query_span is not None:
                    query_span.event(
                        "replica_failover",
                        from_replica=rep.replica_id,
                        error=type(e).__name__,
                        reason=str(e)[:300],
                    )
                if (
                    steal_on
                    and isinstance(e, MeshReplicaDraining)
                    and getattr(e, "steal_ok", False)
                    and getattr(e, "ckpt_key", None) is not None
                ):
                    rows = self._try_steal_dispatch(
                        subplan, preempt, query_span, e.ckpt_key,
                        rm, tried, fast, query_id, group,
                    )
                    if rows is not None:
                        return rows
            finally:
                rm.unclaim(query_id, rep)
                rm.release(rep)

    def _try_steal_dispatch(self, subplan, preempt, query_span, key,
                            rm, tried, fast, query_id, group):
        """Drain-failover work stealing: instead of resuming the
        drained query wholesale on one sibling, split its UNSTARTED
        chunk range [k0, K) at mid — the primary sibling resumes
        [k0, mid) from the host-portable checkpoint while a helper
        sibling computes [mid, K) from zero carries and publishes them;
        the primary merges the helper's packed rows at its mid boundary
        (byte-identical: append accumulators pack live rows in chunk
        order). Opportunistic end to end — returns None (the caller's
        failover loop resumes wholesale) when fewer than two siblings
        are placeable, the range is too small, or any stage falls
        apart."""
        import threading as _t

        from trino_tpu.parallel.mesh_chunk import (
            MeshDeviceLost,
            MeshStuck,
        )
        from trino_tpu.parallel.mesh_plan import MeshExecutor
        from trino_tpu.recovery.checkpoint import CHECKPOINTS

        ck = CHECKPOINTS.get(key)
        if ck is None or ck.n_chunks - ck.next_chunk < 2:
            return None
        prim = rm.place(exclude=tried)
        if prim is None:
            return None
        helper = rm.place(exclude=set(tried) | {prim.replica_id})
        if helper is None:
            rm.release(prim)
            return None
        k0, K = ck.next_chunk, ck.n_chunks
        mid = k0 + (K - k0 + 1) // 2
        steal_key = ("steal",) + tuple(key)
        done = _t.Event()
        caps = dict(ck.resolved_caps)
        try:
            ex_h = MeshExecutor(
                self.catalogs, self.session,
                devices=helper.devices, replica_id=helper.replica_id,
                drain_check=rm.drain_check(helper),
            )
            ex_h.steal_ctx = ("emit", mid, steal_key, done, caps)

            def run_helper():
                hjob = helper.scheduler.submit(
                    f"{query_id or 'q?'}-steal", group=group,
                )
                try:
                    helper.scheduler.acquire(hjob)
                    ex_h.execute(subplan)
                except Exception:
                    pass  # no publish; the primary runs [mid, K) itself
                finally:
                    helper.scheduler.finish(hjob)
                    done.set()

            th = _t.Thread(target=run_helper, daemon=True)
            th.start()
            ex_p = MeshExecutor(
                self.catalogs, self.session,
                devices=prim.devices, replica_id=prim.replica_id,
                drain_check=rm.drain_check(prim),
            )
            ex_p.steal_ctx = ("merge", mid, steal_key, done, caps, 120.0)
            job = prim.scheduler.submit(
                query_id or "q?", group=group, fast=fast, poll=preempt,
            )
            job.aux_check = rm.drain_check(prim)
            ex_p.sched_job = job
            try:
                rows = ex_p.execute(
                    subplan, preempt=preempt, query_span=query_span
                )
            finally:
                prim.scheduler.finish(job)
            th.join(timeout=10.0)
            rm.report_success(prim)
            stolen = int(ex_p.last_run.get("steals", 0) or 0)
            self._sched_steals += stolen
            if query_span is not None and stolen:
                query_span.event(
                    "work_steal",
                    primary=prim.replica_id, helper=helper.replica_id,
                    split_at=mid, of=K,
                )
            return rows
        except (MeshStuck, MeshDeviceLost):
            # the split dispatch itself faulted: hand back to the
            # wholesale failover loop (the checkpoint is still live)
            return None
        finally:
            CHECKPOINTS.discard(steal_key)
            rm.release(helper)
            rm.release(prim)

    def _record_mesh_fallback(self, reason: str, query_span=None) -> None:
        """One mesh->page fallback: bump the aggregate counter, latch
        the reason for QueryInfo/EXPLAIN, export a per-reason counter
        (mesh_fallbacks.{slug}) and drop an instant event on the query
        span so the trace timeline shows where the plane switched."""
        import re

        from trino_tpu.parallel.mesh_plan import bump_mesh_counter
        from trino_tpu.runtime.metrics import METRICS

        bump_mesh_counter("fallbacks")
        self.last_mesh_fallback = reason
        slug = re.sub(r"[^a-z0-9]+", "_", reason.lower()).strip("_")[:40]
        if slug:
            METRICS.increment(f"mesh_fallbacks.{slug}")
        if query_span is not None:
            query_span.event("mesh_fallback", reason=reason[:300])

    def _mesh_plane_line(self, subplan) -> str:
        """The EXPLAIN ANALYZE data-plane line: which plane `execute`
        would pick for this plan, decided STATICALLY (structural
        eligibility + collective census, no second execution) so the
        output is deterministic under program-cache hits."""
        if getattr(self.session, "retry_policy", "none") == "task":
            return "data_plane=fte"
        if not (self.session.mesh_execution and self._mesh_colocated()):
            return "data_plane=http"
        from trino_tpu.parallel.mesh_plan import (
            MeshUnsupported,
            mesh_eligibility,
        )

        try:
            info = mesh_eligibility(subplan)
        except MeshUnsupported as ex:
            self._record_mesh_fallback(str(ex))
            return f"data_plane=http (mesh fallback: {ex})"
        chunk_rows = int(getattr(self.session, "mesh_chunk_rows", 0) or 0)
        chunking = (
            f"chunk_rows={chunk_rows}" if chunk_rows > 0 else "unchunked"
        )
        return (
            f"data_plane=mesh (all_to_all={info['all_to_all']}, "
            f"all_gather={info['all_gather']}, {chunking})"
        )

    def _resident_line(self) -> str:
        """The EXPLAIN ANALYZE resident-tier line: current pin
        population and lifetime counter totals from the process
        singleton (what warm state a re-execution could reuse)."""
        from trino_tpu.resident import RESIDENT

        s = RESIDENT.stats()
        return (
            f"resident= entries={s['entries']} "
            f"pinned_bytes={s['pinned_bytes']} hits={s['hits']} "
            f"misses={s['misses']} pins={s['pins']} "
            f"evictions={s['evictions']} revocations={s['revocations']} "
            f"compactions={s['compactions']}"
        )

    def _recovery_line(self) -> str:
        """The EXPLAIN ANALYZE recovery-tier line: lifetime
        checkpoint/resume counters from the process singletons, plus
        the most recent mesh run's resume position when it resumed."""
        from trino_tpu.parallel.mesh_chunk import last_run_info
        from trino_tpu.recovery import CHECKPOINTS
        from trino_tpu.runtime.metrics import METRICS

        line = (
            f"recovery= checkpoints={CHECKPOINTS.taken} "
            f"resumes={CHECKPOINTS.resumed} "
            f"invalidations={CHECKPOINTS.invalidated} "
            f"spooled_stage_hits="
            f"{int(METRICS.counter('recovery.spooled_stage_hits'))}"
        )
        info = last_run_info()
        resumed = info.get("resumed_from_chunk")
        if resumed is not None:
            line += (
                f" resumed_from_chunk={resumed}/"
                f"{info.get('chunks')}"
            )
        return line

    def _skew_line(self) -> str:
        """The EXPLAIN ANALYZE skew-tier line: lifetime skew-plane
        counters — how often observed stats flagged a hot build key,
        how many exchange edges ran salted, MXU join-project
        selections, and build-overflow spill-mode re-plans."""
        from trino_tpu.runtime.metrics import METRICS

        s = METRICS.snapshot()

        def c(name):
            return int(s.get(f"skew.{name}", 0.0))

        return (
            f"skew= heavy_hitters_detected={c('heavy_hitters_detected')} "
            f"salted_exchanges={c('salted_exchanges')} "
            f"mxu_join_selected={c('mxu_join_selected')} "
            f"spill_mode_replans={c('spill_mode_replans')}"
        )

    def _replica_line(self) -> str:
        """The EXPLAIN ANALYZE replica-plane line: grid shape,
        per-replica lifecycle states (first letter each: a/s/d) and
        THIS runner's placement/failover counters — instance-scoped so
        corpus output stays deterministic across process reuse."""
        rm = self._replicas
        if rm is None:
            n = int(getattr(self.session, "mesh_replicas", 1) or 1)
            return f"replicas= n={n} (single mesh)"
        return rm.stats_line()

    def _scheduler_line(self) -> str:
        """The EXPLAIN ANALYZE preemptive-scheduler line: park/resume/
        preemption counters summed across this runner's schedulers (the
        single-mesh queue plus any replica run queues) and completed
        work-stealing dispatches — instance-scoped, like the replica
        line, so corpus output stays deterministic across process
        reuse."""
        scheds = []
        if self._mesh_scheduler is not None:
            scheds.append(self._mesh_scheduler)
        rm = self._replicas
        if rm is not None:
            scheds.extend(r.scheduler for r in rm.replicas)
        parks = sum(s.parks for s in scheds)
        resumes = sum(s.resumes for s in scheds)
        preempts = sum(s.preemptions for s in scheds)
        refusals = sum(s.park_refusals for s in scheds)
        return (
            f"scheduler= parks={parks} resumes={resumes} "
            f"preemptions={preempts} park_refusals={refusals} "
            f"steals={self._sched_steals}"
        )

    def _membership_line(self) -> str:
        """The EXPLAIN ANALYZE membership line: epoch and join/leave/
        fence counters of the replica plane's heartbeat-driven
        membership (runtime/fabric.py MembershipDriver) — instance-
        scoped like the replica line."""
        rm = self._replicas
        if rm is None:
            return "membership= epoch=0 (single mesh)"
        return rm.membership_line()

    def _concurrency_line(self) -> str:
        """The EXPLAIN ANALYZE concurrency line: live counts from the
        soundness plane (trino_tpu/analysis/) — registered witness
        locks, observed order edges, registered background threads, and
        lifetime witness violations (0 on a sound engine)."""
        from trino_tpu.analysis import concurrency_summary

        s = concurrency_summary()
        return (
            f"concurrency= locks={s['locks']} "
            f"order_edges={s['order_edges']} "
            f"threads_live={s['threads_live']} "
            f"threads_spawned={s['threads_spawned']} "
            f"witness={'on' if s['witness'] else 'off'} "
            f"violations={s['witness_violations']}"
        )

    def _explain_text(self, subplan) -> str:
        """Fragment rendering with per-fragment compile-churn census
        annotations (expected_xla_lowerings — sql/validate.py)."""
        return explain_distributed(
            subplan,
            catalogs=self.catalogs,
            batch_rows=self.session.batch_rows,
            dynamic_filtering=self.session.enable_dynamic_filtering,
            warn_threshold=getattr(
                self.session, "compile_churn_warn_threshold", 0
            ),
        )

    def _explain_analyze(self, subplan) -> MaterializedResult:
        """Distributed EXPLAIN ANALYZE: run the query with operator
        instrumentation on, pull each task's OperatorStats from its
        status (the TaskInfo aggregation path, Driver -> Task -> Stage),
        and render the fragment plan annotated with per-stage operator
        lines summed across that stage's tasks."""
        from trino_tpu.runtime.queryinfo import stage_text

        query_id = f"q{next(_query_counter)}"
        scheduler = QueryScheduler(
            query_id, subplan, self.workers, self.catalogs, self.session,
            self.hash_partitions, collect_stats=True,
        )
        try:
            root_handle, root_tid = scheduler.start()
            self._collect(scheduler, root_handle, root_tid)
            # the TaskInfo aggregation path (runtime/queryinfo.py):
            # merged per-stage operator lines through the shared
            # OperatorStats formatter PLUS the per-task summary lines
            # distributed EXPLAIN ANALYZE used to lose
            stages = self._stage_infos(scheduler.finalize())
            self._record_stage_divergences(subplan, stages)
            lines = [self._explain_text(subplan)]
            for stage in stages:
                lines.append(stage_text(stage))
            report = getattr(self, "_last_adaptive_report", None)
            if report is not None:
                lines.append("\n" + "\n".join(report.lines()))
            # which plane a plain `execute` of this statement would
            # take (the ANALYZE instrumentation itself runs the page
            # scheduler above either way, for the operator stats)
            lines.append(self._mesh_plane_line(subplan))
            lines.append(self._resident_line())
            lines.append(self._recovery_line())
            lines.append(self._skew_line())
            lines.append(self._replica_line())
            lines.append(self._scheduler_line())
            lines.append(self._membership_line())
            lines.append(self._concurrency_line())
            return MaterializedResult(
                [["\n".join(lines)]], ["Query Plan"], [T.VARCHAR]
            )
        finally:
            scheduler.abort()

    def _execute_fte(
        self, subplan, query_id=None, cancel=None, tq=None,
        trace=None, query_span=None, deadline_epoch_s=None,
    ) -> List[list]:
        """retry_policy=TASK: FTE over the spooled exchange."""
        import shutil
        import tempfile

        from trino_tpu.runtime.fte import FaultTolerantQueryScheduler
        from trino_tpu.runtime.spool import read_spool

        query_id = query_id or f"q{next(_query_counter)}"
        spool_dir = tempfile.mkdtemp(prefix=f"trino-tpu-spool-{query_id}-")
        try:
            scheduler = FaultTolerantQueryScheduler(
                query_id,
                subplan,
                self.workers,
                self.catalogs,
                self.session,
                spool_dir,
                self.hash_partitions,
                max_task_retries=self.session.task_retries,
                node_manager=self.node_manager,
                trace=trace,
                query_span=query_span,
                collect_stats=(
                    getattr(self.session, "query_trace", "off") == "on"
                ),
                deadline_epoch_s=deadline_epoch_s,
            )
            if tq is not None:
                # CPU budget over the FTE attempt ledgers (polled task
                # states carry cpu_s; finished attempts keep their last
                # reading in the scheduler's per-task dict)
                tq.cpu_time_fn = scheduler.cpu_time_s
            from trino_tpu.runtime.fte import TaskRetriesExceeded

            try:
                _, root_key = scheduler.run(cancel=cancel)
            except TaskRetriesExceeded as e:
                if "ExceededMemoryLimitError" in str(e) or (
                    "low-memory killer" in str(e)
                ):
                    from trino_tpu.runtime.memory import (
                        ExceededMemoryLimitError,
                    )

                    raise ExceededMemoryLimitError(str(e)) from e
                raise
            finally:
                # bounded-attempt observability, success or failure
                self.last_fte_stats = {
                    "retries": scheduler.retries,
                    "speculative_hits": scheduler.speculative_hits,
                    "speculation_wins": scheduler.speculation_wins,
                    "speculation_losses": scheduler.speculation_losses,
                    "attempts_per_partition": dict(
                        scheduler.attempts_per_partition
                    ),
                    # which quantile sized the straggler threshold, and
                    # the per-fragment wall-time estimates it produced
                    "speculation_percentile": (
                        scheduler.speculation_percentile
                    ),
                    "speculation_estimates": dict(
                        scheduler.speculation_estimates
                    ),
                }
                # QueryInfo stage rollups from the FTE attempt snapshots
                # (taken at each attempt's terminal observation)
                try:
                    self._last_stage_infos = self._stage_infos(
                        scheduler.task_snapshots()
                    )
                    self._record_stage_divergences(
                        subplan, self._last_stage_infos, query_span
                    )
                except Exception:
                    pass
            import os

            root_dir = os.path.join(spool_dir, root_key)
            rows: List[list] = []
            token = 0
            while True:
                pages, token, complete = read_spool(root_dir, 0, token)
                for page in pages:
                    rows.extend(_page_rows(page))
                if complete:
                    return rows
        finally:
            shutil.rmtree(spool_dir, ignore_errors=True)

    def _analyze(self, q: ast.Query, query_span=None):
        import contextlib

        from trino_tpu.sql.optimizer import (
            canonicalize_tstz_keys,
            optimize,
        )

        from trino_tpu.sql.analyzer import (
            set_session_info,
            set_session_zone,
        )

        def phase(name):
            if query_span is None:
                return contextlib.nullcontext()
            from trino_tpu.runtime.tracing import KIND_PHASE

            return query_span.child(name, KIND_PHASE)

        set_session_zone(self.session.timezone)
        set_session_info(
            self.session.catalog, self.session.schema, self.session.user
        )
        analyzer = Analyzer(
            self.catalogs, self.session.catalog, self.session.schema
        )
        with phase("analyze"):
            root = analyzer.plan(q)
        with phase("optimize"):
            root = optimize(root, self.catalogs, self.session)
            # correctness pass (was missing here while present on the
            # single-node path — found by the exchange-key validator:
            # distributed plans hashed tstz join/group keys with the
            # packed zone bits still set, splitting equal instants
            # across tasks)
            root = canonicalize_tstz_keys(root)
        if getattr(self.session, "plan_validation", "passes") != "off":
            from trino_tpu.sql.validate import validate_logical

            with phase("validate"):
                validate_logical(root, stage="canonicalize_tstz_keys")
        return root

    def _collect(
        self, scheduler: QueryScheduler, handle, tid,
        cancel=None, base_qid=None,
    ) -> List[list]:
        """Pull the root stage's single output partition (the
        Query.getNextResult / removePagesFromExchange path,
        server/protocol/Query.java:450)."""
        import time as _time

        from trino_tpu.runtime.metrics import METRICS

        rows: List[list] = []
        token = 0
        while True:
            if cancel is not None and cancel():
                # client abandonment: raising here unwinds into the
                # retry loop's finally — scheduler.abort() removes every
                # task, whose own finally closes its memory contexts, so
                # the pools ledger drains back to zero
                raise RuntimeError(
                    f"Query {scheduler.query_id} abandoned: client "
                    "stopped polling results"
                )
            # the status sweep is the pipelined scheduler's "tick" —
            # its duration distribution is the control-loop health gauge
            t_tick = _time.monotonic()
            if base_qid is not None:
                # deadline kills latch on the tracker before the failed
                # task states propagate — surface the typed error first
                self.query_tracker.check(base_qid)
            self._raise_if_failed(scheduler)
            METRICS.observe(
                "scheduler_tick_s", _time.monotonic() - t_tick
            )
            try:
                pages, token, complete = handle.get_results(
                    tid, 0, token, max_pages=16, wait=0.2
                )
            except Exception:
                # the root buffer can be aborted (low-memory kill, task
                # failure, DELETE /v1/query kill) BETWEEN the failure
                # check above and this fetch — surfacing as RuntimeError
                # in-process or as an HTTP 500 from a remote worker;
                # re-read task states so the query-level verdict carries
                # the real cause, not "buffer aborted"
                self._raise_if_failed(scheduler)
                raise
            for page in pages:
                rows.extend(_page_rows(page))
            if complete:
                # a kill can land between the sweep above and this
                # fetch's completion: a latched tracker error or a
                # failed task must win over a racy 'complete' — on the
                # pipelined plane a failed task always dooms the query,
                # so returning here would hand back a truncated result
                if base_qid is not None:
                    self.query_tracker.check(base_qid)
                self._raise_if_failed(scheduler)
                return rows

    # -- observability plane (QueryInfo registry + trace export) --

    def _stage_infos(self, states) -> List[dict]:
        """fragment id -> [(tid, status)] into StageInfo rollups, with
        per-stage wall-time histogram samples."""
        from trino_tpu.runtime.metrics import METRICS
        from trino_tpu.runtime.queryinfo import (
            build_stage_info,
            build_task_info,
        )

        infos = []
        for fid in sorted(states):
            task_infos = [
                build_task_info(tid, st) for tid, st in states[fid]
            ]
            expected = max(
                (int(st.get("expected_shape_classes") or 0)
                 for _, st in states[fid]),
                default=0,
            )
            info = build_stage_info(
                fid, task_infos, expected_lowerings=expected
            )
            if info["wall_s"] is not None:
                METRICS.observe("stage_wall_s", info["wall_s"])
            infos.append(info)
        return infos

    def _fragment_estimates(self, subplan) -> Dict[int, float]:
        """Optimizer row estimate per fragment root. RemoteSourceNode
        leaves resolve to the (already computed) producer-fragment
        estimates, so every stage diffs against the same numbers the
        fragmenter's partition-count decision used."""
        from trino_tpu.sql import plan as P
        from trino_tpu.sql.stats import PlanStats, StatsCalculator

        frag_rows: Dict[int, float] = {}

        class _FragmentStats(StatsCalculator):
            def _RemoteSourceNode(self, node):
                rows = sum(
                    frag_rows.get(fid, 1.0) for fid in node.fragment_ids
                )
                return PlanStats(max(rows, 1.0))

        calc = _FragmentStats(self.catalogs)

        def walk(sp):
            for c in sp.children:
                walk(c)
            frag_rows[sp.fragment.id] = calc.stats(
                sp.fragment.root
            ).row_count

        walk(subplan)
        return frag_rows

    @staticmethod
    def _stage_output_rows(stage: dict) -> Optional[int]:
        """Rows leaving the stage: what entered the terminal output/sink
        operator of the final pipeline (sinks emit no batches, so their
        input side IS the fragment's output)."""
        groups = stage.get("operator_summaries") or []
        for group in reversed(groups):
            if not group:
                continue
            last = group[-1]
            name = str(last.get("operator") or "")
            if "Output" in name or "Sink" in name:
                return int(last.get("input_rows") or 0)
            return int(last.get("output_rows") or 0)
        return None

    def _record_stage_divergences(
        self, subplan, stages, query_span=None
    ) -> None:
        """Per-fragment estimated_vs_observed: annotate the stage
        rollups (QueryInfo + distributed EXPLAIN ANALYZE render them),
        drop tracer instant events, and count adaptive.divergences.
        Recording is unconditional — divergence observability does not
        depend on adaptive_execution being on."""
        if not stages:
            return
        try:
            from trino_tpu.adaptive.observer import (
                estimated_vs_observed_line,
                record_observation,
            )

            estimates = self._fragment_estimates(subplan)
            threshold = float(
                getattr(self.session, "adaptive_replan_threshold", 4.0)
                or 4.0
            )
            for stage in stages:
                fid = stage.get("fragment_id")
                est = estimates.get(fid)
                observed = self._stage_output_rows(stage)
                if est is None or observed is None:
                    continue
                site = f"fragment:{fid}"
                ratio = record_observation(
                    site, est, observed, threshold, span=query_span
                )
                stage["estimated_vs_observed"] = estimated_vs_observed_line(
                    site, est, observed, ratio
                )
        except Exception:
            pass  # observability must never mask the verdict

    def _drain_query_peaks(self, base_qid: str) -> int:
        """Sum per-worker peak-memory watermarks for this query (every
        attempt namespace: qN, qNr1, ...) and retire them from in-process
        pools. Sum-of-per-worker-peaks is an upper bound on any single
        instant's cluster total — exact when one worker dominates."""
        total = 0
        for w in self.workers:
            pool = getattr(w, "memory_pool", None)
            if pool is not None:
                peaks = pool.query_peaks()
            else:
                try:
                    peaks = (w.status() or {}).get("query_peak_bytes")
                except Exception:
                    peaks = None
            if not peaks:
                continue
            keys = [
                k for k in peaks
                if k == base_qid or k.startswith(base_qid + "r")
            ]
            vals = [peaks[k] for k in keys]
            if vals:
                # attempts are sequential, so the query's peak in this
                # pool is the max attempt watermark, not their sum
                total += max(vals)
            if pool is not None:
                for k in keys:
                    pool.drop_query_peak(k)
        return total

    def _finalize_query(
        self, base_qid, sql, trace, qspan, status, failure_txt,
        rows_n, counters_before,
    ) -> None:
        """Close out the observability plane for one query (success OR
        failure): end the span tree, record histograms, retire per-query
        compile counters and memory watermarks, build the final
        QueryInfo into the bounded registry, and fire the enriched
        QueryCompletedEvent. Never raises — observability must not mask
        the query verdict."""
        try:
            from trino_tpu.exec.stats import engine_counters_delta
            from trino_tpu.runtime.events import QueryCompletedEvent
            from trino_tpu.runtime.metrics import (
                METRICS,
                retire_query_compiles,
            )
            from trino_tpu.runtime.query_tracker import deadline_code
            from trino_tpu.runtime.queryinfo import build_query_info

            qspan.set(state=status)
            qspan.end()
            trace.end_open_spans(qspan.end_s)
            wall = qspan.duration_s
            METRICS.observe("query_wall_s", wall)
            stages = self._last_stage_infos or []
            # recovery tier: a finished query's stage recordings (every
            # attempt namespace) are dead weight — drop them so the
            # recorder stays bounded by in-flight queries
            from trino_tpu.recovery import RECORDER

            RECORDER.purge(base_qid)
            compile_count = int(retire_query_compiles(base_qid))
            peak = self._drain_query_peaks(base_qid)
            counters = engine_counters_delta(
                counters_before, METRICS.snapshot()
            )
            err_code = None
            if failure_txt:
                err_code = deadline_code(failure_txt)
                if err_code is None and (
                    "ExceededMemoryLimitError" in failure_txt
                    or "low-memory killer" in failure_txt
                ):
                    err_code = "EXCEEDED_MEMORY_LIMIT"
            retry_count = max(0, self.last_query_attempts - 1)
            attempt_count = 1
            is_fte = (
                getattr(self.session, "retry_policy", "none") == "task"
            )
            if is_fte and self.last_fte_stats:
                app = (
                    self.last_fte_stats.get("attempts_per_partition")
                    or {}
                )
                attempt_count = sum(app.values()) or 1
            info = build_query_info(
                base_qid, status, sql=sql, wall_s=wall, stages=stages,
                peak_memory_bytes=peak, compile_count=compile_count,
                counters=counters, error_code=err_code,
                failure=failure_txt, retry_count=retry_count,
                attempt_count=attempt_count,
                data_plane=getattr(
                    self, "_last_data_plane", None
                ) or ("fte" if is_fte else "http"),
                mesh_fallback=self.last_mesh_fallback,
            )
            with self._lock:
                self._active_traces.pop(base_qid, None)
                self.last_query_id = base_qid
                self._completed_queries[base_qid] = {
                    "info": info, "trace": trace,
                }
                while (
                    len(self._completed_queries)
                    > self._completed_queries_cap
                ):
                    self._completed_queries.popitem(last=False)
            self.event_listeners.query_completed(QueryCompletedEvent(
                base_qid, sql, status, wall, rows=rows_n,
                failure=failure_txt,
                peak_memory_bytes=peak,
                rows_scanned=int(counters.get("rows_scanned", 0)),
                bytes_scanned=int(counters.get("bytes_scanned", 0)),
                rows_shuffled=int(counters.get("rows_shuffled", 0)),
                compile_count=compile_count,
                cpu_s=sum(s.get("cpu_s") or 0.0 for s in stages),
                error_code=err_code,
                retry_count=retry_count,
                attempt_count=attempt_count,
            ))
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "query observability finalization failed", exc_info=True
            )

    def query_info(self, query_id: str) -> Optional[dict]:
        """GET /v1/query/{id}: the final aggregated QueryInfo."""
        with self._lock:
            entry = self._completed_queries.get(query_id)
        return dict(entry["info"]) if entry else None

    def query_trace_export(self, query_id: str) -> Optional[dict]:
        """Structured span-list export (completed registry first, then
        in-flight traces — a running query serves a partial tree)."""
        with self._lock:
            entry = self._completed_queries.get(query_id)
            trace = (
                entry["trace"] if entry
                else self._active_traces.get(query_id)
            )
        return trace.export() if trace is not None else None

    def query_chrome_trace(self, query_id: str) -> Optional[dict]:
        """Perfetto-loadable Chrome trace-event rendering."""
        from trino_tpu.runtime.tracing import chrome_trace

        export = self.query_trace_export(query_id)
        if export is None:
            return None
        return {"traceEvents": chrome_trace(export)}

    @staticmethod
    def _raise_if_failed(scheduler: QueryScheduler) -> None:
        failed = scheduler.failed_tasks()
        if not failed:
            return
        msg = "; ".join(failed)
        from trino_tpu.runtime.query_tracker import (
            deadline_code,
            deadline_error,
        )

        if deadline_code(msg) is not None:
            # a QueryTracker kill message embeds its error code — the
            # query-level verdict is the typed, NON-RETRYABLE error, not
            # a generic task failure the retry layers would replay
            raise deadline_error("query failed: " + msg)
        if "ExceededMemoryLimitError" in msg or "low-memory killer" in msg:
            # memory kill is a QUERY-level verdict: the caller sees the
            # typed error while other queries (and the worker) keep
            # running
            from trino_tpu.runtime.memory import ExceededMemoryLimitError

            raise ExceededMemoryLimitError("query failed: " + msg)
        raise RuntimeError("query failed: " + msg)


def _scheduler_cpu_s(scheduler) -> float:
    """Aggregate a pipelined attempt's task CPU ledgers (the `cpu_s`
    field every status poll carries) — the query_max_cpu_time_s input."""
    total = 0.0
    for ts in scheduler.tasks.values():
        for handle, tid in ts:
            try:
                total += float(
                    handle.task_state(tid).get("cpu_s") or 0.0
                )
            except Exception:
                pass  # vanished task: its CPU is unknowable, not fatal
    return total


def _page_rows(page: Page) -> List[list]:
    """Decode a wire page to python rows (host-side, no device round
    trip) via the shared decode rules."""
    import numpy as np

    from trino_tpu.block import decode_values

    from trino_tpu.exec.serde import HostNested

    cols = []
    for t, data, valid, dvals in zip(
        page.types, page.columns, page.valids, page.dictionaries
    ):
        if isinstance(data, HostNested):
            cols.append(data.to_pylist())
            continue
        ok = valid if valid is not None else np.ones(len(data), dtype=bool)
        cols.append(decode_values(t, data, ok, dvals))
    return [list(r) for r in zip(*cols)] if cols else []
