"""Process-wide metrics registry.

Analogue of the reference's JMX metrics surface (airlift @Managed beans
exported through the jmx connector / GET /v1/jmx/mbean): named counters
and gauges that subsystems bump, snapshotted as JSON by the
coordinator's `/v1/metrics` endpoint. Counters are monotonically
increasing; gauges are set-to-current.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict


class MetricsRegistry:
    def __init__(self):
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def increment(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        """fn is evaluated at snapshot time (@Managed getter analogue)."""
        with self._lock:
            self._gauges[name] = fn

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            gauges = list(self._gauges.items())
        for name, fn in gauges:
            try:
                out[name] = float(fn())
            except Exception:
                pass  # a failing gauge must not poison the snapshot
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


# the process singleton (MBeanServer analogue)
METRICS = MetricsRegistry()


# -- per-query compile attribution -------------------------------------
#
# jax.monitoring compile events carry a duration but no originating jit
# name, and naming wrappers per query would split the persistent-cache
# key space (defeating cross-query executable reuse). Instead the
# execution paths bracket their dispatch with set_compile_attribution
# and the listener charges each compile to whichever query id the
# *compiling thread* is running — correct because backend compiles
# happen synchronously on the dispatching thread.
_attribution = threading.local()


def set_compile_attribution(query_id) -> object:
    """Tag this thread's subsequent XLA compiles with `query_id`
    (None to clear). Returns the previous tag so callers can restore
    it in a finally block."""
    prev = getattr(_attribution, "query_id", None)
    _attribution.query_id = query_id
    return prev


def compile_attribution():
    return getattr(_attribution, "query_id", None)


_xla_listener_installed = False


def install_xla_compile_listener() -> bool:
    """Bump the `xla_compiles` counter on every backend compile via
    jax.monitoring. NOTE: this counts ALL compiles in the process —
    jax-internal helper jits (jnp.zeros, barriers) included — so it is a
    visibility counter for spotting churn trends, not a per-query
    cache-miss count; the per-query expected-vs-observed comparison uses
    the shape-class ledger (exec/stats.py), which shares a vocabulary
    with the static census (sql/validate.py). Idempotent; returns False
    when this jax build has no monitoring hooks."""
    global _xla_listener_installed
    if _xla_listener_installed:
        return True
    try:
        from jax import monitoring

        def _on_event(event: str, duration: float, **kw) -> None:
            if event == "/jax/core/compile/backend_compile_duration":
                METRICS.increment("xla_compiles")
                qid = compile_attribution()
                if qid is not None:
                    METRICS.increment(f"xla_compiles_by_query.{qid}")

        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:
        return False
    _xla_listener_installed = True
    return True
