"""Process-wide metrics registry.

Analogue of the reference's JMX metrics surface (airlift @Managed beans
exported through the jmx connector / GET /v1/jmx/mbean): named counters,
gauges, and fixed-bucket distributions (CounterStat / DistributionStat /
TimeStat) that subsystems bump, snapshotted as JSON by the coordinator's
`/v1/metrics` endpoint. Counters are monotonically increasing; gauges
are set-to-current; distributions expose count/total/min/max and
p50/p95/p99 quantile estimates.
"""

from __future__ import annotations

import math
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Callable, Dict, List, Optional


class Distribution:
    """Fixed-bucket histogram (DistributionStat/TimeStat analogue).

    Buckets are geometric — powers of two over 1e-6..~5e5 in whatever
    unit the caller observes (seconds here) — so one layout serves
    microsecond page pulls and hour-long queries. Quantiles come from
    the bucket upper edge the cumulative count crosses, clamped to the
    exact observed min/max; for a fixed-bucket sketch that bounds the
    error at one bucket width (~2x), which is what p50-vs-p99 gating
    needs. All-zero-cost: add() is two dict-free array ops under the
    registry lock."""

    _LO = 1e-6
    _N = 40  # 1µs * 2^39 ≈ 6.4 days — saturates the top bucket beyond

    __slots__ = ("counts", "count", "total", "min", "max")

    def __init__(self):
        self.counts = [0] * self._N
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def add(self, value: float) -> None:
        v = float(value)
        if v <= self._LO:
            idx = 0
        else:
            idx = min(self._N - 1, 1 + int(math.log2(v / self._LO)))
        self.counts[idx] += 1
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def _edge(self, idx: int) -> float:
        return self._LO * (2.0 ** idx)

    def percentile(self, p: float) -> float:
        if self.count == 0:
            return 0.0
        target = p * self.count
        seen = 0
        for idx, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                hi = min(self._edge(idx), self.max)
                return max(hi, self.min)
        return self.max or 0.0

    def summary(self) -> Dict[str, float]:
        return {
            "count": float(self.count),
            "total": self.total,
            "avg": self.total / self.count if self.count else 0.0,
            "min": self.min or 0.0,
            "max": self.max or 0.0,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


class MetricsRegistry:
    def __init__(self):
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._distributions: Dict[str, Distribution] = {}
        self._lock = named_lock("MetricsRegistry._lock")

    def increment(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        """fn is evaluated at snapshot time (@Managed getter analogue)."""
        with self._lock:
            self._gauges[name] = fn

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the named distribution."""
        with self._lock:
            dist = self._distributions.get(name)
            if dist is None:
                dist = self._distributions[name] = Distribution()
            dist.add(value)

    def distribution(self, name: str) -> Optional[Dict[str, float]]:
        with self._lock:
            dist = self._distributions.get(name)
            return dist.summary() if dist is not None else None

    # -- retention ------------------------------------------------------
    #
    # Per-query counters (xla_compiles_by_query.{qid}) would otherwise
    # accumulate one entry per query for the life of the process; the
    # coordinator retires them into the query's final QueryInfo at
    # completion and prunes here, keeping the registry bounded.

    def remove(self, name: str) -> float:
        """Drop one counter, returning its final value (0.0 if absent)."""
        with self._lock:
            return self._counters.pop(name, 0.0)

    def remove_prefix(self, prefix: str) -> Dict[str, float]:
        """Drop every counter and distribution whose name starts with
        `prefix`; returns the removed counters' final values."""
        with self._lock:
            removed = {
                k: self._counters.pop(k)
                for k in [k for k in self._counters if k.startswith(prefix)]
            }
            for k in [k for k in self._distributions
                      if k.startswith(prefix)]:
                del self._distributions[k]
            return removed

    def counter_names(self) -> List[str]:
        with self._lock:
            return list(self._counters)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            gauges = list(self._gauges.items())
            dists = [(n, d.summary()) for n, d in
                     self._distributions.items()]
        for name, summary in dists:
            for stat, v in summary.items():
                out[f"{name}.{stat}"] = v
        for name, fn in gauges:
            try:
                out[name] = float(fn())
            except Exception:
                pass  # a failing gauge must not poison the snapshot
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._distributions.clear()


# the process singleton (MBeanServer analogue)
METRICS = MetricsRegistry()


# -- per-query compile attribution -------------------------------------
#
# jax.monitoring compile events carry a duration but no originating jit
# name, and naming wrappers per query would split the persistent-cache
# key space (defeating cross-query executable reuse). Instead the
# execution paths bracket their dispatch with set_compile_attribution
# and the listener charges each compile to whichever query id the
# *compiling thread* is running — correct because backend compiles
# happen synchronously on the dispatching thread.
_attribution = threading.local()


def set_compile_attribution(query_id) -> object:
    """Tag this thread's subsequent XLA compiles with `query_id`
    (None to clear). Returns the previous tag so callers can restore
    it in a finally block."""
    prev = getattr(_attribution, "query_id", None)
    _attribution.query_id = query_id
    return prev


def compile_attribution():
    return getattr(_attribution, "query_id", None)


def retire_query_compiles(query_id) -> float:
    """Pull a query's compile-attribution counters out of the registry
    (base id plus every `{qid}r*` QUERY-retry namespace) and return the
    summed count, for retirement into the final QueryInfo. Exact-match
    plus an `r`-suffix prefix so q3 never swallows q30's counters."""
    total = METRICS.remove(f"xla_compiles_by_query.{query_id}")
    total += sum(
        METRICS.remove_prefix(f"xla_compiles_by_query.{query_id}r").values()
    )
    return total


_xla_listener_installed = False


def install_xla_compile_listener() -> bool:
    """Bump the `xla_compiles` counter on every backend compile via
    jax.monitoring. NOTE: this counts ALL compiles in the process —
    jax-internal helper jits (jnp.zeros, barriers) included — so it is a
    visibility counter for spotting churn trends, not a per-query
    cache-miss count; the per-query expected-vs-observed comparison uses
    the shape-class ledger (exec/stats.py), which shares a vocabulary
    with the static census (sql/validate.py). Idempotent; returns False
    when this jax build has no monitoring hooks."""
    global _xla_listener_installed
    if _xla_listener_installed:
        return True
    try:
        from jax import monitoring

        def _on_event(event: str, duration: float, **kw) -> None:
            if event == "/jax/core/compile/backend_compile_duration":
                METRICS.increment("xla_compiles")
                METRICS.observe("xla_compile_duration_s", duration)
                qid = compile_attribution()
                if qid is not None:
                    METRICS.increment(f"xla_compiles_by_query.{qid}")

        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:
        return False
    _xla_listener_installed = True
    return True
