"""Process-wide metrics registry.

Analogue of the reference's JMX metrics surface (airlift @Managed beans
exported through the jmx connector / GET /v1/jmx/mbean): named counters
and gauges that subsystems bump, snapshotted as JSON by the
coordinator's `/v1/metrics` endpoint. Counters are monotonically
increasing; gauges are set-to-current.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict


class MetricsRegistry:
    def __init__(self):
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def increment(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + delta

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        """fn is evaluated at snapshot time (@Managed getter analogue)."""
        with self._lock:
            self._gauges[name] = fn

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            gauges = list(self._gauges.items())
        for name, fn in gauges:
            try:
                out[name] = float(fn())
            except Exception:
                pass  # a failing gauge must not poison the snapshot
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


# the process singleton (MBeanServer analogue)
METRICS = MetricsRegistry()
