"""Request error tracking: bounded exponential backoff for inter-node calls.

Analogue of main/server/remotetask/RequestErrorTracker.java (SURVEY.md
§5.3): every coordinator->worker and worker->worker request retries
transient failures with exponential backoff + jitter, accumulates the
failures it saw, and — once a per-destination error budget or the hard
deadline is spent — fails the REQUEST with the full failure history
attached. The caller (remote-task client, exchange puller) then fails
the TASK, never the whole query: FTE re-placement and query-retry
policies decide what happens next.

Determinism: jitter draws from a per-tracker `random.Random` seeded from
the destination string unless an explicit seed is given, so the chaos
harness (runtime/chaos.py) replays identical backoff schedules for a
fixed seed.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
import urllib.error
from typing import Callable, List, Optional


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Knobs for one class of inter-node request (config surface
    documented in README "Fault tolerance")."""

    # hard deadline: total seconds of accumulated failure before the
    # request is declared dead (query.remote-task.max-error-duration)
    max_error_duration_s: float = 30.0
    # error budget: max failures per destination per request loop
    # (0 = unbounded within the deadline)
    max_errors: int = 0
    min_backoff_s: float = 0.01
    max_backoff_s: float = 1.0
    backoff_factor: float = 2.0
    # each sleep is scaled by a uniform draw from [1-jitter, 1+jitter]
    jitter: float = 0.25


# a fast-test policy the in-process topologies use; HTTP clients default
# to the production-shaped one above
FAST_RETRY = RetryPolicy(
    max_error_duration_s=5.0, min_backoff_s=0.005, max_backoff_s=0.1
)


class RequestFailedError(RuntimeError):
    """Raised when a request's error budget/deadline is exhausted. The
    receiving scheduler fails the task (and re-places it), not the
    query."""

    def __init__(self, destination: str, failures: List[BaseException]):
        self.destination = destination
        self.failures = list(failures)
        summary = "; ".join(
            f"{type(e).__name__}: {e}" for e in self.failures[-3:]
        )
        super().__init__(
            f"request to {destination} failed after "
            f"{len(self.failures)} attempts: {summary}"
        )


def is_transient(exc: BaseException) -> bool:
    """Retryable failure classification: network-level errors and
    service-unavailable responses retry. Plain 500s carry engine
    application errors (a failed plan re-fails identically) and 4xx are
    protocol errors — retrying fixes neither."""
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in (429, 502, 503, 504)
    return isinstance(
        exc, (urllib.error.URLError, ConnectionError, OSError, TimeoutError)
    )


class RequestErrorTracker:
    """Per-request retry loop state for one destination.

    Usage::

        tracker = RequestErrorTracker("http://w1", policy)
        while True:
            try:
                resp = do_request()
                tracker.on_success()
                return resp
            except Exception as e:
                tracker.on_failure(e)   # sleeps, or raises
                                        # RequestFailedError when spent
    """

    def __init__(
        self,
        destination: str,
        policy: Optional[RetryPolicy] = None,
        seed: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        listener=None,
    ):
        self.destination = destination
        self.policy = policy or RetryPolicy()
        self._rng = random.Random(
            seed if seed is not None else hash(destination) & 0xFFFFFFFF
        )
        self._clock = clock
        self._sleep = sleep
        self._listener = listener  # e.g. a NodeManager breaker hook
        self.failures: List[BaseException] = []
        self._started: Optional[float] = None
        self._attempt = 0

    def backoff_s(self) -> float:
        p = self.policy
        base = min(
            p.max_backoff_s,
            p.min_backoff_s * (p.backoff_factor ** max(self._attempt - 1, 0)),
        )
        if p.jitter <= 0:
            return base
        return base * self._rng.uniform(1 - p.jitter, 1 + p.jitter)

    def on_success(self) -> None:
        self.failures.clear()
        self._started = None
        self._attempt = 0
        if self._listener is not None:
            self._listener.report_success(self.destination)

    def on_failure(self, exc: BaseException) -> None:
        """Record a failure; either sleep the next backoff or raise
        RequestFailedError once the budget/deadline is spent. Protocol
        (non-transient) errors propagate immediately."""
        if self._listener is not None:
            self._listener.report_failure(self.destination)
        if not is_transient(exc):
            raise exc
        now = self._clock()
        if self._started is None:
            self._started = now
        self.failures.append(exc)
        self._attempt += 1
        p = self.policy
        spent_budget = p.max_errors and len(self.failures) >= p.max_errors
        spent_time = now - self._started >= p.max_error_duration_s
        if spent_budget or spent_time:
            raise RequestFailedError(self.destination, self.failures) from exc
        self._sleep(self.backoff_s())


class DestinationErrorStats:
    """Cluster-wide per-destination error counters (observability: the
    /v1/cluster surface and the chaos harness read these to assert
    bounded attempt counts)."""

    def __init__(self):
        self._lock = named_lock("DestinationErrorStats._lock")
        self._errors: dict = {}
        self._requests: dict = {}

    def record(self, destination: str, ok: bool) -> None:
        with self._lock:
            self._requests[destination] = self._requests.get(destination, 0) + 1
            if not ok:
                self._errors[destination] = self._errors.get(destination, 0) + 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                d: {"requests": self._requests.get(d, 0),
                    "errors": self._errors.get(d, 0)}
                for d in self._requests
            }


#: process-wide stats instance the HTTP client and exchange pullers feed
REQUEST_STATS = DestinationErrorStats()


def run_with_retry(
    destination: str,
    fn: Callable[[], object],
    policy: Optional[RetryPolicy] = None,
    seed: Optional[int] = None,
    listener=None,
):
    """The standard retry loop: call `fn` until success, transient
    failures backing off per `policy`; raises RequestFailedError when
    the budget/deadline is spent, or the original error when it is not
    retryable."""
    tracker = RequestErrorTracker(
        destination, policy, seed=seed, listener=listener
    )
    while True:
        try:
            out = fn()
        except BaseException as e:
            REQUEST_STATS.record(destination, ok=False)
            tracker.on_failure(e)
            continue
        REQUEST_STATS.record(destination, ok=True)
        tracker.on_success()
        return out
