"""Worker: the per-host task manager.

Analogue of main/execution/SqlTaskManager.java:109 (updateTask:466 —
idempotent task creation, local planning, driver execution) plus the
results side of TaskResource. The same object serves the in-process
topology (coordinator holds a direct reference — the tier-3
DistributedQueryRunner arrangement) and the HTTP server (worker_http
wraps these methods behind /v1/task endpoints).
"""

from __future__ import annotations

import threading
from trino_tpu.analysis import threadreg
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import Dict, List, Optional, Tuple

from trino_tpu.connectors.spi import CatalogManager
from trino_tpu.runtime.task import TaskExecution, TaskId, TaskSpec


class WorkerShuttingDownError(RuntimeError):
    """Raised by create_task on a draining worker. Schedulers treat it
    like any launch failure and re-place the task on another node; it is
    NOT transient (the worker will never accept the launch), so the HTTP
    layer maps it to a non-retryable status code."""


class Worker:
    def __init__(
        self,
        worker_id: str,
        catalogs: Optional[CatalogManager] = None,
        failure_injector=None,
        memory_pool_bytes: Optional[int] = None,
        location: Optional[str] = None,
        stuck_task_interrupt_s: Optional[float] = None,
        stuck_task_interrupt_warm_s: Optional[float] = None,
    ):
        self.worker_id = worker_id
        # "rack/host" network coordinate (the ICI-island id on a TPU
        # pod); workers carrying one get topology-aware placement
        self.location = location
        # lifecycle (DiscoveryNodeManager's ACTIVE/SHUTTING_DOWN): a
        # draining worker refuses new task launches while running tasks
        # finish and already-produced output stays readable
        self.state = "active"  # active | shutting_down
        self.catalogs = catalogs or CatalogManager()
        self.failure_injector = failure_injector
        self.memory_pool = None
        if memory_pool_bytes is not None:
            from trino_tpu.runtime.memory import MemoryPool

            self.memory_pool = MemoryPool(memory_pool_bytes)
        self._tasks: Dict[str, TaskExecution] = {}
        self._lock = named_lock("Worker._lock")
        # stuck-task watchdog (StuckSplitTasksInterrupter analogue):
        # interrupt any RUNNING task whose per-batch heartbeat is older
        # than this; the failure is RETRYABLE (unlike deadline kills)
        self.stuck_task_interrupt_s = stuck_task_interrupt_s
        # tighter threshold for tasks whose predicted shape classes are
        # all warm (warmup/cache hits or a prior completed run): no
        # first-batch compile stall is possible, so a shorter silence
        # already proves the task is stuck
        self.stuck_task_interrupt_warm_s = stuck_task_interrupt_warm_s
        self.watchdog_interrupts: List[Tuple[str, str]] = []
        self._watchdog_thread: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()

    # -- graceful drain (GracefulShutdownHandler analogue) --
    def shutdown_gracefully(self) -> None:
        """Enter SHUTTING_DOWN: every later create_task is refused (the
        scheduler re-places those partitions); tasks already running
        finish normally and their results/spool stay readable."""
        with self._lock:
            self.state = "shutting_down"

    def running_tasks(self) -> int:
        """Tasks not yet in a terminal state — the drain waiter's
        completion condition (finished/failed/aborted tasks stay
        registered so status and results remain readable)."""
        with self._lock:
            tasks = list(self._tasks.values())
        return sum(
            1 for t in tasks
            if t.state not in ("finished", "failed", "aborted")
        )

    # -- stuck-task watchdog (StuckSplitTasksInterrupter analogue) --
    def watchdog_once(self, now: Optional[float] = None) -> List[str]:
        """One watchdog sweep: interrupt every running task whose batch
        heartbeat is staler than stuck_task_interrupt_s. Returns the
        diagnostics raised this sweep; they also accumulate in
        `watchdog_interrupts` as (task_id, diagnostic) for tests and the
        chaos harness. Explicit-tick twin of start_watchdog, mirroring
        NodeManager.ping_once."""
        if not (self.stuck_task_interrupt_s or self.stuck_task_interrupt_warm_s):
            return []
        with self._lock:
            tasks = list(self._tasks.values())
        fired: List[str] = []
        for t in tasks:
            timeout = self._watchdog_timeout(t)
            if not timeout:
                continue
            diag = t.interrupt_if_stuck(timeout, now=now)
            if diag is not None:
                fired.append(diag)
                self.watchdog_interrupts.append((str(t.spec.task_id), diag))
        return fired

    def _watchdog_timeout(self, task) -> Optional[float]:
        """Per-task threshold: the warm threshold applies only when the
        task's predicted shape classes are ALL warm; otherwise fall back
        to the conservative stuck_task_interrupt_s (which may be unset —
        then warm-only watching still works)."""
        if self.stuck_task_interrupt_warm_s and getattr(
            task, "shapes_warm", False
        ):
            return self.stuck_task_interrupt_warm_s
        return self.stuck_task_interrupt_s

    def start_watchdog(self, poll_s: float = 0.01) -> None:
        if self._watchdog_thread is not None or not (
            self.stuck_task_interrupt_s or self.stuck_task_interrupt_warm_s
        ):
            return
        self._watchdog_stop.clear()

        def loop():
            while not self._watchdog_stop.wait(poll_s):
                self.watchdog_once()

        self._watchdog_thread = threadreg.spawn(
            f"watchdog-{self.worker_id}", loop, owner="Worker"
        )

    def stop_watchdog(self) -> None:
        if self._watchdog_thread is None:
            return
        self._watchdog_stop.set()
        self._watchdog_thread.join(5)
        self._watchdog_thread = None

    # -- task lifecycle (SqlTaskManager.updateTask) --
    def create_task(self, spec: TaskSpec) -> TaskExecution:
        key = str(spec.task_id)
        with self._lock:
            if self.state != "active":
                raise WorkerShuttingDownError(
                    f"worker {self.worker_id} is shutting down"
                )
            existing = self._tasks.get(key)
            if existing is not None:
                return existing  # idempotent re-delivery
            task = TaskExecution(
                spec, self.catalogs, self.failure_injector, self.memory_pool
            )
            self._tasks[key] = task
        task.start()
        return task

    def get_task(self, task_id) -> TaskExecution:
        return self._tasks[str(task_id)]

    def task_state(self, task_id) -> dict:
        t = self._tasks[str(task_id)]
        # cpu_s rides along in every status poll so the coordinator's
        # QueryTracker can sum per-task CPU ledgers into the
        # query_max_cpu_time_s budget without an extra endpoint
        out = {"state": t.state, "failure": t.failure,
               "cpu_s": t.cpu_time_s()}
        stats = t.operator_stats()
        if stats is not None:
            out["stats"] = stats
        # TaskInfo observability surface: wall bounds + lowering counts
        if t.start_time is not None:
            out["start_time"] = t.start_time
        if t.end_time is not None:
            out["end_time"] = t.end_time
        out["shape_classes"] = t.observed_shape_classes()
        out["expected_shape_classes"] = t.expected_shape_classes()
        # operator spans ship only once the task is TERMINAL: grafting a
        # still-open span would poison the coordinator's closed tree
        if t.state in ("finished", "failed", "aborted"):
            spans = t.trace_spans()
            if spans is not None:
                out["spans"] = spans
        return out

    def get_results(
        self, task_id, partition: int, token: int,
        max_pages: int = 16, wait: float = 0.0,
    ):
        return self._tasks[str(task_id)].buffer.get_pages(
            partition, token, max_pages, wait
        )

    def remove_task(self, task_id) -> None:
        with self._lock:
            t = self._tasks.pop(str(task_id), None)
        if t is not None:
            t.abort()

    def abort_query(self, query_id: str) -> None:
        with self._lock:
            doomed = [
                k for k in self._tasks if k.startswith(query_id + ".")
            ]
            tasks = [self._tasks.pop(k) for k in doomed]
        for t in tasks:
            t.abort()

    def fail_query(self, query_id: str, message: str) -> None:
        """Low-memory-killer entry point: mark every task of the query
        FAILED with the kill message (so the coordinator's poll sees a
        query-level memory error, not a vanished task) and abort their
        buffers to unblock consumers. Tasks stay registered until
        remove_task/abort_query — status must remain readable."""
        with self._lock:
            tasks = [
                t for k, t in self._tasks.items()
                if k.startswith(query_id + ".")
            ]
        for t in tasks:
            t.fail(message)

    def task_ids(self) -> List[str]:
        with self._lock:
            return list(self._tasks)

    # -- handle API shared with HttpWorkerClient --
    def results_location(self, task_id):
        """Fetch handle consumers put into TaskSpec.input_locations:
        in-process = the buffer's bound method (zero-copy)."""
        return self._tasks[str(task_id)].buffer.get_pages

    def status(self) -> dict:
        out = {
            "worker_id": self.worker_id,
            "state": self.state,
            "tasks": len(self.task_ids()),
            "running": self.running_tasks(),
        }
        if self.memory_pool is not None:
            # per-query peak watermarks for QueryInfo.peak_memory_bytes
            out["query_peak_bytes"] = self.memory_pool.query_peaks()
        return out


def install_sigterm_self_drain(workers) -> Optional[object]:
    """Route SIGTERM into graceful drain (GracefulShutdownHandler wired
    to the JVM shutdown hook): on the signal every worker in `workers`
    flips to SHUTTING_DOWN — new launches refused, running tasks finish,
    results stay readable — instead of dying mid-task. Returns the
    previous handler (restore it in tests), or None when not on the main
    thread (signal.signal is main-thread-only; embedded runners then
    call shutdown_gracefully directly)."""
    import signal

    workers = list(workers)

    def handler(signum, frame):
        for w in workers:
            w.shutdown_gracefully()

    try:
        return signal.signal(signal.SIGTERM, handler)
    except ValueError:
        return None
