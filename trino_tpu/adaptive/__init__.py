"""Adaptive execution tier: estimate -> observe -> re-plan.

Closes the loop between the optimizer's estimates (sql/stats.py), the
truth observed at materialization barriers, and the plan that executes
the remaining work. Three cooperating pieces:

- spool.py: SpooledValuesNode (a ValuesNode carrying exact observed
  stats) + the generation-guarded SubtreeSpool that caches materialized
  subtrees across consumers and executions.
- observer.py: observed-stats snapshots (rows / NDV / heavy hitters),
  divergence math, and the shared recording protocol (tracer instant
  events + the adaptive.{replans,divergences,spool_hits} counters).
- controller.py: the AdaptiveController that materializes barriers
  (completed build sides, shared subtrees), diffs observed vs estimated
  stats, and re-optimizes the remaining plan when divergence crosses
  `adaptive_replan_threshold` — completed work is substituted back as
  literal sources so it is never redone.
"""

from trino_tpu.adaptive.controller import AdaptiveController, AdaptiveReport
from trino_tpu.adaptive.observer import (
    ObservedStats,
    divergence_ratio,
    observe_rows,
    record_observation,
)
from trino_tpu.adaptive.spool import (
    SPOOL,
    SpooledValuesNode,
    SubtreeSpool,
    plan_fingerprint,
)

__all__ = [
    "AdaptiveController",
    "AdaptiveReport",
    "ObservedStats",
    "divergence_ratio",
    "observe_rows",
    "record_observation",
    "SPOOL",
    "SpooledValuesNode",
    "SubtreeSpool",
    "plan_fingerprint",
]
