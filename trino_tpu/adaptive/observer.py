"""Observed-stats collection + divergence recording.

One snapshot per materialization barrier: row count, per-channel NDV
and the heavy-hitter (modal key) count — the JSPIM-motivated skew
signal. Divergence is the symmetric ratio max(est,obs)/min(est,obs),
so a 100x under- and a 100x over-estimate read the same. Recording is
shared by every barrier kind (completed build sides, shared-subtree
spools, distributed stage roots, mesh prelude exports): a tracer
instant event + the `adaptive.divergences` counter when the ratio
crosses the session threshold — divergence is always RECORDED; only
re-planning is gated on `adaptive_execution`."""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Dict, Optional, Sequence, Tuple

from trino_tpu.sql.stats import ColStats, PlanStats

# heavy-hitter candidates retained per channel: replicating more than a
# handful of hot build keys approaches a broadcast join, which the
# planner would have chosen outright if it were profitable
MAX_HOT_KEYS = 4


@dataclasses.dataclass
class ObservedStats:
    rows: int
    ndv: Dict[int, int]  # channel -> distinct non-null values
    heavy_hitter: Dict[int, int]  # channel -> modal value count
    # channel -> ((value, count), ...) for the top values, so the skew
    # classifier can name WHICH keys are hot, not just how hot the
    # modal one is
    hot: Dict[int, Tuple[Tuple[object, int], ...]] = dataclasses.field(
        default_factory=dict
    )

    def plan_stats(self) -> PlanStats:
        """Exact PlanStats for re-optimization seeding (low/high ride
        along when the channel values are orderable numbers)."""
        cols = {
            ch: ColStats(ndv=float(n)) for ch, n in self.ndv.items()
        }
        return PlanStats(float(self.rows), cols)


def observe_rows(
    rows: Sequence[Sequence[object]],
    channels: Optional[Sequence[int]] = None,
    ndv_channel_cap: int = 8,
) -> ObservedStats:
    """Host-side snapshot over materialized python rows. `channels`
    bounds the per-channel work (join keys first); default: the first
    `ndv_channel_cap` channels."""
    n = len(rows)
    width = len(rows[0]) if n else 0
    if channels is None:
        channels = range(min(width, ndv_channel_cap))
    ndv: Dict[int, int] = {}
    hh: Dict[int, int] = {}
    hot: Dict[int, Tuple[Tuple[object, int], ...]] = {}
    for ch in channels:
        if ch >= width:
            continue
        counts = Counter(r[ch] for r in rows if r[ch] is not None)
        ndv[ch] = len(counts)
        hh[ch] = max(counts.values()) if counts else 0
        hot[ch] = tuple(counts.most_common(MAX_HOT_KEYS))
    return ObservedStats(n, ndv, hh, hot)


def hot_keys(
    obs: ObservedStats, channel: int, threshold: float
) -> Tuple[object, ...]:
    """Heavy-hitter classification (the JSPIM skew test): key values
    whose observed count is at least `threshold` of the rows. Hot keys
    must be plain hashable scalars — integer join keys in practice —
    because they are carried on the plan node and compared against the
    key column at trace time."""
    if obs.rows <= 0 or threshold <= 0.0:
        return ()
    return tuple(
        v
        for v, c in obs.hot.get(channel, ())
        if c >= threshold * obs.rows and isinstance(v, int)
        and not isinstance(v, bool)
    )


def divergence_ratio(estimated: float, observed: float) -> float:
    """Symmetric misestimation factor, >= 1.0."""
    e = max(float(estimated), 1.0)
    o = max(float(observed), 1.0)
    return e / o if e >= o else o / e


def record_observation(
    site: str,
    estimated: float,
    observed: float,
    threshold: float,
    span=None,
    extra: Optional[dict] = None,
) -> float:
    """The shared recording protocol: instant event on the query span
    + `adaptive.divergences` when the ratio crosses `threshold`.
    Returns the ratio so callers gate re-planning on the same number
    they recorded."""
    from trino_tpu.runtime.metrics import METRICS

    ratio = divergence_ratio(estimated, observed)
    divergent = ratio >= threshold
    if span is not None:
        span.event(
            "adaptive_observation",
            site=site[:120],
            estimated_rows=round(float(estimated), 1),
            observed_rows=int(observed),
            divergence=round(ratio, 3),
            divergent=divergent,
            **(extra or {}),
        )
    if divergent:
        METRICS.increment("adaptive.divergences")
    return ratio


def estimated_vs_observed_line(
    site: str, estimated: float, observed: float, ratio: float
) -> str:
    """The EXPLAIN ANALYZE rendering shared by the local and
    distributed paths (so the two cannot drift apart)."""
    return (
        f"estimated_vs_observed: {site} rows "
        f"est={estimated:.0f} obs={observed:.0f} ratio={ratio:.2f}"
    )
