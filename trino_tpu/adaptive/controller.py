"""The adaptive execution controller.

`prepare(root)` runs BETWEEN logical optimization and physical
planning (single-node LocalPlanner or the distributed fragmenter —
both paths call it), and closes the estimate->observe->re-plan loop:

1. shared-subtree materialization: identical subtrees (the analyzer's
   NOT IN rewrite plans its subquery twice; CTEs referenced twice) are
   materialized ONCE into the generation-guarded spool and every seat
   is substituted with the same SpooledValuesNode.
2. barrier observation: the innermost join's build side is a pipeline
   barrier — it completes before its probe starts — so the controller
   materializes it, snapshots observed rows/NDV/heavy-hitters, and
   records the divergence against the optimizer's estimate.
3. mid-query re-planning: when divergence crosses
   `adaptive_replan_threshold`, the REMAINING plan is re-optimized
   with the materialized subtree substituted as a literal source
   carrying exact observed stats (StatsCalculator short-circuits on
   `plan_stats`), so the reorderer/broadcast/partial-agg decisions see
   truth. Completed work is never redone: it rides along as rows. When
   divergence stays under the threshold the loop STOPS — estimates are
   trusted and no further barriers pay the materialization toll.

Re-planned programs re-land on existing capacity-ladder shape classes:
materialized batches pad to bucket_capacity like every other batch,
and the re-optimization runs the same rule set, so the warm loop mints
zero new XLA lowerings (the bench --adaptive-smoke gate).

`preempt` is called at every barrier: a deadline kill latched during
materialization or re-planning surfaces as the same typed error the
execution path raises (EXCEEDED_TIME_LIMIT stays non-retryable
mid-re-plan)."""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

from trino_tpu.adaptive.observer import (
    divergence_ratio,
    estimated_vs_observed_line,
    hot_keys,
    observe_rows,
    record_observation,
)
from trino_tpu.adaptive.spool import (
    MAX_SPOOL_ROWS,
    SPOOL,
    SpooledValuesNode,
    duplicate_subtrees,
    materializable,
    plan_fingerprint,
    spooled_node,
    substitute,
    subtree_tables,
)
from trino_tpu.sql import plan as P

MAX_REPLANS = 2


@dataclasses.dataclass
class _MatResult:
    """One materialization attempt. `entry` is None on spool overflow,
    in which case `overflow_rows` carries the observed row count."""

    entry: Optional[object]
    key: str
    hit: bool
    obs: Optional[object]  # observer.ObservedStats
    overflow_rows: Optional[int]


@dataclasses.dataclass
class AdaptiveReport:
    """What the controller did to one query — rides into QueryInfo and
    the EXPLAIN ANALYZE `adaptive=` section."""

    observations: List[dict] = dataclasses.field(default_factory=list)
    replans: int = 0
    spool_hits: int = 0
    spool_stores: int = 0
    shared_subtrees: int = 0
    transformed: bool = False
    # skew plane (ISSUE 16): heavy hitters classified at build-side
    # barriers, joins annotated for salted repartition, joins re-planned
    # into hybrid-hash spill mode after a build overflow
    heavy_hitters: int = 0
    salted_joins: int = 0
    spill_builds: int = 0

    def as_dict(self) -> dict:
        return {
            "observations": list(self.observations),
            "replans": self.replans,
            "spool_hits": self.spool_hits,
            "spool_stores": self.spool_stores,
            "shared_subtrees": self.shared_subtrees,
            "heavy_hitters": self.heavy_hitters,
            "salted_joins": self.salted_joins,
            "spill_builds": self.spill_builds,
        }

    def lines(self) -> List[str]:
        out = [
            f"adaptive: observations={len(self.observations)} "
            f"replans={self.replans} spool_hits={self.spool_hits} "
            f"spool_stores={self.spool_stores} "
            f"shared_subtrees={self.shared_subtrees}"
        ]
        # the skew line appears only when a skew action fired, so
        # no-skew queries render byte-identically to before
        if self.heavy_hitters or self.salted_joins or self.spill_builds:
            out.append(
                f"skew: heavy_hitters={self.heavy_hitters} "
                f"salted_joins={self.salted_joins} "
                f"spill_builds={self.spill_builds}"
            )
        for o in self.observations:
            suffix = ""
            if o.get("salted"):
                suffix += f" -> salted[{o['salted']}]"
            if o.get("spill"):
                suffix += " -> spill_build"
            if o.get("replanned"):
                suffix += " -> replanned"
                if o.get("trigger") == "ndv":
                    suffix += " (ndv)"
            out.append(
                estimated_vs_observed_line(
                    o["site"], o["estimated"], o["observed"], o["ratio"]
                )
                + suffix
            )
        return out


class AdaptiveController:
    def __init__(
        self,
        catalogs,
        session,
        span=None,
        preempt: Optional[Callable[[], None]] = None,
        stabilizer=None,
        max_replans: int = MAX_REPLANS,
    ):
        self.catalogs = catalogs
        self.session = session
        self.span = span
        self.preempt = preempt
        self.stabilizer = stabilizer
        self.max_replans = max_replans
        self.report = AdaptiveReport()
        self._stats_calc = None

    # -- config ------------------------------------------------------
    @property
    def _adaptive_on(self) -> bool:
        return bool(getattr(self.session, "adaptive_execution", False))

    @property
    def _shared_on(self) -> bool:
        return bool(
            getattr(self.session, "shared_subtree_materialization", False)
        )

    @property
    def _threshold(self) -> float:
        return float(
            getattr(self.session, "adaptive_replan_threshold", 4.0) or 4.0
        )

    @property
    def _salting_on(self) -> bool:
        return bool(getattr(self.session, "skewed_join_salting", False))

    @property
    def _hot_threshold(self) -> float:
        return float(
            getattr(self.session, "skew_hot_key_threshold", 0.2) or 0.2
        )

    @property
    def _spill_min_rows(self) -> int:
        return int(
            getattr(self.session, "skew_spill_min_rows", 1 << 18) or 1 << 18
        )

    def enabled(self) -> bool:
        return self._adaptive_on or self._shared_on

    # -- stats -------------------------------------------------------
    def _estimate_stats(self, node: P.PlanNode):
        from trino_tpu.sql.stats import StatsCalculator

        if self._stats_calc is None:
            self._stats_calc = StatsCalculator(self.catalogs)
        try:
            return self._stats_calc.stats(node)
        except Exception:
            return None

    def _estimate(self, node: P.PlanNode) -> float:
        st = self._estimate_stats(node)
        return st.row_count if st is not None else 1e9

    def _check_preempt(self) -> None:
        if self.preempt is not None:
            self.preempt()

    # -- materialization ---------------------------------------------
    def _run_subtree(self, node: P.PlanNode) -> Optional[list]:
        """Execute one subtree locally to python rows (the completed
        build side / shared subtree). Deterministic by the
        materializable() gate, so running it here and substituting the
        rows is semantically the plan itself."""
        from trino_tpu.exec import CollectorSink, Driver, Pipeline
        from trino_tpu.sql.local_planner import LocalPlanner

        planner = LocalPlanner(
            self.catalogs,
            batch_rows=self.session.batch_rows,
            target_splits=self.session.target_splits,
            dynamic_filtering=self.session.enable_dynamic_filtering,
            stabilizer=self.stabilizer,
        )
        physical = planner.plan(node)
        ctx: dict = {}
        pipelines, chain = physical.instantiate(ctx)
        sink = CollectorSink()
        chain.append(sink)
        for p in pipelines:
            Driver(p).run()
        Driver(Pipeline(chain)).run()
        for flag, msg in ctx.get("deferred_checks", ()):
            if bool(flag):
                raise RuntimeError(msg)
        return sink.rows()

    def _materialize(
        self, node: P.PlanNode, key_channels=None
    ) -> Optional["_MatResult"]:
        """Materialize one subtree into the spool. entry is None when
        the rows exceed the spool bound — the subtree stays in the plan
        — but overflow_rows still reports the observed count, which is
        exactly the DHHJ spill signal (the rows were computed either
        way). Returns None only when nothing ran."""
        key = SPOOL.key(node)
        tables = subtree_tables(node)
        entry = SPOOL.get(key, tables)
        if entry is not None:
            self.report.spool_hits += 1
            obs = getattr(entry, "obs", None)
            if key_channels and (
                obs is None
                or any(ch not in obs.ndv for ch in key_channels)
            ):
                # entry stored by another consumer (or an older path)
                # without this join's key channels — re-observe from the
                # spooled rows so warm runs classify identically to cold
                obs = observe_rows(entry.rows, channels=key_channels)
            return _MatResult(entry, key, True, obs, None)
        rows = self._run_subtree(node)
        if rows is None:
            return None
        if len(rows) > MAX_SPOOL_ROWS:
            return _MatResult(None, key, False, None, len(rows))
        obs = observe_rows(rows, channels=key_channels)
        entry = SPOOL.put(
            key, rows, node.fields, obs.plan_stats(), tables, obs=obs
        )
        self.report.spool_stores += 1
        return _MatResult(entry, key, False, obs, None)

    # -- barrier selection -------------------------------------------
    def _next_barrier(
        self, root: P.PlanNode, visited: set
    ) -> Optional[Tuple[P.JoinNode, P.PlanNode]]:
        """Innermost join whose build side is materializable and not
        yet observed — the first barrier runtime would complete."""
        found: List[Tuple[P.JoinNode, P.PlanNode]] = []

        def walk(n):
            for c in n.children():
                walk(c)
            if isinstance(n, P.JoinNode) and n.kind != "cross":
                sub = n.right
                if (
                    materializable(sub)
                    and plan_fingerprint(sub) not in visited
                ):
                    found.append((n, sub))

        walk(root)
        return found[0] if found else None

    def _validate(self, root: P.PlanNode) -> None:
        if getattr(self.session, "plan_validation", "passes") == "off":
            return
        from trino_tpu.sql.validate import validate_logical

        validate_logical(root, stage="adaptive", rule="adaptive_controller")

    def _replan(self, root: P.PlanNode) -> P.PlanNode:
        """Re-optimize the remaining plan seeded with observed stats
        (the spooled nodes' plan_stats short-circuit the calculator)."""
        from trino_tpu.sql.optimizer import canonicalize_tstz_keys, optimize

        self._stats_calc = None  # new plan, fresh memo
        out = canonicalize_tstz_keys(
            optimize(root, self.catalogs, self.session)
        )
        self._validate(out)
        return out

    # -- entry point --------------------------------------------------
    def prepare(self, root: P.PlanNode) -> P.PlanNode:
        """The estimate->observe->re-plan loop. Returns the (possibly
        transformed) plan; self.report records what happened."""
        if not self.enabled():
            return root
        if self._shared_on:
            root = self._materialize_shared(root)
        if self._adaptive_on:
            root = self._observe_barriers(root)
        if self.report.transformed:
            self._validate(root)
        return root

    def _materialize_shared(self, root: P.PlanNode) -> P.PlanNode:
        for nodes in duplicate_subtrees(root):
            self._check_preempt()
            proto = nodes[0]
            est = self._estimate(proto)
            try:
                res = self._materialize(proto)
            except Exception:
                if self.span is not None:
                    self.span.event(
                        "adaptive_spool_skip",
                        site=type(proto).__name__,
                    )
                continue
            if res is None or res.entry is None:
                continue
            entry, key = res.entry, res.key
            site = f"shared:{type(proto).__name__}[x{len(nodes)}]"
            ratio = record_observation(
                site, est, entry.stats.row_count, self._threshold,
                span=self.span,
            )
            self.report.observations.append({
                "site": site,
                "estimated": est,
                "observed": entry.stats.row_count,
                "ratio": ratio,
            })
            spooled = spooled_node(entry, key, site)
            root = substitute(root, {id(n): spooled for n in nodes})
            # the extra seats reuse the one materialization
            extra = len(nodes) - 1
            self.report.spool_hits += extra
            self.report.shared_subtrees += 1
            from trino_tpu.runtime.metrics import METRICS

            METRICS.increment("adaptive.spool_hits", extra)
            self.report.transformed = True
        return root

    def _observe_barriers(self, root: P.PlanNode) -> P.PlanNode:
        from trino_tpu.runtime.metrics import METRICS

        visited: set = set()
        replans = 0
        while True:
            self._check_preempt()
            barrier = self._next_barrier(root, visited)
            if barrier is None:
                break
            join, sub = barrier
            visited.add(plan_fingerprint(sub))
            est = self._estimate(sub)
            if est > MAX_SPOOL_ROWS * 4:
                # the estimate itself says this barrier is too big to
                # spool; skip it rather than materialize-and-discard
                continue
            try:
                res = self._materialize(
                    sub, key_channels=tuple(join.right_keys)
                )
            except Exception:
                if self.span is not None:
                    self.span.event(
                        "adaptive_observe_skip",
                        site=type(sub).__name__,
                    )
                continue
            if res is None:
                continue
            site = f"build:{type(sub).__name__}"
            if res.entry is None:
                # spool overflow: the build side blew past the estimate
                # hard enough that materializing it is off the table —
                # the DHHJ signal. Annotate the join to pre-open grace
                # partitions (hybrid hash) instead of letting the build
                # thrash through memory revocation at run time.
                observed = int(res.overflow_rows or 0)
                ratio = record_observation(
                    site, est, observed, self._threshold, span=self.span
                )
                obs = {
                    "site": site,
                    "estimated": est,
                    "observed": observed,
                    "ratio": ratio,
                }
                self.report.observations.append(obs)
                if (
                    ratio >= self._threshold
                    and observed > self._spill_min_rows
                    and not join.spill_build
                    and replans < self.max_replans
                ):
                    root = substitute(
                        root,
                        {id(join): dataclasses.replace(
                            join, spill_build=True
                        )},
                    )
                    self.report.transformed = True
                    self.report.spill_builds += 1
                    replans += 1  # spill re-plan spends re-plan budget
                    obs["spill"] = True
                    METRICS.increment("skew.spill_mode_replans")
                    if self.span is not None:
                        self.span.event(
                            "skew_spill_replan",
                            site=site,
                            observed_rows=observed,
                            divergence=round(ratio, 3),
                        )
                continue
            entry, key = res.entry, res.key
            ratio = record_observation(
                site, est, entry.stats.row_count, self._threshold,
                span=self.span,
            )
            obs = {
                "site": site,
                "estimated": est,
                "observed": entry.stats.row_count,
                "ratio": ratio,
            }
            self.report.observations.append(obs)
            # NDV divergence (PR 13 carry-forward): a build side whose
            # key NDV estimate is badly wrong flips build-side selection
            # even when the row count held, so it triggers re-planning
            # on its own — the spooled node's exact plan_stats then seed
            # the re-optimization with observed NDV.
            ndv_ratio = 1.0
            est_stats = self._estimate_stats(sub)
            if res.obs is not None and est_stats is not None:
                for rk in join.right_keys:
                    o_ndv = res.obs.ndv.get(rk)
                    if not o_ndv:
                        continue
                    e_ndv = est_stats.col(rk).ndv
                    if e_ndv is None:
                        e_ndv = est_stats.row_count
                    ndv_ratio = max(
                        ndv_ratio, divergence_ratio(e_ndv, o_ndv)
                    )
            # heavy-hitter classification (JSPIM): the modal build keys
            # against the session threshold, from OBSERVED stats
            hot: Tuple = ()
            if res.obs is not None and len(join.right_keys) == 1:
                hot = hot_keys(
                    res.obs, join.right_keys[0], self._hot_threshold
                )
            if hot:
                self.report.heavy_hitters += len(hot)
                METRICS.increment(
                    "skew.heavy_hitters_detected", len(hot)
                )
                if self.span is not None:
                    self.span.event(
                        "skew_heavy_hitters",
                        site=site,
                        hot_keys=len(hot),
                        modal_count=res.obs.heavy_hitter.get(
                            join.right_keys[0], 0
                        ),
                        build_rows=entry.stats.row_count,
                    )
            salt = bool(
                hot
                and self._salting_on
                and join.kind in ("inner", "left", "semi", "anti")
                and len(join.right_keys) == 1
                and not join.skew_hot_keys
            )
            spooled = spooled_node(entry, key, site)
            if salt:
                root = substitute(
                    root,
                    {id(join): dataclasses.replace(
                        join, right=spooled, skew_hot_keys=tuple(hot)
                    )},
                )
                self.report.salted_joins += 1
                obs["salted"] = len(hot)
            else:
                root = substitute(root, {id(sub): spooled})
            self.report.transformed = True
            trigger_ratio = max(ratio, ndv_ratio)
            if trigger_ratio >= self._threshold and replans < self.max_replans:
                self._check_preempt()
                root = self._replan(root)
                replans += 1
                obs["replanned"] = True
                if ratio < self._threshold <= ndv_ratio:
                    obs["trigger"] = "ndv"
                self.report.replans += 1
                METRICS.increment("adaptive.replans")
                if self.span is not None:
                    self.span.event(
                        "adaptive_replan",
                        site=site,
                        divergence=round(ratio, 3),
                        ndv_divergence=round(ndv_ratio, 3),
                        attempt=replans,
                    )
                if salt:
                    # re-optimization rebuilds join nodes from scratch;
                    # re-seat the salting annotation on the join that
                    # still builds from our spooled rows
                    root = self._reannotate(root, key, tuple(hot))
            else:
                # estimates held (or the budget is spent): stop paying
                # the materialization toll
                break
        return root

    def _reannotate(
        self, root: P.PlanNode, spool_key: str, hot: Tuple
    ) -> P.PlanNode:
        """Re-apply skew_hot_keys after a re-plan: find the join whose
        build side is still the spooled node we classified. If the
        re-optimizer flipped build sides the hot set describes the
        wrong side — leave the join unannotated (correct, just not
        salted)."""
        replacements = {}

        def walk(n):
            for c in n.children():
                walk(c)
            if (
                isinstance(n, P.JoinNode)
                and n.kind in ("inner", "left", "semi", "anti")
                and len(n.right_keys) == 1
                and not n.skew_hot_keys
                and isinstance(n.right, SpooledValuesNode)
                and n.right.spool_key == spool_key
            ):
                replacements[id(n)] = dataclasses.replace(
                    n, skew_hot_keys=hot
                )

        walk(root)
        return substitute(root, replacements) if replacements else root
