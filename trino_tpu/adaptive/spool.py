"""Shared-subtree materialization: SpooledValuesNode + the spool.

A materialized subtree re-enters the plan as a SpooledValuesNode — a
ValuesNode subclass, so every existing isinstance check (the planner's
ValuesOperator path, EvaluateEmptyJoin, the fragmenter's SINGLE leaf
rule, the validators' row-width check) applies unchanged. The node
carries the EXACT observed PlanStats of the rows it holds, which is
what seeds re-optimization with truth instead of estimates
(StatsCalculator short-circuits on the `plan_stats` attribute).

The SubtreeSpool is the process-wide cache of materialized subtrees,
keyed by (structural fingerprint, table-generation vector). Generation
guarding reuses the resident tier's per-table write counters
(trino_tpu/resident GENERATIONS): any write to a table a spooled
subtree read bumps that table's generation, which changes the key, so
a stale entry is unreachable — the same invalidation protocol the
resident pins and the plan cache use."""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.sql import plan as P

# materialization guard rails: a barrier bigger than this stays in the
# plan (materializing it would trade one misestimated join for an
# equally unbounded host transfer)
MAX_SPOOL_ROWS = 1 << 18

# node types a materializable subtree may contain — deterministic
# relational core only (no remote sources / exchanges: those belong to
# the fragmenter, and materializing them would hide a data plane)
_MATERIALIZABLE_NODES = (
    P.ScanNode,
    P.ValuesNode,
    P.FilterNode,
    P.ProjectNode,
    P.AggregateNode,
    P.JoinNode,
    P.SortNode,
    P.TopNNode,
    P.LimitNode,
    P.EnforceSingleRowNode,
    P.UnionAllNode,
)


@dataclasses.dataclass(frozen=True)
class SpooledValuesNode(P.ValuesNode):
    """A materialized subtree as a literal source. `plan_stats` is the
    exact observed statistics of `rows` (excluded from eq/hash — two
    spools of the same rows are the same plan); `spool_key` names the
    SubtreeSpool entry so EXPLAIN and the physical planner can reach
    the device-resident batches without a python round trip;
    `source_desc` is the one-line provenance EXPLAIN renders."""

    spool_key: str = ""
    source_desc: str = ""
    plan_stats: Optional[object] = dataclasses.field(
        default=None, compare=False, hash=False
    )


def plan_fingerprint(node: P.PlanNode) -> str:
    """Structural identity of a subtree. Frozen-dataclass reprs are
    recursive and value-complete (handles include pushed constraints,
    expressions print their IR), so the repr IS the structure; hash it
    to keep spool keys short."""
    return hashlib.sha256(repr(node).encode()).hexdigest()[:24]


def subtree_tables(node: P.PlanNode) -> Tuple[Tuple[str, str, str], ...]:
    """Sorted (catalog, schema, table) triples the subtree reads — the
    generation-guard domain."""
    out = set()

    def walk(n):
        if isinstance(n, P.ScanNode):
            h = n.handle
            out.add((n.catalog.lower(), h.schema.lower(), h.table.lower()))
        for c in n.children():
            walk(c)

    walk(node)
    return tuple(sorted(out))


def _field_materializable(t: T.DataType) -> bool:
    """Types whose python values round-trip exactly through
    CollectorSink.rows() -> ValuesNode -> RelBatch.from_pydict:
    integer-like (incl. DATE/TIMESTAMP epoch values), floats, booleans
    and dictionary strings. Decimals re-quantize through float and
    TIMESTAMP_TZ decodes to display text, so both stay in the plan."""
    if t.is_nested or t.lanes != 1:
        return False
    if t.is_decimal or t.kind == T.TypeKind.TIMESTAMP_TZ:
        return False
    return True


def materializable(node: P.PlanNode) -> bool:
    """Whether a subtree may be replaced by its materialized rows:
    deterministic relational core only, all output types
    round-trippable."""
    if isinstance(node, P.ValuesNode):
        return False  # already literal — nothing to gain
    ok = True

    def walk(n):
        nonlocal ok
        if not isinstance(n, _MATERIALIZABLE_NODES):
            ok = False
            return
        for c in n.children():
            walk(c)

    walk(node)
    return ok and all(_field_materializable(f.type) for f in node.fields)


@dataclasses.dataclass
class SpoolEntry:
    rows: Tuple[Tuple[object, ...], ...]
    fields: Tuple[P.Field, ...]
    stats: object  # sql.stats.PlanStats
    tables: Tuple[Tuple[str, str, str], ...]
    generations: Tuple[int, ...]
    # the full ObservedStats snapshot (None for entries stored by paths
    # that never observed one, e.g. recovery stage teeing). Persisting
    # it matters for skew: a WARM spool hit must re-classify the same
    # heavy hitters the cold run saw, or the warm plan diverges from
    # the cold one and mints new lowerings.
    obs: Optional[object] = None


class SubtreeSpool:
    """Generation-guarded LRU of materialized subtrees. One entry
    serves every consumer of an identical subtree within a query (the
    NOT IN rewrite plans its subquery twice) and repeat executions of
    the same query until a table it read is written."""

    def __init__(self, max_entries: int = 64):
        self._lock = named_lock("SubtreeSpool._lock")
        self._entries: "OrderedDict[str, SpoolEntry]" = OrderedDict()
        self._max = max_entries
        self.stores = 0
        self.hits = 0
        self.invalidations = 0

    def _generations(self, tables) -> Tuple[int, ...]:
        from trino_tpu.resident import GENERATIONS

        return GENERATIONS.snapshot(tables)

    def key(self, node: P.PlanNode) -> str:
        return plan_fingerprint(node)

    def get(self, key: str, tables) -> Optional[SpoolEntry]:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            if self._generations(e.tables) != e.generations:
                # a write landed on a table this entry read: the entry
                # is stale by construction — drop it
                del self._entries[key]
                self.invalidations += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            from trino_tpu.runtime.metrics import METRICS

            METRICS.increment("adaptive.spool_hits")
            return e

    def put(self, key: str, rows, fields, stats, tables,
            obs=None) -> SpoolEntry:
        e = SpoolEntry(
            rows=tuple(tuple(r) for r in rows),
            fields=tuple(fields),
            stats=stats,
            tables=tuple(tables),
            generations=self._generations(tables),
            obs=obs,
        )
        with self._lock:
            self._entries[key] = e
            self._entries.move_to_end(key)
            self.stores += 1
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)
        return e

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats_line(self) -> str:
        with self._lock:
            return (
                f"spool: entries={len(self._entries)} stores={self.stores} "
                f"hits={self.hits} invalidations={self.invalidations}"
            )


SPOOL = SubtreeSpool()


def spooled_node(
    entry: SpoolEntry, key: str, source_desc: str
) -> SpooledValuesNode:
    return SpooledValuesNode(
        fields=entry.fields,
        rows=entry.rows,
        spool_key=key,
        source_desc=source_desc,
        plan_stats=entry.stats,
    )


def substitute(
    root: P.PlanNode, replacements: Dict[int, P.PlanNode]
) -> P.PlanNode:
    """Rebuild `root` with every node whose id() appears in
    `replacements` swapped for its replacement (identity-keyed: the
    same subtree object appearing twice is replaced at both seats)."""

    def walk(n: P.PlanNode) -> P.PlanNode:
        r = replacements.get(id(n))
        if r is not None:
            return r
        kids = n.children()
        if not kids:
            return n
        new_kids = [walk(c) for c in kids]
        if all(a is b for a, b in zip(new_kids, kids)):
            return n
        if isinstance(n, P.UnionAllNode):
            return dataclasses.replace(n, inputs=tuple(new_kids))
        if isinstance(n, P.JoinNode):
            return dataclasses.replace(
                n, left=new_kids[0], right=new_kids[1]
            )
        return dataclasses.replace(n, child=new_kids[0])

    return walk(root)


def duplicate_subtrees(
    root: P.PlanNode, min_nodes: int = 1
) -> List[List[P.PlanNode]]:
    """Identical-subtree groups (>= 2 occurrences), outermost first.
    A subtree must contain a ScanNode to count (repeated literal
    Values are already free). Bare scans qualify: the NOT IN rewrite
    plans its subquery twice, and after predicate pushdown that
    subquery IS one constrained scan. Nested duplicates are
    suppressed: once a subtree is grouped, its descendants are not."""
    by_fp: Dict[str, List[P.PlanNode]] = {}
    sizes: Dict[int, int] = {}

    def measure(n) -> int:
        s = 1 + sum(measure(c) for c in n.children())
        sizes[id(n)] = s
        return s

    measure(root)

    def has_scan(n) -> bool:
        if isinstance(n, P.ScanNode):
            return True
        return any(has_scan(c) for c in n.children())

    def collect(n):
        if n is not root:
            by_fp.setdefault(plan_fingerprint(n), []).append(n)
        for c in n.children():
            collect(c)

    collect(root)
    groups = [
        nodes
        for nodes in by_fp.values()
        if len(nodes) >= 2
        and sizes[id(nodes[0])] >= min_nodes
        and materializable(nodes[0])
        and has_scan(nodes[0])
    ]
    # outermost (largest) first, and drop groups nested inside one we
    # already took — the outer materialization subsumes them
    groups.sort(key=lambda ns: -sizes[id(ns[0])])
    taken_ids: set = set()

    def ids_of(n, acc):
        acc.add(id(n))
        for c in n.children():
            ids_of(c, acc)

    out: List[List[P.PlanNode]] = []
    for nodes in groups:
        if any(id(n) in taken_ids for n in nodes):
            continue
        out.append(nodes)
        for n in nodes:
            ids_of(n, taken_ids)
    return out
