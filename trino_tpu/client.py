"""Python client for the statement protocol.

Analogue of client/trino-client's StatementClientV1 (StatementClientV1.
java:65, advance():334 — POST /v1/statement then follow nextUri until
the results are exhausted; SURVEY.md §2.11)."""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.request
from typing import List, Optional


class QueryError(RuntimeError):
    pass


@dataclasses.dataclass
class ClientResult:
    query_id: str
    columns: List[dict]
    rows: List[list]

    @property
    def column_names(self) -> List[str]:
        return [c["name"] for c in self.columns]


class Client:
    def __init__(self, uri: str, timeout: float = 60.0,
                 poll_interval: float = 0.05, headers: Optional[dict] = None):
        self.uri = uri.rstrip("/")
        self.timeout = timeout
        self.poll_interval = poll_interval
        self.headers = dict(headers or {})
        # this connection's transaction (X-Trino-Transaction-Id model:
        # the client carries the id; the server holds no session state)
        self.transaction_id: Optional[str] = None
        # prepared statements are also client session state
        # (X-Trino-Prepared-Statement / addedPrepare protocol)
        self.prepared: dict = {}

    def _request(self, method: str, url: str, body: Optional[bytes] = None) -> dict:
        headers = dict(self.headers)
        headers["X-Trino-Transaction-Id"] = self.transaction_id or "NONE"
        if self.prepared:
            import urllib.parse as _up

            headers["X-Trino-Prepared-Statement"] = ",".join(
                f"{k}={_up.quote(v)}" for k, v in self.prepared.items()
            )
        req = urllib.request.Request(
            url, data=body, method=method, headers=headers
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def execute(self, sql: str) -> ClientResult:
        """Submit and drain: the StatementClientV1 polling loop."""
        out = self._request(
            "POST", f"{self.uri}/v1/statement", sql.encode("utf-8")
        )
        columns: List[dict] = []
        rows: List[list] = []
        query_id = out.get("id", "")
        deadline = time.monotonic() + self.timeout
        while True:
            # transaction headers apply even on FAILED responses: a
            # failed COMMIT/ROLLBACK still cleared the server-side
            # transaction, and keeping a dead id would wedge every later
            # statement on this connection with "unknown transaction"
            if out.get("startedTransactionId"):
                self.transaction_id = out["startedTransactionId"]
            if out.get("clearedTransactionId"):
                self.transaction_id = None
            if out.get("addedPrepare"):
                ap = out["addedPrepare"]
                self.prepared[ap["name"]] = ap["sql"]
            if out.get("deallocatedPrepare"):
                self.prepared.pop(out["deallocatedPrepare"], None)
            if "error" in out:
                raise QueryError(out["error"].get("message", "query failed"))
            if out.get("columns"):
                columns = out["columns"]
            rows.extend(out.get("data", ()))
            next_uri = out.get("nextUri")
            if next_uri is None:
                return ClientResult(query_id, columns, rows)
            if time.monotonic() > deadline:
                raise QueryError(f"query {query_id} timed out client-side")
            if not out.get("data"):
                time.sleep(self.poll_interval)
            out = self._request("GET", next_uri)

    def cancel(self, query_id: str) -> None:
        self._request("DELETE", f"{self.uri}/v1/statement/executing/{query_id}")
