"""Resident state tier: device-pinned warm state shared ACROSS queries.

The serving tier (PR 8) cached plans; the mesh plane (PR 10) cached
compiled programs. Both still rebuild their DATA every execution: the
mesh prelude re-runs build sides from host batches, and the fast lane's
point lookups re-scan the probed table. This package pins that state on
the device between queries:

- `manager.py` — per-table generation counters (the plan cache's
  generation guard made table-granular) and the `ResidentStateManager`:
  a pin budget with LRU eviction, optional charging against a PR 2
  MemoryPool (the low-memory killer revokes pins before killing
  queries), and the `resident.*` counter surface.
- `table.py` — `ResidentTable`: a point-lookup hash table whose probe
  side lives on device at a capacity-ladder rung, probed by a
  shape-stable jitted program, with an append-only delta side and a
  background compaction merge that folds the delta back at ladder
  rungs.
- `fastlane.py` — the serving-tier hook: classify a point lookup (the
  micro-batcher's strict classifier), probe the pinned table on a hit,
  build+pin on a miss, and degrade to the cold execute path whenever
  anything is surprising.

Invalidation protocol: DML bumps the written table's generation (an
INSERT may instead ride the delta path and re-key the entry), DDL drops
the table's entries, and wholesale events (COMMIT, catalog
registration) bump a global epoch that stales every key.
"""

from trino_tpu.resident.manager import (  # noqa: F401
    GENERATIONS,
    RESIDENT,
    ResidentStateManager,
    TableGenerations,
)
