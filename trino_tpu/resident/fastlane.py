"""Serving fast lane over pinned resident tables.

`try_resident_lookup` sits in front of the normal execute path on the
coordinator server: it reuses the micro-batcher's STRICT point-lookup
classifier, and when the probed table is named in the
`resident_tables` session property it serves the lookup from a pinned
`ResidentTable` — a device probe, zero rebuild, zero plan-cache or
scheduler work. A miss (first touch, or a generation bump from DML)
builds the table with ONE oracle scan through the ordinary execute
path, pins it under the current generation snapshot, and serves from
the pin thereafter. Anything surprising — unclassifiable statement,
unconfigured table, nested-typed select list, pin-budget overflow,
per-key fanout past the probe rung — returns None so the caller falls
through to the cold path; the fast lane degrades, it never fails a
query.

Write integration (`table_written`, called from the engine's
invalidation path): INSERTs whose rows were captured by a `DeltaTap`
append to the pinned table's delta side and RE-KEY the entry under the
table's new generation (the table stays warm); UPDATE/DELETE/MERGE/DDL
evict. When the delta crosses half its budget a background compaction
(the warmup-thread idiom: daemon worker, never on the query path)
folds it into the base at a ladder rung.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import List, Optional, Tuple

from trino_tpu.resident.manager import GENERATIONS, RESIDENT, table_key
from trino_tpu.resident.table import ResidentTable

_lock = named_lock("fastlane._lock")
_compaction_pool = None
_pending_compactions: List = []


def _resolve_table(table_sql: str, session) -> Tuple[str, str, str]:
    parts = table_sql.split(".")
    cat, schema = session.catalog, session.schema
    if len(parts) == 2:
        schema = parts[0]
    elif len(parts) == 3:
        cat, schema = parts[0], parts[1]
    return table_key(cat, schema, parts[-1])


def _configured(tkey: Tuple[str, str, str], session) -> bool:
    names = [
        t.strip().lower()
        for t in str(getattr(session, "resident_tables", "") or "").split(",")
        if t.strip()
    ]
    cat, schema, table = tkey
    return (
        table in names
        or f"{schema}.{table}" in names
        or f"{cat}.{schema}.{table}" in names
    )


def _full_key(tkey, key_col, select_sql, dkind, sig, rung, gen) -> Tuple:
    # convention: the generation snapshot is always the LAST component
    return ("fastlane", tkey, key_col, select_sql, dkind, sig, rung, gen)


def _index_key(tkey, key_col, select_sql, dkind) -> Tuple:
    return ("fastlane", tkey, key_col, select_sql, dkind)


def try_resident_lookup(runner, sql: str, identity=None, prepared=None,
                        query_span=None):
    """MaterializedResult from a pinned table, or None = cold path."""
    from trino_tpu.runtime.metrics import METRICS

    session = getattr(runner, "session", None)
    if session is None or not getattr(session, "resident_tables", ""):
        return None
    from trino_tpu.serving.batcher import classify

    look = classify(sql, runner=runner, prepared=prepared)
    if look is None:
        return None
    tkey = _resolve_table(look.table_sql, session)
    if not _configured(tkey, session):
        return None
    dkind = look.group_key[3]
    ikey = _index_key(tkey, look.key_col, look.select_sql, dkind)
    gen = GENERATIONS.snapshot([tkey])

    # access control re-checks on every lookup, pinned or not — a pin
    # must never become a bypass
    ac = getattr(runner, "access_control", None)
    if ac is not None:
        from trino_tpu.security import Identity

        ident = identity or Identity(session.user)
        cols = [look.key_col] + [
            c.strip() for c in look.select_sql.split(",")
        ]
        ac.check_can_select(ident, *tkey, cols)

    found = RESIDENT.find(ikey)
    if found is not None:
        key, table = found
        if key[-1] == gen and isinstance(table, ResidentTable):
            rows = table.probe(look.value)
            if rows is None:
                return None  # fanout past the probe rung: cold path
            RESIDENT.lookup(key)  # counts the hit, touches LRU
            if query_span is not None:
                query_span.event("resident_hit", table=".".join(tkey))
            from trino_tpu.engine import MaterializedResult

            return MaterializedResult(
                rows, list(table.names), list(table.types)
            )
        # stale generation that invalidation missed (epoch bump):
        # reclaim the pin and rebuild below
        RESIDENT.evict(key)
    RESIDENT.note_miss()

    # -- cold build: one oracle scan through the ordinary path --------
    try:
        return _build_and_probe(
            runner, session, look, tkey, ikey, gen, dkind, identity,
            query_span,
        )
    except Exception:
        METRICS.increment("resident.skips")
        return None


def _build_and_probe(runner, session, look, tkey, ikey, gen, dkind,
                     identity, query_span):
    from trino_tpu.runtime.metrics import METRICS

    # principled eligibility (the census-satellite rule): nested-typed
    # select columns have no scalar device layout to pin against —
    # counted skip, not a silent one
    if not _eligible_columns(runner, tkey, look, METRICS):
        return None
    oracle_sql = (
        f"SELECT {look.key_col}, {look.select_sql} FROM {look.table_sql}"
    )
    kwargs = {"identity": identity} if identity is not None else {}
    result = runner.execute(oracle_sql, **kwargs)
    names = list(result.column_names[1:])
    types = list(result.column_types[1:])
    table = ResidentTable(
        look.key_col, names, types,
        [r[0] for r in result.rows],
        [r[1:] for r in result.rows],
        string_key=(dkind == "s"),
        delta_max_rows=int(
            getattr(session, "resident_delta_max_rows", 4096)
        ),
    )
    RESIDENT.configure(
        int(getattr(session, "resident_pin_budget_mb", 64)) << 20
    )
    key = _full_key(
        tkey, look.key_col, look.select_sql, dkind,
        table.dtype_sig, table.base_cap, gen,
    )
    pinned = RESIDENT.pin(
        key, table, table.device_bytes, [tkey], index_key=ikey
    )
    if not pinned:
        # budget overflow: serve this one lookup from the transient
        # build, but nothing stays pinned (graceful degradation)
        METRICS.increment("resident.skips")
    rows = table.probe(look.value)
    if rows is None:
        return None
    if query_span is not None:
        query_span.event(
            "resident_build", table=".".join(tkey), pinned=pinned
        )
    from trino_tpu.engine import MaterializedResult

    return MaterializedResult(rows, names, types)


def _eligible_columns(runner, tkey, look, METRICS) -> bool:
    # same predicate the census uses for its [nested] classes
    # (sql/validate.nested_column_types) — classification stays
    # principled and in one place
    from trino_tpu.sql.validate import nested_column_types

    try:
        catalogs = getattr(runner, "catalogs", None)
        if catalogs is None:
            return True
        conn = catalogs.get(tkey[0])
        handle = conn.metadata.get_table_handle(tkey[1], tkey[2])
        if handle is None:
            return True  # let the oracle query raise the real error
        meta = conn.metadata.get_table_metadata(handle)
        wanted = {look.key_col.lower()} | {
            c.strip().lower() for c in look.select_sql.split(",")
        }
        if nested_column_types([
            c.type for c in meta.columns if c.name.lower() in wanted
        ]):
            METRICS.increment("resident.skips_nested")
            return False
        return True
    except Exception:
        return True


# -- write-path integration -------------------------------------------


class DeltaTap:
    """Captures the host rows of one INSERT as they stream into the
    connector sink (the engine tees its page sink through this)."""

    def __init__(self, names: List[str]):
        self.names = [n.lower() for n in names]
        self.rows: List[list] = []

    def add_batch(self, batch) -> None:
        self.rows.extend(batch.to_pylists())


class TeeSink:
    """Connector-sink wrapper feeding a DeltaTap (append/finish shim
    compatible with both plain page sinks and ScaledWriterSink)."""

    def __init__(self, inner, tap: DeltaTap):
        self._inner = inner
        self._tap = tap

    def append(self, batch) -> None:
        try:
            self._tap.add_batch(batch)
        except Exception:
            self._tap.rows = None  # poisoned tap: eviction, not bad data
        self._inner.append(batch)

    def finish(self) -> int:
        return self._inner.finish()


def delta_tap(catalog: str, schema: str, table: str,
              column_names) -> Optional[DeltaTap]:
    """A tap when any pinned entry could absorb this table's insert;
    None keeps the write path untouched."""
    tkey = table_key(catalog, schema, table)
    if not RESIDENT.entries_for(tkey):
        return None
    return DeltaTap(list(column_names))


def table_written(catalog: str, schema: str, table: str,
                  appended: bool = False,
                  tap: Optional[DeltaTap] = None) -> None:
    """Engine notification AFTER a write and AFTER the generation bump:
    appends with captured rows ride the delta; everything else
    evicts."""
    tkey = table_key(catalog, schema, table)
    keys = RESIDENT.entries_for(tkey)
    if not keys:
        return
    new_gen = GENERATIONS.snapshot([tkey])
    for key in keys:
        entry_payload = RESIDENT.peek(key)
        if (
            appended
            and tap is not None
            and tap.rows is not None
            and isinstance(entry_payload, ResidentTable)
            and key[0] == "fastlane"
        ):
            t = entry_payload
            rows = _project(tap, t.key_col, t.names)
            if rows is not None and t.delta_room(len(rows)):
                if t.append_delta([r[0] for r in rows],
                                  [r[1:] for r in rows]):
                    new_key = key[:-1] + (new_gen,)
                    RESIDENT.rekey(key, new_key)
                    RESIDENT.set_bytes(new_key, t.device_bytes)
                    if t.wants_compaction():
                        _schedule_compaction(new_key, t)
                    continue
        RESIDENT.evict(key)


def _project(tap: DeltaTap, key_col: str,
             value_names: List[str]) -> Optional[List[list]]:
    """Tap rows (full table schema) -> [key, values...] rows in the
    resident table's column order; None when a column is missing."""
    try:
        pos = {n: i for i, n in enumerate(tap.names)}
        idxs = [pos[key_col.lower()]] + [
            pos[n.lower()] for n in value_names
        ]
    except KeyError:
        return None
    return [[row[i] for i in idxs] for row in tap.rows]


def table_dropped(catalog: str, schema: str, table: str) -> None:
    RESIDENT.drop_table(table_key(catalog, schema, table))


# -- background compaction (the warmup-thread idiom) -------------------


def _schedule_compaction(key: Tuple, table: ResidentTable) -> None:
    global _compaction_pool
    with _lock:
        if _compaction_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            from trino_tpu.analysis.threadreg import THREADS

            # Executor workers are non-daemon on 3.9+; the pool is a
            # process-lifetime singleton, so sanction its one worker
            # with the registry rather than tearing it down per-query.
            _compaction_pool = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix="trino-tpu-resident-compact",
                initializer=lambda: THREADS.adopt_current(
                    owner="ResidentManager", long_lived=True),
            )
        fut = _compaction_pool.submit(_compact_one, key, table)
        _pending_compactions[:] = [
            f for f in _pending_compactions if not f.done()
        ]
        _pending_compactions.append(fut)


def _compact_one(key: Tuple, table: ResidentTable) -> None:
    try:
        old_rung = table.base_cap
        table.compact()
        RESIDENT.note_compaction()
        # fold the new rung into the key so the key stays honest
        if key[0] == "fastlane" and table.base_cap != old_rung:
            new_key = key[:6] + (table.base_cap,) + key[7:]
            RESIDENT.rekey(key, new_key)
            key = new_key
        RESIDENT.set_bytes(key, table.device_bytes)
    except Exception:
        # a failed compaction leaves base+delta intact and correct;
        # drop the pin only if the table is now inconsistent — it is
        # not, so just leave it and let DML churn evict eventually
        pass


def drain_compactions(timeout_s: float = 30.0) -> None:
    """Test/bench hook: wait for scheduled compactions to settle."""
    import concurrent.futures as cf

    with _lock:
        pending = list(_pending_compactions)
    if pending:
        cf.wait(pending, timeout=timeout_s)
    with _lock:
        _pending_compactions[:] = [
            f for f in _pending_compactions if not f.done()
        ]
