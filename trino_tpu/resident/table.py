"""Device-resident point-lookup hash tables with delta maintenance.

A `ResidentTable` pins the PROBE side of a point lookup on device: the
key column as an int64 array padded to a capacity-ladder rung plus a
live mask. Probing is a shape-stable jitted program — mask-and-
`nonzero(size=...)` — so a warm lookup does zero host->device table
transfer and zero rebuild; the only readback is a tiny index vector.
Result VALUES stay host-side (result rows materialize on the host
regardless), indexed positionally by the device match indices. String
keys dictionary-encode through a host map (the dictionary IS the string
hash table; the device still arbitrates the probe so dtype/shape
classes stay uniform).

Writes ride an append-only delta: inserts land in a small delta-side
table at a low capacity rung (`resident_delta_max_rows`), probes check
base+delta (two dispatches of the SAME probe program at two rungs), and
a background compaction merge — a jitted densify-concat program at
ladder rungs — folds the delta back into the base so probe shapes stay
inside already-compiled classes. Probe and compaction programs register
WarmupEntrys (the compile regime can AOT-warm them) and are cached in
PROGRAM_CACHE keyed by their capacity pair, shared across tables.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import bucket_capacity
from trino_tpu.compile.cache import PROGRAM_CACHE
from trino_tpu.compile.warmup import WarmupEntry, note_classes_warm

# matches returned per probe before the fast path bails to the cold
# execute (a point-lookup key with >16 duplicate rows is not a point
# lookup worth pinning)
PROBE_OUT_CAP = 16

# WarmupEntry registry for resident programs (the MESH_WARMUP_ENTRIES
# idiom): bounded, observable, consumable by any WarmupService.
RESIDENT_WARMUP_ENTRIES: List[WarmupEntry] = []
_MAX_WARMUP_ENTRIES = 64
_warm_lock = named_lock("table._warm_lock")


def register_resident_warmup(entries: Sequence[WarmupEntry]) -> None:
    with _warm_lock:
        known = {(e.operator, e.capacities, e.out_dtypes)
                 for e in RESIDENT_WARMUP_ENTRIES}
        RESIDENT_WARMUP_ENTRIES.extend(
            e for e in entries
            if (e.operator, e.capacities, e.out_dtypes) not in known
        )
        del RESIDENT_WARMUP_ENTRIES[:-_MAX_WARMUP_ENTRIES]


def resident_warmup_entries() -> List[WarmupEntry]:
    with _warm_lock:
        return list(RESIDENT_WARMUP_ENTRIES)


# -- programs (shared across tables, keyed by capacity class) ----------


def _probe_program(cap: int, out_cap: int):
    def build():
        def probe(keys, valid, q):
            match = valid & (keys == q)
            idx = jnp.nonzero(match, size=out_cap, fill_value=cap)[0]
            return idx, jnp.sum(match)

        return jax.jit(probe)

    return PROGRAM_CACHE.get_or_create(
        ("resident-probe", cap, out_cap), build
    )


def _compact_program(base_cap: int, delta_cap: int, out_cap: int):
    """Densify-concat merge: live base keys then live delta keys, in
    order, padded to `out_cap` (a ladder rung sized to the live
    total)."""

    def build():
        def compact(bk, bv, dk, dv):
            keys = jnp.concatenate([bk, dk])
            valid = jnp.concatenate([bv, dv])
            total = keys.shape[0]
            idx = jnp.nonzero(valid, size=out_cap, fill_value=total)[0]
            guarded = jnp.concatenate(
                [keys, jnp.zeros((1,), dtype=keys.dtype)]
            )
            new_keys = guarded[idx]
            new_valid = jnp.arange(out_cap) < jnp.sum(valid)
            return new_keys, new_valid

        return jax.jit(compact)

    return PROGRAM_CACHE.get_or_create(
        ("resident-compact", base_cap, delta_cap, out_cap), build
    )


class _ProbeWarmer:
    """WarmupEntry.fn adapter: ignores the zeros batch the service
    hands it and dispatches the probe at its recorded shapes."""

    def __init__(self, cap: int, out_cap: int):
        self.cap, self.out_cap = cap, out_cap

    def __call__(self, _batch) -> None:
        fn = _probe_program(self.cap, self.out_cap)
        idx, n = fn(
            jnp.zeros((self.cap,), dtype=jnp.int64),
            jnp.zeros((self.cap,), dtype=bool),
            jnp.asarray(0, dtype=jnp.int64),
        )
        jax.block_until_ready((idx, n))


class _CompactWarmer:
    def __init__(self, base_cap: int, delta_cap: int, out_cap: int):
        self.base_cap, self.delta_cap, self.out_cap = (
            base_cap, delta_cap, out_cap,
        )

    def __call__(self, _batch) -> None:
        fn = _compact_program(self.base_cap, self.delta_cap, self.out_cap)
        out = fn(
            jnp.zeros((self.base_cap,), dtype=jnp.int64),
            jnp.zeros((self.base_cap,), dtype=bool),
            jnp.zeros((self.delta_cap,), dtype=jnp.int64),
            jnp.zeros((self.delta_cap,), dtype=bool),
        )
        jax.block_until_ready(out)


def _probe_entry(cap: int) -> WarmupEntry:
    return WarmupEntry(
        operator="ResidentProbe",
        fn=_ProbeWarmer(cap, PROBE_OUT_CAP),
        in_schema=[(T.BIGINT, None)],
        out_dtypes=("int64",),
        capacities=(cap,),
    )


def _compact_entry(base_cap: int, delta_cap: int, out_cap: int) -> WarmupEntry:
    return WarmupEntry(
        operator="ResidentCompact",
        fn=_CompactWarmer(base_cap, delta_cap, out_cap),
        in_schema=[(T.BIGINT, None)],
        out_dtypes=(f"d{delta_cap}", f"o{out_cap}"),
        capacities=(base_cap,),
    )


# -- the table ---------------------------------------------------------


class ResidentTable:
    """One pinned point-lookup table: key column + live mask on device,
    value rows host-side, plus an append-only delta at a low rung."""

    def __init__(self, key_col: str, names: List[str], types: List,
                 key_values: List, value_rows: List[list],
                 string_key: bool, delta_max_rows: int = 4096):
        self.key_col = key_col
        self.names = list(names)
        self.types = list(types)
        self.string_key = bool(string_key)
        self.delta_max_rows = max(1, int(delta_max_rows))
        # string keys dictionary-encode through a host map; int keys
        # are their own code
        self._code_of = {} if string_key else None
        codes = [self._encode(k) for k in key_values]
        self.base_cap = bucket_capacity(max(16, len(codes)))
        self.base_live = len(codes)
        self.base_keys = jnp.asarray(
            np.pad(
                np.asarray(codes, dtype=np.int64),
                (0, self.base_cap - len(codes)),
            )
        )
        self.base_valid = jnp.asarray(
            np.arange(self.base_cap) < len(codes)
        )
        self.base_rows: List[list] = [list(r) for r in value_rows]
        self.delta_cap = bucket_capacity(max(16, self.delta_max_rows))
        self._delta_codes: List[int] = []
        self.delta_rows: List[list] = []
        self._delta_keys = None
        self._delta_valid = None
        self._lock = named_rlock("ResidentTable._lock")
        register_resident_warmup(
            [_probe_entry(self.base_cap), _probe_entry(self.delta_cap)]
        )
        # pay probe compiles at build time (the build already paid a
        # full table scan; two dead dispatches keep them off the first
        # warm lookup) and mark the classes warm for the watchdog
        _ProbeWarmer(self.base_cap, PROBE_OUT_CAP)(None)
        _ProbeWarmer(self.delta_cap, PROBE_OUT_CAP)(None)
        note_classes_warm([
            ("ResidentProbe", self.base_cap, ("int64",)),
            ("ResidentProbe", self.delta_cap, ("int64",)),
        ])

    # -- keys ----------------------------------------------------------
    def _encode(self, key, create: bool = True) -> Optional[int]:
        if self._code_of is None:
            return int(key)
        code = self._code_of.get(key)
        if code is None and create:
            code = len(self._code_of)
            self._code_of[key] = code
        return code

    @property
    def dtype_sig(self) -> Tuple[str, ...]:
        return ("str" if self.string_key else "int64",) + tuple(
            str(t) for t in self.types
        )

    @property
    def device_bytes(self) -> int:
        total = self.base_keys.nbytes + self.base_valid.nbytes
        if self._delta_keys is not None:
            total += self._delta_keys.nbytes + self._delta_valid.nbytes
        return int(total)

    # -- probe ---------------------------------------------------------
    def probe(self, key) -> Optional[List[list]]:
        """All value rows matching `key`, base order then delta order
        (append order — the oracle's scan order). None = bail to the
        cold path (per-key fanout exceeded PROBE_OUT_CAP)."""
        with self._lock:
            code = self._encode(key, create=False)
            if code is None:
                return []  # never-seen string key: provably no rows
            fn = _probe_program(self.base_cap, PROBE_OUT_CAP)
            q = jnp.asarray(code, dtype=jnp.int64)
            idx, n = fn(self.base_keys, self.base_valid, q)
            parts = [(idx, n, self.base_rows, self.base_cap)]
            if self._delta_keys is not None:
                dfn = _probe_program(self.delta_cap, PROBE_OUT_CAP)
                didx, dn = dfn(self._delta_keys, self._delta_valid, q)
                parts.append((didx, dn, self.delta_rows, self.delta_cap))
            out: List[list] = []
            for pidx, pn, rows, cap in parts:
                host_idx, host_n = jax.device_get((pidx, pn))
                if int(host_n) > PROBE_OUT_CAP:
                    return None
                for i in np.asarray(host_idx):
                    if int(i) < cap and int(i) < len(rows):
                        out.append(list(rows[int(i)]))
            return out

    # -- delta maintenance --------------------------------------------
    def delta_room(self, n_rows: int) -> bool:
        with self._lock:
            return len(self.delta_rows) + n_rows <= self.delta_max_rows

    def append_delta(self, key_values: List, value_rows: List[list]) -> bool:
        """Append inserted rows to the delta side. False = out of delta
        room (caller evicts; the next lookup rebuilds cold)."""
        with self._lock:
            if len(self.delta_rows) + len(key_values) > self.delta_max_rows:
                return False
            self._delta_codes.extend(self._encode(k) for k in key_values)
            self.delta_rows.extend(list(r) for r in value_rows)
            n = len(self._delta_codes)
            self._delta_keys = jnp.asarray(
                np.pad(
                    np.asarray(self._delta_codes, dtype=np.int64),
                    (0, self.delta_cap - n),
                )
            )
            self._delta_valid = jnp.asarray(np.arange(self.delta_cap) < n)
            return True

    @property
    def delta_count(self) -> int:
        with self._lock:
            return len(self.delta_rows)

    def wants_compaction(self) -> bool:
        with self._lock:
            return len(self.delta_rows) >= max(
                1, self.delta_max_rows // 2
            )

    def compact(self) -> None:
        """Fold the delta into the base at a ladder rung sized to the
        live total, via the jitted densify-concat program, then warm
        the probe at the (possibly new) base rung so post-compaction
        probes land on a compiled class."""
        with self._lock:
            if not self.delta_rows or self._delta_keys is None:
                return
            live_total = self.base_live + len(self.delta_rows)
            out_cap = bucket_capacity(max(16, live_total))
            old_cap = self.base_cap
            register_resident_warmup([
                _compact_entry(old_cap, self.delta_cap, out_cap),
                _probe_entry(out_cap),
            ])
            fn = _compact_program(old_cap, self.delta_cap, out_cap)
            new_keys, new_valid = fn(
                self.base_keys, self.base_valid,
                self._delta_keys, self._delta_valid,
            )
            # host rows follow the same densify order: live base rows
            # (positions 0..L-1 are dense by construction) then delta
            merged = [list(r) for r in self.base_rows[: self.base_live]]
            merged.extend(list(r) for r in self.delta_rows)
            self.base_keys = new_keys
            self.base_valid = new_valid
            self.base_cap = out_cap
            self.base_live = live_total
            self.base_rows = merged
            self._delta_codes = []
            self.delta_rows = []
            self._delta_keys = None
            self._delta_valid = None
            # pre-warm the probe at the new rung off the query path
            _ProbeWarmer(self.base_cap, PROBE_OUT_CAP)(None)
            note_classes_warm([
                ("ResidentProbe", self.base_cap, ("int64",)),
                ("ResidentCompact", old_cap, (f"d{self.delta_cap}",
                                              f"o{out_cap}")),
            ])
