"""Generation-guarded pin manager for device-resident warm state.

`TableGenerations` is the invalidation clock: the plan cache's single
`generation` store-guard (serving/plan_cache.py) made table-granular.
Every resident cache key embeds a generation snapshot of the tables it
was built from; a write bumps the table's counter, so stale entries
become unreachable by key — and `invalidate_table` evicts them eagerly
so their device memory is actually reclaimed, not just orphaned.

`ResidentStateManager` owns the pin budget. Pinned payloads are opaque
(the mesh prelude pins its exported pctx tuple; the fast lane pins
`ResidentTable`s); the manager tracks bytes, evicts LRU-first when a
pin would exceed the budget, and refuses gracefully (cold path, never
an error) when a single payload cannot fit. When attached to a PR 2
MemoryPool the pinned bytes are charged against the pool and registered
revocable, so a query under memory pressure reclaims pins BEFORE the
low-memory killer picks a victim — warm state is the cheapest thing in
the building to throw away.

Counters surface in /v1/metrics as
resident.{hits,misses,pins,evictions,revocations,compactions} plus the
resident_pinned_bytes gauge.
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from collections import OrderedDict
from typing import Dict, FrozenSet, List, Optional, Tuple


def table_key(catalog: str, schema: str, table: str) -> Tuple[str, str, str]:
    """Canonical (catalog, schema, table) key — case-folded like the
    analyzer's identifier resolution."""
    return (str(catalog).lower(), str(schema).lower(), str(table).lower())


class TableGenerations:
    """Per-table write counters plus a global epoch for wholesale
    events (COMMIT, catalog registration) that cannot name a table."""

    def __init__(self):
        self._lock = named_lock("TableGenerations._lock")
        self._gens: Dict[Tuple[str, str, str], int] = {}
        self._epoch = 0

    def get(self, key: Tuple[str, str, str]) -> Tuple[int, int]:
        with self._lock:
            return (self._epoch, self._gens.get(key, 0))

    def bump(self, key: Tuple[str, str, str]) -> Tuple[int, int]:
        with self._lock:
            self._gens[key] = self._gens.get(key, 0) + 1
            return (self._epoch, self._gens[key])

    def bump_all(self) -> None:
        with self._lock:
            self._epoch += 1

    def snapshot(self, keys) -> Tuple:
        """Hashable generation vector over a table set — the generation
        component of a resident cache key."""
        return tuple(sorted((k, self.get(k)) for k in set(keys)))


class _Entry:
    __slots__ = ("payload", "bytes", "tables", "index_key")

    def __init__(self, payload, bytes_, tables, index_key):
        self.payload = payload
        self.bytes = int(bytes_)
        self.tables: FrozenSet = frozenset(tables)
        self.index_key = index_key


class ResidentStateManager:
    """LRU pin store under a device-memory budget.

    Keys are opaque hashable tuples whose LAST component is a
    `TableGenerations.snapshot(...)` of the entry's source tables;
    `index_key` (optional) is a generation-free alias so a consumer can
    find "the current pinned entry for this logical object" without
    recomputing build-time key components (dtype sig, capacity rung)."""

    def __init__(self, budget_bytes: int = 64 << 20):
        self._lock = named_rlock("ResidentStateManager._lock")
        self.budget_bytes = int(budget_bytes)
        self._entries: "OrderedDict[Tuple, _Entry]" = OrderedDict()
        self._index: Dict[Tuple, Tuple] = {}
        self._pinned_bytes = 0
        self._pool = None
        self._pool_cid: Optional[int] = None
        # bytes actually reserved in the CURRENT pool — may lag
        # _pinned_bytes when pins predate the attach or a re-charge was
        # refused; frees clamp to it so the pool ledger never goes
        # negative
        self._pool_charged = 0
        self.hits = 0
        self.misses = 0
        self.pins = 0
        self.pin_rejects = 0
        self.evictions = 0
        self.revocations = 0
        self.compactions = 0
        self._gauge_registered = False

    # -- configuration -------------------------------------------------
    def configure(self, budget_bytes: int) -> None:
        with self._lock:
            self.budget_bytes = int(budget_bytes)
            while self._pinned_bytes > self.budget_bytes and self._entries:
                self._evict_lru()

    def attach_pool(self, pool) -> None:
        """Charge pins against a MemoryPool and register them revocable:
        pool.reserve under pressure calls back into `_revoke`, freeing
        every pin before the exhaustion handler considers killing a
        query."""
        with self._lock:
            self.detach_pool()
            self._pool = pool
            self._pool_cid = pool.register_revocable(self._revoke)
            # best-effort charge of pre-existing pins; a refusal leaves
            # them uncharged (the revocable registration is what the
            # killer needs either way)
            if self._pinned_bytes and pool.try_reserve(
                self._pinned_bytes, query_id="resident"
            ):
                self._pool_charged = self._pinned_bytes
            pool.set_revocable(self._pool_cid, self._pinned_bytes)

    def detach_pool(self) -> None:
        with self._lock:
            if self._pool is not None and self._pool_cid is not None:
                try:
                    self._pool.unregister_revocable(self._pool_cid)
                    if self._pool_charged:
                        self._pool.free(
                            self._pool_charged, query_id="resident"
                        )
                except Exception:
                    pass
            self._pool = None
            self._pool_cid = None
            self._pool_charged = 0

    def _pool_reserve(self, bytes_: int) -> bool:
        """Charge `bytes_` to the attached pool; True when charged (or
        no pool is attached)."""
        if self._pool is None or not bytes_:
            return True
        try:
            if self._pool.try_reserve(bytes_, query_id="resident"):
                self._pool_charged += bytes_
                return True
            return False
        except Exception:
            return False

    def _pool_free(self, bytes_: int) -> None:
        give = min(int(bytes_), self._pool_charged)
        if self._pool is None or give <= 0:
            return
        try:
            self._pool.free(give, query_id="resident")
            self._pool_charged -= give
        except Exception:
            pass

    def _register_gauge(self) -> None:
        if self._gauge_registered:
            return
        from trino_tpu.runtime.metrics import METRICS

        METRICS.register_gauge(
            "resident_pinned_bytes", lambda: float(self._pinned_bytes)
        )
        METRICS.register_gauge(
            "resident_entries", lambda: float(len(self._entries))
        )
        self._gauge_registered = True

    # -- cache ops -----------------------------------------------------
    def lookup(self, key: Tuple):
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                METRICS.increment("resident.misses")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            METRICS.increment("resident.hits")
            return entry.payload

    def peek(self, key: Tuple):
        """Payload without hit/miss accounting or LRU touch (the write
        path inspecting candidates for delta absorption)."""
        with self._lock:
            entry = self._entries.get(key)
            return None if entry is None else entry.payload

    def note_miss(self) -> None:
        """Count a miss discovered before the full key exists (the fast
        lane's index lookup failed, so `lookup` was never called)."""
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            self.misses += 1
        METRICS.increment("resident.misses")

    def find(self, index_key: Tuple):
        """Resolve a generation-free alias to its live (key, payload);
        None when nothing is pinned under it."""
        with self._lock:
            key = self._index.get(index_key)
            if key is None:
                return None
            entry = self._entries.get(key)
            if entry is None:
                self._index.pop(index_key, None)
                return None
            return key, entry.payload

    def pin(self, key: Tuple, payload, bytes_: int, tables,
            index_key: Optional[Tuple] = None) -> bool:
        """Pin a payload, evicting LRU entries to fit. Returns False —
        the caller's cold path, never an error — when the payload alone
        exceeds the budget or the attached pool refuses the charge."""
        from trino_tpu.runtime.metrics import METRICS

        bytes_ = int(bytes_)
        with self._lock:
            self._register_gauge()
            if bytes_ > self.budget_bytes:
                self.pin_rejects += 1
                METRICS.increment("resident.pin_rejects")
                return False
            if key in self._entries:
                self._evict(key)  # replace: release the old charge first
            while (
                self._pinned_bytes + bytes_ > self.budget_bytes
                and self._entries
            ):
                self._evict_lru()
            if not self._pool_reserve(bytes_):
                self.pin_rejects += 1
                METRICS.increment("resident.pin_rejects")
                return False
            entry = _Entry(payload, bytes_, tables, index_key)
            self._entries[key] = entry
            self._entries.move_to_end(key)
            self._pinned_bytes += bytes_
            if index_key is not None:
                self._index[index_key] = key
            self.pins += 1
            METRICS.increment("resident.pins")
            self._sync_pool_revocable()
            return True

    def rekey(self, old_key: Tuple, new_key: Tuple) -> bool:
        """Move an entry to a new key (the delta path: an append keeps
        the payload warm under the table's NEW generation)."""
        with self._lock:
            entry = self._entries.pop(old_key, None)
            if entry is None:
                return False
            self._entries[new_key] = entry
            self._entries.move_to_end(new_key)
            if entry.index_key is not None:
                self._index[entry.index_key] = new_key
            return True

    def set_bytes(self, key: Tuple, bytes_: int) -> None:
        """Re-charge an entry whose device footprint changed (delta
        growth, compaction)."""
        bytes_ = int(bytes_)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return
            delta = bytes_ - entry.bytes
            entry.bytes = bytes_
            self._pinned_bytes += delta
            if delta > 0:
                self._pool_reserve(delta)
            elif delta < 0:
                self._pool_free(-delta)
            while self._pinned_bytes > self.budget_bytes and len(self._entries) > 1:
                self._evict_lru()
            self._sync_pool_revocable()

    # -- invalidation --------------------------------------------------
    def invalidate_table(self, tkey: Tuple[str, str, str]) -> int:
        """Evict every entry built from this table (DML/DDL). Returns
        the eviction count."""
        with self._lock:
            victims = [
                k for k, e in self._entries.items() if tkey in e.tables
            ]
            for k in victims:
                self._evict(k)
            return len(victims)

    drop_table = invalidate_table  # DDL alias: same eviction, clearer call sites

    def entries_for_prefix(self, prefix: Tuple) -> List[Tuple]:
        """Live keys sharing a leading tuple prefix (stale-generation
        sweep: same logical object, any generation)."""
        n = len(prefix)
        with self._lock:
            return [
                k for k in self._entries
                if isinstance(k, tuple) and k[:n] == prefix
            ]

    def entries_for(self, tkey: Tuple[str, str, str]) -> List[Tuple]:
        with self._lock:
            return [
                k for k, e in self._entries.items() if tkey in e.tables
            ]

    def evict_all(self) -> None:
        with self._lock:
            while self._entries:
                self._evict_lru()

    def evict(self, key: Tuple) -> bool:
        with self._lock:
            if key not in self._entries:
                return False
            self._evict(key)
            return True

    # -- internals (lock held) -----------------------------------------
    def _evict(self, key: Tuple) -> None:
        from trino_tpu.runtime.metrics import METRICS

        entry = self._entries.pop(key)
        self._pinned_bytes -= entry.bytes
        if entry.index_key is not None and self._index.get(entry.index_key) == key:
            self._index.pop(entry.index_key, None)
        self._pool_free(entry.bytes)
        self.evictions += 1
        METRICS.increment("resident.evictions")
        self._sync_pool_revocable()

    def _evict_lru(self) -> None:
        key = next(iter(self._entries))
        self._evict(key)

    def _sync_pool_revocable(self) -> None:
        if self._pool is not None and self._pool_cid is not None:
            try:
                self._pool.set_revocable(self._pool_cid, self._pinned_bytes)
            except Exception:
                pass

    def _revoke(self) -> None:
        """MemoryPool revocation callback: a query needs the bytes more
        than the warm state does. Drop every pin (counted separately
        from ordinary LRU evictions)."""
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            n = len(self._entries)
            while self._entries:
                self._evict_lru()
            if n:
                self.revocations += n
                METRICS.increment("resident.revocations", n)

    def note_compaction(self) -> None:
        from trino_tpu.runtime.metrics import METRICS

        with self._lock:
            self.compactions += 1
        METRICS.increment("resident.compactions")

    # -- observability -------------------------------------------------
    @property
    def pinned_bytes(self) -> int:
        return self._pinned_bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "pinned_bytes": self._pinned_bytes,
                "budget_bytes": self.budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "pins": self.pins,
                "pin_rejects": self.pin_rejects,
                "evictions": self.evictions,
                "revocations": self.revocations,
                "compactions": self.compactions,
            }

    def reset_stats(self) -> None:
        """Test/corpus hook: zero the counters (entries stay pinned)."""
        with self._lock:
            self.hits = self.misses = self.pins = 0
            self.pin_rejects = self.evictions = 0
            self.revocations = self.compactions = 0


# Process singletons (the METRICS / PROGRAM_CACHE idiom): one clock and
# one pin budget per process, shared by every runner and the mesh plane.
GENERATIONS = TableGenerations()
RESIDENT = ResidentStateManager()
