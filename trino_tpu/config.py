"""Config + session-property system.

Analogue of airlift @Config binding (etc/config.properties -> typed
config objects; 353 @Config annotations in trino-main) and the typed
session-property registry (main/SystemSessionProperties.java, ~200
properties — SURVEY.md §5.6). Properties are declared once with type +
default + description; SET SESSION goes through `validate`, and config
files bind by the same registry."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class PropertyMetadata:
    name: str
    type: type  # bool | int | float | str
    default: Any
    description: str
    allowed: Optional[tuple] = None  # enum-valued string properties

    def parse(self, text: str) -> Any:
        if self.type is bool:
            if text.lower() in ("true", "1", "on"):
                return True
            if text.lower() in ("false", "0", "off"):
                return False
            raise ValueError(f"{self.name}: expected boolean, got {text!r}")
        return self.type(text)


class PropertyRegistry:
    def __init__(self):
        self._props: Dict[str, PropertyMetadata] = {}

    def register(
        self, name: str, type_: type, default, description: str,
        allowed: Optional[tuple] = None,
    ) -> None:
        self._props[name] = PropertyMetadata(
            name, type_, default, description, allowed
        )

    def validate(self, name: str, value: Any) -> Any:
        meta = self._props.get(name)
        if meta is None:
            raise ValueError(f"unknown session property {name!r}")
        if isinstance(value, str) and meta.type is not str:
            value = meta.parse(value)
        elif meta.type is float and isinstance(value, int):
            value = float(value)
        elif not isinstance(value, meta.type):
            raise ValueError(
                f"{name}: expected {meta.type.__name__}, got {type(value).__name__}"
            )
        if meta.allowed is not None and value not in meta.allowed:
            raise ValueError(
                f"{name}: must be one of {meta.allowed}, got {value!r}"
            )
        return value

    def default(self, name: str) -> Any:
        return self._props[name].default

    def all(self) -> List[PropertyMetadata]:
        return sorted(self._props.values(), key=lambda m: m.name)


# The engine's system session properties (SystemSessionProperties
# analogue — the switchboard the executor consults per query).
SYSTEM_PROPERTIES = PropertyRegistry()
for _name, _type, _default, _desc, _allowed in [
    ("batch_rows", int, 1 << 20, "max rows per device batch", None),
    ("target_splits", int, 1, "target connector split count per scan", None),
    ("hash_partition_count", int, 4, "tasks per hash-distributed stage", None),
    ("retry_policy", str, "none", "none | query | task",
     ("none", "query", "task")),
    ("query_retry_count", int, 2,
     "whole-query retry attempts (retry_policy=query)", None),
    ("task_retries", int, 3, "per-task retry attempts (FTE)", None),
    ("memory_pool_bytes", int, 0, "per-query memory budget (0 = unlimited)", None),
    ("enable_dynamic_filtering", bool, True, "probe-side join pruning", None),
    ("broadcast_join_threshold", int, 1_000_000,
     "max estimated build rows for a broadcast join", None),
    ("mesh_execution", bool, True,
     "run colocated fragments over the device-mesh collective exchange", None),
    ("mesh_chunk_rows", int, 0,
     "per-shard rows per mesh chunk-step: the driver scan splits into "
     "ceil(rows/chunk) jit steps with host preemption checks (deadline/"
     "abandonment/watchdog) at every chunk boundary; 0 compiles the "
     "plan as one program (preemption checks only bracket it)", None),
    ("enable_optimizer", bool, True,
     "run the iterative plan-optimizer pipeline", None),
    ("enable_pushdown", bool, True,
     "push supported filter conjuncts and projections into connector "
     "scans (apply_filter/apply_projection SPI)", None),
    ("join_reordering_strategy", str, "automatic",
     "cost-based join reordering: automatic | none",
     ("automatic", "none")),
    ("speculation_enabled", bool, True,
     "FTE: duplicate straggler tasks, first finisher wins", None),
    ("speculation_quantile", float, 2.0,
     "FTE: speculate once a task runs this multiple of the stage's "
     "median committed-attempt wall time", None),
    ("task_concurrency", int, 2,
     "intra-task pipeline parallelism via the local exchange (1 = off)",
     None),
    # -- cluster resiliency (runtime/error_tracker, discovery, memory) --
    ("request_max_error_duration_s", float, 30.0,
     "per-destination transient-error budget before a remote request "
     "is declared failed (RequestErrorTracker deadline)", None),
    ("node_breaker_threshold", int, 3,
     "consecutive failed probes/requests before a worker's circuit "
     "breaker opens (graylist)", None),
    ("node_breaker_cooldown_s", float, 1.0,
     "seconds a graylisted worker sits out before a half-open probe",
     None),
    ("low_memory_killer_enabled", bool, True,
     "under cluster pool exhaustion (after revocation/spill), kill the "
     "single largest query instead of stalling everyone", None),
    # -- deadline hierarchy (runtime/query_tracker.py); 0 = unlimited --
    ("query_max_planning_time_s", float, 0.0,
     "kill a query still PLANNING after this long "
     "(EXCEEDED_TIME_LIMIT, non-retryable)", None),
    ("query_max_execution_time_s", float, 0.0,
     "kill a query EXECUTING (post-planning) after this long "
     "(EXCEEDED_TIME_LIMIT, non-retryable)", None),
    ("query_max_run_time_s", float, 0.0,
     "end-to-end wall bound: queued + planning + execution "
     "(EXCEEDED_TIME_LIMIT, non-retryable)", None),
    ("query_max_cpu_time_s", float, 0.0,
     "kill a query whose tasks' aggregated CPU ledgers exceed this "
     "(EXCEEDED_CPU_LIMIT, non-retryable)", None),
    ("client_timeout_s", float, 300.0,
     "reap a query whose client stopped polling nextUri for this long: "
     "tasks cancelled, resource-group slot and memory released", None),
    ("stuck_task_interrupt_s", float, 0.0,
     "worker watchdog: interrupt a task making no batch progress for "
     "this long (failure is RETRYABLE — a hung split may succeed "
     "elsewhere); 0 disables", None),
    ("speculation_percentile", float, 0.75,
     "FTE speculation bases its per-fragment duration estimate on this "
     "quantile of committed attempt wall times (p75 default)", None),
    # -- plan validation (sql/validate.py, PlanSanityChecker analogue) --
    ("plan_validation", str, "passes",
     "run plan sanity checkers: off | passes (after each optimizer "
     "pass + fragmentation) | rules (additionally after every rule "
     "application, plus plan-determinism double-planning — debug mode)",
     ("off", "passes", "rules")),
    ("compile_churn_warn_threshold", int, 32,
     "EXPLAIN (ANALYZE) warns when the shape census predicts more "
     "distinct (operator, capacity, dtype) XLA lowerings than this",
     None),
    # -- compile regime (compile/: shapes, warmup, cache) --
    ("shape_stabilization", bool, True,
     "pad scan chunks to the capacity class of their pre-pruning span "
     "so pushdown/dynamic-filter pruning and FTE retries re-land on "
     "census-predicted XLA lowerings", None),
    ("capacity_ladder_base", int, 2,
     "geometric ratio between capacity-ladder rungs (power of two; "
     "2 = the native bucket_capacity grid, larger = fewer, coarser "
     "capacity classes)", None),
    ("warmup_mode", str, "off",
     "census-driven AOT warmup of predicted lowerings: off | "
     "background (compile while the query runs) | block (wait for "
     "warmup before execution)", ("off", "background", "block")),
    ("stuck_task_interrupt_warm_s", float, 0.0,
     "aggressive stuck-task watchdog threshold applied once a task's "
     "predicted shape classes are all warm (warmup/cache hits or a "
     "prior completed run); 0 falls back to stuck_task_interrupt_s",
     None),
    # -- serving tier (trino_tpu/serving/) --
    ("plan_cache_entries", int, 256,
     "LRU bound of the prepared-statement plan cache (canonical text + "
     "plan-shaping properties + parameter dtype vector keyed)", None),
    ("micro_batch_window_ms", float, 0.0,
     "inter-query micro-batching: coalesce same-shape point lookups "
     "arriving within this window onto one shared device step; 0 "
     "disables batching", None),
    ("micro_batch_max", int, 16,
     "max point lookups coalesced into one shared device step", None),
    ("admission_fast_depth", int, 64,
     "max in-flight submissions in the fast admission lane "
     "(cached-plan point queries); arrivals beyond it are shed with "
     "429 + Retry-After", None),
    ("admission_general_depth", int, 256,
     "max in-flight submissions in the general admission lane; "
     "arrivals beyond it are shed with 429 + Retry-After", None),
    ("admission_retry_after_s", float, 1.0,
     "Retry-After hint returned with shed (429) submissions", None),
    # -- resident state tier (trino_tpu/resident/) --
    ("resident_tables", str, "",
     "comma-separated tables (table, schema.table or "
     "catalog.schema.table) whose point lookups the serving fast lane "
     "serves from pinned device-resident hash tables; empty disables "
     "the fast lane", None),
    ("resident_pin_budget_mb", int, 64,
     "device-memory budget for resident pins (fast-lane hash tables "
     "and mesh prelude contexts), LRU-evicted and revocable under "
     "memory pressure; 0 disables pinning entirely", None),
    ("resident_delta_max_rows", int, 4096,
     "capacity of a pinned table's append-only delta side; background "
     "compaction folds the delta into the base once it crosses half "
     "this, and an insert that cannot fit evicts the pin instead", None),
    # -- adaptive execution tier (trino_tpu/adaptive/) --
    ("adaptive_execution", bool, False,
     "mid-query re-planning: materialize pipeline barriers (completed "
     "join build sides), diff observed rows/NDV against sql/stats.py "
     "estimates, and re-optimize the remaining plan when divergence "
     "crosses adaptive_replan_threshold; completed work is substituted "
     "back as literal sources and never redone", None),
    ("adaptive_replan_threshold", float, 4.0,
     "divergence ratio max(est,obs)/min(est,obs) at or above which an "
     "observation triggers re-planning of the remaining plan (and is "
     "counted in adaptive.divergences regardless of whether "
     "adaptive_execution is on)", None),
    ("skewed_join_salting", bool, False,
     "skew-aware join plane: when a build-side barrier's modal key "
     "crosses skew_hot_key_threshold, annotate the join so the mesh "
     "plane replicates hot build rows to every shard and salts hot "
     "probe rows across the all_to_all (requires adaptive_execution)",
     None),
    ("skew_hot_key_threshold", float, 0.2,
     "fraction of observed build rows a single key value must reach "
     "to be classified a heavy hitter", None),
    ("skew_spill_min_rows", int, 1 << 18,
     "minimum observed build rows before a divergent build-side "
     "barrier re-plans the join into hybrid-hash spill mode "
     "(pre-opened grace partitions)", None),
    ("mxu_join_enabled", bool, False,
     "plan high-fanout equi-join + aggregation as the MXU matmul "
     "join-project kernel (ops/mxu_join.py) when profitable", None),
    ("mxu_join_min_work", float, 16.0,
     "estimated fanout x build-NDV product at or above which the MXU "
     "join-project kernel is selected over the padded-gather path",
     None),
    ("shared_subtree_materialization", bool, False,
     "materialize identical subtrees (NOT IN rewrites plan the "
     "subquery twice; CTEs referenced twice) once into the "
     "generation-guarded spool and feed every consumer — and the "
     "re-planner — from the same rows", None),
    # -- recovery tier (trino_tpu/recovery/) --
    ("mesh_checkpoint_interval_chunks", int, 0,
     "snapshot the mesh step loop's device carries to the host-side "
     "generation-guarded checkpoint store every N chunk boundaries so "
     "MeshStuck/device-loss faults resume from the last checkpoint "
     "instead of chunk 0; 0 disables checkpointing", None),
    ("mesh_resume_attempts", int, 2,
     "max in-run resume attempts from a mesh checkpoint before the "
     "fault escalates to the page-plane fallback / QUERY retry", None),
    ("recovery_spool_stages", bool, False,
     "tee completed non-root fragment outputs into the subtree spool "
     "so QUERY-level retry substitutes finished stages as literal "
     "sources instead of recomputing them (FTE settles lift committed "
     "stage spool files into the same store)", None),
    # -- replicated serving meshes (trino_tpu/runtime/replicas.py) --
    ("mesh_replicas", int, 1,
     "carve the device set into this many identical sub-meshes "
     "(replica x partition named-axis grid); the coordinator "
     "load-balances mesh queries across healthy replicas and each "
     "replica runs the same prelude/step/flush programs unchanged; "
     "1 (or too few devices) keeps the single full-width mesh", None),
    ("replica_failover_enabled", bool, True,
     "when a replica dies or drains mid-query, re-place its in-flight "
     "chunked query onto a healthy sibling sub-mesh — the sibling "
     "restores the host-portable mesh checkpoint and continues from "
     "chunk k instead of falling back to the page plane", None),
    ("replica_breaker_threshold", int, 3,
     "consecutive mesh-run failures before a replica's circuit breaker "
     "opens (the replica leaves the placement pool until a later "
     "success closes it)", None),
    ("replica_breaker_cooldown_s", float, 1.0,
     "seconds an open replica breaker sits out before a half-open "
     "placement probe may try the replica again", None),
    # -- preemptive multi-tenancy (runtime/scheduler.py) --
    ("mesh_scheduler", bool, True,
     "run mesh queries through the chunk-granular weighted-fair "
     "scheduler (per-mesh run queue with fast-lane point lookups and "
     "virtual-time accounting per resource group) instead of a bare "
     "exec lock; False restores PR 17 serialization", None),
    ("preemption_enabled", bool, True,
     "allow a fast-lane arrival to park the running analytic at the "
     "next chunk boundary (device carries snapshot to the host "
     "checkpoint store, device memory released, resume from chunk k "
     "on the same warm rungs); False degrades preemption to in-place "
     "yields between whole runs", None),
    ("park_max_bytes", int, 256 << 20,
     "host-memory budget for parked query snapshots in the mesh "
     "checkpoint store; a park that would exceed it is refused and "
     "the query runs to completion instead (never query failure)",
     None),
    ("mesh_scheduler_weights", str, "",
     "per-resource-group scheduling weights for the mesh scheduler, "
     "'group=weight,...' (scheduling_weight analogue); unlisted "
     "groups weigh 1", None),
    ("mesh_scheduler_min_slice_chunks", int, 1,
     "minimum chunk-steps a query runs between preemptions "
     "(bounded-slice guarantee: a continuous fast-lane stream cannot "
     "live-lock the analytic)", None),
    ("mesh_scheduler_group", str, "",
     "resource group this session's mesh queries are accounted to in "
     "the weighted-fair scheduler; empty uses 'default'", None),
    ("mesh_steal_enabled", bool, True,
     "on drain failover of a chunked all-append query, split the "
     "unstarted chunk range across two sibling replicas (primary "
     "resumes [k, mid), helper computes [mid, K) and the primary "
     "merges the helper's packed live rows) instead of resuming "
     "wholesale on one", None),
    ("mesh_park_max_bytes", int, 0,
     "aggregate host-memory pool for parked snapshots apportioned "
     "across resource groups by scheduler weight (a group over its "
     "share gets an in-place yield instead of a park); 0 keeps the "
     "single undivided park_max_bytes budget", None),
    # -- multi-host replica fabric (runtime/fabric.py) --
    ("fabric_peers", str, "",
     "comma-separated base URIs of peer coordinator fabric endpoints "
     "(http://host:port); non-empty attaches the checkpoint push/pull "
     "fabric: checkpoints stream asynchronously to every peer and "
     "failover pulls the last pushed snapshot on demand", None),
    ("fabric_queue_depth", int, 8,
     "bounded depth of the asynchronous checkpoint push queue; a full "
     "queue sheds the push (fabric.push_sheds) instead of blocking "
     "the chunk loop", None),
    ("fabric_max_error_duration_s", float, 5.0,
     "per-peer transient-error budget for fabric pushes and pulls "
     "(RequestErrorTracker deadline); exhaustion degrades to a local "
     "restart, never query failure", None),
    # -- observability (runtime/tracing.py) --
    ("query_trace", str, "off",
     "record a full span tree per query (phases, stages, task attempts, "
     "operators; worker spans grafted into the coordinator's tree) "
     "exportable as JSON/Chrome trace-event via GET /v1/query/{id}/trace",
     ("off", "on")),
]:
    SYSTEM_PROPERTIES.register(_name, _type, _default, _desc, _allowed)


def load_properties_file(path: str) -> Dict[str, str]:
    """key=value config file (etc/config.properties format: # comments,
    blank lines ignored)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"malformed config line: {line!r}")
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def bind_session(session, overrides: Dict[str, Any]) -> None:
    """Apply validated property values onto a Session (the
    SessionPropertyManager.validate path)."""
    for name, value in overrides.items():
        value = SYSTEM_PROPERTIES.validate(name, value)
        if name == "memory_pool_bytes":
            value = value or None  # 0 means unlimited
        setattr(session, name, value)
