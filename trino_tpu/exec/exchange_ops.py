"""Exchange boundary operators: partitioned output + remote source.

Analogues of main/operator/output/PartitionedOutputOperator.java:46
(PagePartitioner:191 — hash rows into per-partition appenders feeding
the OutputBuffer) and main/operator/ExchangeOperator.java:44 /
MergeOperator.java:46 (a SourceOperator wrapping the exchange client,
optionally merge-sorting). SURVEY.md §2.8, §3.4.

TPU-first delta: partition ids are computed on device in one jitted
kernel over the whole batch; the host then splits the already-compacted
wire Page with numpy boolean masks (pages cross the process boundary on
the host side anyway). Dead rows never reach the wire.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import RelBatch
from trino_tpu.exec.operators import Operator, _concat_sort
from trino_tpu.ops import tz
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.exec.serde import Page
from trino_tpu.ops.hashing import (
    canonical_hash_input,
    hash32,
    partition_of,
)
from trino_tpu.ops.sort import SortKey


@partial(jax.jit, static_argnames=("n", "has_lut"))
def _partition_ids(keys, valids, luts, live, n: int, has_lut: tuple):
    """Device kernel: row -> destination partition (dead rows -> -1).
    Keys are canonicalized (dtype-widened; dictionary codes mapped to
    value hashes via `luts`) so co-partitioned fragments agree."""
    lanes = []
    li = 0
    for k, h in zip(keys, has_lut):
        if h:
            lanes.append(canonical_hash_input(k, luts[li]))
            li += 1
        else:
            lanes.append(canonical_hash_input(k))
    pid = partition_of(hash32(lanes, list(valids)), n)
    return jnp.where(live, pid, -1)


def split_page(page: Page, pid: np.ndarray, n: int) -> List[Page]:
    """Split a compacted wire page by per-row partition id: ONE native
    scatter pass over all partitions (PagePartitioner's per-partition
    appenders collapsed; trino_tpu/native). Nested columns (HostNested)
    partition by per-partition row-index gather — their flattened
    children follow the selected rows' slices."""
    from trino_tpu import native
    from trino_tpu.exec.serde import HostNested, slice_host_nested

    nested_idx = [
        i for i, c in enumerate(page.columns) if isinstance(c, HostNested)
    ]
    flat: List[np.ndarray] = []
    valid_pos: List[int] = []
    for i, c in enumerate(page.columns):
        if i in nested_idx:
            # placeholder keeps column positions aligned in `parts`
            flat.append(np.zeros(len(pid), dtype=np.int8))
        else:
            flat.append(c)
    for v in page.valids:
        if v is not None:
            valid_pos.append(len(flat))
            flat.append(v)
    parts = native.partition_scatter(flat, pid, n)
    counts = np.bincount(pid[pid >= 0], minlength=n)
    nested_rows = (
        {p: np.nonzero(pid == p)[0] for p in range(n)} if nested_idx else {}
    )
    width = page.width
    out = []
    for p in range(n):
        cols = list(parts[p][:width])
        for i in nested_idx:
            cols[i] = slice_host_nested(page.columns[i], nested_rows[p])
        valids: List = []
        vi = width
        for v in page.valids:
            if v is None:
                valids.append(None)
            else:
                valids.append(parts[p][vi])
                vi += 1
        out.append(
            Page(page.types, cols, valids, page.dictionaries, int(counts[p]))
        )
    return out


def hash_split_batch(
    batch: RelBatch,
    key_channels: Sequence[int],
    n: int,
    lut_cache: Optional[dict] = None,
) -> List[Page]:
    """Split a device batch into n wire pages by canonical key hash —
    the PagePartitioner core, shared by the exchange output operator and
    the grace-join partitioner (both must route equal keys identically)."""
    from trino_tpu.ops.hashing import dictionary_lut

    lut_cache = lut_cache if lut_cache is not None else {}
    keys, valids, luts, has_lut = [], [], [], []
    for c in key_channels:
        col = batch.columns[c]
        lut = None
        if col.dictionary is not None and len(col.dictionary) > 0:
            lut = lut_cache.get(col.dictionary.values)
            if lut is None:
                lut = jnp.asarray(dictionary_lut(col.dictionary))
                lut_cache[col.dictionary.values] = lut
        if lut is not None:
            luts.append(lut)
            has_lut.append(True)
        else:
            has_lut.append(False)
        data = col.data
        if col.type.kind == T.TypeKind.TIMESTAMP_TZ:
            # equal instants in different zones must land in the same
            # partition: hash the packed millis, never the zone bits
            data = data & ~tz.ZONE_MASK
        keys.append(data)
        valids.append(col.valid_mask())
    pid = _partition_ids(
        tuple(keys), tuple(valids), tuple(luts),
        batch.live_mask(), n, tuple(has_lut),
    )
    page = Page.from_batch(batch)
    live = (
        np.asarray(jax.device_get(batch.live)).astype(bool)
        if batch.live is not None
        else np.ones(batch.capacity, dtype=bool)
    )
    pid_np = np.asarray(jax.device_get(pid))[live]
    return split_page(page, pid_np, n)


class SkewedPartitionRebalancer:
    """Load-balanced routing for "arbitrary" output partitions
    (output/SkewedPartitionRebalancer.java analogue, reduced to its
    essence: the reference shifts traffic off skewed scaled-writer
    partitions once max/mean exceeds a threshold; routing every page to
    the least-loaded partition by cumulative bytes achieves the same
    bound continuously — valid precisely because "arbitrary" consumers
    need no key colocation)."""

    def __init__(self, n_partitions: int):
        self._bytes = [0.0] * max(n_partitions, 1)

    def pick(self, size_bytes: int) -> int:
        i = min(range(len(self._bytes)), key=lambda p: self._bytes[p])
        self._bytes[i] += max(size_bytes, 1)
        return i

    def skew(self) -> float:
        """max/mean load (1.0 = perfectly even) — observability hook."""
        mean = sum(self._bytes) / len(self._bytes)
        return (max(self._bytes) / mean) if mean else 1.0


class PartitionedOutputOperator(Operator):
    """Terminal sink of every fragment pipeline: splits each output batch
    into the task's OutputBuffer partitions. kind: "single" | "hash" |
    "broadcast" | "arbitrary" (the SystemPartitioningHandle set,
    SystemPartitioningHandle.java:48–55)."""

    def __init__(
        self,
        buffer,  # runtime.buffers.OutputBuffer
        kind: str,
        hash_channels: Sequence[int] = (),
        n_partitions: int = 1,
    ):
        assert kind in ("single", "hash", "broadcast", "arbitrary"), kind
        self._buffer = buffer
        self._kind = kind
        self._hash_channels = list(hash_channels)
        self._n = n_partitions
        self._rebalancer = SkewedPartitionRebalancer(n_partitions)
        self._finishing = False
        self._lut_cache: dict = {}

    def add_input(self, batch: RelBatch) -> None:
        if self._kind == "hash" and self._n > 1:
            parts = hash_split_batch(
                batch, self._hash_channels, self._n, self._lut_cache
            )
            for p, part in enumerate(parts):
                if part.row_count:
                    METRICS.increment("rows_shuffled", part.row_count)
                    self._buffer.enqueue(p, part)
            return
        page = Page.from_batch(batch)
        if page.row_count == 0:
            return
        if self._kind == "broadcast":
            # each replica crosses the wire: count the copies
            METRICS.increment("rows_shuffled", page.row_count * self._n)
            for p in range(self._n):
                self._buffer.enqueue(p, page)
        elif self._kind == "arbitrary":
            # least-loaded by bytes, not blind round-robin: uneven page
            # sizes otherwise skew downstream tasks
            METRICS.increment("rows_shuffled", page.row_count)
            self._buffer.enqueue(
                self._rebalancer.pick(page.size_bytes()), page
            )
        else:
            # single/gather (and hash collapsed to one partition) still
            # crosses the exchange: count it
            METRICS.increment("rows_shuffled", page.row_count)
            self._buffer.enqueue(0, page)

    def finish(self) -> None:
        if not self._finishing:
            self._finishing = True
            self._buffer.set_no_more_pages()

    def is_finished(self) -> bool:
        return self._finishing


class RemoteSourceOperator(Operator):
    """Source operator pulling wire pages from an exchange client.
    With `merge_keys` it behaves like MergeOperator: waits for all
    producers, then emits one merged sorted batch."""

    def __init__(
        self,
        source,  # poll() -> Optional[Page]; is_finished() -> bool
        merge_keys: Optional[Sequence[SortKey]] = None,
        ladder=None,  # compile.shapes.CapacityLadder; snaps page capacities
    ):
        self._source = source
        self._merge_keys = tuple(merge_keys) if merge_keys else None
        self._ladder = ladder
        self._pending: List[RelBatch] = []
        self._done = False

    def _page_capacity(self, row_count: int) -> Optional[int]:
        # snap exchange-page capacities onto the session's capacity
        # ladder (base 2 == the native bucket grid, so the default is a
        # no-op; a coarser ladder collapses consumer-side classes)
        if self._ladder is None:
            return None
        return self._ladder.rung(row_count)

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[RelBatch]:
        if self._done:
            return None
        if self._merge_keys is not None:
            page = self._source.poll()
            while page is not None:
                if page.row_count:
                    self._pending.append(
                        page.to_batch(capacity=self._page_capacity(page.row_count))
                    )
                page = self._source.poll()
            if not self._source.is_finished():
                return None
            self._done = True
            if not self._pending:
                return None
            out = _concat_sort(tuple(self._pending), self._merge_keys)
            self._pending = []
            return out
        page = self._source.poll()
        # skip zero-row pages INSIDE the call: returning None for one
        # while is_blocked() reports "drained, not blocked" would let the
        # driver diagnose a stall one poll before _done could be set
        while page is not None and page.row_count == 0:
            page = self._source.poll()
        if page is None:
            if self._source.is_finished():
                self._done = True
            return None
        return page.to_batch(capacity=self._page_capacity(page.row_count))

    def is_blocked(self) -> bool:
        return not self._done and not self._source.is_finished()

    def is_finished(self) -> bool:
        # _done is set by get_output once the source reports finished and
        # the last page has been drained (or merged and emitted)
        return self._done
