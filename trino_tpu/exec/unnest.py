"""UNNEST over ARRAY columns.

Analogue of main/operator/unnest/UnnestOperator.java. TPU-first split:
index CONSTRUCTION (which (row, element) pairs exist) is cheap integer
work done on host from the lengths arrays; all DATA movement — the
replicated child columns and the flattened element gathers — runs as
vectorized device gathers at bucketed output capacity. The flat element
store never moves host-side.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import ArrayColumn, Column, RelBatch, bucket_capacity


class UnnestOperator:
    """One batch in -> one expanded batch out (streaming per batch; no
    consolidation needed, expansion is row-local)."""

    def __init__(self, array_channels, ordinality: bool, input_schema):
        self._channels = list(array_channels)
        self._ordinality = ordinality
        self._schema = input_schema
        self._out: Optional[RelBatch] = None
        self._finishing = False

    def needs_input(self) -> bool:
        return self._out is None and not self._finishing

    def is_blocked(self) -> bool:
        return False

    def finish(self) -> None:
        self._finishing = True

    def is_finished(self) -> bool:
        return self._finishing and self._out is None

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def add_input(self, batch: RelBatch) -> None:
        live = np.asarray(jax.device_get(batch.live_mask()))
        arrays: List[ArrayColumn] = []
        for ch in self._channels:
            col = batch.columns[ch]
            if not isinstance(col, ArrayColumn):
                raise TypeError(
                    "UNNEST argument is not an ARRAY column "
                    "(array values cannot cross an exchange yet)"
                )
            arrays.append(col)
        lengths = []
        for col in arrays:
            ln = np.asarray(jax.device_get(col.data)).astype(np.int64)
            if col.valid is not None:
                ln = np.where(
                    np.asarray(jax.device_get(col.valid)), ln, 0
                )
            lengths.append(np.where(live, ln, 0))
        starts = [
            np.asarray(jax.device_get(col.starts)) for col in arrays
        ]
        # zip semantics: per row, max length across the arrays
        per_row = np.maximum.reduce(lengths)
        total = int(per_row.sum())
        row_idx = np.repeat(np.arange(len(per_row)), per_row)
        # element index within the row: global position - row's start
        cum = np.concatenate([[0], np.cumsum(per_row)[:-1]])
        elem_idx = np.arange(total, dtype=np.int64) - cum[row_idx]
        cap = bucket_capacity(max(total, 1))
        pad_rows = np.zeros(cap, dtype=np.int64)
        pad_rows[:total] = row_idx
        pad_elems = np.zeros(cap, dtype=np.int64)
        pad_elems[:total] = elem_idx
        d_rows = jnp.asarray(pad_rows)
        d_elems = jnp.asarray(pad_elems)
        live_out = np.zeros(cap, dtype=bool)
        live_out[:total] = True
        d_live = jnp.asarray(live_out)
        # replicate child columns (device gather)
        out_cols = [c.gather(d_rows) for c in batch.columns]
        # element columns: flat gather with per-array zip-padding NULLs
        for col, ln, st in zip(arrays, lengths, starts):
            flat_pos = jnp.asarray(st[pad_rows]) + d_elems
            in_range = d_elems < jnp.asarray(ln[pad_rows])
            ecol = col.flat.gather(flat_pos)
            valid = (
                in_range
                if ecol.valid is None
                else (ecol.valid & in_range)
            )
            if isinstance(ecol, ArrayColumn):
                # ARRAY(ARRAY(...)): the gathered element is itself an
                # array view — keep starts/flat, just merge validity
                out_cols.append(ArrayColumn(
                    col.type.element, ecol.data, valid,
                    ecol.dictionary, ecol.starts, ecol.flat,
                ))
                continue
            out_cols.append(
                Column(col.type.element, ecol.data, valid, ecol.dictionary)
            )
        if self._ordinality:
            out_cols.append(
                Column(T.BIGINT, (d_elems + 1).astype(jnp.int64), None, None)
            )
        self._out = RelBatch(out_cols, d_live)
