from trino_tpu.exec.driver import Driver, Pipeline, run_pipelines
from trino_tpu.exec.operators import (
    AggSpec,
    CollectorSink,
    CrossJoinBuildSink,
    CrossJoinOperator,
    FilterProjectOperator,
    HashAggregationOperator,
    HashBuildSink,
    JoinBridge,
    LimitOperator,
    LookupJoinOperator,
    Operator,
    SortOperator,
    TableScanOperator,
    TopNOperator,
    ValuesOperator,
)
