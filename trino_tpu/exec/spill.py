"""Spill-to-disk: out-of-core state for aggregation/sort/join.

Analogue of main/spiller/ (FileSingleStreamSpiller — serialized pages to
local disk; GenericPartitioningSpiller — hash-partitioned spill files;
docs/admin/spill.rst — SURVEY.md §5.4). The wire serde is the spill
format, so spilled state is exactly what an exchange would ship: for
aggregation that means partial-state pages merge back with the same
FINAL-step machinery used by the distributed path (HBM -> host-disk
eviction reuses the partial->final contract)."""

from __future__ import annotations

import os
import tempfile
from typing import Iterator, List, Optional

from trino_tpu.block import RelBatch
from trino_tpu.exec.serde import Page, deserialize_page, serialize_batch, serialize_page


class FileSpiller:
    """Append-only single-stream spiller (FileSingleStreamSpiller)."""

    def __init__(self, spill_dir: Optional[str] = None):
        self._dir = spill_dir or tempfile.gettempdir()
        fd, self._path = tempfile.mkstemp(
            prefix="trino-tpu-spill-", suffix=".pages", dir=self._dir
        )
        self._file = os.fdopen(fd, "wb+")
        self._offsets: List[tuple] = []  # (offset, length, capacity|None)
        self.spilled_bytes = 0

    def spill(self, batch: RelBatch) -> None:
        # record the source capacity so re-reads re-enter the operator
        # on the class it was first compiled for (shape stabilization:
        # serialization compacts to live rows, and re-bucketing the
        # compacted count would mint a fresh — usually smaller — class)
        self._append(serialize_batch(batch), capacity=batch.capacity)

    def spill_page(self, page: Page, capacity: Optional[int] = None) -> None:
        self._append(serialize_page(page), capacity=capacity)

    def _append(self, data: bytes, capacity: Optional[int] = None) -> None:
        off = self._file.tell()
        self._file.write(data)
        self._offsets.append((off, len(data), capacity))
        self.spilled_bytes += len(data)

    @property
    def batch_count(self) -> int:
        return len(self._offsets)

    def unspill(self) -> Iterator[RelBatch]:
        """Read batches back (merge-on-unspill consumes these) at their
        original spill-time capacity."""
        self._file.flush()
        for off, ln, cap in self._offsets:
            self._file.seek(off)
            page = deserialize_page(self._file.read(ln))
            yield page.to_batch(capacity=cap)

    def unspill_pages(self) -> Iterator[Page]:
        self._file.flush()
        for off, ln, _cap in self._offsets:
            self._file.seek(off)
            yield deserialize_page(self._file.read(ln))

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            try:
                os.unlink(self._path)
            except OSError:
                pass


class GracePartitionSpill:
    """Hash-partitioned spill of one JOIN side (GenericPartitioningSpiller
    + PartitionedLookupSourceFactory.java:56 analogue): rows route to one
    of N partition files by canonical key hash — the same routing the
    exchange uses, so build and probe sides agree — and the join later
    builds + probes one partition at a time (grace hash join)."""

    def __init__(self, n_partitions: int, key_channels,
                 spill_dir: Optional[str] = None):
        self.n = n_partitions
        self.key_channels = list(key_channels)
        self._spillers = [
            FileSpiller(spill_dir) for _ in range(n_partitions)
        ]
        self._lut_cache: dict = {}
        self.spilled_bytes = 0

    def add(self, batch: RelBatch) -> None:
        from trino_tpu.exec.exchange_ops import hash_split_batch

        pages = hash_split_batch(
            batch, self.key_channels, self.n, self._lut_cache
        )
        for p, page in enumerate(pages):
            if page.row_count:
                self._spillers[p].spill_page(page)
        self.spilled_bytes = sum(s.spilled_bytes for s in self._spillers)

    def partition_pages(self, p: int) -> List[Page]:
        return list(self._spillers[p].unspill_pages())

    def close(self) -> None:
        for s in self._spillers:
            s.close()
