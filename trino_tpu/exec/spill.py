"""Spill-to-disk: out-of-core state for aggregation/sort/join.

Analogue of main/spiller/ (FileSingleStreamSpiller — serialized pages to
local disk; GenericPartitioningSpiller — hash-partitioned spill files;
docs/admin/spill.rst — SURVEY.md §5.4). The wire serde is the spill
format, so spilled state is exactly what an exchange would ship: for
aggregation that means partial-state pages merge back with the same
FINAL-step machinery used by the distributed path (HBM -> host-disk
eviction reuses the partial->final contract)."""

from __future__ import annotations

import os
import tempfile
from typing import Iterator, List, Optional

from trino_tpu.block import RelBatch
from trino_tpu.exec.serde import deserialize_page, serialize_batch


class FileSpiller:
    """Append-only single-stream spiller (FileSingleStreamSpiller)."""

    def __init__(self, spill_dir: Optional[str] = None):
        self._dir = spill_dir or tempfile.gettempdir()
        fd, self._path = tempfile.mkstemp(
            prefix="trino-tpu-spill-", suffix=".pages", dir=self._dir
        )
        self._file = os.fdopen(fd, "wb+")
        self._offsets: List[tuple] = []  # (offset, length)
        self.spilled_bytes = 0

    def spill(self, batch: RelBatch) -> None:
        data = serialize_batch(batch)
        off = self._file.tell()
        self._file.write(data)
        self._offsets.append((off, len(data)))
        self.spilled_bytes += len(data)

    @property
    def batch_count(self) -> int:
        return len(self._offsets)

    def unspill(self) -> Iterator[RelBatch]:
        """Read batches back (merge-on-unspill consumes these)."""
        self._file.flush()
        for off, ln in self._offsets:
            self._file.seek(off)
            yield deserialize_page(self._file.read(ln)).to_batch()

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            try:
                os.unlink(self._path)
            except OSError:
                pass
