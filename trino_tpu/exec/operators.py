"""Physical operators.

Analogue of Trino's operator layer (main/operator/Operator.java:21-96 —
needsInput/addInput/getOutput/finish/isBlocked; SURVEY.md §2.6), pulled
batch-at-a-time by the host Driver while all data-parallel work runs as
jit-compiled XLA programs over RelBatch pytrees. TPU-first deltas:

- Operators never loop over rows; each add_input/get_output launches a
  fixed-shape device program (the analogue of the JIT'd PageProcessor /
  GroupByHash / PagesHash inner loops, compiled by jax.jit instead of
  airlift-bytecode — SURVEY.md §2.9).
- Filters only flip `live` mask bits; dead rows ride along until an
  explicit compact (static shapes).
- Dynamic result sizes (join fan-out, group counts) are handled by the
  two-phase count/expand pattern with host-chosen bucketed capacities.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import (
    Column,
    Dictionary,
    RelBatch,
    bucket_capacity,
    concat_batches,
)
from trino_tpu.expr.compile import Bound
from trino_tpu.ops import groupby as G
from trino_tpu.ops import join as J
from trino_tpu.ops.sort import SortKey, sort_order


class Operator:
    """Pull/push contract (main/operator/Operator.java:21)."""

    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, batch: RelBatch) -> None:
        raise NotImplementedError

    def get_output(self) -> Optional[RelBatch]:
        return None

    def finish(self) -> None:
        """No more input will arrive (Operator.finish)."""
        self._finishing = True

    def is_finished(self) -> bool:
        raise NotImplementedError

    _finishing = False


def empty_batch(schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
                capacity: int = 16) -> RelBatch:
    cols = [
        Column(t, jnp.zeros(capacity, dtype=t.dtype), None, d) for t, d in schema
    ]
    return RelBatch(cols, jnp.zeros(capacity, dtype=jnp.bool_))


def batch_schema(batch: RelBatch) -> List[Tuple[T.DataType, Optional[Dictionary]]]:
    return [(c.type, c.dictionary) for c in batch.columns]


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------


class TableScanOperator(Operator):
    """Pulls batches from a ConnectorPageSource over a list of splits
    (TableScanOperator.java:47)."""

    def __init__(self, page_source, splits, columns: Sequence[str], batch_rows: int):
        self._iters = iter(
            batch
            for split in splits
            for batch in page_source.batches(split, columns, batch_rows)
        )
        self._done = False

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[RelBatch]:
        if self._done:
            return None
        nxt = next(self._iters, None)
        if nxt is None:
            self._done = True
            return None
        return nxt

    def is_finished(self) -> bool:
        return self._done


class ValuesOperator(Operator):
    """Emits a fixed list of batches (ValuesOperator.java)."""

    def __init__(self, batches: Sequence[RelBatch]):
        self._batches = list(batches)

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[RelBatch]:
        if self._batches:
            return self._batches.pop(0)
        return None

    def is_finished(self) -> bool:
        return not self._batches


# ---------------------------------------------------------------------------
# Filter + project
# ---------------------------------------------------------------------------


class FilterProjectOperator(Operator):
    """Bound filter/projections fused into one jitted device program —
    the FilterAndProjectOperator + PageProcessor analogue
    (main/operator/FilterAndProjectOperator.java:40, project/PageProcessor.java:53)."""

    def __init__(self, filter_bound: Optional[Bound], projections: Sequence[Bound]):
        self._out: Optional[RelBatch] = None
        self._done = False
        projections = list(projections)

        def fn(batch: RelBatch) -> RelBatch:
            cols = [c.data for c in batch.columns]
            valids = [c.valid for c in batch.columns]
            live = batch.live
            if filter_bound is not None:
                d, v = filter_bound.fn(cols, valids)
                keep = d if v is None else (d & v)
                live = keep if live is None else (live & keep)
            out_cols = []
            for b in projections:
                data, valid = b.fn(cols, valids)
                out_cols.append(Column(b.type, data, valid, b.dictionary))
            return RelBatch(out_cols, live)

        self._fn = jax.jit(fn)

    def needs_input(self) -> bool:
        return self._out is None and not self._finishing

    def add_input(self, batch: RelBatch) -> None:
        self._out = self._fn(batch)

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


# ---------------------------------------------------------------------------
# Limit
# ---------------------------------------------------------------------------


@jax.jit
def _limit_batch(batch: RelBatch, remaining: jnp.ndarray):
    live = batch.live_mask()
    rank = jnp.cumsum(live.astype(jnp.int64))  # 1-based among live rows
    keep = live & (rank <= remaining)
    taken = jnp.minimum(rank[-1] if live.shape[0] else jnp.int64(0), remaining)
    return RelBatch(batch.columns, keep), taken


class LimitOperator(Operator):
    """LIMIT n (LimitOperator.java): masks rows past the remaining count."""

    def __init__(self, n: int):
        self._remaining = n
        self._out: Optional[RelBatch] = None

    def needs_input(self) -> bool:
        return self._out is None and self._remaining > 0 and not self._finishing

    def add_input(self, batch: RelBatch) -> None:
        out, taken = _limit_batch(batch, jnp.int64(self._remaining))
        self._remaining -= int(taken)
        self._out = out

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._out is None and (self._finishing or self._remaining <= 0)


# ---------------------------------------------------------------------------
# Sort / TopN
# ---------------------------------------------------------------------------


def _apply_sort(batch: RelBatch, keys: Sequence[SortKey]) -> jnp.ndarray:
    return sort_order(
        [batch.columns[k.channel].data for k in keys],
        [batch.columns[k.channel].valid for k in keys],
        [k.descending for k in keys],
        [k.nulls_first for k in keys],
        batch.live,
    )


@jax.jit
def _gather_sorted(batch: RelBatch, order: jnp.ndarray):
    n_live = jnp.sum(batch.live_mask())
    live = jnp.arange(order.shape[0]) < n_live
    return batch.gather(order, live)


class SortOperator(Operator):
    """Full ORDER BY: consolidate + one device sort at finish
    (OrderByOperator.java:44; comparator chains become stable argsorts)."""

    def __init__(self, keys: Sequence[SortKey],
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]]):
        self._keys = list(keys)
        self._schema = list(input_schema)
        self._inputs: List[RelBatch] = []
        self._out: Optional[RelBatch] = None

    def add_input(self, batch: RelBatch) -> None:
        self._inputs.append(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        batches = self._inputs or [empty_batch(self._schema)]
        merged = concat_batches(batches)
        order = _apply_sort(merged, self._keys)
        self._out = _gather_sorted(merged, order)
        self._inputs = []

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


class TopNOperator(Operator):
    """ORDER BY + LIMIT n with a bounded device reservoir
    (TopNOperator.java:35)."""

    def __init__(self, keys: Sequence[SortKey], n: int,
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]]):
        self._keys = list(keys)
        self._n = n
        self._schema = list(input_schema)
        self._reservoir: Optional[RelBatch] = None
        self._out: Optional[RelBatch] = None

    def add_input(self, batch: RelBatch) -> None:
        merged = (
            batch
            if self._reservoir is None
            else concat_batches([self._reservoir, batch])
        )
        order = _apply_sort(merged, self._keys)
        cap = bucket_capacity(min(self._n, merged.capacity))
        top = order[:cap]
        n_live = jnp.minimum(jnp.sum(merged.live_mask()), self._n)
        live = jnp.arange(cap) < n_live
        self._reservoir = merged.gather(top, live)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        self._out = (
            self._reservoir
            if self._reservoir is not None
            else empty_batch(self._schema)
        )

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


# ---------------------------------------------------------------------------
# Hash aggregation
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind in {sum,count,count_star,avg,min,max,any},
    arg_channel indexes the operator's input (None for count_star),
    out_type is the SQL result type."""

    kind: str
    arg_channel: Optional[int]
    out_type: T.DataType
    distinct: bool = False


def _agg_state_init(spec: AggSpec, arg_dtype, capacity: int):
    """(value_state, count_state) arrays of shape (capacity,)."""
    if spec.kind in ("count", "count_star"):
        return (jnp.zeros(capacity, dtype=jnp.int64),)
    if spec.kind in ("sum", "avg"):
        acc_dt = jnp.float64 if np.issubdtype(arg_dtype, np.floating) else jnp.int64
        return (
            jnp.zeros(capacity, dtype=acc_dt),
            jnp.zeros(capacity, dtype=jnp.int64),
        )
    if spec.kind in ("min", "max"):
        if np.issubdtype(arg_dtype, np.floating):
            extreme = jnp.inf if spec.kind == "min" else -jnp.inf
        elif arg_dtype == np.bool_:
            extreme = True if spec.kind == "min" else False
        else:
            info = np.iinfo(arg_dtype)
            extreme = info.max if spec.kind == "min" else info.min
        return (
            jnp.full(capacity, extreme, dtype=arg_dtype),
            jnp.zeros(capacity, dtype=jnp.int64),
        )
    if spec.kind == "any":
        return (
            jnp.zeros(capacity, dtype=arg_dtype),
            jnp.zeros(capacity, dtype=jnp.int64),
        )
    raise NotImplementedError(spec.kind)


def _agg_state_update(spec: AggSpec, state, gid, data, valid, live, capacity):
    """Scatter one batch into the running state. gid == capacity drops."""
    weight = live if valid is None else (live & valid)
    idx = jnp.where(weight, gid, capacity)
    if spec.kind in ("count", "count_star"):
        (cnt,) = state
        return (cnt.at[idx].add(1, mode="drop"),)
    if spec.kind in ("sum", "avg"):
        acc, cnt = state
        return (
            acc.at[idx].add(data.astype(acc.dtype), mode="drop"),
            cnt.at[idx].add(1, mode="drop"),
        )
    if spec.kind in ("min", "max"):
        acc, cnt = state
        op = acc.at[idx].min if spec.kind == "min" else acc.at[idx].max
        return (op(data, mode="drop"), cnt.at[idx].add(1, mode="drop"))
    if spec.kind == "any":
        acc, cnt = state
        first = cnt == 0
        upd = acc.at[idx].set(data, mode="drop")
        return (jnp.where(first, upd, acc), cnt.at[idx].add(1, mode="drop"))
    raise NotImplementedError(spec.kind)


def _agg_state_migrate(spec: AggSpec, arg_dtype, state, remap, new_capacity):
    """Move accumulator state through a table rebuild: new[remap[i]] = old[i].
    Fresh slots must hold the same identity element as _agg_state_init
    (min/max extremes, not zero)."""
    fresh = _agg_state_init(spec, arg_dtype, new_capacity)
    return tuple(
        f.at[remap].set(arr, mode="drop") for f, arr in zip(fresh, state)
    )


def _agg_output(spec: AggSpec, state, arg_type: Optional[T.DataType],
                arg_dict: Optional[Dictionary]) -> Column:
    """Finalize a state into the SQL result column. Decimal accumulators
    hold scaled int64 at the ARG's scale; rescale to the output type."""
    out_t = spec.out_type
    if spec.kind in ("count", "count_star"):
        (cnt,) = state
        return Column(out_t, cnt.astype(jnp.int64), None, None)
    acc, cnt = state
    has = cnt > 0
    arg_sf = (
        T.decimal_scale_factor(arg_type)
        if arg_type is not None and arg_type.is_decimal
        else 1
    )
    out_sf = T.decimal_scale_factor(out_t) if out_t.is_decimal else None
    if spec.kind == "sum":
        if out_t.is_floating:
            return Column(out_t, acc.astype(out_t.dtype) / arg_sf, has, None)
        if out_sf is not None and out_sf != arg_sf:
            acc = acc * (out_sf // arg_sf) if out_sf > arg_sf else acc // (arg_sf // out_sf)
        return Column(out_t, acc.astype(out_t.dtype), has, None)
    if spec.kind == "avg":
        q = acc.astype(jnp.float64) / jnp.maximum(cnt, 1)
        if out_t.is_floating:
            return Column(out_t, (q / arg_sf).astype(out_t.dtype), has, None)
        # decimal average: rescale to the output scale, round half away
        q = q * (out_sf / arg_sf)
        data = (jnp.sign(q) * jnp.floor(jnp.abs(q) + 0.5)).astype(out_t.dtype)
        return Column(out_t, data, has, None)
    if spec.kind in ("min", "max", "any"):
        safe = jnp.where(has, acc, jnp.zeros((), dtype=acc.dtype))
        if out_t.is_floating and arg_sf != 1:
            return Column(out_t, safe.astype(out_t.dtype) / arg_sf, has, None)
        return Column(out_t, safe.astype(out_t.dtype), has, arg_dict)
    raise NotImplementedError(spec.kind)


class HashAggregationOperator(Operator):
    """GROUP BY + aggregates over the streaming group table
    (HashAggregationOperator.java:53 + GroupByHash; rebuild-on-overflow
    replaces tryRehash). `group_channels` select the key columns;
    aggregates read their arg channels. Output schema =
    [group keys..., aggregate results...]."""

    def __init__(
        self,
        group_channels: Sequence[int],
        aggregates: Sequence[AggSpec],
        input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
        initial_capacity: int = 1024,
    ):
        self._group_channels = list(group_channels)
        self._aggs = list(aggregates)
        self._schema = list(input_schema)
        self._global = not self._group_channels
        cap = 1 if self._global else initial_capacity
        self._capacity = cap
        key_dtypes = [self._schema[c][0].dtype for c in self._group_channels]
        self._table = G.new_group_table(key_dtypes, cap) if not self._global else None
        self._states = [
            _agg_state_init(
                a,
                self._schema[a.arg_channel][0].dtype
                if a.arg_channel is not None
                else np.int64,
                cap,
            )
            for a in self._aggs
        ]
        self._out: Optional[RelBatch] = None
        self._seen_any = False

        @jax.jit
        def _update_states(states, gid, batch: RelBatch):
            capacity = states[0][0].shape[0]
            live = batch.live_mask()
            new_states = []
            for a, st in zip(self._aggs, states):
                if a.arg_channel is None:
                    data, valid = jnp.zeros_like(live, dtype=jnp.int64), None
                else:
                    col = batch.columns[a.arg_channel]
                    data, valid = col.data, col.valid
                new_states.append(
                    _agg_state_update(a, st, gid, data, valid, live, capacity)
                )
            return new_states

        self._update_states = _update_states

    def add_input(self, batch: RelBatch) -> None:
        self._seen_any = True
        if self._global:
            gid = jnp.where(batch.live_mask(), 0, 1).astype(jnp.int32)
        else:
            keys = [batch.columns[c].data for c in self._group_channels]
            valids = [batch.columns[c].valid_mask() for c in self._group_channels]
            gid, table, overflowed = G.insert_group_ids(
                self._table, keys, valids, batch.live_mask()
            )
            self._table = table
            # grow-and-retry until the whole batch fits (keys inserted by
            # a failed round carry zero state, so re-inserting is safe:
            # accumulation below runs exactly once)
            while bool(overflowed):
                self._grow(self._capacity * 2)
                gid, self._table, overflowed = G.insert_group_ids(
                    self._table, keys, valids, batch.live_mask()
                )
            # keep load factor below ~62% so probe chains stay short
            if int(self._table.num_groups()) * 8 > self._capacity * 5:
                self._grow_after = True
        self._states = self._update_states(self._states, gid, batch)
        if getattr(self, "_grow_after", False):
            self._grow_after = False
            self._grow(self._capacity * 2)

    def _grow(self, new_capacity: int) -> None:
        self._table, remap = G.grow_table(self._table, new_capacity)
        self._states = [
            _agg_state_migrate(a, self._arg_dtype(a), st, remap, new_capacity)
            for a, st in zip(self._aggs, self._states)
        ]
        self._capacity = new_capacity

    def _arg_dtype(self, a: AggSpec):
        return (
            self._schema[a.arg_channel][0].dtype
            if a.arg_channel is not None
            else np.int64
        )

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        cols: List[Column] = []
        if self._global:
            live = jnp.ones(1, dtype=jnp.bool_)
        else:
            live = self._table.slot_used
            for ch, sk, sv in zip(
                self._group_channels, self._table.slot_keys, self._table.slot_valids
            ):
                t, d = self._schema[ch]
                cols.append(Column(t, sk, sv, d))
        for a, st in zip(self._aggs, self._states):
            arg_t, arg_d = (
                self._schema[a.arg_channel] if a.arg_channel is not None else (None, None)
            )
            cols.append(_agg_output(a, st, arg_t, arg_d))
        self._out = RelBatch(cols, live)

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


# ---------------------------------------------------------------------------
# Hash join
# ---------------------------------------------------------------------------


class JoinBridge:
    """Build->probe handoff (PartitionedLookupSourceFactory analogue,
    join/PartitionedLookupSourceFactory.java:56). The planner runs the
    build pipeline to completion before starting the probe pipeline."""

    def __init__(self):
        self.lookup_source: Optional[J.LookupSource] = None
        self.build_batch: Optional[RelBatch] = None


class HashBuildSink(Operator):
    """Consumes the build side, consolidates, builds the LookupSource
    (HashBuilderOperator.java:58 — one sort instead of row inserts)."""

    def __init__(self, bridge: JoinBridge, key_channels: Sequence[int],
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]]):
        self._bridge = bridge
        self._keys = list(key_channels)
        self._schema = list(input_schema)
        self._inputs: List[RelBatch] = []

    def add_input(self, batch: RelBatch) -> None:
        self._inputs.append(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        merged = concat_batches(self._inputs or [empty_batch(self._schema)])
        keys = [merged.columns[c].data for c in self._keys]
        valids = [merged.columns[c].valid_mask() for c in self._keys]
        self._bridge.lookup_source = J.build_lookup(keys, valids, merged.live_mask())
        self._bridge.build_batch = merged
        self._inputs = []

    def get_output(self) -> Optional[RelBatch]:
        return None

    def is_finished(self) -> bool:
        return self._finishing


class LookupJoinOperator(Operator):
    """Probe side (LookupJoinOperator.java:36). join_type in
    {inner, left, semi, anti}. Output schema for inner/left =
    [probe columns..., build columns...]; for semi/anti = probe columns.

    `residual` (optional Bound over the concatenated pair schema) is
    evaluated on candidate pairs BEFORE match flags are computed, which
    is what makes filtered semi/anti joins (Q21-style `l2.suppkey <>
    l1.suppkey`) correct.
    """

    def __init__(
        self,
        bridge: JoinBridge,
        key_channels: Sequence[int],
        join_type: str,
        probe_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
        residual: Optional[Bound] = None,
    ):
        self._bridge = bridge
        self._keys = list(key_channels)
        self._type = join_type
        self._probe_schema = list(probe_schema)
        self._residual = residual
        self._outputs: List[RelBatch] = []

    def needs_input(self) -> bool:
        return not self._outputs and not self._finishing

    def _pair_batch(self, probe: RelBatch, pi, bi, ok) -> RelBatch:
        build = self._bridge.build_batch
        cols = [c.gather(pi) for c in probe.columns]
        cols += [c.gather(bi) for c in build.columns]
        return RelBatch(cols, ok)

    def add_input(self, probe: RelBatch) -> None:
        ls = self._bridge.lookup_source
        keys = [probe.columns[c].data for c in self._keys]
        valids = [probe.columns[c].valid_mask() for c in self._keys]
        live = probe.live_mask()
        lo, counts, total = J.probe_counts(ls, keys, valids, live)
        total = int(total)
        out_cap = bucket_capacity(max(total, 1))
        pi, bi, ok = J.expand_matches(ls, keys, valids, lo, counts, out_cap)
        pairs = self._pair_batch(probe, pi, bi, ok)
        if self._residual is not None:
            cols = [c.data for c in pairs.columns]
            vs = [c.valid for c in pairs.columns]
            d, v = self._residual.fn(cols, vs)
            keep = d if v is None else (d & v)
            ok = ok & keep
            pairs = RelBatch(pairs.columns, ok)
        if self._type == "inner":
            self._outputs.append(pairs)
            return
        matched = J.probe_matched_flags(probe.capacity, pi, ok)
        if self._type == "semi":
            self._outputs.append(probe.mask(matched))
            return
        if self._type == "anti":
            self._outputs.append(probe.mask(~matched))
            return
        if self._type == "left":
            self._outputs.append(pairs)
            # unmatched probe rows keep probe columns, NULL build columns
            build = self._bridge.build_batch
            nulls = [
                Column(
                    c.type,
                    jnp.zeros(probe.capacity, dtype=c.type.dtype),
                    jnp.zeros(probe.capacity, dtype=jnp.bool_),
                    c.dictionary,
                )
                for c in build.columns
            ]
            self._outputs.append(
                RelBatch(list(probe.columns) + nulls, live & ~matched)
            )
            return
        raise NotImplementedError(self._type)

    def get_output(self) -> Optional[RelBatch]:
        if self._outputs:
            return self._outputs.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


# ---------------------------------------------------------------------------
# Cross join (NestedLoopJoinOperator.java analogue)
# ---------------------------------------------------------------------------


class CrossJoinBuildSink(Operator):
    """Collects the (small) build side of a cross join."""

    def __init__(self, bridge: JoinBridge,
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]]):
        self._bridge = bridge
        self._schema = list(input_schema)
        self._inputs: List[RelBatch] = []

    def add_input(self, batch: RelBatch) -> None:
        self._inputs.append(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        merged = concat_batches(self._inputs or [empty_batch(self._schema)]).compact()
        self._bridge.build_batch = merged
        self._inputs = []

    def is_finished(self) -> bool:
        return self._finishing


class CrossJoinOperator(Operator):
    """Probe x build cartesian product; build side expected small
    (scalar-subquery bridges are 1 row)."""

    def __init__(self, bridge: JoinBridge):
        self._bridge = bridge
        self._outputs: List[RelBatch] = []

    def needs_input(self) -> bool:
        return not self._outputs and not self._finishing

    def add_input(self, probe: RelBatch) -> None:
        build = self._bridge.build_batch
        n_build = build.row_count()
        for b in range(n_build):
            bcols = [
                Column(
                    c.type,
                    jnp.broadcast_to(c.data[b], (probe.capacity,)),
                    None
                    if c.valid is None
                    else jnp.broadcast_to(c.valid[b], (probe.capacity,)),
                    c.dictionary,
                )
                for c in build.columns
            ]
            self._outputs.append(RelBatch(list(probe.columns) + bcols, probe.live))

    def get_output(self) -> Optional[RelBatch]:
        if self._outputs:
            return self._outputs.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


# ---------------------------------------------------------------------------
# Sink
# ---------------------------------------------------------------------------


class CollectorSink(Operator):
    """Terminal sink gathering result batches (the coordinator-protocol
    Query.getNextResult analogue for the in-process runner)."""

    def __init__(self):
        self.batches: List[RelBatch] = []

    def add_input(self, batch: RelBatch) -> None:
        self.batches.append(batch)

    def is_finished(self) -> bool:
        return self._finishing

    def rows(self) -> List[list]:
        out = []
        for b in self.batches:
            out.extend(b.to_pylists())
        return out
