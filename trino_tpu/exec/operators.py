"""Physical operators.

Analogue of Trino's operator layer (main/operator/Operator.java:21-96 —
needsInput/addInput/getOutput/finish/isBlocked; SURVEY.md §2.6), pulled
batch-at-a-time by the host Driver while all data-parallel work runs as
jit-compiled XLA programs over RelBatch pytrees. TPU-first deltas:

- Operators never loop over rows; each add_input/get_output launches a
  fixed-shape device program (the analogue of the JIT'd PageProcessor /
  GroupByHash / PagesHash inner loops, compiled by jax.jit instead of
  airlift-bytecode — SURVEY.md §2.9).
- Filters only flip `live` mask bits; dead rows ride along until an
  explicit compact (static shapes).
- Dynamic result sizes (join fan-out, group counts) are handled by the
  two-phase count/expand pattern with host-chosen bucketed capacities.
"""

from __future__ import annotations

import dataclasses
import os as _os
import threading as _threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import (
    Column,
    Dictionary,
    RelBatch,
    bucket_capacity,
    concat_batches,
)
from trino_tpu.expr.compile import Bound
from trino_tpu.ops import groupby as G
from trino_tpu.ops.gather import take_clip
from trino_tpu.ops import join as J
from trino_tpu.ops.sort import SortKey, sort_order


class Operator:
    """Pull/push contract (main/operator/Operator.java:21)."""

    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, batch: RelBatch) -> None:
        raise NotImplementedError

    def get_output(self) -> Optional[RelBatch]:
        return None

    def finish(self) -> None:
        """No more input will arrive (Operator.finish)."""
        self._finishing = True

    def is_finished(self) -> bool:
        raise NotImplementedError

    def is_blocked(self) -> bool:
        """True when the operator is waiting on an async event (remote
        pages, buffer space) — Operator.isBlocked's ListenableFuture
        collapsed to a poll (the driver sleeps instead of parking on a
        future)."""
        return False

    _finishing = False


def empty_batch(schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
                capacity: int = 16) -> RelBatch:
    from trino_tpu.block import phys_zeros

    cols = [
        Column(t, phys_zeros(t, capacity), None, d) for t, d in schema
    ]
    return RelBatch(cols, jnp.zeros(capacity, dtype=jnp.bool_))


def batch_schema(batch: RelBatch) -> List[Tuple[T.DataType, Optional[Dictionary]]]:
    return [(c.type, c.dictionary) for c in batch.columns]


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------


class TableScanOperator(Operator):
    """Pulls batches from a ConnectorPageSource over a list of splits
    (TableScanOperator.java:47)."""

    def __init__(self, page_source, splits, columns: Sequence[str], batch_rows: int,
                 stabilizer=None):
        self._page_source = page_source
        self._splits = list(splits)
        self._columns = columns
        self._batch_rows = batch_rows
        self._stabilizer = stabilizer
        # zero-arg callable -> ColumnConstraints discovered at runtime
        # (dynamic-filter build domains); folded into every split's
        # handle just before the first page is pulled, so connector-
        # level pruning (parquet row-group stats, constraint masks)
        # applies to them exactly like planned pushdown
        self._runtime_constraints = None
        self._iters = None
        self._done = False

    def set_runtime_constraints(self, fn) -> None:
        self._runtime_constraints = fn

    def _start(self):
        splits = self._splits
        if self._runtime_constraints is not None:
            try:
                cs = tuple(self._runtime_constraints() or ())
            except Exception:
                cs = ()  # pruning is best-effort; the join still filters
            if cs:
                import dataclasses as _dc

                from trino_tpu.connectors.pushdown import (
                    merge_handle_constraints,
                )
                from trino_tpu.runtime.metrics import METRICS

                splits = [
                    _dc.replace(
                        s, table=merge_handle_constraints(s.table, cs)
                    )
                    for s in splits
                ]
                METRICS.increment("dynamic_filter_scan_constraints")
        page_source, columns = self._page_source, self._columns
        batch_rows, stabilizer = self._batch_rows, self._stabilizer

        def _gen():
            for split in splits:
                if stabilizer is not None:
                    try:
                        # argument binding raises TypeError immediately
                        # for page sources predating the stabilizer kwarg
                        it = page_source.batches(
                            split, columns, batch_rows, stabilizer=stabilizer
                        )
                    except TypeError:
                        it = page_source.batches(split, columns, batch_rows)
                else:
                    it = page_source.batches(split, columns, batch_rows)
                yield from it

        return _gen()

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[RelBatch]:
        if self._done:
            return None
        if self._iters is None:
            self._iters = self._start()
        nxt = next(self._iters, None)
        if nxt is None:
            self._done = True
            return None
        from trino_tpu.runtime.metrics import METRICS

        if nxt.live is not None:
            n = int(np.asarray(nxt.live).sum())
        elif nxt.columns:
            n = int(nxt.columns[0].data.shape[0])
        else:
            n = 0
        METRICS.increment("rows_scanned", n)
        return nxt

    def is_finished(self) -> bool:
        return self._done


class ValuesOperator(Operator):
    """Emits a fixed list of batches (ValuesOperator.java)."""

    def __init__(self, batches: Sequence[RelBatch]):
        self._batches = list(batches)

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[RelBatch]:
        if self._batches:
            return self._batches.pop(0)
        return None

    def is_finished(self) -> bool:
        return not self._batches


# ---------------------------------------------------------------------------
# Filter + project
# ---------------------------------------------------------------------------


def make_filter_project_fn(
    filter_bound: Optional[Bound], projections: Sequence[Bound],
    name: str = "filter_project",
):
    """Compile the fused filter+project device program once; shared by
    every operator instance the factory creates (the PageProcessor cache
    discipline — PageFunctionCompiler.java:103 caches per expression).
    `name` labels the jit for profiles/compile logs; it must be stable
    across queries (operator-derived, never a query id) or it would
    split the persistent compile-cache key space."""
    projections = list(projections)

    def fn(batch: RelBatch) -> RelBatch:
        # nested columns (ARRAY/MAP/ROW) ride the cols list WHOLE — their
        # starts/flat/children would be silently dropped by a bare data
        # array; nested-aware bindings unwrap what they need
        cols = [
            c if c.type.is_nested else c.data for c in batch.columns
        ]
        valids = [c.valid for c in batch.columns]
        live = batch.live
        if filter_bound is not None:
            d, v = filter_bound.fn(cols, valids)
            keep = d if v is None else (d & v)  # NULL predicate = drop
            live = keep if live is None else (live & keep)
        out_cols = []
        for b in projections:
            data, valid = b.fn(cols, valids)
            if isinstance(data, Column):
                # nested-typed result (column passthrough, map_keys,
                # row_pack, ...): already a full Column; merge validity
                if valid is not None:
                    v0 = data.valid
                    data = data.with_data(
                        data.data, valid if v0 is None else (v0 & valid)
                    )
                out_cols.append(data)
                continue
            d = b.dictionary
            from trino_tpu.block import RuntimeDictionary

            if (
                (d is None or isinstance(d, RuntimeDictionary))
                and b.type.is_string
                and b.input_ref is not None
                and b.input_ref < len(batch.columns)
            ):
                # runtime-dictionary passthrough for pure column refs:
                # the dictionary is pytree aux data, so a new runtime
                # dictionary (listagg output) retraces this program
                d = batch.columns[b.input_ref].dictionary
            out_cols.append(Column(b.type, data, valid, d))
        return RelBatch(out_cols, live)

    fn.__name__ = fn.__qualname__ = name
    return jax.jit(fn)


def compose_batch_fns(f1, f2, name: str = "filter_project_chain"):
    """Fuse two per-batch device programs into one (plan-time; the
    composed jit is cached with the plan). On remote-attached devices
    every separate program launch costs a host round trip, so the
    planner folds adjacent filter/project stages — and folds them into
    the consuming blocking operator's kernel — the way XLA fusion folds
    elementwise ops into the matmul."""
    def composed(b):
        return f2(f1(b))

    composed.__name__ = composed.__qualname__ = name
    return jax.jit(composed)


class FilterProjectOperator(Operator):
    """Bound filter/projections fused into one jitted device program —
    the FilterAndProjectOperator + PageProcessor analogue
    (main/operator/FilterAndProjectOperator.java:40, project/PageProcessor.java:53)."""

    def __init__(
        self,
        filter_bound: Optional[Bound],
        projections: Sequence[Bound],
        fn=None,
    ):
        self._out: Optional[RelBatch] = None
        self._done = False
        self._fn = fn if fn is not None else make_filter_project_fn(
            filter_bound, projections
        )

    def needs_input(self) -> bool:
        return self._out is None and not self._finishing

    def add_input(self, batch: RelBatch) -> None:
        self._out = self._fn(batch)

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


# ---------------------------------------------------------------------------
# Limit
# ---------------------------------------------------------------------------


@jax.jit
def _limit_batch(batch: RelBatch, skip: jnp.ndarray, remaining: jnp.ndarray):
    live = batch.live_mask()
    rank = jnp.cumsum(live.astype(jnp.int64))  # 1-based among live rows
    keep = live & (rank > skip) & (rank <= skip + remaining)
    n_live = rank[-1] if live.shape[0] else jnp.int64(0)
    skipped = jnp.minimum(n_live, skip)
    taken = jnp.minimum(n_live - skipped, remaining)
    return RelBatch(batch.columns, keep), skipped, taken


class LimitOperator(Operator):
    """LIMIT n OFFSET k (LimitOperator.java): masks rows outside the
    remaining window. The skip/remaining counters live ON DEVICE —
    reading them back per batch would cost a full tunnel round trip
    (~130ms measured); the cost is only that the operator cannot
    early-terminate its upstream, which engine sources bound anyway."""

    def __init__(self, n: Optional[int], offset: int = 0):
        self._skip = None  # device scalars, lazily initialized
        self._remaining = None
        self._init = (n if n is not None else (1 << 60), offset)
        self._out: Optional[RelBatch] = None

    def needs_input(self) -> bool:
        return self._out is None and not self._finishing

    def add_input(self, batch: RelBatch) -> None:
        if self._remaining is None:
            n, offset = self._init
            self._remaining = jnp.int64(n)
            self._skip = jnp.int64(offset)
        out, skipped, taken = _limit_batch(batch, self._skip, self._remaining)
        self._skip = self._skip - skipped
        self._remaining = self._remaining - taken
        self._out = out

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._out is None and self._finishing


# ---------------------------------------------------------------------------
# Sort / TopN
# ---------------------------------------------------------------------------


def _apply_sort(batch: RelBatch, keys: Sequence[SortKey]) -> jnp.ndarray:
    return sort_order(
        [batch.columns[k.channel].data for k in keys],
        [batch.columns[k.channel].valid for k in keys],
        [k.descending for k in keys],
        [k.nulls_first for k in keys],
        batch.live,
    )


@partial(jax.jit, static_argnames=("keys", "pre_fn"))
def _concat_sort_pre(
    parts: Tuple[RelBatch, ...], keys: Tuple[SortKey, ...], pre_fn
) -> RelBatch:
    """_concat_sort with a fused upstream filter/project applied to each
    part inside the same program."""
    return _concat_sort.__wrapped__(
        tuple(pre_fn(p) for p in parts), keys
    )


@partial(jax.jit, static_argnames=("keys",))
def _concat_sort(parts: Tuple[RelBatch, ...], keys: Tuple[SortKey, ...]) -> RelBatch:
    """Consolidate + sort + front-pack in ONE device program — eager op
    dispatch is a per-op host round trip on remote-attached TPUs, so
    whole-phase fusion matters beyond XLA fusion itself."""
    merged = concat_batches(list(parts))
    order = _apply_sort(merged, keys)
    n_live = jnp.sum(merged.live_mask())
    live = jnp.arange(order.shape[0]) < n_live
    return merged.gather(order, live)


@partial(jax.jit, static_argnames=("keys", "n", "cap"))
def _topn_merge(
    parts: Tuple[RelBatch, ...], keys: Tuple[SortKey, ...], n: int, cap: int
) -> RelBatch:
    merged = concat_batches(list(parts))
    order = _apply_sort(merged, keys)
    # clamp to the merged capacity: a bucketed cap larger than the
    # concatenated parts (mixed part capacities, e.g. 16+64=80 -> 128)
    # would slice order short while building a longer live mask
    cap = min(cap, int(order.shape[0]))
    top = order[:cap]
    n_live = jnp.minimum(jnp.sum(merged.live_mask()), n)
    live = jnp.arange(cap) < n_live
    return merged.gather(top, live)


class SortOperator(Operator):
    """Full ORDER BY: consolidate + one device sort at finish
    (OrderByOperator.java:44; comparator chains become stable argsorts)."""

    def __init__(self, keys: Sequence[SortKey],
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
                 memory_context=None, pre_fn=None):
        self._keys = list(keys)
        self._schema = list(input_schema)
        self._pre = pre_fn  # fused upstream filter/project (plan-time jit)
        self._inputs: List[RelBatch] = []
        self._out: Optional[RelBatch] = None
        # revocable accumulation (OrderByOperator's spill path): revoke
        # compacts buffered input into a sorted run on disk; finish
        # re-reads runs for the final device sort (which materializes —
        # the streaming k-way merge is the MergeOperator's job upstream)
        self._memory = memory_context
        self._spiller = None
        self._in_finish = False
        # cross-thread revocation (see HashAggregationOperator) serializes
        # all buffered-state mutation on this lock
        self._state_lock = named_lock("SortOperator._state_lock")
        if self._memory is not None:
            self._memory.set_revoker(self._revoke_memory)

    def add_input(self, batch: RelBatch) -> None:
        with self._state_lock:
            self._inputs.append(batch)
        self._track_memory()

    def _track_memory(self) -> None:
        """Bounds ACCUMULATION memory; the final sort materializes the
        output batch outside the accounted state (same exemption as the
        aggregation finish — see HashAggregationOperator._track_memory)."""
        if self._memory is None:
            return
        from trino_tpu.runtime.memory import batch_bytes

        total = sum(batch_bytes(b) for b in self._inputs)
        try:
            self._memory.set_bytes(total)
        except Exception:
            if not self._inputs:
                raise
            self._revoke_memory()
            return
        self._memory.set_revocable_bytes(total)

    def _revoke_memory(self) -> None:
        with self._state_lock:
            if not self._inputs or self._in_finish:
                return
            if self._spiller is None:
                from trino_tpu.exec.spill import FileSpiller

                self._spiller = FileSpiller()
            run = self._sorted(tuple(self._inputs)).compact()
            self._spiller.spill(run)
            self._inputs = []
        self._track_memory()

    def _sorted(self, parts: tuple) -> RelBatch:
        if self._pre is not None:
            return _concat_sort_pre(parts, tuple(self._keys), self._pre)
        return _concat_sort(parts, tuple(self._keys))

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        with self._state_lock:
            self._in_finish = True
            batches = list(self._inputs)
            self._inputs = []
            spiller, self._spiller = self._spiller, None
        if spiller is not None:
            # spilled runs already passed the fused pre stage; fold the
            # remaining raw inputs first, then merge runs un-prefixed
            folded = [self._sorted(tuple(batches))] if batches else []
            folded.extend(spiller.unspill())
            spiller.close()
            self._out = _concat_sort(tuple(folded), tuple(self._keys))
        elif batches:
            self._out = self._sorted(tuple(batches))
        else:
            # no input at all: emit the (post-pre) empty schema directly
            self._out = _concat_sort(
                (empty_batch(self._schema),), tuple(self._keys)
            )
        if self._memory is not None:
            self._memory.set_bytes(0)
            self._memory.set_revocable_bytes(0)

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


class TopNOperator(Operator):
    """ORDER BY + LIMIT n with a bounded device reservoir
    (TopNOperator.java:35)."""

    def __init__(self, keys: Sequence[SortKey], n: int,
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
                 pre_fn=None):
        self._keys = list(keys)
        self._n = n
        self._schema = list(input_schema)
        self._pre = pre_fn
        self._reservoir: Optional[RelBatch] = None
        self._out: Optional[RelBatch] = None

    def add_input(self, batch: RelBatch) -> None:
        if self._pre is not None:
            # fused into the same program as the reservoir merge below
            # only when shapes allow; one extra launch is still bounded
            batch = self._pre(batch)
        parts = (
            (batch,)
            if self._reservoir is None
            else (self._reservoir, batch)
        )
        cap = bucket_capacity(min(self._n, sum(p.capacity for p in parts)))
        self._reservoir = _topn_merge(parts, tuple(self._keys), self._n, cap)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        self._out = (
            self._reservoir
            if self._reservoir is not None
            else empty_batch(self._schema)
        )

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


# ---------------------------------------------------------------------------
# Window functions
# ---------------------------------------------------------------------------


@partial(
    jax.jit,
    static_argnames=("partition_channels", "order_keys", "functions", "frame"),
)
def _window_compute(
    batch: RelBatch,
    partition_channels: tuple,
    order_keys: tuple,
    functions: tuple,  # (kind, arg_channel, out_dtype_str, offset, arg_scale_factor, out_is_float)
    frame: str,
):
    """One device program computing every window column over the sorted
    batch (the whole WindowOperator inner loop as segmented scans —
    ops/window.py). Traced under jit by the operator."""
    from trino_tpu.ops import window as W

    live = batch.live_mask()
    n = batch.capacity
    part_cols = [batch.columns[c] for c in partition_channels]
    key_data = [c.data for c in part_cols]
    key_valids = [c.valid for c in part_cols]
    descending = [False] * len(part_cols)
    nulls_first = [False] * len(part_cols)
    for k in order_keys:
        col = batch.columns[k.channel]
        key_data.append(col.data)
        key_valids.append(col.valid)
        descending.append(k.descending)
        nulls_first.append(k.nulls_first)
    order = (
        sort_order(key_data, key_valids, descending, nulls_first, live)
        if key_data
        else jnp.argsort(~live, stable=True)
    )
    s_live = take_clip(live, order)
    s_cols = [c.gather(order) for c in batch.columns]

    # partition boundaries (dead tail isolated as its own segment)
    part_inputs = [take_clip(d, order) for d in key_data[: len(part_cols)]]
    part_vmasks = [
        None if v is None else take_clip(v, order)
        for v in key_valids[: len(part_cols)]
    ]
    part_start = W.segment_starts(
        part_inputs + [s_live], part_vmasks + [None], n
    )
    peer_inputs = [
        take_clip(batch.columns[k.channel].data, order) for k in order_keys
    ]
    peer_vmasks = [
        None
        if batch.columns[k.channel].valid is None
        else take_clip(batch.columns[k.channel].valid, order)
        for k in order_keys
    ]
    peer_start = part_start | W.segment_starts(peer_inputs, peer_vmasks, n) if peer_inputs else part_start

    out_cols = []
    for kind, arg_ch, out_dt, offset, arg_sf, out_float, out_sf, out_lanes in functions:
        out_dtype = np.dtype(out_dt)
        if kind == "row_number":
            out_cols.append((W.row_number(part_start).astype(out_dtype), None))
        elif kind == "rank":
            out_cols.append((W.rank(part_start, peer_start).astype(out_dtype), None))
        elif kind == "dense_rank":
            out_cols.append((W.dense_rank(part_start, peer_start).astype(out_dtype), None))
        elif kind == "percent_rank":
            out_cols.append((W.percent_rank(part_start, peer_start).astype(out_dtype), None))
        elif kind == "cume_dist":
            out_cols.append((W.cume_dist(part_start, peer_start).astype(out_dtype), None))
        elif kind == "ntile":
            out_cols.append((W.ntile(offset, part_start).astype(out_dtype), None))
        elif kind in ("lead", "lag"):
            col = s_cols[arg_ch]
            off = offset if kind == "lag" else -offset
            data, valid = W.shift_in_partition(col.data, col.valid, part_start, off)
            out_cols.append((data, valid & s_live))
        elif kind == "first_value":
            col = s_cols[arg_ch]
            data, valid = W.first_value(col.data, col.valid, part_start)
            out_cols.append((data, valid))
        elif kind == "last_value":
            col = s_cols[arg_ch]
            data, valid = W.last_value(col.data, col.valid, part_start, peer_start, frame)
            out_cols.append((data, valid))
        elif kind == "nth_value":
            col = s_cols[arg_ch]
            data, valid = W.nth_value(
                col.data, col.valid, part_start, peer_start, frame, offset
            )
            out_cols.append((data, valid & s_live if valid is not None else None))
        elif kind in ("count", "count_star"):
            if arg_ch is None:
                vals, valid = None, None
            else:
                vals, valid = s_cols[arg_ch].data, s_cols[arg_ch].valid
            v, _ = W.windowed_agg("count", vals, valid, s_live, part_start, peer_start, frame, 0)
            out_cols.append((v.astype(out_dtype), None))
        elif kind in ("sum", "avg", "min", "max"):
            col = s_cols[arg_ch]
            if getattr(col.data, "ndim", 1) == 2:
                raise NotImplementedError(
                    "window aggregates over decimal(>18) arguments"
                )
            if kind in ("min", "max"):
                vals = col.data
                neutral = minmax_neutral(col.data.dtype, kind)
            else:
                acc_dt = (
                    jnp.float64
                    if jnp.issubdtype(col.data.dtype, jnp.floating)
                    else jnp.int64
                )
                vals = col.data.astype(acc_dt)
                neutral = 0
            v, cnt = W.windowed_agg(kind, vals, col.valid, s_live, part_start, peer_start, frame, neutral)
            has = cnt > 0
            if kind == "avg":
                q = v.astype(jnp.float64) / jnp.maximum(cnt, 1) / arg_sf
                if out_sf is not None:
                    # decimal avg: rescale into the output's scaled-int64
                    # domain, rounding half away (same as _agg_output)
                    q = q * out_sf
                    q = jnp.sign(q) * jnp.floor(jnp.abs(q) + 0.5)
                out_cols.append((q.astype(out_dtype), has))
            elif kind == "sum" and out_float:
                out_cols.append(((v / arg_sf).astype(out_dtype), has))
            else:
                safe = jnp.where(has, v, jnp.zeros((), v.dtype))
                if out_lanes == 2:
                    # sum(decimal) -> decimal(38,s): widen the int64
                    # accumulator into limb pairs (same contract as
                    # _agg_output's short-input long-output sum)
                    from trino_tpu.ops import int128 as I128

                    h, lo = I128.from_i64(safe.astype(jnp.int64))
                    out_cols.append((jnp.stack([h, lo], axis=-1), has))
                else:
                    out_cols.append((safe.astype(out_dtype), has))
        else:
            raise NotImplementedError(f"window function {kind}")
    return s_cols, s_live, out_cols


def window_fn_tuples(specs, schema) -> tuple:
    """Static per-function tuples for the jitted window kernel —
    shared by WindowOperator and the mesh fragment compiler."""
    fns = []
    for s in specs:
        # decimal args are int64 at the arg scale; divide only when
        # the OUTPUT leaves the scaled domain (avg -> DOUBLE, float
        # sums). Decimal sum/min/max keep the arg scale unchanged.
        arg_sf = 1
        out_float = s.out_type.is_floating
        # decimal OUTPUT scale factor: avg over decimal re-scales its
        # float quotient back into the output's scaled-int64 domain
        out_sf = (
            T.decimal_scale_factor(s.out_type)
            if s.out_type.is_decimal
            else None
        )
        if s.arg_channel is not None:
            arg_t = schema[s.arg_channel][0]
            if arg_t.is_decimal and (s.kind == "avg" or out_float):
                arg_sf = T.decimal_scale_factor(arg_t)
        fns.append(
            (s.kind, s.arg_channel, s.out_type.dtype.str, s.offset,
             arg_sf, out_float, out_sf, s.out_type.lanes)
        )
    return tuple(fns)


class WindowOperator(Operator):
    """Blocking window evaluation (WindowOperator.java:69): consume all
    input, sort once by (partition, order), emit child columns + window
    results in sorted order."""

    def __init__(
        self,
        partition_channels: Sequence[int],
        order_keys: Sequence[SortKey],
        functions: Sequence,  # plan.WindowFuncSpec
        frame: str,
        input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
    ):
        self._partition = tuple(partition_channels)
        self._order = tuple(order_keys)
        self._specs = list(functions)
        self._frame = frame
        self._schema = list(input_schema)
        self._inputs: List[RelBatch] = []
        self._out: Optional[RelBatch] = None
        self._fns = window_fn_tuples(self._specs, self._schema)

    def add_input(self, batch: RelBatch) -> None:
        self._inputs.append(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        parts = self._inputs or [empty_batch(self._schema)]
        merged = concat_batches(parts)
        self._inputs = []
        s_cols, s_live, out_cols = _window_compute(
            merged, self._partition, self._order, self._fns, self._frame
        )
        cols = list(s_cols)
        for spec, (data, valid) in zip(self._specs, out_cols):
            d = None
            if spec.arg_channel is not None and spec.kind in (
                "lead", "lag", "first_value", "last_value", "nth_value",
                "min", "max"
            ):
                d = s_cols[spec.arg_channel].dictionary
            cols.append(Column(spec.out_type, data, valid, d))
        self._out = RelBatch(cols, s_live)

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


# ---------------------------------------------------------------------------
# Hash aggregation
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind in {sum,count,count_star,avg,min,max,any} or
    the holistic kinds {min_by,max_by,approx_percentile} (which need the
    raw rows, not mergeable accumulators — the planner forces them
    single-step); arg_channel indexes the operator's input (None for
    count_star), out_type is the SQL result type. The holistic set
    below (HOLISTIC_KINDS) is the single source of truth the fragmenter
    gates single-step planning on."""

    kind: str
    arg_channel: Optional[int]
    out_type: T.DataType
    distinct: bool = False
    arg2_channel: Optional[int] = None
    percentile: Optional[float] = None
    separator: Optional[str] = None  # listagg
    arg3_channel: Optional[int] = None  # pctl_merge bucket-max channel
    param: Optional[float] = None  # numeric_histogram/approx_most_frequent b
    post: Optional[str] = None  # fused sketch accessor: card | vq | qv


# pctl_merge is the bounded MERGE half of the mergeable approx_percentile
# (sql/optimizer.RewriteApproxPercentile): it buffers quantile-bucket
# summaries, never raw rows. approx_distinct / approx_percentile appear
# here only as the enable_optimizer=False fallback.
# r4 collect-path aggregates: per-group containers are assembled
# host-side from the device's group-contiguous row order (the
# reference's ArrayAggregationFunction and MapAggregationFunction
# likewise build their Blocks on the heap). Finalized by
# _collect_column.
_COLLECT_KINDS = (
    "array_agg", "map_agg", "multimap_agg", "histogram",
    "numeric_histogram", "approx_most_frequent", "map_union",
    "bitwise_and_agg", "bitwise_or_agg", "bitwise_xor_agg",
    # sketch builders (expr/pyfns digests on the varchar carrier)
    "approx_set", "tdigest_agg", "sketch_merge",
)

HOLISTIC_KINDS = (
    "min_by", "max_by", "approx_percentile", "listagg", "approx_distinct",
    "pctl_merge",
) + _COLLECT_KINDS


def _bht_histogram(vals, b: int):
    """Ben-Haim/Tom-Tov streaming histogram, batch form: merge the two
    closest centroids until <= b remain (the reference's
    NumericHistogram, operator/aggregation/NumericHistogramAggregation).
    Returns {centroid: weight} or None for empty input."""
    if not vals or b <= 0:
        return None
    pts: List[List[float]] = []
    for v in sorted(float(x) for x in vals):
        if pts and pts[-1][0] == v:
            pts[-1][1] += 1.0
        else:
            pts.append([v, 1.0])
    while len(pts) > b:
        bi, bgap = 0, float("inf")
        for i in range(len(pts) - 1):
            gap = pts[i + 1][0] - pts[i][0]
            if gap < bgap:
                bi, bgap = i, gap
        (v1, c1), (v2, c2) = pts[bi], pts[bi + 1]
        pts[bi] = [(v1 * c1 + v2 * c2) / (c1 + c2), c1 + c2]
        del pts[bi + 1]
    return {v: c for v, c in pts}


def minmax_neutral(dtype, kind: str):
    """Identity element for min/max accumulators: the single source of
    truth shared by every aggregation path (batch init, global fold,
    partial-state merge) — keep these in sync or partial->final
    aggregation silently diverges from single-step."""
    if jnp.issubdtype(np.dtype(dtype), np.floating):
        return np.inf if kind == "min" else -np.inf
    if np.dtype(dtype) == np.bool_:
        return kind == "min"
    info = np.iinfo(np.dtype(dtype))
    return info.max if kind == "min" else info.min


def _agg_state_init(spec: AggSpec, arg_dtype, capacity: int):
    """(value_state, count_state) arrays of shape (capacity,)."""
    if spec.kind in ("count", "count_star"):
        return (jnp.zeros(capacity, dtype=jnp.int64),)
    if spec.kind in ("sum", "avg"):
        acc_dt = jnp.float64 if np.issubdtype(arg_dtype, np.floating) else jnp.int64
        return (
            jnp.zeros(capacity, dtype=acc_dt),
            jnp.zeros(capacity, dtype=jnp.int64),
        )
    if spec.kind in ("min", "max"):
        return (
            jnp.full(capacity, minmax_neutral(arg_dtype, spec.kind), dtype=arg_dtype),
            jnp.zeros(capacity, dtype=jnp.int64),
        )
    if spec.kind == "any":
        return (
            jnp.zeros(capacity, dtype=arg_dtype),
            jnp.zeros(capacity, dtype=jnp.int64),
        )
    raise NotImplementedError(spec.kind)


def _agg_state_update(spec: AggSpec, state, gid, data, valid, live, capacity):
    """Scatter one batch into the running state. gid == capacity drops."""
    weight = live if valid is None else (live & valid)
    idx = jnp.where(weight, gid, capacity)
    if spec.kind in ("count", "count_star"):
        (cnt,) = state
        return (cnt.at[idx].add(1, mode="drop"),)
    if spec.kind in ("sum", "avg"):
        acc, cnt = state
        return (
            acc.at[idx].add(data.astype(acc.dtype), mode="drop"),
            cnt.at[idx].add(1, mode="drop"),
        )
    if spec.kind in ("min", "max"):
        acc, cnt = state
        op = acc.at[idx].min if spec.kind == "min" else acc.at[idx].max
        return (op(data, mode="drop"), cnt.at[idx].add(1, mode="drop"))
    if spec.kind == "any":
        acc, cnt = state
        first = cnt == 0
        upd = acc.at[idx].set(data, mode="drop")
        return (jnp.where(first, upd, acc), cnt.at[idx].add(1, mode="drop"))
    raise NotImplementedError(spec.kind)


def _agg_state_migrate(spec: AggSpec, arg_dtype, state, remap, new_capacity):
    """Move accumulator state through a table rebuild: new[remap[i]] = old[i].
    Fresh slots must hold the same identity element as _agg_state_init
    (min/max extremes, not zero)."""
    fresh = _agg_state_init(spec, arg_dtype, new_capacity)
    return tuple(
        f.at[remap].set(arr, mode="drop") for f, arr in zip(fresh, state)
    )


def _agg_output(spec: AggSpec, state, arg_type: Optional[T.DataType],
                arg_dict: Optional[Dictionary]) -> Column:
    """Finalize a state into the SQL result column. Decimal accumulators
    hold scaled int64 at the ARG's scale; rescale to the output type."""
    out_t = spec.out_type
    if spec.kind in ("count", "count_star"):
        (cnt,) = state
        return Column(out_t, cnt.astype(jnp.int64), None, None)
    if len(state) == 3:
        # Int128 limb-join state (sum/avg over a long-decimal arg)
        from trino_tpu.ops import int128 as I128

        h, lo, cnt = state
        has = cnt > 0
        if spec.kind in ("min", "max", "any"):
            return Column(
                out_t, jnp.stack([h, lo], axis=-1), has, arg_dict
            )
        if spec.kind == "avg":
            h, lo = I128.div_round_i64(
                h, lo, jnp.maximum(cnt, 1).astype(jnp.int64)
            )
        arg_s = arg_type.scale or 0
        out_s = out_t.scale or 0
        if out_s > arg_s:
            h, lo = I128.rescale_up(h, lo, out_s - arg_s)
        elif arg_s > out_s:
            h, lo = I128.rescale_down_round(h, lo, arg_s - out_s)
        if out_t.is_long_decimal:
            return Column(out_t, jnp.stack([h, lo], axis=-1), has, None)
        x, _ = I128.to_i64(h, lo)
        return Column(out_t, x.astype(out_t.dtype), has, None)
    acc, cnt = state
    has = cnt > 0
    arg_sf = (
        T.decimal_scale_factor(arg_type)
        if arg_type is not None and arg_type.is_decimal
        else 1
    )
    out_sf = T.decimal_scale_factor(out_t) if out_t.is_decimal else None
    if spec.kind == "sum":
        if out_t.is_floating:
            return Column(out_t, acc.astype(out_t.dtype) / arg_sf, has, None)
        if out_sf is not None and out_sf != arg_sf:
            acc = acc * (out_sf // arg_sf) if out_sf > arg_sf else acc // (arg_sf // out_sf)
        if out_t.is_long_decimal:
            # sum(decimal) -> decimal(38, s): the int64 accumulator
            # widens into limb pairs (exact while per-batch partials fit
            # int64; the limb-split accumulator is the extension point)
            from trino_tpu.ops import int128 as I128

            h, lo = I128.from_i64(acc.astype(jnp.int64))
            return Column(out_t, jnp.stack([h, lo], axis=-1), has, None)
        return Column(out_t, acc.astype(out_t.dtype), has, None)
    if spec.kind == "avg":
        q = acc.astype(jnp.float64) / jnp.maximum(cnt, 1)
        if out_t.is_floating:
            return Column(out_t, (q / arg_sf).astype(out_t.dtype), has, None)
        # decimal average: rescale to the output scale, round half away
        q = q * (out_sf / arg_sf)
        data = (jnp.sign(q) * jnp.floor(jnp.abs(q) + 0.5)).astype(out_t.dtype)
        return Column(out_t, data, has, None)
    if spec.kind in ("min", "max", "any"):
        safe = jnp.where(has, acc, jnp.zeros((), dtype=acc.dtype))
        if out_t.is_floating and arg_sf != 1:
            return Column(out_t, safe.astype(out_t.dtype) / arg_sf, has, None)
        return Column(out_t, safe.astype(out_t.dtype), has, arg_dict)
    raise NotImplementedError(spec.kind)


def agg_state_meta(
    spec: AggSpec,
    input_schema: Sequence[Tuple[T.DataType, "Optional[Dictionary]"]],
) -> List[Tuple[T.DataType, "Optional[Dictionary]"]]:
    """Wire schema of one aggregate's partial state: (value, count)
    columns. This is the accumulator-serialization contract between
    PARTIAL and FINAL aggregation steps (the analogue of Trino's
    aggregation state serialized to Blocks for partial->final,
    main/operator/aggregation/ — SURVEY.md §2.6)."""
    if spec.kind in ("count", "count_star"):
        return [(T.BIGINT, None), (T.BIGINT, None)]
    arg_t, arg_d = input_schema[spec.arg_channel]
    if spec.kind in ("sum", "avg"):
        if arg_t.is_long_decimal:
            # ONE (hi, lo) Int128 value column at the argument's scale:
            # per-state limb sums join into an exact Int128 before the
            # wire, and the final step limb-splits them again — so long
            # decimals ride any exchange as an ordinary (n, 2) column
            # (Int128ArrayBlock on the page wire, AddExchanges.java:140)
            return [
                (T.DataType(T.TypeKind.DECIMAL, 38, arg_t.scale), None),
                (T.BIGINT, None),
            ]
        if arg_t.is_floating:
            val_t = T.DOUBLE
        elif arg_t.is_decimal:
            val_t = T.DataType(T.TypeKind.DECIMAL, 18, arg_t.scale)
        else:
            val_t = T.BIGINT
        return [(val_t, None), (T.BIGINT, None)]
    # min/max/any carry the argument representation through the wire
    return [(arg_t, arg_d), (T.BIGINT, None)]


def partial_output_schema(
    aggs: Sequence[AggSpec],
    group_channels: Sequence[int],
    input_schema: Sequence[Tuple[T.DataType, "Optional[Dictionary]"]],
) -> List[Tuple[T.DataType, "Optional[Dictionary]"]]:
    """Schema of a PARTIAL aggregation's output batch:
    [group keys..., (value, count) per aggregate...]."""
    out = [input_schema[c] for c in group_channels]
    for a in aggs:
        out.extend(agg_state_meta(a, input_schema))
    return out


# -- Int128 sum accumulation (DecimalSumAggregation analogue) --------------
# A long-decimal (n, 2) argument cannot ride the 1-D sort-carry
# aggregation kernels, and a single int64 accumulator would overflow; it
# splits into FOUR 32-bit limb columns whose int64 sums are each exact
# for < 2^31 rows, recombined into (hi, lo) at finalize:
#   value = l0 + l1*2^32 + h0*2^64 + h1*2^96   (h1 signed, rest unsigned)

_LIMB_MASK = 0xFFFFFFFF


def _append_long_decimal_slots(a, col, live, values, vvalids, reds) -> None:
    """Value-slot assembly for an aggregate over a decimal(>18) (n, 2)
    column: count reads only validity; sum/avg limb-split into four
    exact int64 slots; min/max ride the coupled (hi, lo) lexicographic
    reducers; any picks both limbs at the same first row. Shared by the
    three ingest paths (per-batch, streaming, holistic)."""
    if a.kind == "count":
        values.append(live.astype(jnp.int64))
        vvalids.append(col.valid)
        reds.append("count")
        return
    if a.kind in ("min", "max"):
        values.extend([col.data[:, 0], col.data[:, 1]])
        vvalids.extend([col.valid, col.valid])
        reds.extend([f"{a.kind}128h", f"{a.kind}128l"])
        return
    if a.kind == "any":
        values.extend([col.data[:, 0], col.data[:, 1]])
        vvalids.extend([col.valid, col.valid])
        reds.extend(["first", "first"])
        return
    if a.kind not in ("sum", "avg"):
        raise NotImplementedError(
            f"{a.kind}() over decimal(>18) arguments"
        )
    for piece in _limb_split(col.data):
        values.append(piece)
        vvalids.append(col.valid)
        reds.append("sum")


def _agg_slot_count(spec: "AggSpec", arg_type: Optional[T.DataType]) -> int:
    """State (value, count) slot pairs one aggregate occupies."""
    if arg_type is None or not arg_type.is_long_decimal:
        return 1
    if spec.kind in ("sum", "avg"):
        return 4
    if spec.kind in ("min", "max", "any"):
        return 2
    return 1


def _slots_to_state(spec: "AggSpec", arg_type: Optional[T.DataType],
                    vals, cnts, si: int):
    """One aggregate's finalize-ready state from its value/count slots
    starting at `si`. Returns (state, next_si) — the ONE slots->state
    switch shared by every finalize path (4 limb-sum slots join into an
    Int128; 2 slots ARE the (hi, lo) pair; count reads one slot)."""
    kslots = _agg_slot_count(spec, arg_type)
    if kslots == 4:
        h, lo = _limb_join(vals[si: si + 4])
        state = (h, lo, cnts[si])
    elif kslots == 2:
        state = (vals[si], vals[si + 1], cnts[si])
    elif spec.kind in ("count", "count_star"):
        state = (vals[si],)
    else:
        state = (vals[si], cnts[si])
    return state, si + kslots


def _slots_to_wire_column(spec: "AggSpec", arg_type: Optional[T.DataType],
                          vt, vd, vals, si: int):
    """One aggregate's wire-format VALUE column from its slots at `si`
    (the serialization half of _slots_to_state: partial emit and spill
    share it on both data planes). Returns (column, next_si)."""
    kslots = _agg_slot_count(spec, arg_type)
    if kslots == 4:
        h, lo = _limb_join(vals[si: si + 4])
        col = Column(vt, jnp.stack([h, lo], axis=-1), None, vd)
    elif kslots == 2:
        col = Column(
            vt, jnp.stack([vals[si], vals[si + 1]], axis=-1), None, vd
        )
    else:
        col = Column(vt, vals[si].astype(vt.dtype), None, vd)
    return col, si + kslots


def _slot_merge_reducers(spec: "AggSpec", arg_type: Optional[T.DataType]):
    """Per-slot reducers for MERGING two group states of one aggregate
    (the _MERGE_REDUCER analogue at slot granularity: long-decimal sums
    merge as four limb sums, extremes as the coupled (hi, lo) pair)."""
    if arg_type is not None and arg_type.is_long_decimal:
        if spec.kind in ("sum", "avg"):
            return ["sum"] * 4
        if spec.kind in ("min", "max"):
            return [f"{spec.kind}128h", f"{spec.kind}128l"]
        if spec.kind == "any":
            return ["first", "first"]
    return [_MERGE_REDUCER[spec.kind]]


def _limb_split(d: jnp.ndarray) -> List[jnp.ndarray]:
    h, lo = d[:, 0], d[:, 1]
    m = jnp.int64(_LIMB_MASK)
    return [
        lo & m,
        (lo >> jnp.int64(32)) & m,
        h & m,
        h >> jnp.int64(32),
    ]


def _lex128_reduce(h, lo, w, kind: str):
    """Masked whole-array Int128 min/max over (hi, lo) rows: signed hi
    first, then unsigned lo among rows holding the winning hi
    (Int128Math.compare's lexicographic order, vectorized)."""
    big = jnp.iinfo(jnp.int64).max
    sgn = jnp.int64(-0x8000000000000000)
    lo_u = lo ^ sgn
    if kind == "min":
        m1 = jnp.min(jnp.where(w, h, big))
        m2 = jnp.min(jnp.where(w & (h == m1), lo_u, big)) ^ sgn
    else:
        m1 = jnp.max(jnp.where(w, h, -big - 1))
        m2 = jnp.max(jnp.where(w & (h == m1), lo_u, -big - 1)) ^ sgn
    return m1, m2


def _limb_join(sums: Sequence[jnp.ndarray]):
    """Four limb-sum arrays -> (hi, lo) Int128."""
    from trino_tpu.ops import int128 as I128

    h, lo = I128.from_i64(sums[3].astype(jnp.int64))
    for s in (sums[2], sums[1], sums[0]):
        h, lo = I128.mul_128_64(h, lo, jnp.int64(1 << 32))
        ah, al = I128.from_i64(s.astype(jnp.int64))
        h, lo = I128.add(h, lo, ah, al)
    return h, lo


_BATCH_REDUCER = {"sum": "sum", "avg": "sum", "count": "count",
                  "count_star": "count", "min": "min", "max": "max",
                  "any": "first"}
# merging two partial states: counts add, mins min, firsts keep-first
_MERGE_REDUCER = {"sum": "sum", "avg": "sum", "count": "sum",
                  "count_star": "sum", "min": "min", "max": "max",
                  "any": "first"}

@partial(jax.jit, static_argnames=("reducers", "out_capacity"))
def _merge_group_states(states: tuple, reducers: tuple, out_capacity: int):
    """Concat N (keys, valids, used, vals, cnts) group-state sets and
    re-group-reduce them — the whole N-way merge is ONE device program
    (per-batch pairwise merges would cost a program launch each)."""
    n_keys = len(states[0][0])
    keys = [
        jnp.concatenate([s[0][i] for s in states]) for i in range(n_keys)
    ]
    valids = [
        jnp.concatenate([s[1][i] for s in states]) for i in range(n_keys)
    ]
    mask = jnp.concatenate([s[2] for s in states])
    values, vvalids, reds = [], [], []
    for i, mred in enumerate(reducers):
        v = jnp.concatenate([s[3][i] for s in states])
        c = jnp.concatenate([s[4][i] for s in states])
        values.append(v)
        vvalids.append((c > 0) if mred == "first" else None)
        reds.append(mred)
        values.append(c)
        vvalids.append(None)
        reds.append("sum")
    gk, gv, used, vals, _, ngroups, ovf = G.sort_group_reduce(
        keys, valids, mask, values, tuple(vvalids), tuple(reds), out_capacity
    )
    return (
        (tuple(gk), tuple(gv), used, tuple(vals[0::2]), tuple(vals[1::2])),
        ngroups,
        ovf,
    )


@jax.jit
def _any_flags(flags: tuple):
    return jnp.any(jnp.stack(flags))


@partial(jax.jit, static_argnames=(
    "groups", "aggs", "cap", "pre_fn", "dense_dims", "mxu_dims"))
def _agg_ingest(batch: RelBatch, groups: tuple, aggs: tuple, cap: int, pre_fn,
                dense_dims=None, mxu_dims=None):
    """Fused upstream filter/project + per-batch group-reduce in ONE
    device program (scan->filter->project->partial-aggregate is the Q1
    hot path; separate launches pay a host round trip each on
    remote-attached devices)."""
    if pre_fn is not None:
        batch = pre_fn(batch)
    keys, valids = [], []
    for c in groups:
        col = batch.columns[c]
        v = col.valid_mask()
        if getattr(col.data, "ndim", 1) == 2:
            # long-decimal key: group by its two int64 limbs (pair
            # equality == value equality; output reassembles them)
            keys.extend([col.data[:, 0], col.data[:, 1]])
            valids.extend([v, v])
        else:
            keys.append(col.data)
            valids.append(v)
    live = batch.live_mask()
    values, vvalids, reds = [], [], []
    for a in aggs:
        if a.arg_channel is None:
            values.append(live.astype(jnp.int64))
            vvalids.append(None)
        elif getattr(batch.columns[a.arg_channel].data, "ndim", 1) == 2:
            _append_long_decimal_slots(
                a, batch.columns[a.arg_channel], live, values, vvalids, reds
            )
            continue
        else:
            col = batch.columns[a.arg_channel]
            values.append(col.data)
            vvalids.append(col.valid)
        reds.append(_BATCH_REDUCER[a.kind])
    if dense_dims is not None:
        return G.dense_group_reduce(
            keys, valids, live, values, tuple(vvalids), tuple(reds),
            dense_dims, cap,
        )
    if mxu_dims is not None:
        return G.mxu_group_reduce(
            keys, valids, live, values, tuple(vvalids), tuple(reds),
            mxu_dims, cap,
        )
    return G.sort_group_reduce(
        keys, valids, live, values, tuple(vvalids), tuple(reds), cap
    )


@partial(jax.jit, static_argnames=("aggs", "arg_types"))
def _finalize_grouped(acc, aggs: tuple, arg_types: tuple):
    """Whole grouped finalize as ONE device program (the eager
    per-aggregate finalize costs one host dispatch per op — ruinous over
    a tunneled device link)."""
    gk, gv, used, vals, cnts = acc
    out = []
    si = 0
    for a, arg_t in zip(aggs, arg_types):
        state, si = _slots_to_state(a, arg_t, vals, cnts, si)
        col = _agg_output(a, state, arg_t, None)
        out.append((col.data, col.valid))
    return out


# Shared across concurrent query threads; the unlocked check-then-insert
# let two threads mint distinct jitted callables for the same spec
# (dispatch-cache churn on every later call). First build wins now.
_global_fn_lock = named_lock("operators._global_fn_lock")
_GLOBAL_FN_CACHE: Dict[Tuple[AggSpec, ...], object] = {}


def _global_update_fn(aggs: Tuple[AggSpec, ...], long_flags: tuple = ()):
    """Jitted whole-batch reduction for GROUP-BY-less aggregation —
    shared across instances (AccumulatorCompiler cache analogue).
    long_flags marks aggregates whose argument is a long decimal: their
    sum state is an Int128 (hi, lo) pair accumulated from limb sums."""
    if not long_flags:
        long_flags = (False,) * len(aggs)
    if (aggs, long_flags) not in _GLOBAL_FN_CACHE:

        @jax.jit
        def update(states, batch: RelBatch):
            from trino_tpu.ops import int128 as I128

            live = batch.live_mask()
            out = []
            for a, is_long, (val, cnt) in zip(aggs, long_flags, states):
                if a.arg_channel is None:
                    data, valid = live.astype(jnp.int64), None
                else:
                    col = batch.columns[a.arg_channel]
                    data, valid = col.data, col.valid
                w = live if valid is None else (live & valid)
                n = jnp.sum(w.astype(jnp.int64))
                if a.kind in ("count", "count_star"):
                    out.append((val + n, cnt + n))
                elif is_long and a.kind in ("sum", "avg"):
                    limb_sums = [
                        jnp.sum(jnp.where(w, piece, jnp.int64(0)))
                        for piece in _limb_split(data)
                    ]
                    bh, bl = _limb_join(limb_sums)
                    h, lo = I128.add(val[0], val[1], bh, bl)
                    out.append((jnp.stack([h, lo]), cnt + n))
                elif a.kind in ("sum", "avg"):
                    contrib = jnp.where(w, data.astype(val.dtype), 0)
                    out.append((val + jnp.sum(contrib), cnt + n))
                elif is_long and a.kind in ("min", "max"):
                    # lexicographic (hi, unsigned lo) batch reduce, then
                    # an Int128 compare against the running state
                    m1, m2 = _lex128_reduce(data[:, 0], data[:, 1], w, a.kind)
                    from trino_tpu.ops import int128 as I128x

                    better = I128x.lt(m1, m2, val[0], val[1])
                    if a.kind == "max":
                        better = I128x.lt(val[0], val[1], m1, m2)
                    better = better & (n > 0)
                    first = cnt == 0
                    take = (better | first) & (n > 0)
                    nh = jnp.where(take, m1, val[0])
                    nl = jnp.where(take, m2, val[1])
                    out.append((jnp.stack([nh, nl]), cnt + n))
                elif a.kind in ("min", "max"):
                    neutral = minmax_neutral(data.dtype, a.kind)
                    masked = jnp.where(w, data, jnp.asarray(neutral, data.dtype))
                    red = jnp.min(masked) if a.kind == "min" else jnp.max(masked)
                    op = jnp.minimum if a.kind == "min" else jnp.maximum
                    out.append((op(val, red.astype(val.dtype)), cnt + n))
                elif a.kind == "any":
                    first = data[jnp.argmax(w)]
                    new_val = jnp.where(
                        cnt > 0, val, jnp.where(jnp.any(w), first, val)
                    )
                    out.append((new_val, cnt + n))
                else:
                    raise NotImplementedError(a.kind)
            return out

        with _global_fn_lock:
            _GLOBAL_FN_CACHE.setdefault((aggs, long_flags), update)
    return _GLOBAL_FN_CACHE[(aggs, long_flags)]


class HashAggregationOperator(Operator):
    """GROUP BY + aggregates (HashAggregationOperator.java:53 +
    GroupByHash). The engine-path implementation is the SORT-BASED
    group-reduce (ops/groupby.sort_group_reduce) — XLA lowers scatters
    near-serially on TPU, so the linear-probe table is reserved for the
    mesh-exchange partials while this operator reduces each batch by
    sort + segmented scans and then merges per-batch group states the
    same way (partial->final within one operator). Output schema =
    [group keys..., aggregate results...]; group rows come out dense."""

    def __init__(
        self,
        group_channels: Sequence[int],
        aggregates: Sequence[AggSpec],
        input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
        initial_capacity: int = 1024,
        step: str = "single",
        memory_context=None,
        deferred_checks: Optional[List] = None,
        pre_fn=None,
    ):
        """step: "single" (raw rows in, results out), "partial" (raw rows
        in, serialized accumulator state out) or "final" (accumulator
        state in, results out) — AggregationNode.Step analogue. In final
        mode the input layout is partial_output_schema's, whose state
        value columns carry each aggregate's original argument
        representation (decimal scale, dictionary) — finalization reads
        it straight from the input schema."""
        assert step in ("single", "partial", "final"), step
        self._step = step
        self._pre = pre_fn  # fused upstream stage (plan-time jit)
        self._group_channels = list(group_channels)
        self._aggs = list(aggregates)
        self._schema = list(input_schema)
        self._global = not self._group_channels
        self._cap = initial_capacity
        # accumulated group state: (keys, valids, used, vals, cnts);
        # per-batch states collect in _pending and merge in ONE N-way
        # device program at the next materialization point
        self._acc = None
        self._pending: List[tuple] = []
        # a state ingested off the wire (_add_state_input) may carry
        # DUPLICATE group keys within one batch (a spooled-stage replay
        # concatenates several producer pages into one values batch), so
        # it must pass through a group-reduce even when it is the only
        # pending state
        self._unreduced_state = False
        # deferred per-batch overflow records: (pending index, device
        # ovf flag, device ngroups, retained input batch, capacity)
        self._pending_meta: List[tuple] = []
        self._gstate = None
        self._out: Optional[RelBatch] = None
        # spill support (SpillableHashAggregationBuilder analogue):
        # revoke() serializes the group state in the partial wire format
        # and resets; finish() merges spilled state back via the same
        # FINAL-step machinery the distributed exchange uses.
        self._memory = memory_context
        self._spiller = None
        self._in_finish = False
        # holistic aggregates (min_by/max_by/approx_percentile) need the
        # raw rows: collect batches, reduce once at finish (the planner
        # guarantees step == "single"); no spill, no partial wire format
        self._holistic = any(a.kind in HOLISTIC_KINDS for a in self._aggs)
        if self._holistic:
            assert step == "single", "holistic aggregates run single-step"
        self._collected: List[RelBatch] = []
        # revocation runs on the RESERVING thread (MemoryPool.reserve
        # calls the victim's callback), so every state mutation and the
        # revoke itself serialize on this lock; accounting calls happen
        # OUTSIDE it to keep lock ordering acyclic across operators
        self._state_lock = named_lock("HashAggregationOperator._state_lock")
        if self._memory is not None and not self._global and not self._holistic:
            self._memory.set_revoker(self._revoke_memory)
        self._arg_meta = [
            input_schema[a.arg_channel] if a.arg_channel is not None else (None, None)
            for a in self._aggs
        ]
        # state (value, count) slot pairs across all aggregates: long-
        # decimal sums occupy four limb slots (_agg_slot_count)
        self._n_slots = sum(
            _agg_slot_count(a, m[0])
            for a, m in zip(self._aggs, self._arg_meta)
        )
        # Static group-cardinality bound: dictionary-coded and boolean
        # keys bound the distinct-group count at PLAN time, so the table
        # can never overflow and the per-batch host sync on the overflow
        # flag disappears (the host<->device round trip dominates on a
        # tunneled device — the reason Trino precomputes hash channels
        # is the same "decide statically, not per row" discipline).
        bound = 1
        dims = []
        for c in self._group_channels:
            t, d = self._schema[c]
            if t.is_string and d is not None and len(d) > 0:
                dims.append(len(d))
                bound *= len(d) + 1  # +1: the NULL group
            elif t.kind == T.TypeKind.BOOLEAN:
                dims.append(2)
                bound *= 3  # true/false/null
            else:
                bound = 0
                break
        self._static_bound = bound if 0 < bound <= (1 << 16) else None
        # dense-slot reduce: tiny bounded domains skip sorting entirely
        # (per-group masked reductions unroll into one fused program)
        self._dense_dims = (
            tuple(dims)
            if self._static_bound is not None
            and bound <= 64
            and self._group_channels
            and all(
                _BATCH_REDUCER.get(a.kind) in ("sum", "count", "min", "max")
                # long-decimal extremes need the coupled (hi, lo)
                # reducers only the sort path implements
                and not (
                    a.kind in ("min", "max")
                    and a.arg_channel is not None
                    and self._schema[a.arg_channel][0].is_long_decimal
                )
                for a in self._aggs
            )
            else None
        )
        # MXU one-hot contraction (ops/mxu_groupby.py Pallas kernel) for
        # the mid-cardinality band where the unrolled dense path would
        # emit one reduction per slot: sum/count of integer-kind values
        # over bounded domains up to 2048 slots
        def _int_kind(a: AggSpec) -> bool:
            if a.arg_channel is None:
                return True
            t, _ = self._schema[a.arg_channel]
            return not t.is_floating
        self._mxu_dims = (
            tuple(dims)
            if self._dense_dims is None
            and self._static_bound is not None
            and bound <= 2048
            and self._group_channels
            and all(
                _BATCH_REDUCER.get(a.kind) in ("sum", "count")
                and _int_kind(a)
                for a in self._aggs
            )
            and (
                jax.default_backend() == "tpu"
                or _os.environ.get("TRINO_TPU_FORCE_MXU") == "1"
            )
            else None
        )
        self._deferred_ovf: List = []
        # execution-level list of (device flag, message): checked ONCE
        # after results materialize, so no mid-query host sync
        self._checks = deferred_checks
        if self._static_bound is not None:
            self._cap = max(bucket_capacity(self._static_bound), 16)
        if self._global and step != "final":
            self._update = _global_update_fn(
                tuple(self._aggs),
                tuple(
                    a.arg_channel is not None
                    and input_schema[a.arg_channel][0].is_long_decimal
                    for a in self._aggs
                ),
            )

    # -- grouped path --
    def _batch_values(self, batch: RelBatch):
        live = batch.live_mask()
        values, vvalids, reds = [], [], []
        for a in self._aggs:
            if a.arg_channel is None:
                values.append(live.astype(jnp.int64))
                vvalids.append(None)
            elif getattr(batch.columns[a.arg_channel].data, "ndim", 1) == 2:
                _append_long_decimal_slots(
                    a, batch.columns[a.arg_channel], live,
                    values, vvalids, reds,
                )
                continue
            else:
                col = batch.columns[a.arg_channel]
                values.append(col.data)
                vvalids.append(col.valid)
            reds.append(_BATCH_REDUCER[a.kind])
        return live, values, vvalids, tuple(reds)

    def add_input(self, batch: RelBatch) -> None:
        if self._holistic:
            if self._pre is not None:
                batch = self._pre(batch)
            self._collected.append(batch)
            if self._memory is not None:
                # the collect path buffers raw rows: account them so the
                # pool sees the pressure (not revocable — no sketch to
                # spill; oversized holistic inputs fail loudly instead)
                total = 0
                for b in self._collected:
                    for c in b.columns:
                        total += c.data.size * c.data.dtype.itemsize
                        if c.valid is not None:
                            total += c.valid.size
                self._memory.set_bytes(total)
            return
        if self._step == "final":
            if self._pre is not None:
                batch = self._pre(batch)
            self._add_state_input(batch)
            return
        if self._global:
            if self._pre is not None:
                batch = self._pre(batch)
            if self._gstate is None:
                self._gstate = self._global_init()
            self._gstate = self._update(self._gstate, batch)
            return
        # a batch can never have more groups than rows, so the
        # per-batch table caps at the batch capacity regardless of
        # how large the operator's table has grown (an oversized
        # per-batch cap multiplies every state array for nothing).
        # The dense/MXU paths are exempt: they address slots by
        # mixed-radix position, so the table must hold the FULL
        # domain even when the batch has fewer rows than slots.
        if self._dense_dims is not None or self._mxu_dims is not None:
            cap = self._cap
        else:
            cap = min(self._cap, bucket_capacity(batch.capacity))
        gk, gv, used, vals, cnts, ngroups, ovf = _agg_ingest(
            batch, tuple(self._group_channels), tuple(self._aggs),
            cap, self._pre, self._dense_dims, self._mxu_dims,
        )
        new = (tuple(gk), tuple(gv), used, tuple(vals), tuple(cnts))
        if self._static_bound is not None:
            # overflow impossible by the plan-time bound: defer the
            # flag and verify ONCE at finish (fail-loud guard against
            # a runtime dictionary outgrowing the plan-time one)
            self._deferred_ovf.append(ovf)
            with self._state_lock:
                self._pending.append(new)
        else:
            # Deferred rehash: reading `ovf` here costs a ~130ms tunnel
            # round trip PER BATCH. The flag + group count start an
            # async host copy now and are READ one batch later (depth-1
            # pipeline: the copy overlaps the next batch's upstream
            # device work), so an overflow replays immediately at the
            # true group count (the tryRehash analogue) and grows
            # self._cap for the batches that follow.
            for scalar in (ovf, ngroups):
                try:
                    scalar.copy_to_host_async()
                except AttributeError:
                    pass
            with self._state_lock:
                self._pending.append(new)
                self._pending_meta.append(
                    (len(self._pending) - 1, ovf, ngroups, batch, cap)
                )
                while len(self._pending_meta) > 1:
                    self._resolve_one_locked()
        self._track_memory()

    def _resolve_one_locked(self) -> None:
        """Settle the OLDEST deferred per-batch overflow record; its
        flag has been copying to the host since ingest (caller holds
        _state_lock). The flag also covers sort_group_reduce's 62-bit
        hash-collision detector, so the replay LOOPS (capacity doubling
        reseeds via _order_seed) until it comes back clean — same
        semantics as the old per-batch retry ladder."""
        idx, ovf, ngroups, batch, cap = self._pending_meta.pop(0)
        while bool(ovf):
            cap = max(cap * 2, bucket_capacity(int(ngroups)))
            self._cap = max(self._cap, cap)
            gk, gv, used, vals, cnts, ngroups, ovf = _agg_ingest(
                batch, tuple(self._group_channels), tuple(self._aggs),
                cap, self._pre, self._dense_dims, self._mxu_dims,
            )
            self._pending[idx] = (
                tuple(gk), tuple(gv), used, tuple(vals), tuple(cnts)
            )

    def _resolve_pending_locked(self) -> None:
        """Drain every deferred overflow record (merge points)."""
        while self._pending_meta:
            self._resolve_one_locked()

    def _merge_pending_locked(self) -> None:
        """Fold _pending (+ current acc) into ONE merged state with a
        single N-way device program (caller holds _state_lock)."""
        self._resolve_pending_locked()
        states = ([self._acc] if self._acc is not None else []) + self._pending
        self._pending = []
        if not states:
            return
        if len(states) == 1 and not self._unreduced_state:
            self._acc = states[0]
            return
        reducers = []
        for i, x in enumerate(self._aggs):
            reducers.extend(_slot_merge_reducers(x, self._arg_meta[i][0]))
        reducers = tuple(reducers)
        # distinct groups across N states cannot exceed the concatenated
        # slot count, so the merge table caps there (bounds the output
        # arrays by the data, not by a possibly-overgrown _cap)
        concat_len = sum(int(s[2].shape[0]) for s in states)
        while True:
            cap = min(
                max(self._cap, 16), bucket_capacity(max(concat_len, 16))
            )
            merged, ngroups, ovf = _merge_group_states(
                tuple(states), reducers, cap
            )
            if self._static_bound is not None:
                self._deferred_ovf.append(ovf)
                break
            if not bool(ovf):
                break
            self._cap = max(self._cap * 2, bucket_capacity(int(ngroups)))
        self._acc = merged
        self._unreduced_state = False

    # -- final step: consume serialized accumulator state --
    def _add_state_input(self, batch: RelBatch) -> None:
        """Ingest a partial_output_schema-layout batch (the exchange's
        output) directly as a group-state set and merge it in."""
        k = len(self._group_channels)
        live = batch.live_mask()
        if self._global:
            self._merge_global_state(batch, live)
            return
        # the wire layout is uniform — k key columns then ONE
        # (value, count) pair per aggregate; long-decimal columns arrive
        # as (n, 2) limb pairs and split back into the internal slot
        # layout here (keys into limb key lanes, sums into four 32-bit
        # limb-sum slots, extremes/firsts into (hi, lo) slots)
        keys, valids = [], []
        for c in range(k):
            col = batch.columns[c]
            v = col.valid_mask()
            if getattr(col.data, "ndim", 1) == 2:
                keys.extend([col.data[:, 0], col.data[:, 1]])
                valids.extend([v, v])
            else:
                keys.append(col.data)
                valids.append(v)
        vals, cnts = [], []
        for i, a in enumerate(self._aggs):
            val_col = batch.columns[k + 2 * i]
            cnt = batch.columns[k + 2 * i + 1].data.astype(jnp.int64)
            if getattr(val_col.data, "ndim", 1) == 2:
                if a.kind in ("sum", "avg"):
                    pieces = _limb_split(val_col.data)
                else:  # min/max/any: the slots ARE the (hi, lo) pair
                    pieces = [val_col.data[:, 0], val_col.data[:, 1]]
                for p in pieces:
                    vals.append(p)
                    cnts.append(cnt)
            else:
                vals.append(val_col.data)
                cnts.append(cnt)
        new = (tuple(keys), tuple(valids), live, tuple(vals), tuple(cnts))
        with self._state_lock:
            self._pending.append(new)
            self._unreduced_state = True
        self._track_memory()

    def _merge_global_state(self, batch: RelBatch, live) -> None:
        """Global (no GROUP BY) final step: fold incoming single-row
        states with the merge reducers."""
        if self._gstate is None:
            self._gstate = self._global_init()
        from trino_tpu.ops import int128 as I128

        out = []
        for i, a in enumerate(self._aggs):
            val, cnt = self._gstate[i]
            v_in = batch.columns[2 * i].data
            c_in = batch.columns[2 * i + 1].data.astype(jnp.int64)
            c_in = jnp.where(live, c_in, 0)
            n = jnp.sum(c_in)
            red = _MERGE_REDUCER[a.kind]
            if getattr(v_in, "ndim", 1) == 2:
                # Int128 partial states: merge in limb arithmetic
                present = live & (c_in > 0)
                if red == "sum":
                    limb_sums = [
                        jnp.sum(jnp.where(live, piece, jnp.int64(0)))
                        for piece in _limb_split(v_in)
                    ]
                    bh, bl = _limb_join(limb_sums)
                    h, lo = I128.add(val[0], val[1], bh, bl)
                    out.append((jnp.stack([h, lo]), cnt + n))
                elif red in ("min", "max"):
                    h, lo = v_in[:, 0], v_in[:, 1]
                    m1, m2 = _lex128_reduce(h, lo, present, red)
                    better = (
                        I128.lt(m1, m2, val[0], val[1])
                        if red == "min"
                        else I128.lt(val[0], val[1], m1, m2)
                    )
                    take = (better | (cnt == 0)) & jnp.any(present)
                    nh = jnp.where(take, m1, val[0])
                    nl = jnp.where(take, m2, val[1])
                    out.append((jnp.stack([nh, nl]), cnt + n))
                else:  # first
                    first = v_in[jnp.argmax(present)]
                    new_val = jnp.where(
                        cnt > 0, val, jnp.where(jnp.any(present), first, val)
                    )
                    out.append((new_val, cnt + n))
                continue
            if red == "sum":
                neutral = jnp.zeros((), dtype=val.dtype)
                contrib = jnp.where(live, v_in.astype(val.dtype), neutral)
                out.append((val + jnp.sum(contrib), cnt + n))
            elif red in ("min", "max"):
                neutral = minmax_neutral(v_in.dtype, red)
                present = live & (c_in > 0)
                masked = jnp.where(present, v_in, jnp.asarray(neutral, v_in.dtype))
                r = jnp.min(masked) if red == "min" else jnp.max(masked)
                op = jnp.minimum if red == "min" else jnp.maximum
                out.append((op(val, r.astype(val.dtype)), cnt + n))
            else:  # first
                present = live & (c_in > 0)
                first = v_in[jnp.argmax(present)]
                new_val = jnp.where(
                    cnt > 0, val, jnp.where(jnp.any(present), first, val)
                )
                out.append((new_val, cnt + n))
        self._gstate = out

    # -- partial step: emit serialized accumulator state --
    def _partial_state_batch(self) -> RelBatch:
        """Current grouped state as a partial-wire-format batch (the
        accumulator serialization shared by the exchange AND the
        spiller)."""
        if self._acc is None:
            key_dts = []
            for c in self._group_channels:
                t = self._schema[c][0]
                key_dts.extend([t.dtype] * t.lanes)
            self._acc = (
                [jnp.zeros(16, dtype=dt) for dt in key_dts],
                [jnp.zeros(16, dtype=jnp.bool_) for _ in key_dts],
                jnp.zeros(16, dtype=jnp.bool_),
                [jnp.zeros(16, dtype=jnp.int64) for _ in range(self._n_slots)],
                [jnp.zeros(16, dtype=jnp.int64) for _ in range(self._n_slots)],
            )
        cols: List[Column] = []
        gk, gv, used, vals, cnts = self._acc
        ki = 0
        for ch in self._group_channels:
            t, d = self._schema[ch]
            if t.lanes == 2:  # reassemble split long-decimal key limbs
                cols.append(Column(
                    t, jnp.stack([gk[ki], gk[ki + 1]], axis=-1), gv[ki], d,
                ))
                ki += 2
            else:
                cols.append(Column(t, gk[ki], gv[ki], d))
                ki += 1
        si = 0
        for a, (arg_t, _) in zip(self._aggs, self._arg_meta):
            vt, vd = agg_state_meta(a, self._schema)[0]
            cnt = cnts[si]
            col, si = _slots_to_wire_column(a, arg_t, vt, vd, vals, si)
            cols.append(col)
            cols.append(Column(T.BIGINT, cnt.astype(jnp.int64), None, None))
        return RelBatch(cols, used)

    def _emit_partial(self) -> None:
        if self._global:
            cols: List[Column] = []
            states = self._gstate if self._gstate is not None else self._global_init()
            for a, (val, cnt) in zip(self._aggs, states):
                vt, vd = agg_state_meta(a, self._schema)[0]
                cols.append(Column(vt, val[None].astype(vt.dtype), None, vd))
                cols.append(Column(T.BIGINT, cnt[None].astype(jnp.int64), None, None))
            self._out = RelBatch(cols, jnp.ones(1, dtype=jnp.bool_))
            return
        out = self._partial_state_batch()
        if out.capacity >= _SHRINK_MIN_CAPACITY and self._dense_dims is None \
                and self._mxu_dims is None:
            out = _shrink_prefix(out, int(jnp.sum(out.live_mask())))
        self._out = out

    # -- holistic (collect) path: min_by/max_by/approx_percentile --
    def _finish_holistic(self) -> RelBatch:
        """One pass over ALL collected rows: regular aggregates via
        sort_group_reduce, order statistics via grouped_argbest /
        grouped_percentile — all three sort by the same key chain, so
        their group slots align (ops/groupby._segment_bounds)."""
        if self._collected:
            mega = concat_batches(self._collected)
        else:
            mega = None
        if mega is None or mega.live_mask().shape[0] == 0:
            # zero rows collected: one all-dead row keeps every shape
            # non-empty so the global path can slice its single slot
            cols = [
                Column(t, jnp.zeros(1, dtype=t.dtype),
                       jnp.zeros(1, dtype=jnp.bool_), d)
                for t, d in self._schema
            ]
            mega = RelBatch(cols, jnp.zeros(1, dtype=jnp.bool_))
        self._collected = []
        keys = [mega.columns[c].data for c in self._group_channels]
        valids = [mega.columns[c].valid_mask() for c in self._group_channels]
        live = mega.live_mask()

        regular = [
            (i, a) for i, a in enumerate(self._aggs)
            if a.kind not in HOLISTIC_KINDS
        ]
        values, vvalids, reds = [], [], []
        for _, a in regular:
            if a.arg_channel is None:
                values.append(live.astype(jnp.int64))
                vvalids.append(None)
            elif getattr(mega.columns[a.arg_channel].data, "ndim", 1) == 2:
                _append_long_decimal_slots(
                    a, mega.columns[a.arg_channel], live,
                    values, vvalids, reds,
                )
                continue
            else:
                col = mega.columns[a.arg_channel]
                values.append(col.data)
                vvalids.append(col.valid)
            reds.append(_BATCH_REDUCER[a.kind])

        cap = self._cap
        while True:
            gk, gv, used, vals, cnts, ngroups, ovf = G.sort_group_reduce(
                tuple(keys), tuple(valids), live, tuple(values),
                tuple(vvalids), tuple(reds), cap,
            )
            if not self._group_channels or not bool(ovf):
                break
            cap = max(cap * 2, bucket_capacity(int(ngroups)))
        self._cap = cap

        agg_cols: Dict[int, Column] = {}
        si = 0
        for (i, a) in regular:
            arg_t, arg_d = self._arg_meta[i]
            state, si = _slots_to_state(a, arg_t, vals, cnts, si)
            agg_cols[i] = _agg_output(a, state, arg_t, arg_d)
        # one key sort shared by every argbest kernel (percentile needs
        # its own value pre-ordering and sorts separately)
        shared_order = (
            G.key_order(tuple(keys), tuple(valids), live, cap)
            if any(a.kind in ("min_by", "max_by") for a in self._aggs)
            else None
        )
        for i, a in enumerate(self._aggs):
            if a.kind not in HOLISTIC_KINDS:
                continue
            xcol = mega.columns[a.arg_channel]
            if a.kind in ("min_by", "max_by"):
                bycol = mega.columns[a.arg2_channel]
                data, valid = G.grouped_argbest(
                    tuple(keys), tuple(valids), live,
                    bycol.data, bycol.valid, xcol.data, xcol.valid,
                    a.kind, cap, order=shared_order,
                )
            elif a.kind == "listagg":
                agg_cols[i] = self._listagg_column(
                    a, keys, valids, live, xcol, cap
                )
                continue
            elif a.kind in _COLLECT_KINDS:
                agg_cols[i] = self._collect_column(
                    a, keys, valids, live, mega, cap
                )
                continue
            elif a.kind == "approx_distinct":
                cnts_d = G.grouped_count_distinct(
                    tuple(keys), tuple(valids), live,
                    xcol.data, xcol.valid, cap,
                )
                agg_cols[i] = Column(T.BIGINT, cnts_d, None, None)
                continue
            elif a.kind == "pctl_merge":
                ccol = mega.columns[a.arg2_channel]
                mxcol = mega.columns[a.arg3_channel]
                data, valid = G.grouped_weighted_percentile(
                    tuple(keys), tuple(valids), live,
                    xcol.data, xcol.valid, ccol.data, mxcol.data,
                    a.percentile, cap,
                )
            else:  # approx_percentile
                data, valid = G.grouped_percentile(
                    tuple(keys), tuple(valids), live,
                    xcol.data, xcol.valid, a.percentile, cap,
                )
            agg_cols[i] = Column(
                a.out_type, data.astype(a.out_type.dtype), valid,
                xcol.dictionary,
            )

        out_cols: List[Column] = []
        for ch, kk, vv in zip(self._group_channels, gk, gv):
            t, d = self._schema[ch]
            out_cols.append(Column(t, kk, vv, d))
        for i in range(len(self._aggs)):
            out_cols.append(agg_cols[i])
        if self._global:
            # global aggregation over empty input still yields ONE row
            # (counts 0, other aggregates NULL) — slot 0 carries it.
            # Nested (map/array) outputs slice through gather: rebuilding
            # a flat Column from .data would drop their starts/flat
            # arrays (the lengths array alone is not the value)
            pos = jnp.zeros(1, dtype=jnp.int32)
            return RelBatch(
                [c.gather(pos) if c.type.is_nested
                 or c.type.kind == T.TypeKind.ARRAY
                 else Column(c.type, c.data[:1], None if c.valid is None
                             else c.valid[:1], c.dictionary)
                 for c in out_cols],
                jnp.ones(1, dtype=jnp.bool_),
            )
        return RelBatch(out_cols, used)

    def _collect_column(self, a: AggSpec, keys, valids, live, mega, cap):
        """Collect-path aggregates (array_agg/map_agg/histogram/...):
        the device delivers group-contiguous, value-ordered row order
        (ops/groupby.grouped_rows_order); the host assembles each
        group's container. Holistic by construction — the fragmenter
        runs these single-step after a gather, exactly like listagg
        (reference: ArrayAggregationFunction / MapAggregationFunction /
        Histogram build their result Blocks on the heap too)."""
        xcol = mega.columns[a.arg_channel]
        gid, sm, order, n_groups, overflowed = G.grouped_rows_order(
            tuple(keys), tuple(valids), live, xcol.data, xcol.valid, cap
        )
        gid_h, sm_h, ord_h, n_h, ov_h = jax.device_get(
            (gid, sm, order, n_groups, overflowed)
        )
        if bool(ov_h):
            # the finish loop settles capacity through sort_group_reduce
            # before holistic finalizers run, so this cannot fire unless
            # that invariant breaks — fail loudly, not with a bad gather
            raise RuntimeError("collect aggregate group overflow")
        n_h = int(n_h)

        def pyvals(ch):
            lst = jax.device_get(mega.columns[ch]).to_pylist()
            return [lst[i] for i in ord_h]

        xs = pyvals(a.arg_channel)
        ys = pyvals(a.arg2_channel) if a.arg2_channel is not None else None
        groups: List[list] = [[] for _ in range(n_h)]
        for j, (g, ok) in enumerate(zip(gid_h, sm_h)):
            if ok and 0 <= g < n_h:
                groups[g].append(
                    (xs[j], ys[j]) if ys is not None else xs[j]
                )

        kind = a.kind
        if kind in ("bitwise_and_agg", "bitwise_or_agg", "bitwise_xor_agg"):
            op = {"bitwise_and_agg": lambda s, v: s & v,
                  "bitwise_or_agg": lambda s, v: s | v,
                  "bitwise_xor_agg": lambda s, v: s ^ v}[kind]
            data = np.zeros(cap, dtype=np.int64)
            valid = np.zeros(cap, dtype=bool)
            for g, vals in enumerate(groups):
                vals = [v for v in vals if v is not None]
                if not vals:
                    continue
                acc = vals[0]
                for v in vals[1:]:
                    acc = op(acc, v)
                # wrap to signed 64-bit (python ints are unbounded)
                acc &= (1 << 64) - 1
                data[g] = acc - (1 << 64) if acc >= (1 << 63) else acc
                valid[g] = True
            return Column(
                T.BIGINT, jnp.asarray(data), jnp.asarray(valid), None
            )

        out_vals: List[object] = [None] * cap
        for g, vals in enumerate(groups):
            if kind == "array_agg":
                # NULL elements are kept (the reference's array_agg does)
                out_vals[g] = vals if vals else None
            elif kind == "map_agg":
                m = {k: v for k, v in vals if k is not None}
                out_vals[g] = m or None
            elif kind == "multimap_agg":
                mm: Dict[object, list] = {}
                for k, v in vals:
                    if k is not None:
                        mm.setdefault(k, []).append(v)
                out_vals[g] = mm or None
            elif kind == "histogram":
                h: Dict[object, int] = {}
                for v in vals:
                    if v is not None:
                        h[v] = h.get(v, 0) + 1
                out_vals[g] = h or None
            elif kind == "approx_most_frequent":
                b = int(a.param or 3)
                h = {}
                for v in vals:
                    if v is not None:
                        h[v] = h.get(v, 0) + 1
                top = sorted(h.items(), key=lambda kv: (-kv[1], str(kv[0])))
                out_vals[g] = dict(top[:b]) or None
            elif kind == "numeric_histogram":
                out_vals[g] = _bht_histogram(
                    [v for v in vals if v is not None], int(a.param or 10)
                )
            elif kind == "map_union":
                merged: Dict[object, object] = {}
                for m in vals:
                    if m:
                        merged.update(m)
                out_vals[g] = merged or None
            elif kind == "approx_set":
                from trino_tpu.expr.pyfns import hll_from_values

                nn = [v for v in vals if v is not None]
                out_vals[g] = hll_from_values(nn) if nn else None
            elif kind == "tdigest_agg":
                from trino_tpu.expr.pyfns import tdigest_from_values

                nn = [v for v in vals if v is not None]
                out_vals[g] = tdigest_from_values(nn) if nn else None
            elif kind == "sketch_merge":
                from trino_tpu.expr.pyfns import sketch_merge

                nn = [v for v in vals if v is not None]
                out_vals[g] = sketch_merge(nn) if nn else None
        if a.post:
            # fused sketch accessor: the digest never leaves the host
            from trino_tpu.expr.pyfns import (
                hll_cardinality, tdigest_quantile_at_value,
                tdigest_value_at_quantile,
            )

            if a.post == "vaq":
                # values_at_quantiles: one array(double) per group
                arrs: List[object] = [None] * cap
                for g in range(n_h):
                    d = out_vals[g]
                    if d is None:
                        continue
                    arrs[g] = [
                        tdigest_value_at_quantile(d, float(q))
                        for q in (a.param or ())
                    ]
                return Column.from_pylist(a.out_type, arrs, capacity=cap)
            data = np.zeros(
                cap, dtype=np.int64 if a.post == "card" else np.float64
            )
            valid = np.zeros(cap, dtype=bool)
            for g in range(n_h):
                d = out_vals[g]
                if d is None:
                    continue
                if a.post == "card":
                    r = hll_cardinality(d)
                elif a.post == "vq":
                    r = tdigest_value_at_quantile(d, float(a.param))
                else:
                    r = tdigest_quantile_at_value(d, float(a.param))
                if r is not None:
                    data[g] = r
                    valid[g] = True
            return Column(
                a.out_type, jnp.asarray(data), jnp.asarray(valid), None
            )
        return Column.from_pylist(a.out_type, out_vals, capacity=cap)

    def _listagg_column(self, a: AggSpec, keys, valids, live, xcol, cap):
        """listagg/string_agg: concatenating group members into NEW
        strings is host-side work by nature (Trino's
        ListaggAggregationFunction builds its VARCHAR on the heap too);
        the device groups and value-orders the rows, the host joins
        dictionary values per dense group id. Element order is the
        value's lexical order (deterministic; WITHIN GROUP custom
        orderings are future work)."""
        gid, w, codes, n_groups, _ = G.grouped_rows_sorted(
            tuple(keys), tuple(valids), live, xcol.data, xcol.valid, cap
        )
        gid_h, w_h, codes_h, n_h = jax.device_get((gid, w, codes, n_groups))
        dict_values = xcol.dictionary.values if xcol.dictionary else []
        parts: List[List[str]] = [[] for _ in range(int(n_h))]
        for g, ok, c in zip(gid_h, w_h, codes_h):
            if ok and 0 <= g < len(parts) and 0 <= c < len(dict_values):
                parts[g].append(dict_values[int(c)])
        sep = a.separator or ""
        strings = [sep.join(p) for p in parts]
        out_dict = Dictionary(strings)
        data = np.zeros(cap, dtype=np.int32)
        valid = np.zeros(cap, dtype=bool)
        for g, s in enumerate(strings):
            if parts[g]:
                data[g] = out_dict.code(s)
                valid[g] = True
        return Column(
            T.VARCHAR, jnp.asarray(data), jnp.asarray(valid), out_dict
        )

    # -- spill (revocable memory) --
    def _revoke_memory(self) -> None:
        """startMemoryRevoke/finishMemoryRevoke collapsed: dump the group
        state to disk in the partial wire format and reset. May be called
        from ANOTHER task's thread (MemoryPool.reserve picks victims), so
        the whole snapshot-spill-reset runs under the state lock."""
        with self._state_lock:
            if self._in_finish:
                return  # finish owns state
            self._merge_pending_locked()
            if self._acc is None:
                return  # nothing to give back
            if self._spiller is None:
                from trino_tpu.exec.spill import FileSpiller

                self._spiller = FileSpiller()
            self._spiller.spill(self._partial_state_batch())
            self._acc = None
        self._track_memory()

    def _track_memory(self) -> None:
        """Account the accumulation-state footprint. The pool bounds
        ACCUMULATION memory; the finish-phase merge+finalize produces the
        operator's output (not operator state) and is exempt — the
        partitioned-spill refinement (grace merge of 1/N partitions at a
        time) is the next step toward bounding finish too."""
        if self._memory is None or self._in_finish:
            return
        from trino_tpu.runtime.memory import batch_bytes

        total = 0
        for st in ([self._acc] if self._acc is not None else []) + list(self._pending):
            gk, gv, used, vals, cnts = st
            for arr in [*gk, *gv, used, *vals, *cnts]:
                total += arr.size * arr.dtype.itemsize
        # the depth-1 deferred-rehash queue retains one input batch
        for _, _, _, b, _ in self._pending_meta:
            total += batch_bytes(b)
        try:
            self._memory.set_bytes(total)
        except Exception:
            # pool exhausted even after revoking others: spill our own
            # state (self-revocation) and account the reset footprint
            if self._acc is None and not self._pending:
                raise
            self._revoke_memory()
            return
        self._memory.set_revocable_bytes(total)

    # -- global path --
    def _global_init(self):
        states = []
        for a in self._aggs:
            dt = (
                self._schema[a.arg_channel][0].dtype
                if a.arg_channel is not None
                else np.dtype(np.int64)
            )
            if a.kind in ("count", "count_star"):
                val = jnp.int64(0)
            elif a.kind in ("sum", "avg"):
                if (
                    a.arg_channel is not None
                    and self._schema[a.arg_channel][0].is_long_decimal
                ):
                    val = jnp.zeros(2, dtype=jnp.int64)  # Int128 (hi, lo)
                else:
                    acc_dt = (
                        jnp.float64 if np.issubdtype(dt, np.floating) else jnp.int64
                    )
                    val = jnp.zeros((), dtype=acc_dt)
            elif a.kind in ("min", "max"):
                if (
                    a.arg_channel is not None
                    and self._schema[a.arg_channel][0].is_long_decimal
                ):
                    val = jnp.zeros(2, dtype=jnp.int64)  # replaced on first row
                else:
                    val = jnp.asarray(minmax_neutral(dt, a.kind), dtype=dt)
            else:  # any
                if (
                    a.arg_channel is not None
                    and self._schema[a.arg_channel][0].is_long_decimal
                ):
                    val = jnp.zeros(2, dtype=jnp.int64)
                else:
                    val = jnp.zeros((), dtype=dt)
            states.append((val, jnp.int64(0)))
        return states

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        if self._holistic:
            self._out = self._finish_holistic()
            return
        with self._state_lock:
            # flips revocation off atomically; from here finish owns state
            self._in_finish = True
            spiller, self._spiller = self._spiller, None
        if spiller is not None:
            # merge-on-unspill: spilled partial states re-enter through
            # the FINAL-step ingestion path
            for b in spiller.unspill():
                self._add_state_input(b)
            spiller.close()
        with self._state_lock:
            self._merge_pending_locked()
        if self._memory is not None and not self._global:
            self._memory.set_bytes(0)
            self._memory.set_revocable_bytes(0)
        if self._deferred_ovf:
            flag = _any_flags(tuple(self._deferred_ovf))
            msg = (
                "group table overflowed its plan-time bound "
                "(runtime dictionary larger than planned)"
            )
            if self._checks is not None:
                # deferred to the end-of-query sync point
                self._checks.append((flag, msg))
            elif bool(flag):
                raise RuntimeError(msg)
            self._deferred_ovf = []
        if self._step == "partial":
            self._emit_partial()
            return
        cols: List[Column] = []
        if self._global:
            states = self._gstate if self._gstate is not None else self._global_init()
            live = jnp.ones(1, dtype=jnp.bool_)
            for i, (a, (val, cnt)) in enumerate(zip(self._aggs, states)):
                arg_t, arg_d = self._arg_meta[i]
                long_arg = arg_t is not None and arg_t.is_long_decimal
                if a.kind in ("count", "count_star"):
                    state = (val[None],)
                elif long_arg and a.kind in ("sum", "avg", "min", "max", "any"):
                    # Int128 (hi, lo) scalar state
                    state = (val[0][None], val[1][None], cnt[None])
                else:
                    state = (val[None], cnt[None])
                cols.append(_agg_output(a, state, arg_t, arg_d))
            self._out = RelBatch(cols, live)
            return
        if self._acc is None:
            # no input: empty group set (long-decimal keys occupy two
            # int64 limb slots — the split-key layout of _agg_ingest)
            key_dts = []
            for c in self._group_channels:
                t = self._schema[c][0]
                key_dts.extend([t.dtype] * t.lanes)
            self._acc = (
                [jnp.zeros(16, dtype=dt) for dt in key_dts],
                [jnp.zeros(16, dtype=jnp.bool_) for _ in key_dts],
                jnp.zeros(16, dtype=jnp.bool_),
                [jnp.zeros(16, dtype=jnp.int64) for _ in range(self._n_slots)],
                [jnp.zeros(16, dtype=jnp.int64) for _ in range(self._n_slots)],
            )
        gk, gv, used, vals, cnts = self._acc
        ki = 0
        for ch in self._group_channels:
            t, d = self._schema[ch]
            if t.lanes == 2:  # reassemble split long-decimal limbs
                cols.append(Column(
                    t, jnp.stack([gk[ki], gk[ki + 1]], axis=-1),
                    gv[ki], d,
                ))
                ki += 2
            else:
                cols.append(Column(t, gk[ki], gv[ki], d))
                ki += 1
        outs = _finalize_grouped(
            (tuple(gk), tuple(gv), used, tuple(vals), tuple(cnts)),
            tuple(self._aggs),
            tuple(t for t, _ in self._arg_meta),
        )
        for a, (arg_t, arg_d), (data, valid) in zip(
            self._aggs, self._arg_meta, outs
        ):
            d = arg_d if a.kind in ("min", "max", "any") else None
            cols.append(Column(a.out_type, data, valid, d))
        out = RelBatch(cols, used)
        if out.capacity >= _SHRINK_MIN_CAPACITY and self._dense_dims is None \
                and self._mxu_dims is None:
            # sort-path group rows are prefix-dense: hand downstream
            # operators the live size, not the table capacity
            out = _shrink_prefix(out, int(jnp.sum(used)))
        self._out = out

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


# ---------------------------------------------------------------------------
# Hash join
# ---------------------------------------------------------------------------


class JoinBridge:
    """Build->probe handoff (PartitionedLookupSourceFactory analogue,
    join/PartitionedLookupSourceFactory.java:56). The planner runs the
    build pipeline to completion before starting the probe pipeline.
    When the build side spilled (grace mode), `grace` carries the
    hash-partitioned build pages instead of a device lookup source."""

    def __init__(self):
        self.lookup_source: Optional[J.LookupSource] = None
        self.build_batch: Optional[RelBatch] = None
        # build-side key dictionaries, for probe-side code remapping
        self.key_dicts: Optional[List[Optional[Dictionary]]] = None
        # build-side key channel indexes (dynamic-filter domains)
        self.build_key_channels: List[int] = []
        # grace mode: partitioned build spill + schema to rebuild from
        self.grace = None  # Optional[spill.GracePartitionSpill]
        self.build_schema: Optional[list] = None


@partial(jax.jit, static_argnames=("key_channels",))
def _consolidate_build(parts: Tuple[RelBatch, ...], key_channels: Tuple[int, ...]):
    """Consolidate build batches + build the LookupSource in one device
    program (HashBuilderOperator.java:58)."""
    merged = concat_batches(list(parts))
    keys, valids = [], []
    for c in key_channels:
        col = merged.columns[c]
        v = col.valid_mask()
        if getattr(col.data, "ndim", 1) == 2:  # long-decimal limbs
            keys.extend([col.data[:, 0], col.data[:, 1]])
            valids.extend([v, v])
        else:
            keys.append(col.data)
            valids.append(v)
    return J.build_lookup(keys, valids, merged.live_mask()), merged


GRACE_PARTITIONS = 8

# batches whose capacity dwarfs their live count get host-compacted at
# blocking boundaries: every downstream kernel then compiles at the
# small shape and moves less HBM. (An earlier note here blamed sort
# compile time "growing brutally with array length"; r3 measurement
# localized that to lax.associative_scan — now banned, see
# ops/groupby.py — while sort itself compiles in ~20-60s at any
# multi-million-row shape. Compaction remains worthwhile for runtime.)
_SHRINK_MIN_CAPACITY = 1 << 17


def _shrink_prefix(batch: RelBatch, live_count: int) -> RelBatch:
    """Slice a PREFIX-dense batch (live rows packed from slot 0 — the
    sort-path aggregation output contract) down to a bucketed capacity."""
    new_cap = max(bucket_capacity(live_count), 16)
    if new_cap >= batch.capacity:
        return batch
    cols = [
        Column(
            c.type,
            c.data[:new_cap],
            None if c.valid is None else c.valid[:new_cap],
            c.dictionary,
        )
        for c in batch.columns
    ]
    live = None if batch.live is None else batch.live[:new_cap]
    return RelBatch(cols, live)


class HashBuildSink(Operator):
    """Consumes the build side, consolidates, builds the LookupSource
    (HashBuilderOperator.java:58 — one sort instead of row inserts).

    Out-of-core: under memory pressure the revocation protocol flips
    the sink into GRACE mode (HashBuilderOperator spill states,
    HashBuilderOperator.java:163-206): accumulated and future batches
    hash-partition to disk and the probe runs partition-wise."""

    def __init__(self, bridge: JoinBridge, key_channels: Sequence[int],
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
                 memory_context=None, force_spill: bool = False):
        self._bridge = bridge
        self._keys = list(key_channels)
        self._schema = list(input_schema)
        self._inputs: List[RelBatch] = []
        self._memory = memory_context
        self._grace = None
        self._state_lock = named_lock("HashBuildSink._state_lock")
        if force_spill:
            # adaptive spill-mode re-plan (skewed/oversized build): open
            # the grace partitions up front instead of waiting for the
            # pool's revocation callback — every batch partitions to
            # disk on arrival and the device never holds the full build
            from trino_tpu.exec.spill import GracePartitionSpill

            self._grace = GracePartitionSpill(GRACE_PARTITIONS, self._keys)
        if self._memory is not None:
            self._memory.set_revoker(self._revoke_memory)

    def add_input(self, batch: RelBatch) -> None:
        with self._state_lock:
            if self._grace is not None:
                self._grace.add(batch)
                return
            self._inputs.append(batch)
        self._track_memory()

    def _track_memory(self) -> None:
        if self._memory is None:
            return
        from trino_tpu.runtime.memory import batch_bytes

        with self._state_lock:
            total = sum(batch_bytes(b) for b in self._inputs)
        try:
            self._memory.set_bytes(total)
        except Exception:
            if total == 0:
                raise
            self._revoke_memory()
            return
        # a concurrent revocation may have spilled the inputs between the
        # snapshot and set_bytes; advertise only what is STILL revocable
        # (set_bytes cannot run under _state_lock — the pool's victim
        # callbacks re-enter this operator)
        with self._state_lock:
            still = sum(batch_bytes(b) for b in self._inputs)
        self._memory.set_revocable_bytes(min(total, still))

    def _revoke_memory(self) -> None:
        """startMemoryRevoke: dump accumulated build rows into the
        hash-partitioned spill and continue in grace mode."""
        with self._state_lock:
            if self._finishing or self._grace is not None and not self._inputs:
                return
            if self._grace is None:
                from trino_tpu.exec.spill import GracePartitionSpill

                self._grace = GracePartitionSpill(
                    GRACE_PARTITIONS, self._keys
                )
            for b in self._inputs:
                self._grace.add(b)
            self._inputs = []
        if self._memory is not None:
            self._memory.set_bytes(0)
            self._memory.set_revocable_bytes(0)

    def finish(self) -> None:
        if self._finishing:
            return
        with self._state_lock:
            self._finishing = True
            grace, inputs = self._grace, self._inputs
            self._inputs = []
        if grace is not None:
            for b in inputs:
                grace.add(b)
            self._bridge.grace = grace
            self._bridge.build_schema = self._schema
            self._bridge.build_key_channels = list(self._keys)
            if self._memory is not None:
                self._memory.set_bytes(0)
                self._memory.set_revocable_bytes(0)
            return
        parts = tuple(inputs or [empty_batch(self._schema)])
        total_cap = sum(b.capacity for b in parts)
        if total_cap >= _SHRINK_MIN_CAPACITY:
            # sparse build side (e.g. a HAVING-filtered aggregate):
            # host-compact so the lookup build and every probe compile
            # at the live size, not the upstream capacity
            counts = jax.device_get(
                [jnp.sum(b.live_mask().astype(jnp.int32)) for b in parts]
            )
            n_live = int(sum(int(c) for c in counts))
            target = max(bucket_capacity(n_live), 16)
            if target * 4 <= total_cap:
                from trino_tpu.exec.serde import Page as _Page
                from trino_tpu.exec.serde import concat_pages

                merged_host = concat_pages(
                    [_Page.from_batch(b) for b in parts]
                )
                parts = (merged_host.to_batch(target),)
        ls, merged = _consolidate_build(parts, tuple(self._keys))
        self._bridge.lookup_source = ls
        self._bridge.build_batch = merged
        self._bridge.key_dicts = [
            merged.columns[c].dictionary for c in self._keys
        ]
        self._bridge.build_key_channels = list(self._keys)
        if self._memory is not None:
            # the retained build side still occupies its reservation,
            # but it is NOT revocable anymore (the probe needs it live);
            # leaving revocable bytes registered would make the pool's
            # revoke loop pick a victim that can never release
            self._memory.set_revocable_bytes(0)

    def get_output(self) -> Optional[RelBatch]:
        return None

    def is_finished(self) -> bool:
        return self._finishing


class MxuJoinAggOperator(Operator):
    """Join-project-aggregate over the MXU (ops/mxu_join.py): consumes
    probe pages of an inner single-key equi-join whose aggregate
    arguments are all probe-side and whose group columns are all
    build-side, and contracts each page against the one-hot key-id
    indicator on the systolic array instead of expanding pairs.

    Emits ONE partial page at finish — per build row, the summed probe
    contributions of its key — which the planner feeds into an ordinary
    HashAggregationOperator for the final grouping. The build side
    arrives through the standard JoinBridge (the planner runs the build
    pipeline to completion first); the planner constructs that sink
    without a memory context, so the bridge never flips to grace mode
    under this operator."""

    def __init__(self, bridge: JoinBridge, key_channel: int, aggs,
                 group_channels: Sequence[int]):
        self._bridge = bridge
        self._key = key_channel
        # static layout for the kernel: agg kinds + probe arg channels
        self._kinds = tuple(a.kind for a in aggs)
        self._args = tuple(a.arg_channel for a in aggs)
        self._groups = list(group_channels)
        self._analysis = None
        self._acc = None
        self._outputs: List[RelBatch] = []

    def _analyze(self):
        from trino_tpu.ops import mxu_join as MJ

        ls = self._bridge.lookup_source
        build = self._bridge.build_batch
        kc = self._bridge.build_key_channels[0]
        col = build.columns[kc]
        kid, kid_by_pos, distinct, n_distinct, hash_pure = (
            MJ.build_key_analysis(
                col.data, col.valid_mask(), build.live_mask(),
                ls.sorted_hash, ls.perm,
            )
        )
        # one host read at the build barrier: hash-collision purity
        # decides the probe lookup path for the whole query
        self._analysis = (
            kid, kid_by_pos, distinct, n_distinct,
            bool(jax.device_get(hash_pure)),
        )

    def add_input(self, probe: RelBatch) -> None:
        from trino_tpu.ops import mxu_join as MJ

        if self._analysis is None:
            self._analyze()
        _kid, kid_by_pos, distinct, n_distinct, hash_pure = self._analysis
        kcol = probe.columns[self._key]
        kv = kcol.valid_mask()
        arg_data, arg_valid = [], []
        for ch in self._args:
            if ch is None:  # count_star placeholder, unread
                arg_data.append(kcol.data)
                arg_valid.append(kv)
            else:
                c = probe.columns[ch]
                arg_data.append(c.data)
                arg_valid.append(c.valid_mask())
        capacity = self._bridge.build_batch.capacity
        use_mxu = (
            capacity <= MJ.MAX_CAPACITY and probe.capacity <= MJ.MAX_ROWS
        )
        sums = MJ.probe_page_sums(
            self._bridge.lookup_source, kid_by_pos, distinct, n_distinct,
            kcol.data, kv, probe.live_mask(),
            tuple(arg_data), tuple(arg_valid), self._kinds, capacity,
            use_mxu, jax.default_backend() != "tpu", hash_pure,
        )
        self._acc = (
            list(sums)
            if self._acc is None
            else [a + s for a, s in zip(self._acc, sums)]
        )

    def finish(self) -> None:
        from trino_tpu.ops import mxu_join as MJ

        if self._finishing:
            return
        self._finishing = True
        if self._analysis is None:
            self._analyze()
        kid = self._analysis[0]
        build = self._bridge.build_batch
        if self._acc is None:
            # no probe pages arrived: zero accumulators, nothing matches
            n_cols = sum(
                2 if k == "sum" else (1 if k == "count" else 0)
                for k in self._kinds
            )
            z = jnp.zeros(build.capacity, dtype=jnp.int64)
            self._acc = [z] * (n_cols + 1)
        live, outs = MJ.finalize_partials(
            kid, build.live_mask(), tuple(self._acc), self._kinds
        )
        cols = [build.columns[ch] for ch in self._groups]
        for data, valid in outs:
            cols.append(Column(T.BIGINT, data, valid, None))
        self._outputs.append(RelBatch(cols, live))

    def get_output(self) -> Optional[RelBatch]:
        return self._outputs.pop(0) if self._outputs else None

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


@partial(jax.jit, static_argnames=("out_cap", "pkc", "bkc"))
def _expand_pairs(ls, probe: RelBatch, build: RelBatch, keys, valids,
                  lo, counts, out_cap: int, pkc=None, bkc=None):
    """Expansion + pair gather in one device program (JoinProbe +
    LookupJoinPageBuilder fused — join/LookupJoinOperator.java:36).

    When the join keys are plain pass-through columns (pkc/bkc name
    them in the probe/build schemas), the hash-collision verify runs on
    the GATHERED pair columns — the expansion would gather them anyway,
    so the separate per-key verify gathers disappear."""
    on_pairs = pkc is not None
    pi, bi, ok = J.expand_matches(
        ls, keys, valids, lo, counts, out_cap, verify=not on_pairs
    )
    pairs_probe = probe.gather(pi)
    pairs_build = build.gather(bi)
    if on_pairs:
        for pc, bc in zip(pkc, bkc):
            a = pairs_probe.columns[pc]
            b = pairs_build.columns[bc]
            eqd = a.data == b.data
            if getattr(eqd, "ndim", 1) == 2:  # long-decimal limb pairs
                eqd = eqd.all(axis=-1)
            ok = ok & eqd
            if a.valid is not None:
                ok = ok & a.valid
            if b.valid is not None:
                ok = ok & b.valid
    cols = list(pairs_probe.columns) + list(pairs_build.columns)
    return pi, bi, ok, RelBatch(cols, ok)


@jax.jit
def _fanout_le_one(counts):
    """Device flag: no probe row has more than one candidate match."""
    return jnp.all(counts <= 1)


@partial(jax.jit, static_argnames=("pkc", "bkc"))
def _expand_pairs_fanout1(ls, probe: RelBatch, build: RelBatch, keys,
                          valids, lo, counts, pkc=None, bkc=None):
    """Fanout<=1 expansion (every probe row matches at most one build
    row — the PK-side FK join that dominates TPC-H/DS): the pair batch
    IS the probe batch with the matched build row appended. The probe
    columns pass through untouched — no offsets, no repeat machinery,
    and none of the ~16ms/M-element random gathers the general
    expansion pays per probe column. Caller guarantees max(counts) <= 1
    (checked on device alongside the deferred total)."""
    spos = jnp.clip(lo, 0, ls.perm.shape[0] - 1)
    bi = take_clip(ls.perm, spos)
    ok = counts > 0
    pairs_build = build.gather(bi)
    if pkc is not None:
        for pc, bc in zip(pkc, bkc):
            a = probe.columns[pc]
            b = pairs_build.columns[bc]
            eqd = a.data == b.data
            if getattr(eqd, "ndim", 1) == 2:  # long-decimal limb pairs
                eqd = eqd.all(axis=-1)
            ok = ok & eqd
            if a.valid is not None:
                ok = ok & a.valid
            if b.valid is not None:
                ok = ok & b.valid
    else:
        for pk, pv, bk, bv in zip(keys, valids, ls.key_cols, ls.key_valids):
            b = take_clip(bk, jnp.clip(bi, 0, bk.shape[0] - 1))
            bvv = take_clip(bv, jnp.clip(bi, 0, bv.shape[0] - 1))
            eqd = pk == b
            if getattr(eqd, "ndim", 1) == 2:
                eqd = eqd.all(axis=-1)
            ok = ok & eqd & pv & bvv
    live = probe.live_mask() & ok
    pi = jnp.arange(probe.capacity, dtype=jnp.int32)
    cols = list(probe.columns) + list(pairs_build.columns)
    return pi, bi, live, RelBatch(cols, live)


@jax.jit
def _segment_any(counts, pi, ok, probe_capacity):
    """Per-probe-row 'any verified pair' WITHOUT scatter: pi is emitted
    in nondecreasing order by expand_matches, so each probe row's pairs
    are the segment [off-counts, off) — reduce via cumsum+gather."""
    e = ok.shape[0]
    okc = jnp.cumsum(ok.astype(jnp.int32))
    exc = okc - ok.astype(jnp.int32)
    off = jnp.cumsum(counts)
    start = off - counts
    seg = take_clip(okc, jnp.clip(off - 1, 0, max(e - 1, 0))) - take_clip(
        exc, jnp.clip(start, 0, max(e - 1, 0))
    )
    return (counts > 0) & (seg > 0)


@jax.jit
def _left_unmatched(probe: RelBatch, build: RelBatch, matched):
    """Unmatched probe rows with NULL build columns (LEFT outer arm).
    null_column keeps nested build columns structurally valid."""
    from trino_tpu.block import null_column

    nulls = [
        null_column(c.type, probe.capacity, c.dictionary)
        for c in build.columns
    ]
    return RelBatch(
        list(probe.columns) + nulls, probe.live_mask() & ~matched
    )


def _right_unmatched(probe_schema, build: RelBatch, matched_b):
    """Unmatched BUILD rows with NULL probe columns (the RIGHT/FULL
    outer arm — join/LookupOuterOperator.java analogue)."""
    from trino_tpu.block import null_column

    nulls = [
        null_column(t, build.capacity, d) for t, d in probe_schema
    ]
    return RelBatch(
        nulls + list(build.columns), build.live_mask() & ~matched_b
    )


def make_residual_fn(residual: Bound):
    """Plan-time compiled residual evaluator over pair batches."""

    @jax.jit
    def fn(pairs: RelBatch):
        # nested columns ride whole (same contract as
        # make_filter_project_fn) so map/row navigation works in
        # residual conjuncts too
        cols = [
            c if c.type.is_nested else c.data for c in pairs.columns
        ]
        vs = [c.valid for c in pairs.columns]
        d, v = residual.fn(cols, vs)
        return d if v is None else (d & v)

    return fn


class LookupJoinOperator(Operator):
    """Probe side (LookupJoinOperator.java:36). join_type in
    {inner, left, semi, anti}. Output schema for inner/left =
    [probe columns..., build columns...]; for semi/anti = probe columns.

    `residual` (optional Bound over the concatenated pair schema) is
    evaluated on candidate pairs BEFORE match flags are computed, which
    is what makes filtered semi/anti joins (Q21-style `l2.suppkey <>
    l1.suppkey`) correct.
    """

    def __init__(
        self,
        bridge: JoinBridge,
        key_channels: Sequence[int],
        join_type: str,
        probe_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]],
        residual: Optional[Bound] = None,
        residual_fn=None,
    ):
        self._bridge = bridge
        self._keys = list(key_channels)
        self._type = join_type
        self._probe_schema = list(probe_schema)
        self._residual = residual
        self._residual_fn = (
            residual_fn
            if residual_fn is not None
            else (make_residual_fn(residual) if residual is not None else None)
        )
        self._outputs: List[RelBatch] = []
        self._remap_cache: Dict[tuple, jnp.ndarray] = {}
        # grace mode: probe rows hash-partition to disk alongside the
        # spilled build; partitions join pairwise at finish
        self._probe_spill = None
        # FULL outer: build-side matched bitmap accumulated across probe
        # batches; unmatched build rows emit at finish (LookupOuter)
        self._build_matched = None
        # Pipelined expansion (the per-batch `int(total)` host read
        # costs a full ~130ms tunnel round trip on remote-attached
        # TPUs — measured to dominate TPC-H SF10 wall time): batch i's
        # match total starts copying to the host the moment its count
        # pass is dispatched, and is only READ when batch i+1 arrives —
        # by then the copy has overlapped with the next batch's
        # upstream device work, so the expansion still gets its EXACT
        # bucketed capacity (small outputs stay small) without a
        # blocking round trip per batch.
        self._probe_pending: List[dict] = []

    def needs_input(self) -> bool:
        return not self._outputs and not self._finishing

    def add_input(self, probe: RelBatch) -> None:
        if self._bridge.grace is not None:
            if self._probe_spill is None:
                from trino_tpu.exec.spill import GracePartitionSpill

                self._probe_spill = GracePartitionSpill(
                    self._bridge.grace.n, self._keys
                )
            self._probe_spill.add(probe)
            return
        self._probe_one(
            self._bridge.lookup_source,
            self._bridge.build_batch,
            self._bridge.key_dicts,
            probe,
        )

    def _probe_one(self, ls, build, key_dicts, probe: RelBatch) -> None:
        keys = []
        valids = []
        remapped = False
        for i, c in enumerate(self._keys):
            col = probe.columns[c]
            v = col.valid_mask()
            build_dict = key_dicts[i] if key_dicts else None
            if (
                col.dictionary is not None
                and build_dict is not None
                and col.dictionary != build_dict
            ):
                # cross-dictionary string join: remap probe codes onto the
                # build dictionary by VALUE; absent values -> -1 (never
                # matches a build code). TypeOperators' equality contract
                # for the dictionary-encoded representation.
                ck = (col.dictionary.values, build_dict.values)
                remap = self._remap_cache.get(ck)
                if remap is None:
                    remap = jnp.asarray(
                        [build_dict.code(v) for v in col.dictionary.values],
                        dtype=jnp.int32,
                    )
                    self._remap_cache[ck] = remap
                keys.append(
                    take_clip(remap, col.data)
                )
                valids.append(v)
                remapped = True
            elif getattr(col.data, "ndim", 1) == 2:
                # long-decimal key: probe by its two int64 limbs (the
                # build side split identically in _consolidate_build)
                keys.extend([col.data[:, 0], col.data[:, 1]])
                valids.extend([v, v])
            else:
                keys.append(col.data)
                valids.append(v)
        live = probe.live_mask()
        lo, counts, total = J.probe_counts(ls, keys, valids, live)
        fan1 = _fanout_le_one(counts)
        for scalar in (total, fan1):
            try:
                scalar.copy_to_host_async()
            except AttributeError:
                pass
        self._probe_pending.append({
            "ls": ls, "build": build, "probe": probe, "keys": keys,
            "valids": valids, "lo": lo, "counts": counts, "total": total,
            "fan1": fan1, "remapped": remapped,
        })
        # depth-1 pipeline: settle the PREVIOUS batch — its total has
        # been in flight while this batch's upstream ran on device
        while len(self._probe_pending) > 1:
            self._expand_oldest()

    def _expand_oldest(self) -> None:
        rec = self._probe_pending.pop(0)
        ls, build, probe = rec["ls"], rec["build"], rec["probe"]
        # pair-column verify only when every key is a pass-through
        # column (a dictionary remap substitutes codes the pair batch
        # does not carry)
        pkc = bkc = None
        if not rec.get("remapped") and self._bridge.build_key_channels:
            pkc = tuple(self._keys)
            bkc = tuple(self._bridge.build_key_channels)
        total = int(rec["total"])
        dense = total * 4 >= rec["probe"].capacity
        if dense and "fan1" in rec and bool(rec["fan1"]):
            # fanout<=1 (PK-side FK join) AND most probe rows match:
            # pairs = probe batch + one matched build row, probe
            # columns untouched — skips the repeat expansion AND every
            # probe-side gather. Sparse joins keep the exact-capacity
            # expansion below: reusing the 4M-padded probe batch for a
            # 30k-match join would drag the FULL padding through every
            # downstream operator (measured 4x on TPC-H Q3)
            pi, bi, ok, pairs = _expand_pairs_fanout1(
                ls, probe, build, rec["keys"], rec["valids"],
                rec["lo"], rec["counts"], pkc=pkc, bkc=bkc,
            )
            if self._residual_fn is not None:
                ok = ok & self._residual_fn(pairs)
                pairs = RelBatch(pairs.columns, ok)
            matched = ok
        else:
            out_cap = bucket_capacity(max(total, 1))
            pi, bi, ok, pairs = _expand_pairs(
                ls, probe, build, rec["keys"], rec["valids"],
                rec["lo"], rec["counts"], out_cap, pkc=pkc, bkc=bkc,
            )
            if self._residual_fn is not None:
                ok = ok & self._residual_fn(pairs)
                pairs = RelBatch(pairs.columns, ok)
            matched = None
        if self._type == "inner":
            self._outputs.append(pairs)
            return
        if matched is None:
            matched = _segment_any(rec["counts"], pi, ok, probe.capacity)
        if self._type == "semi":
            self._outputs.append(probe.mask(matched))
            return
        if self._type == "anti":
            self._outputs.append(probe.mask(~matched))
            return
        if self._type in ("mark", "mark_exists"):
            # mark join: probe rows pass through with an appended
            # BOOLEAN match column (SemiJoinNode's semiJoinOutput — the
            # device for subqueries in general positions: under OR, in
            # the SELECT list). "mark" carries IN's three-valued
            # semantics on the validity lane: no match is UNKNOWN when
            # the probe key is NULL against a nonempty build, or the
            # build side contains NULL keys; "mark_exists" is two-valued.
            valid = None
            if self._type == "mark":
                build = self._bridge.build_batch
                b_live = build.live_mask()
                nonempty = jnp.any(b_live)
                has_null = jnp.zeros((), dtype=jnp.bool_)
                for ch in self._bridge.build_key_channels:
                    bc = build.columns[ch]
                    if bc.valid is not None:
                        has_null = has_null | jnp.any(b_live & ~bc.valid)
                pv = None
                for vv in rec["valids"]:
                    pv = vv if pv is None else (pv & vv)
                probe_null = (
                    ~pv if pv is not None
                    else jnp.zeros_like(matched)
                )
                unknown = (~matched) & (
                    (probe_null & nonempty) | has_null
                )
                valid = ~unknown
            col = Column(T.BOOLEAN, matched, valid, None)
            self._outputs.append(
                RelBatch(list(probe.columns) + [col], probe.live_mask())
            )
            return
        if self._type == "left":
            self._outputs.append(pairs)
            self._outputs.append(_left_unmatched(probe, build, matched))
            return
        if self._type == "full":
            self._outputs.append(pairs)
            self._outputs.append(_left_unmatched(probe, build, matched))
            self._build_matched = J.build_matched_flags(
                build.capacity, bi, ok, prior=self._build_matched
            )
            return
        raise NotImplementedError(self._type)

    def _resolve_spec(self) -> None:
        """Drain every pending probe batch (finish / partition end)."""
        while self._probe_pending:
            self._expand_oldest()

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        self._resolve_spec()
        if self._bridge.grace is None:
            if self._type == "full":
                build = self._bridge.build_batch
                mb = (
                    self._build_matched
                    if self._build_matched is not None
                    else jnp.zeros(build.capacity, dtype=jnp.bool_)
                )
                self._outputs.append(
                    _right_unmatched(self._probe_schema, build, mb)
                )
            return
        # grace probe (PartitionedConsumption analogue): for each hash
        # partition, rebuild that slice of the build side on device and
        # probe its probe-side pages — partition-wise correctness holds
        # because both sides routed by the same canonical key hash
        grace = self._bridge.grace
        for p in range(grace.n):
            probe_pages = (
                self._probe_spill.partition_pages(p)
                if self._probe_spill is not None
                else []
            )
            if not probe_pages and self._type != "full":
                continue  # before touching the build spill: no probe rows
            build_pages = grace.partition_pages(p)
            parts = tuple(
                [pg.to_batch() for pg in build_pages]
                or [empty_batch(self._bridge.build_schema)]
            )
            ls, merged = _consolidate_build(
                parts, tuple(self._bridge.build_key_channels)
            )
            key_dicts = [
                merged.columns[c].dictionary
                for c in self._bridge.build_key_channels
            ]
            # full outer: matched flags are PER PARTITION (each build row
            # lives in exactly one hash partition, so partition-local
            # flags are complete)
            self._build_matched = None
            for pg in probe_pages:
                self._probe_one(ls, merged, key_dicts, pg.to_batch())
            self._resolve_spec()
            if self._type == "full":
                mb = (
                    self._build_matched
                    if self._build_matched is not None
                    else jnp.zeros(merged.capacity, dtype=jnp.bool_)
                )
                self._outputs.append(
                    _right_unmatched(self._probe_schema, merged, mb)
                )
        if self._probe_spill is not None:
            self._probe_spill.close()
            self._probe_spill = None
        # the build spill is fully consumed too: release its files (the
        # probe operator is the bridge's single consumer)
        grace.close()
        self._bridge.grace = None

    def get_output(self) -> Optional[RelBatch]:
        if self._outputs:
            return self._outputs.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


@partial(jax.jit, static_argnames=("channels",))
def _df_domains(build: RelBatch, channels: tuple):
    """Per-key min/max over the build side's live+valid rows."""
    live = build.live_mask()
    out = []
    for c in channels:
        col = build.columns[c]
        w = live if col.valid is None else (live & col.valid)
        lo_n = minmax_neutral(col.data.dtype, "min")
        hi_n = minmax_neutral(col.data.dtype, "max")
        lo = jnp.min(jnp.where(w, col.data, jnp.asarray(lo_n, col.data.dtype)))
        hi = jnp.max(jnp.where(w, col.data, jnp.asarray(hi_n, col.data.dtype)))
        out.append((lo, hi, jnp.any(w)))
    return out


@jax.jit
def _df_filter(batch: RelBatch, keys, domains):
    """Drop probe rows outside [lo, hi] on every key (NULL keys never
    match an inner/semi join, so they drop too)."""
    keep = batch.live_mask()
    for (c_data, c_valid), (lo, hi, any_rows) in zip(keys, domains):
        ok = (c_data >= lo) & (c_data <= hi) & any_rows
        if c_valid is not None:
            ok = ok & c_valid
        keep = keep & ok
    return batch.mask(keep)


class DynamicFilterOperator(Operator):
    """Probe-side pruning from build-side key domains — the LOCAL form
    of dynamic filtering (DynamicFilterSourceOperator + DynamicFilter
    SPI, SURVEY.md §5.6): the build pipeline has already completed when
    the probe pipeline starts, so the bridge's build batch supplies
    min/max domains directly. The coordinator-distributed form (domains
    shipped to remote scan fragments) rides the same domain computation.
    Applies to inner/semi probes only; dictionary-coded keys are skipped
    unless both sides share the dictionary (code order is only
    meaningful within one dictionary)."""

    def __init__(self, bridge: JoinBridge, key_channels: Sequence[int]):
        self._bridge = bridge
        self._keys = list(key_channels)
        self._domains = None
        self._active_channels: Optional[List[int]] = None
        self._out: Optional[RelBatch] = None

    def _prepare(self, probe: RelBatch) -> None:
        build = self._bridge.build_batch
        if build is None:  # grace mode: no device build to read domains from
            self._active_channels = []
            return
        key_dicts = self._bridge.key_dicts or [None] * len(self._keys)
        active = []
        for i, c in enumerate(self._keys):
            if getattr(probe.columns[c].data, "ndim", 1) == 2:
                continue  # long-decimal keys: no scalar min/max domain
            probe_dict = probe.columns[c].dictionary
            if key_dicts[i] is None and probe_dict is None:
                active.append((i, c))
            elif key_dicts[i] is not None and key_dicts[i] == probe_dict:
                active.append((i, c))
        self._active_channels = active
        if active:
            all_domains = _df_domains(
                build, tuple(self._bridge.build_key_channels)
            )
            self._domains = [all_domains[i] for i, _ in active]

    def needs_input(self) -> bool:
        return self._out is None and not self._finishing

    def add_input(self, batch: RelBatch) -> None:
        if self._active_channels is None:
            self._prepare(batch)
        if not self._active_channels:
            self._out = batch
            return
        keys = tuple(
            (batch.columns[c].data, batch.columns[c].valid)
            for _, c in self._active_channels
        )
        self._out = _df_filter(batch, keys, tuple(self._domains))

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


def dynamic_filter_constraints(
    bridge: JoinBridge,
    key_types,
    key_names,
    max_in_list: int = 64,
) -> tuple:
    """Build-side key domains as ColumnConstraints — the connector
    reuse of dynamic filtering: when the probe is a bare scan, these
    fold into its splits' handles so build-side bounds prune parquet
    row groups (range_predicate) and mask rows (constraint_mask) at the
    source, not just at the DynamicFilterOperator.

    Per key: an IN-list when the build has few distinct values (exact
    multi-range domain), else the [min, max] range. Returns () until
    the build completes (the probe's driver runs after the build
    pipeline, so by first probe page the bridge is populated — but a
    non-blocking peek keeps this safe anywhere)."""
    from trino_tpu.connectors.pushdown import _pushable_type
    from trino_tpu.connectors.spi import ColumnConstraint

    build = bridge.build_batch
    if build is None:
        return ()
    live = np.asarray(jax.device_get(build.live_mask())).astype(bool)
    out = []
    for i, bc in enumerate(bridge.build_key_channels):
        if i >= len(key_names):
            break
        t = key_types[i]
        if t is None or not _pushable_type(t):
            continue
        col = build.columns[bc]
        if getattr(col.data, "ndim", 1) == 2 or col.dictionary is not None:
            continue  # long-decimal limbs / dictionary codes: no raw domain
        data = np.asarray(jax.device_get(col.data))
        w = live
        if col.valid is not None:
            w = w & np.asarray(jax.device_get(col.valid)).astype(bool)
        vals = data[w]
        if vals.size == 0:
            continue  # empty build: the join itself yields nothing
        uniq = np.unique(vals)
        if uniq.size <= max_in_list:
            out.append(ColumnConstraint(
                key_names[i], "in", tuple(v.item() for v in uniq)
            ))
        else:
            out.append(
                ColumnConstraint(key_names[i], "ge", uniq[0].item())
            )
            out.append(
                ColumnConstraint(key_names[i], "le", uniq[-1].item())
            )
    return tuple(out)


# ---------------------------------------------------------------------------
# Cross join (NestedLoopJoinOperator.java analogue)
# ---------------------------------------------------------------------------


@jax.jit
def _consolidate_compact(parts: Tuple[RelBatch, ...]) -> RelBatch:
    return concat_batches(list(parts)).compact()


@partial(jax.jit, static_argnames=("b",))
def _cross_row(probe: RelBatch, build: RelBatch, b: int) -> RelBatch:
    def bcast(c):
        # long-decimal columns broadcast their (2,) limb row
        shape = (
            (probe.capacity, 2)
            if getattr(c.data, "ndim", 1) == 2
            else (probe.capacity,)
        )
        return jnp.broadcast_to(c.data[b], shape)

    bcols = [
        Column(
            c.type,
            bcast(c),
            None
            if c.valid is None
            else jnp.broadcast_to(c.valid[b], (probe.capacity,)),
            c.dictionary,
        )
        for c in build.columns
    ]
    return RelBatch(list(probe.columns) + bcols, probe.live)


class CrossJoinBuildSink(Operator):
    """Collects the (small) build side of a cross join."""

    def __init__(self, bridge: JoinBridge,
                 input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]]):
        self._bridge = bridge
        self._schema = list(input_schema)
        self._inputs: List[RelBatch] = []

    def add_input(self, batch: RelBatch) -> None:
        self._inputs.append(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        merged = _consolidate_compact(tuple(self._inputs or [empty_batch(self._schema)]))
        self._bridge.build_batch = merged
        self._inputs = []

    def is_finished(self) -> bool:
        return self._finishing


class CrossJoinOperator(Operator):
    """Probe x build cartesian product; build side expected small
    (scalar-subquery bridges are 1 row)."""

    def __init__(self, bridge: JoinBridge):
        self._bridge = bridge
        self._outputs: List[RelBatch] = []

    def needs_input(self) -> bool:
        return not self._outputs and not self._finishing

    def add_input(self, probe: RelBatch) -> None:
        build = self._bridge.build_batch
        n_build = build.row_count()
        for b in range(n_build):
            self._outputs.append(_cross_row(probe, build, b))

    def get_output(self) -> Optional[RelBatch]:
        if self._outputs:
            return self._outputs.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


# ---------------------------------------------------------------------------
# Sink
# ---------------------------------------------------------------------------


class ScaledWriterSink:
    """Writer scale-out driven by OBSERVED output volume — the
    SCALED_WRITER_* partitioning + ScaledWriterScheduler analogue
    (main/sql/planner/SystemPartitioningHandle.java:53-54,
    main/execution/scheduler/ScaledWriterScheduler.java): start with
    one connector sink, add another whenever the written volume
    exceeds scale_rows x current writer count (up to max_writers), and
    round-robin batches across the active sinks. Volume is measured in
    batch capacities — static shapes, so no device sync on the write
    path."""

    COUNTERS = {"max_writers": 0, "scale_ups": 0}

    def __init__(self, make_sink, max_writers: int,
                 scale_rows: int = 1 << 21):
        self._make = make_sink
        self._sinks = [make_sink()]
        self._max = max(1, max_writers)
        self._scale_rows = scale_rows
        self._rows = 0
        self._rr = 0

    def append(self, batch) -> None:
        self._rows += batch.capacity
        if (
            self._rows > self._scale_rows * len(self._sinks)
            and len(self._sinks) < self._max
        ):
            self._sinks.append(self._make())
            ScaledWriterSink.COUNTERS["scale_ups"] += 1
        self._rr += 1
        self._sinks[self._rr % len(self._sinks)].append(batch)

    def finish(self) -> int:
        total = 0
        for s in self._sinks:
            total += s.finish()
        ScaledWriterSink.COUNTERS["max_writers"] = max(
            ScaledWriterSink.COUNTERS["max_writers"], len(self._sinks)
        )
        return total


class TableWriterOperator(Operator):
    """Terminal sink writing batches into a connector page sink
    (TableWriterOperator + TableFinishOperator collapsed — the commit
    handshake is the sink's finish(), whose row count lands in
    `rows_written`; SURVEY.md §2.6)."""

    def __init__(self, sink):
        self._sink = sink
        self.rows_written = 0

    def add_input(self, batch: RelBatch) -> None:
        self._sink.append(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        self.rows_written = self._sink.finish()

    def is_finished(self) -> bool:
        return self._finishing


class BufferSink(Operator):
    """Collects batches for a later pipeline (the LocalExchange handoff,
    main/operator/exchange/LocalExchange.java:67 — single-buffer form)."""

    def __init__(self):
        self.batches: List[RelBatch] = []

    def add_input(self, batch: RelBatch) -> None:
        self.batches.append(batch)

    def is_finished(self) -> bool:
        return self._finishing


class BufferSource(Operator):
    """Replays one or more BufferSinks' batches (consumer side of the
    handoff). The producing pipelines must run first."""

    def __init__(self, sinks: Sequence[BufferSink]):
        self._sinks = list(sinks)
        self._batches: Optional[List[RelBatch]] = None
        self._i = 0

    def needs_input(self) -> bool:
        return False

    def _all(self) -> List[RelBatch]:
        # producers are guaranteed finished before this pipeline runs
        if self._batches is None:
            self._batches = [b for s in self._sinks for b in s.batches]
        return self._batches

    def get_output(self) -> Optional[RelBatch]:
        batches = self._all()
        if self._i < len(batches):
            b = batches[self._i]
            self._i += 1
            return b
        return None

    def is_finished(self) -> bool:
        return self._i >= len(self._all())


class EnforceSingleRowOperator(Operator):
    """Scalar-subquery cardinality guard (the reference's
    EnforceSingleRowOperator): exactly one input row passes through;
    ZERO rows produce one all-NULL row (the SQL scalar-subquery empty
    result); more than one raises. The row-count sync happens once at
    finish — scalar subqueries are tiny by construction."""

    def __init__(self, input_schema: Sequence[Tuple[T.DataType, Optional[Dictionary]]]):
        self._schema = list(input_schema)
        self._inputs: List[RelBatch] = []
        self._out: Optional[RelBatch] = None

    def add_input(self, batch: RelBatch) -> None:
        self._inputs.append(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        self._finishing = True
        total = sum(b.row_count() for b in self._inputs)
        if total > 1:
            raise RuntimeError("Scalar sub-query has returned multiple rows")
        if total == 1:
            merged = concat_batches(self._inputs) if len(self._inputs) > 1 \
                else self._inputs[0]
            self._out = merged.compact()
            self._inputs = []
            return
        # zero rows: one all-NULL row
        cols = [
            Column(
                t,
                jnp.zeros(16, dtype=t.dtype),
                jnp.zeros(16, dtype=jnp.bool_),
                d,
            )
            for t, d in self._schema
        ]
        live = jnp.zeros(16, dtype=jnp.bool_).at[0].set(True)
        self._out = RelBatch(cols, live)

    def get_output(self) -> Optional[RelBatch]:
        out, self._out = self._out, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._out is None


class CollectorSink(Operator):
    """Terminal sink gathering result batches (the coordinator-protocol
    Query.getNextResult analogue for the in-process runner)."""

    def __init__(self):
        self.batches: List[RelBatch] = []

    def add_input(self, batch: RelBatch) -> None:
        self.batches.append(batch)

    def is_finished(self) -> bool:
        return self._finishing

    def rows(self) -> List[list]:
        return self.rows_with(())[0]

    def rows_with(self, extra: tuple):
        """Fetch all result batches PLUS auxiliary device values (e.g.
        deferred assertion flags) in ONE device->host round trip.
        device_get puts every leaf's transfer in flight before waiting,
        so the whole tree costs ~one link round trip — measured on the
        tunneled device: 21 leaves via device_get = 1 RTT, while a
        device-side pack-into-one-buffer program costs 2 (dispatch +
        fetch). Don't 'optimize' this into a packing kernel."""
        host_batches, host_extra = jax.device_get((self.batches, list(extra)))
        out = []
        for b in host_batches:
            out.extend(b.to_pylists())
        return out, host_extra
