"""Driver: the host loop moving device batches through an operator
pipeline.

Analogue of main/operator/Driver.java:65 (processInternal:371 — for each
adjacent operator pair, page = current.getOutput(); next.addInput(page);
finish cascade :417). TPU-first delta: the loop never touches data; it
only launches jitted device programs and handles the (rare) host-sync
points (join fan-out sizing, group-table growth). Trino's 1s-quantum
cooperative scheduling is unnecessary single-pipeline; the multi-driver
form arrives with the task runtime layer.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

from trino_tpu.exec.operators import Operator


@dataclasses.dataclass
class Pipeline:
    """An ordered operator chain ending in a sink. Pipelines are executed
    in dependency order (build pipelines before their probe pipelines —
    the ordering Trino derives from LocalExecutionPlanner's pipeline
    DAG)."""

    operators: List[Operator]


class TaskAbortedError(RuntimeError):
    """Raised by Driver.run when the owning task was aborted or failed
    externally (kill, low-memory killer) — cooperative cancellation at
    batch boundaries so a doomed task stops burning device cycles."""


class Driver:
    """Runs one pipeline to completion (Driver.processInternal analogue)."""

    def __init__(self, pipeline: Pipeline, should_stop=None, observer=None):
        self.ops = pipeline.operators
        self._finish_signalled = [False] * len(self.ops)
        self._should_stop = should_stop
        # observer(op_name, moved) fires after every batch move (moved=
        # True) and on blocked waits (moved=False) — the stuck-task
        # watchdog's per-batch heartbeat (TaskExecution._on_batch):
        # a task whose heartbeat goes stale past stuck_task_interrupt_s
        # is interrupted through should_stop
        self._observer = observer

    def run(self) -> None:
        ops = self.ops
        n = len(ops)
        while not ops[-1].is_finished():
            if self._should_stop is not None and self._should_stop():
                raise TaskAbortedError("task aborted")
            progressed = False
            for i in range(n - 1):
                cur, nxt = ops[i], ops[i + 1]
                if nxt.is_finished():
                    continue
                # move as many batches as the pair allows (Driver.java:389)
                while nxt.needs_input():
                    # cancellation is checked per batch, not just per
                    # sweep: a killed task (low-memory killer, drain
                    # re-placement, speculation loser) must stop inside
                    # a long batch train, not after it
                    if self._should_stop is not None and self._should_stop():
                        raise TaskAbortedError("task aborted")
                    out = cur.get_output()
                    if out is None:
                        break
                    nxt.add_input(out)
                    progressed = True
                    if self._observer is not None:
                        self._observer(type(cur).__name__, True)
                # finish cascade (Driver.java:417)
                if cur.is_finished() and not self._finish_signalled[i + 1]:
                    nxt.finish()
                    self._finish_signalled[i + 1] = True
                    progressed = True
            if not progressed and not ops[-1].is_finished():
                blocked = [o for o in ops if o.is_blocked()]
                if blocked:
                    # blocked on remote pages / buffer space: yield the
                    # thread (Driver.java:446 union of blocked futures,
                    # collapsed to a poll-and-sleep). This is NOT "stuck"
                    # — starvation on input is the UPSTREAM task's
                    # problem (its own watchdog names the real culprit),
                    # so the heartbeat stays fresh here
                    if self._observer is not None:
                        self._observer(type(blocked[0]).__name__, False)
                    import time

                    time.sleep(0.001)
                    continue
                raise RuntimeError(
                    "pipeline stalled: "
                    + ", ".join(
                        f"{type(o).__name__}(fin={o.is_finished()})" for o in ops
                    )
                )


def run_pipelines(pipelines: Sequence[Pipeline]) -> None:
    for p in pipelines:
        Driver(p).run()
