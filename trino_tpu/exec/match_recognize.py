"""MATCH_RECOGNIZE execution.

Analogue of the reference's row pattern recognition
(main/operator/PatternRecognitionOperator + operator/window/pattern/ —
Matcher.java's NFA over IrRowPattern). TPU-first split of the work:

- The per-variable DEFINE predicates are ordinary vectorized
  expressions: PREV/NEXT navigation becomes shifted column copies, so
  ALL condition evaluation runs as ONE jitted device program over the
  consolidated input — no per-row predicate interpretation (this is
  where the reference spends its per-row `Computation` evaluations).
- What remains inherently sequential — the pattern automaton walking
  row classifications — runs on host over the precomputed boolean
  masks, one numpy bitmap per variable. Matching cost is independent
  of column count/width.

Supported subset (documented in sql/analyzer.py): concatenation,
alternation, *, +, ?, {n,m}; ONE ROW PER MATCH; AFTER MATCH SKIP PAST
LAST ROW / TO NEXT ROW; measures FIRST/LAST(var.col), var.col,
MATCH_NUMBER(), CLASSIFIER(). Greedy quantifiers with backtracking,
leftmost match preference — the standard's default semantics.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity

_STEP_CAP = 10_000_000  # backtracking budget (pathological patterns)


class _Budget:
    __slots__ = ("left",)

    def __init__(self, n: int):
        self.left = n


def _match_here(node, masks: Dict[str, np.ndarray], pos: int, end: int,
                tags: List[str], budget: _Budget):
    """Generator of match end positions for `node` starting at `pos`
    (exclusive end bound `end`), longest-first (greedy). Appends
    variable tags for consumed rows to `tags`; callers truncate on
    backtrack via the returned checkpoint discipline."""
    budget.left -= 1
    if budget.left <= 0:
        raise RuntimeError("MATCH_RECOGNIZE backtracking budget exceeded")
    kind = node[0]
    if kind == "var":
        name = node[1]
        if pos < end and masks[name][pos]:
            tags.append(name)
            yield pos + 1
            tags.pop()
        return
    if kind == "seq":
        parts = node[1]

        def seq_from(i: int, p: int):
            if i == len(parts):
                yield p
                return
            for q in _match_here(parts[i], masks, p, end, tags, budget):
                yield from seq_from(i + 1, q)

        yield from seq_from(0, pos)
        return
    if kind == "alt":
        for part in node[1]:
            yield from _match_here(part, masks, pos, end, tags, budget)
        return
    if kind == "opt":
        yield from _match_here(node[1], masks, pos, end, tags, budget)
        yield pos  # greedy: try consuming first, then empty
        return
    if kind in ("star", "plus"):
        inner = node[1]

        def repeat_from(p: int, count: int):
            # greedy: extend first (longest), then accept
            for q in _match_here(inner, masks, p, end, tags, budget):
                if q > p:  # forbid zero-width loop
                    yield from repeat_from(q, count + 1)
            if kind == "star" or count >= 1:
                yield p

        yield from repeat_from(pos, 0)
        return
    if kind == "rep":
        inner, lo, hi = node[1], node[2], node[3]

        def rep_from(p: int, count: int):
            if hi is not None and count == hi:
                yield p
                return
            for q in _match_here(inner, masks, p, end, tags, budget):
                if q > p:
                    yield from rep_from(q, count + 1)
            if count >= lo:
                yield p

        yield from rep_from(pos, 0)
        return
    raise ValueError(f"unknown pattern node {kind!r}")


def find_matches(
    pattern,
    masks: Dict[str, np.ndarray],
    start: int,
    end: int,
    after_match: str,
) -> List[Tuple[int, int, List[str]]]:
    """All matches in [start, end): list of (lo, hi, tags). Greedy
    leftmost-longest per start position; AFTER MATCH SKIP controls the
    resume point."""
    out = []
    pos = start
    while pos < end:
        budget = _Budget(_STEP_CAP)
        tags: List[str] = []
        got = None
        for endpos in _match_here(pattern, masks, pos, end, tags, budget):
            # greedy-first generator order: the first yield IS the match.
            # An empty match (endpos == pos) still produces an output row
            # (SQL standard ONE ROW PER MATCH; NULL measures, no tags).
            got = (pos, endpos, list(tags[: endpos - pos]))
            break
        if got is None:
            pos += 1
            continue
        out.append(got)
        if after_match == "next_row":
            pos = got[0] + 1
        else:  # past_last; an empty match must still advance
            pos = max(got[1], got[0] + 1)
    return out


class MatchRecognizeOperator:
    """Consolidate -> one device predicate program -> host automaton ->
    one output batch."""

    def __init__(self, spec, input_schema, define_fns):
        """spec: plan.MatchRecognizeNode; define_fns: [(var, bound_fn)]
        where bound_fn(extended RelBatch) -> (bool data, valid)."""
        import dataclasses as _dc

        spec = _dc.replace(spec, pattern=_normalize_pattern(spec.pattern))
        self._spec = spec
        self._schema = input_schema
        self._define_fns = define_fns
        self._inputs: List[RelBatch] = []
        self._out: Optional[RelBatch] = None
        self._finished = False

    def needs_input(self) -> bool:
        return not self._finished

    def add_input(self, batch: RelBatch) -> None:
        self._inputs.append(batch)

    def is_blocked(self) -> bool:
        return False

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self._out = self._run()

    def is_finished(self) -> bool:
        return self._finished and self._out is None

    def get_output(self) -> Optional[RelBatch]:
        if self._out is None:
            return None
        out, self._out = self._out, None
        return out

    # -- the work --
    def _consolidate(self) -> Tuple[List[np.ndarray], List[Optional[np.ndarray]], int]:
        cols: List[List[np.ndarray]] = [[] for _ in self._schema]
        valids: List[List[Optional[np.ndarray]]] = [[] for _ in self._schema]
        lengths: List[int] = []
        total = 0
        for b in self._inputs:
            live = np.asarray(jax.device_get(b.live_mask()))
            k = int(live.sum())
            total += k
            lengths.append(k)
            for i, c in enumerate(b.columns):
                cols[i].append(np.asarray(jax.device_get(c.data))[live])
                valids[i].append(
                    np.asarray(jax.device_get(c.valid))[live]
                    if c.valid is not None
                    else None
                )
        data = [
            np.concatenate(c) if c else np.zeros(0, dtype=t.dtype)
            for c, (t, _) in zip(cols, self._schema)
        ]
        merged_valids: List[Optional[np.ndarray]] = []
        for vlist in valids:
            if any(v is not None for v in vlist):
                merged_valids.append(np.concatenate([
                    v if v is not None else np.ones(k, dtype=bool)
                    for v, k in zip(vlist, lengths)
                ]))
            else:
                merged_valids.append(None)
        return data, merged_valids, total

    def _run(self) -> RelBatch:
        spec = self._spec
        data, valids, n = self._consolidate()
        # order within partitions (host lexsort; keys reversed: last is
        # primary)
        sort_keys: List[np.ndarray] = []
        for k in reversed(spec.order_keys):
            arr = data[k.channel]
            sort_keys.append(-arr if k.descending else arr)
        for ch in reversed(spec.partition_channels):
            sort_keys.append(data[ch])
        order = (
            np.lexsort(sort_keys) if sort_keys else np.arange(n)
        )
        data = [d[order] for d in data]
        valids = [v[order] if v is not None else None for v in valids]
        # partition boundaries
        if spec.partition_channels:
            keys = np.stack(
                [data[ch] for ch in spec.partition_channels], axis=1
            )
            if n:
                change = np.any(keys[1:] != keys[:-1], axis=1)
                bounds = [0] + (np.nonzero(change)[0] + 1).tolist() + [n]
            else:
                bounds = [0, 0]
        else:
            bounds = [0, n]
        # shifted copies (partition-aware: rows shifted across a
        # partition edge are NULL -> predicate false via valid mask)
        ext_data = list(data)
        ext_valids = list(valids)
        part_id = np.zeros(n, dtype=np.int64)
        for i in range(len(bounds) - 1):
            part_id[bounds[i]:bounds[i + 1]] = i
        for ch, off in spec.shifts:
            shifted = np.roll(data[ch], off)
            v = valids[ch]
            sv = (
                np.roll(v, off)
                if v is not None
                else np.ones(n, dtype=bool)
            )
            same_part = np.roll(part_id, off) == part_id
            if n:
                if off > 0:
                    same_part[:off] = False
                elif off < 0:
                    same_part[off:] = False
            sv = sv & same_part
            ext_data.append(shifted)
            ext_valids.append(sv)
        # one device program evaluates every DEFINE over the extension
        ext_types = [t for t, _ in self._schema] + [
            self._schema[ch][0] for ch, _ in spec.shifts
        ]
        ext_dicts = [d for _, d in self._schema] + [
            self._schema[ch][1] for ch, _ in spec.shifts
        ]
        cap = bucket_capacity(max(n, 1))
        cols = []
        for t, d, arr, v in zip(ext_types, ext_dicts, ext_data, ext_valids):
            pad = np.zeros(cap, dtype=t.dtype)
            pad[:n] = arr
            pv = None
            if v is not None:
                pvm = np.zeros(cap, dtype=bool)
                pvm[:n] = v
                pv = jnp.asarray(pvm)
            cols.append(Column(t, jnp.asarray(pad), pv, d))
        live = np.zeros(cap, dtype=bool)
        live[:n] = True
        ext_batch = RelBatch(cols, jnp.asarray(live))
        # nested columns ride whole (make_filter_project_fn contract)
        ext_cols = [
            c if c.type.is_nested else c.data for c in ext_batch.columns
        ]
        ext_vs = [c.valid for c in ext_batch.columns]
        masks: Dict[str, np.ndarray] = {}
        for var, fn in self._define_fns:
            mdata, mvalid = fn(ext_cols, ext_vs)
            m = np.asarray(jax.device_get(mdata))[:n].astype(bool)
            if mvalid is not None:
                m &= np.asarray(jax.device_get(mvalid))[:n]
            masks[var] = m
        # pattern vars with no DEFINE match every row (the standard's
        # undefined-variable TRUE)
        for var in _pattern_vars(spec.pattern):
            if var not in masks:
                masks[var] = np.ones(n, dtype=bool)
        # the automaton
        match_rows: List[list] = []
        classifier_dict_values: List[str] = sorted(_pattern_vars(spec.pattern))
        cl_dict = Dictionary(classifier_dict_values)
        for b in range(len(bounds) - 1):
            lo, hi = bounds[b], bounds[b + 1]
            match_no = 0  # MATCH_NUMBER() numbers within the partition
            for mlo, mhi, tags in find_matches(
                spec.pattern, masks, lo, hi, spec.after_match
            ):
                match_no += 1
                row = []
                for ch in spec.partition_channels:
                    row.append((data[ch][mlo],
                                valids[ch][mlo] if valids[ch] is not None
                                else True))
                for m in spec.measures:
                    row.append(self._measure(
                        m, data, valids, mlo, mhi, tags, match_no, cl_dict
                    ))
                match_rows.append(row)
        return self._build_output(match_rows, cl_dict)

    def _measure(self, m, data, valids, mlo, mhi, tags, match_no, cl_dict):
        if m.kind == "match_number":
            return (match_no, True)
        if m.kind == "classifier":
            if not tags:  # empty match: CLASSIFIER() is NULL
                return (0, False)
            return (cl_dict.code(tags[-1]), True)
        # first/last over rows tagged var (or the whole match)
        if m.var is None:
            positions = range(mlo, mhi)
        else:
            positions = [
                mlo + i for i, t in enumerate(tags) if t == m.var
            ]
        if not positions:
            return (0, False)  # var matched no rows -> NULL
        pos = positions[0] if m.kind == "first" else positions[-1]
        v = valids[m.channel]
        return (
            data[m.channel][pos],
            bool(v[pos]) if v is not None else True,
        )

    def _build_output(self, match_rows, cl_dict) -> RelBatch:
        spec = self._spec
        n = len(match_rows)
        cap = bucket_capacity(max(n, 1))
        out_cols = []
        col_dicts = []
        for ch in spec.partition_channels:
            col_dicts.append(self._schema[ch][1])
        for m in spec.measures:
            if m.kind == "classifier":
                col_dicts.append(cl_dict)
            elif m.channel is not None:
                col_dicts.append(self._schema[m.channel][1])
            else:
                col_dicts.append(None)
        for i, f in enumerate(spec.fields):
            arr = np.zeros(cap, dtype=f.type.dtype)
            valid = np.zeros(cap, dtype=bool)
            any_null = False
            for r, row in enumerate(match_rows):
                val, ok = row[i]
                arr[r] = val
                valid[r] = ok
                any_null |= not ok
            out_cols.append(
                Column(
                    f.type,
                    jnp.asarray(arr),
                    jnp.asarray(valid) if any_null else None,
                    col_dicts[i],
                )
            )
        live = np.zeros(cap, dtype=bool)
        live[:n] = True
        return RelBatch(out_cols, jnp.asarray(live))


def _pattern_vars(node) -> set:
    kind = node[0]
    if kind == "var":
        return {node[1].lower()}
    if kind in ("seq", "alt"):
        out = set()
        for p in node[1]:
            out |= _pattern_vars(p)
        return out
    return _pattern_vars(node[1])


def _normalize_pattern(node):
    """Lowercase variable names so mask lookups match the analyzer's
    lowercased DEFINE keys (quoted mixed-case variables included)."""
    kind = node[0]
    if kind == "var":
        return ("var", node[1].lower())
    if kind in ("seq", "alt"):
        return (kind, [_normalize_pattern(p) for p in node[1]])
    if kind == "rep":
        return ("rep", _normalize_pattern(node[1]), node[2], node[3])
    return (kind, _normalize_pattern(node[1]))
