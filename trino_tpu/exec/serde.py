"""Page wire format: host-side serialized batches for remote exchange.

Analogue of Trino's serialized-page format (main/execution/buffer/
PagesSerdeUtil.java:53 — length-prefixed header with positionCount +
codec markers, per-block encodings; PageSerializer.java:18 adds LZ4;
SURVEY.md §2.8). TPU-first delta: the wire unit is a host ``Page`` —
compacted numpy SoA columns — because pages cross process/host
boundaries only after leaving the device. Compression is zlib (the
stdlib stand-in for airlift LZ4; the native C++ serde plugs in behind
the same two functions).

Framing:  [u8 codec] [u32 raw_len] [body]
  codec: 0 = raw body, 1 = zlib-compressed body.
The body is a SELF-DESCRIBING binary layout (see _encode_body) — typed
column descriptors + raw numpy buffers. No object deserializer ever
touches wire bytes: pages arrive over worker HTTP ports, and a pickle
body there would be remote code execution for anyone who can reach the
port (the reference's wire is likewise a typed binary layout with
LZ4+AES, PagesSerdeUtil.java:53 / PagesSerdeFactory.java:24-44).
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity

_HEADER = struct.Struct("<BI")


@dataclasses.dataclass
class HostNested:
    """Host-side compacted NESTED column (ARRAY/MAP/ROW): per-row
    physical array (lengths / entry counts / presence), validity, and
    child columns — the ArrayBlockEncoding/MapBlock/RowBlock analogue.
    Children are HostNested too (leaves have no children)."""

    type: T.DataType
    data: np.ndarray
    valid: Optional[np.ndarray]
    dictionary: Optional[Tuple[str, ...]]
    children: List["HostNested"]

    def nbytes(self) -> int:
        n = self.data.nbytes + (self.valid.nbytes if self.valid is not None else 0)
        return n + sum(c.nbytes() for c in self.children)

    def to_pylist(self) -> list:
        """Decode to python values (lists / dicts / tuples / scalars) —
        the host-side result path, no device round trip."""
        from trino_tpu.block import decode_values

        t = self.type
        n = len(self.data)
        valid = self.valid if self.valid is not None else np.ones(n, bool)
        if t.kind in (T.TypeKind.ARRAY, T.TypeKind.MAP):
            lengths = self.data
            offs = np.concatenate([[0], np.cumsum(lengths)])
            if t.kind == T.TypeKind.ARRAY:
                flat = self.children[0].to_pylist()
                return [
                    list(flat[offs[i]:offs[i + 1]]) if valid[i] else None
                    for i in range(n)
                ]
            ks = self.children[0].to_pylist()
            vs = self.children[1].to_pylist()
            return [
                dict(zip(ks[offs[i]:offs[i + 1]], vs[offs[i]:offs[i + 1]]))
                if valid[i] else None
                for i in range(n)
            ]
        if t.kind == T.TypeKind.ROW:
            kid_vals = [c.to_pylist() for c in self.children]
            return [
                tuple(kv[i] for kv in kid_vals) if valid[i] else None
                for i in range(n)
            ]
        return decode_values(t, self.data, valid, self.dictionary)


def _slice_ranges(starts: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Flat positions of the concatenated [starts[i], starts[i]+len[i])
    slices — the vectorized gather list for nested compaction."""
    total = int(lengths.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    out_off = np.concatenate([[0], np.cumsum(lengths)[:-1]])
    return np.repeat(starts.astype(np.int64), lengths) + (
        np.arange(total, dtype=np.int64) - np.repeat(out_off, lengths)
    )


def slice_host_nested(hn: HostNested, idx: np.ndarray) -> HostNested:
    """Row selection over an already-compacted HostNested (exchange
    partitioning): keeps the selected rows and exactly their element
    slices, recursively."""
    data = hn.data[idx]
    valid = hn.valid[idx] if hn.valid is not None else None
    t = hn.type
    if t.kind in (T.TypeKind.ARRAY, T.TypeKind.MAP):
        starts = (np.cumsum(hn.data) - hn.data).astype(np.int64)
        flat_idx = _slice_ranges(starts[idx], data.astype(np.int64))
        kids = [slice_host_nested(c, flat_idx) for c in hn.children]
        return HostNested(t, data, valid, hn.dictionary, kids)
    if t.kind == T.TypeKind.ROW:
        kids = [slice_host_nested(c, idx) for c in hn.children]
        return HostNested(t, data, valid, hn.dictionary, kids)
    return HostNested(t, data, valid, hn.dictionary, [])


def _compact_nested(col, idx: np.ndarray) -> HostNested:
    """Device-host nested column -> HostNested keeping rows `idx`
    (recursively flattening only those rows' element slices)."""
    from trino_tpu.block import ArrayColumn, MapColumn, RowColumn

    data = np.asarray(col.data)[idx]
    valid = np.asarray(col.valid)[idx] if col.valid is not None else None
    if isinstance(col, (ArrayColumn, MapColumn)):
        lengths = data.astype(np.int64)
        if valid is not None:
            lengths = np.where(valid, lengths, 0)
        starts = np.asarray(col.starts)[idx]
        flat_idx = _slice_ranges(starts, lengths)
        if isinstance(col, ArrayColumn):
            kids = [_compact_nested(col.flat, flat_idx)]
        else:
            kids = [
                _compact_nested(col.flat_keys, flat_idx),
                _compact_nested(col.flat_values, flat_idx),
            ]
        return HostNested(col.type, lengths.astype(np.int32), valid, None, kids)
    if isinstance(col, RowColumn):
        kids = [_compact_nested(c, idx) for c in col.children]
        return HostNested(col.type, data, valid, None, kids)
    # leaf
    dvals = col.dictionary.values if col.dictionary is not None else None
    return HostNested(col.type, data, valid, dvals, [])


def _nested_to_device(hn: HostNested, capacity: int):
    """HostNested -> device column (padded to `capacity`)."""
    import jax.numpy as jnp

    from trino_tpu.block import ArrayColumn, MapColumn, RowColumn

    t = hn.type
    if t.kind in (T.TypeKind.ARRAY, T.TypeKind.MAP):
        n = len(hn.data)
        lengths = np.zeros(capacity, dtype=np.int32)
        lengths[:n] = hn.data
        starts = np.zeros(capacity, dtype=np.int32)
        cum = np.cumsum(hn.data) - hn.data
        starts[:n] = cum
        valid = None
        if hn.valid is not None:
            v = np.zeros(capacity, dtype=bool)
            v[:n] = hn.valid
            valid = jnp.asarray(v)
        total = int(hn.data.sum())
        child_cap = max(bucket_capacity(total), 16)
        if t.kind == T.TypeKind.ARRAY:
            return ArrayColumn(
                t, jnp.asarray(lengths), valid, None, jnp.asarray(starts),
                _nested_to_device(hn.children[0], child_cap),
            )
        return MapColumn(
            t, jnp.asarray(lengths), valid, None, jnp.asarray(starts),
            _nested_to_device(hn.children[0], child_cap),
            _nested_to_device(hn.children[1], child_cap),
        )
    if t.kind == T.TypeKind.ROW:
        n = len(hn.data)
        presence = np.zeros(capacity, dtype=np.int8)
        presence[:n] = hn.data
        valid = None
        if hn.valid is not None:
            v = np.zeros(capacity, dtype=bool)
            v[:n] = hn.valid
            valid = jnp.asarray(v)
        kids = [_nested_to_device(c, capacity) for c in hn.children]
        return RowColumn(t, jnp.asarray(presence), valid, None, kids)
    d = Dictionary(hn.dictionary) if hn.dictionary is not None else None
    return Column.from_numpy(t, hn.data, hn.valid, d, capacity=capacity)
COMPRESS_MIN_BYTES = 1 << 13  # below this, compression costs more than it saves


@dataclasses.dataclass
class Page:
    """Host-side compacted batch: the unit of exchange between tasks.

    `columns[i]` has exactly `row_count` entries (no capacity padding —
    dead rows never cross the wire, like Page.compact before serialize).
    """

    types: List[T.DataType]
    columns: List[np.ndarray]
    valids: List[Optional[np.ndarray]]
    dictionaries: List[Optional[Tuple[str, ...]]]
    row_count: int

    @property
    def width(self) -> int:
        return len(self.columns)

    def size_bytes(self) -> int:
        n = 0
        for c in self.columns:
            n += c.nbytes() if isinstance(c, HostNested) else c.nbytes
        for v in self.valids:
            if v is not None:
                n += v.nbytes
        return n

    @staticmethod
    def from_batch(batch: RelBatch) -> "Page":
        """Device batch -> compacted host page (one device->host copy;
        live-row extraction via the native mask_gather sweep for flat
        columns; nested columns — ARRAY/MAP/ROW — compact recursively
        into HostNested trees, flattening only the live rows' slices)."""
        import jax

        from trino_tpu import native
        from trino_tpu.block import ArrayColumn, MapColumn, RowColumn

        host = jax.device_get(batch)
        live = (
            np.asarray(host.live).astype(bool)
            if host.live is not None
            else np.ones(batch.capacity, dtype=bool)
        )
        nested = [
            isinstance(c, (ArrayColumn, MapColumn, RowColumn))
            for c in host.columns
        ]
        live_idx = np.nonzero(live)[0] if any(nested) else None
        flat: List[np.ndarray] = []
        valid_idx: List[Optional[int]] = []
        for c, nest in zip(host.columns, nested):
            if nest:
                continue
            flat.append(np.asarray(c.data))
            if c.valid is not None:
                valid_idx.append(len(flat))
                flat.append(np.asarray(c.valid))
            else:
                valid_idx.append(None)
        compacted = native.mask_compact(flat, live)
        cols, valids, dicts, typs = [], [], [], []
        i = 0
        vi_iter = iter(valid_idx)
        for c, nest in zip(host.columns, nested):
            if nest:
                cols.append(_compact_nested(c, live_idx))
                valids.append(None)  # validity lives inside the HostNested
                dicts.append(None)
                typs.append(c.type)
                continue
            vi = next(vi_iter)
            cols.append(compacted[i])
            i += 1
            if vi is not None:
                valids.append(compacted[i])
                i += 1
            else:
                valids.append(None)
            dicts.append(c.dictionary.values if c.dictionary is not None else None)
            typs.append(c.type)
        return Page(typs, cols, valids, dicts, int(live.sum()))

    def to_batch(self, capacity: Optional[int] = None) -> RelBatch:
        """Host page -> device batch (padded back to bucketed capacity)."""
        import jax.numpy as jnp

        cap = capacity if capacity is not None else bucket_capacity(self.row_count)
        out = []
        for t, data, valid, dvals in zip(
            self.types, self.columns, self.valids, self.dictionaries
        ):
            if isinstance(data, HostNested):
                out.append(_nested_to_device(data, cap))
                continue
            d = Dictionary(dvals) if dvals is not None else None
            # Dictionary values are sorted + deduped on construction; wire
            # pages are encoded against the exact tuple, so re-encode codes
            # if sorting changed positions (it never does for tables whose
            # dictionaries were built by Dictionary itself).
            if d is not None and d.values != tuple(dvals):
                remap = np.asarray([d.code(v) for v in dvals], dtype=np.int32)
                data = remap[data]
            out.append(Column.from_numpy(t, data, valid, d, capacity=cap))
        live = None
        if self.row_count != cap:
            lv = np.zeros(cap, dtype=bool)
            lv[: self.row_count] = True
            live = jnp.asarray(lv)
        return RelBatch(out, live)


# --- self-describing binary page body (no pickle: bytes received from a
# worker's HTTP port must never reach an object deserializer — the
# reference's page wire is likewise a typed binary layout,
# PagesSerdeUtil.java:53; nested encodings per ArrayBlockEncoding /
# MapBlock / RowBlock). Layout, little-endian:
#   magic u32 'TPG2' | row_count u32 | width u16
#   per column (recursive; nested children are columns at their own
#   row counts — flattened elements for ARRAY/MAP, parallel fields
#   for ROW):
#     type descriptor: kind u8 | precision i16 (-1 none) | scale i16
#       | n_sub u8 | per sub: name_len u8 + utf8 name + descriptor
#     dtype_len u8 | dtype ascii  (numpy dtype str, e.g. '<i8')
#     flags u8 (1 = validity present, 2 = dictionary present)
#     [dict_count u32 | per value: len u32 + utf8]   (if dictionary)
#     n_rows u32 (this level)
#     data_nbytes u64 | raw per-row physical bytes (values / lengths /
#       entry counts / presence)
#     [n_rows validity bytes]                        (if validity)
#     n_children u8 | child columns...

_MAGIC = 0x54504732  # 'TPG2'
_KINDS = list(T.TypeKind)
_KIND_ID = {k: i for i, k in enumerate(_KINDS)}
_COL_HEAD = struct.Struct("<BhhB")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def _enc_type(out: bytearray, t: T.DataType) -> None:
    p = -1 if t.precision is None else int(t.precision)
    s = -1 if t.scale is None else int(t.scale)
    if t.kind == T.TypeKind.ARRAY:
        subs = [(None, t.element)]
    elif t.kind == T.TypeKind.MAP:
        subs = [(None, t.key), (None, t.element)]
    elif t.kind == T.TypeKind.ROW:
        subs = list(t.row_fields)
    else:
        subs = []
    out += _COL_HEAD.pack(_KIND_ID[t.kind], p, s, len(subs))
    for name, st in subs:
        nb = (name or "").encode("utf-8")
        out += bytes([len(nb)]) + nb
        _enc_type(out, st)


def _dec_type(take) -> T.DataType:
    kind_id, p, s, n_sub = _COL_HEAD.unpack(take(_COL_HEAD.size))
    kind = _KINDS[kind_id]
    subs = []
    for _ in range(n_sub):
        (nl,) = take(1)
        name = bytes(take(nl)).decode("utf-8") or None
        subs.append((name, _dec_type(take)))
    if kind == T.TypeKind.ARRAY:
        return T.array_of(subs[0][1])
    if kind == T.TypeKind.MAP:
        return T.map_of(subs[0][1], subs[1][1])
    if kind == T.TypeKind.ROW:
        return T.DataType(kind, row_fields=tuple(subs))
    return T.DataType(kind, None if p < 0 else p, None if s < 0 else s)


def _enc_col(out: bytearray, t: T.DataType, data: np.ndarray,
             valid: Optional[np.ndarray],
             dvals: Optional[Tuple[str, ...]],
             children: List[HostNested]) -> None:
    _enc_type(out, t)
    ds = data.dtype.str.encode("ascii")
    out += bytes([len(ds)]) + ds
    flags = (1 if valid is not None else 0) | (2 if dvals is not None else 0)
    out += bytes([flags])
    if dvals is not None:
        out += _U32.pack(len(dvals))
        for v in dvals:
            vb = v.encode("utf-8")
            out += _U32.pack(len(vb)) + vb
    n_rows = int(data.shape[0])
    out += _U32.pack(n_rows)
    raw = np.ascontiguousarray(data).tobytes()
    out += _U64.pack(len(raw)) + raw
    if valid is not None:
        out += np.ascontiguousarray(valid, dtype=np.bool_).tobytes()
    out += bytes([len(children)])
    for c in children:
        _enc_col(out, c.type, c.data, c.valid, c.dictionary, c.children)


def _dec_col(take):
    """-> (type, data, valid, dvals, children: List[HostNested])."""
    t = _dec_type(take)
    (ds_len,) = take(1)
    dtype = np.dtype(bytes(take(ds_len)).decode("ascii"))
    (flags,) = take(1)
    dvals = None
    if flags & 2:
        (n_vals,) = _U32.unpack(take(4))
        vals = []
        for _ in range(n_vals):
            (vl,) = _U32.unpack(take(4))
            vals.append(bytes(take(vl)).decode("utf-8"))
        dvals = tuple(vals)
    (n_rows,) = _U32.unpack(take(4))
    (nbytes,) = _U64.unpack(take(8))
    data = np.frombuffer(take(nbytes), dtype=dtype).copy()
    if t.lanes == 2:  # long-decimal (n, 2) limb pairs flatten on wire
        data = data.reshape(-1, 2)
    if data.shape[0] != n_rows:
        raise ValueError("column length does not match row count")
    valid = None
    if flags & 1:
        valid = np.frombuffer(take(n_rows), dtype=np.bool_).copy()
    (n_children,) = take(1)
    children = []
    for _ in range(n_children):
        ct, cd, cv, cdv, cc = _dec_col(take)
        children.append(HostNested(ct, cd, cv, cdv, cc))
    # structural validation: a corrupt nested frame must fail loudly,
    # not decode into clamped gathers / silently-truncated slices
    if t.kind in (T.TypeKind.ARRAY, T.TypeKind.MAP):
        want = int(data.astype(np.int64).sum()) if n_rows else 0
        for c in children:
            if c.data.shape[0] != want:
                raise ValueError(
                    "nested child length does not match sum of parent"
                    " lengths"
                )
    elif t.kind == T.TypeKind.ROW:
        for c in children:
            if c.data.shape[0] != n_rows:
                raise ValueError(
                    "row child length does not match parent row count"
                )
    return t, data, valid, dvals, children


def _encode_body(page: Page) -> bytes:
    out = bytearray()
    out += _U32.pack(_MAGIC)
    out += _U32.pack(page.row_count)
    out += _U16.pack(page.width)
    for t, col, valid, dvals in zip(
        page.types, page.columns, page.valids, page.dictionaries
    ):
        if isinstance(col, HostNested):
            _enc_col(out, col.type, col.data, col.valid, col.dictionary,
                     col.children)
        else:
            _enc_col(out, t, col, valid, dvals, [])
    return bytes(out)


def _decode_body(body) -> Page:
    mv = memoryview(body)
    off = 0

    def take(n):
        nonlocal off
        piece = mv[off : off + n]
        off += n
        return piece

    (magic,) = _U32.unpack(take(4))
    if magic != _MAGIC:
        raise ValueError("bad page magic")
    (rows,) = _U32.unpack(take(4))
    (width,) = _U16.unpack(take(2))
    types: List[T.DataType] = []
    cols: List = []
    valids: List[Optional[np.ndarray]] = []
    dicts: List[Optional[Tuple[str, ...]]] = []
    for _ in range(width):
        t, data, valid, dvals, children = _dec_col(take)
        if t.is_nested:
            if data.shape[0] != rows:
                raise ValueError("column length does not match row count")
            cols.append(HostNested(t, data, valid, dvals, children))
            valids.append(None)
            dicts.append(None)
        else:
            if data.shape[0] != rows:
                raise ValueError("column length does not match row count")
            cols.append(data)
            valids.append(valid)
            dicts.append(dvals)
        types.append(t)
    return Page(types, cols, valids, dicts, rows)


def serialize_page(page: Page, compress: Optional[bool] = None) -> bytes:
    body = _encode_body(page)
    if compress is None:
        compress = len(body) >= COMPRESS_MIN_BYTES
    if compress:
        packed = zlib.compress(body, 1)
        return _HEADER.pack(1, len(body)) + packed
    return _HEADER.pack(0, len(body)) + body


def deserialize_page(data: bytes) -> Page:
    codec, raw_len = _HEADER.unpack_from(data, 0)
    body = data[_HEADER.size :]
    if codec == 1:
        body = zlib.decompress(body)
        if len(body) != raw_len:
            raise ValueError("corrupt page frame")
    return _decode_body(body)


def serialize_batch(batch: RelBatch, compress: Optional[bool] = None) -> bytes:
    return serialize_page(Page.from_batch(batch), compress)


def deserialize_batch(data: bytes) -> RelBatch:
    return deserialize_page(data).to_batch()


def concat_pages(pages: Sequence[Page]) -> Page:
    """Merge wire pages into one (consumer-side consolidation). String
    columns are re-encoded onto a unified dictionary."""
    pages = [p for p in pages if p.row_count > 0] or list(pages[:1])
    if len(pages) == 1:
        return pages[0]
    width = pages[0].width
    types = pages[0].types
    cols, valids, dicts = [], [], []
    for i in range(width):
        dvals = [p.dictionaries[i] for p in pages]
        if any(d is not None for d in dvals):
            merged = Dictionary([v for d in dvals if d is not None for v in d])
            parts = []
            for p, d in zip(pages, dvals):
                remap = np.asarray(
                    [merged.code(v) for v in (d or ())], dtype=np.int32
                )
                c = p.columns[i]
                parts.append(remap[c] if len(remap) else c)
            cols.append(np.concatenate(parts))
            dicts.append(merged.values)
        else:
            cols.append(np.concatenate([p.columns[i] for p in pages]))
            dicts.append(None)
        if any(p.valids[i] is not None for p in pages):
            valids.append(
                np.concatenate(
                    [
                        p.valids[i]
                        if p.valids[i] is not None
                        else np.ones(p.row_count, dtype=bool)
                        for p in pages
                    ]
                )
            )
        else:
            valids.append(None)
    return Page(types, cols, valids, dicts, sum(p.row_count for p in pages))
