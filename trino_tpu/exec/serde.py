"""Page wire format: host-side serialized batches for remote exchange.

Analogue of Trino's serialized-page format (main/execution/buffer/
PagesSerdeUtil.java:53 — length-prefixed header with positionCount +
codec markers, per-block encodings; PageSerializer.java:18 adds LZ4;
SURVEY.md §2.8). TPU-first delta: the wire unit is a host ``Page`` —
compacted numpy SoA columns — because pages cross process/host
boundaries only after leaving the device. Compression is zlib (the
stdlib stand-in for airlift LZ4; the native C++ serde plugs in behind
the same two functions).

Framing:  [u8 codec] [u32 raw_len] [body]
  codec: 0 = raw body, 1 = zlib-compressed body.
The body is a SELF-DESCRIBING binary layout (see _encode_body) — typed
column descriptors + raw numpy buffers. No object deserializer ever
touches wire bytes: pages arrive over worker HTTP ports, and a pickle
body there would be remote code execution for anyone who can reach the
port (the reference's wire is likewise a typed binary layout with
LZ4+AES, PagesSerdeUtil.java:53 / PagesSerdeFactory.java:24-44).
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity

_HEADER = struct.Struct("<BI")
COMPRESS_MIN_BYTES = 1 << 13  # below this, compression costs more than it saves


@dataclasses.dataclass
class Page:
    """Host-side compacted batch: the unit of exchange between tasks.

    `columns[i]` has exactly `row_count` entries (no capacity padding —
    dead rows never cross the wire, like Page.compact before serialize).
    """

    types: List[T.DataType]
    columns: List[np.ndarray]
    valids: List[Optional[np.ndarray]]
    dictionaries: List[Optional[Tuple[str, ...]]]
    row_count: int

    @property
    def width(self) -> int:
        return len(self.columns)

    def size_bytes(self) -> int:
        n = 0
        for c in self.columns:
            n += c.nbytes
        for v in self.valids:
            if v is not None:
                n += v.nbytes
        return n

    @staticmethod
    def from_batch(batch: RelBatch) -> "Page":
        """Device batch -> compacted host page (one device->host copy;
        live-row extraction via the native mask_gather sweep)."""
        import jax

        from trino_tpu import native
        from trino_tpu.block import ArrayColumn

        for c in batch.columns:
            if isinstance(c, ArrayColumn):
                # nested columns have no wire layout yet; losing the
                # flat element store silently would corrupt data
                raise NotImplementedError(
                    "ARRAY columns cannot cross an exchange — UNNEST"
                    " them in the producing fragment"
                )

        host = jax.device_get(batch)
        live = (
            np.asarray(host.live).astype(bool)
            if host.live is not None
            else np.ones(batch.capacity, dtype=bool)
        )
        flat: List[np.ndarray] = []
        valid_idx: List[Optional[int]] = []
        for c in host.columns:
            flat.append(np.asarray(c.data))
            if c.valid is not None:
                valid_idx.append(len(flat))
                flat.append(np.asarray(c.valid))
            else:
                valid_idx.append(None)
        compacted = native.mask_compact(flat, live)
        cols, valids, dicts, typs = [], [], [], []
        i = 0
        for c, vi in zip(host.columns, valid_idx):
            cols.append(compacted[i])
            i += 1
            if vi is not None:
                valids.append(compacted[i])
                i += 1
            else:
                valids.append(None)
            dicts.append(c.dictionary.values if c.dictionary is not None else None)
            typs.append(c.type)
        return Page(typs, cols, valids, dicts, int(live.sum()))

    def to_batch(self, capacity: Optional[int] = None) -> RelBatch:
        """Host page -> device batch (padded back to bucketed capacity)."""
        import jax.numpy as jnp

        cap = capacity if capacity is not None else bucket_capacity(self.row_count)
        out = []
        for t, data, valid, dvals in zip(
            self.types, self.columns, self.valids, self.dictionaries
        ):
            d = Dictionary(dvals) if dvals is not None else None
            # Dictionary values are sorted + deduped on construction; wire
            # pages are encoded against the exact tuple, so re-encode codes
            # if sorting changed positions (it never does for tables whose
            # dictionaries were built by Dictionary itself).
            if d is not None and d.values != tuple(dvals):
                remap = np.asarray([d.code(v) for v in dvals], dtype=np.int32)
                data = remap[data]
            out.append(Column.from_numpy(t, data, valid, d, capacity=cap))
        live = None
        if self.row_count != cap:
            lv = np.zeros(cap, dtype=bool)
            lv[: self.row_count] = True
            live = jnp.asarray(lv)
        return RelBatch(out, live)


# --- self-describing binary page body (no pickle: bytes received from a
# worker's HTTP port must never reach an object deserializer — the
# reference's page wire is likewise a typed binary layout,
# PagesSerdeUtil.java:53). Layout, little-endian:
#   magic u32 'TPG1' | row_count u32 | width u16
#   per column:
#     kind u8 (TypeKind ordinal) | precision i16 (-1 none) | scale i16
#     dtype_len u8 | dtype ascii  (numpy dtype str, e.g. '<i8')
#     flags u8 (1 = validity present, 2 = dictionary present)
#     [dict_count u32 | per value: len u32 + utf8]   (if dictionary)
#     data_nbytes u64 | raw column bytes
#     [row_count validity bytes]                     (if validity)

_MAGIC = 0x54504731  # 'TPG1'
_KINDS = list(T.TypeKind)
_KIND_ID = {k: i for i, k in enumerate(_KINDS)}
_COL_HEAD = struct.Struct("<BhhB")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def _encode_body(page: Page) -> bytes:
    out = bytearray()
    out += _U32.pack(_MAGIC)
    out += _U32.pack(page.row_count)
    out += _U16.pack(page.width)
    for t, col, valid, dvals in zip(
        page.types, page.columns, page.valids, page.dictionaries
    ):
        p = -1 if t.precision is None else int(t.precision)
        s = -1 if t.scale is None else int(t.scale)
        out += _COL_HEAD.pack(_KIND_ID[t.kind], p, s, 0)
        ds = col.dtype.str.encode("ascii")
        out += bytes([len(ds)]) + ds
        flags = (1 if valid is not None else 0) | (2 if dvals is not None else 0)
        out += bytes([flags])
        if dvals is not None:
            out += _U32.pack(len(dvals))
            for v in dvals:
                vb = v.encode("utf-8")
                out += _U32.pack(len(vb)) + vb
        data = col.tobytes()
        out += _U64.pack(len(data)) + data
        if valid is not None:
            out += np.ascontiguousarray(valid, dtype=np.bool_).tobytes()
    return bytes(out)


def _decode_body(body) -> Page:
    mv = memoryview(body)
    off = 0

    def take(n):
        nonlocal off
        piece = mv[off : off + n]
        off += n
        return piece

    (magic,) = _U32.unpack(take(4))
    if magic != _MAGIC:
        raise ValueError("bad page magic")
    (rows,) = _U32.unpack(take(4))
    (width,) = _U16.unpack(take(2))
    types: List[T.DataType] = []
    cols: List[np.ndarray] = []
    valids: List[Optional[np.ndarray]] = []
    dicts: List[Optional[Tuple[str, ...]]] = []
    for _ in range(width):
        kind_id, p, s, _pad = _COL_HEAD.unpack(take(_COL_HEAD.size))
        t = T.DataType(
            _KINDS[kind_id], None if p < 0 else p, None if s < 0 else s
        )
        (ds_len,) = take(1)
        dtype = np.dtype(bytes(take(ds_len)).decode("ascii"))
        (flags,) = take(1)
        dvals = None
        if flags & 2:
            (n_vals,) = _U32.unpack(take(4))
            vals = []
            for _ in range(n_vals):
                (vl,) = _U32.unpack(take(4))
                vals.append(bytes(take(vl)).decode("utf-8"))
            dvals = tuple(vals)
        (nbytes,) = _U64.unpack(take(8))
        col = np.frombuffer(take(nbytes), dtype=dtype).copy()
        if col.shape[0] != rows:
            raise ValueError("column length does not match row count")
        valid = None
        if flags & 1:
            valid = np.frombuffer(take(rows), dtype=np.bool_).copy()
        types.append(t)
        cols.append(col)
        valids.append(valid)
        dicts.append(dvals)
    return Page(types, cols, valids, dicts, rows)


def serialize_page(page: Page, compress: Optional[bool] = None) -> bytes:
    body = _encode_body(page)
    if compress is None:
        compress = len(body) >= COMPRESS_MIN_BYTES
    if compress:
        packed = zlib.compress(body, 1)
        return _HEADER.pack(1, len(body)) + packed
    return _HEADER.pack(0, len(body)) + body


def deserialize_page(data: bytes) -> Page:
    codec, raw_len = _HEADER.unpack_from(data, 0)
    body = data[_HEADER.size :]
    if codec == 1:
        body = zlib.decompress(body)
        if len(body) != raw_len:
            raise ValueError("corrupt page frame")
    return _decode_body(body)


def serialize_batch(batch: RelBatch, compress: Optional[bool] = None) -> bytes:
    return serialize_page(Page.from_batch(batch), compress)


def deserialize_batch(data: bytes) -> RelBatch:
    return deserialize_page(data).to_batch()


def concat_pages(pages: Sequence[Page]) -> Page:
    """Merge wire pages into one (consumer-side consolidation). String
    columns are re-encoded onto a unified dictionary."""
    pages = [p for p in pages if p.row_count > 0] or list(pages[:1])
    if len(pages) == 1:
        return pages[0]
    width = pages[0].width
    types = pages[0].types
    cols, valids, dicts = [], [], []
    for i in range(width):
        dvals = [p.dictionaries[i] for p in pages]
        if any(d is not None for d in dvals):
            merged = Dictionary([v for d in dvals if d is not None for v in d])
            parts = []
            for p, d in zip(pages, dvals):
                remap = np.asarray(
                    [merged.code(v) for v in (d or ())], dtype=np.int32
                )
                c = p.columns[i]
                parts.append(remap[c] if len(remap) else c)
            cols.append(np.concatenate(parts))
            dicts.append(merged.values)
        else:
            cols.append(np.concatenate([p.columns[i] for p in pages]))
            dicts.append(None)
        if any(p.valids[i] is not None for p in pages):
            valids.append(
                np.concatenate(
                    [
                        p.valids[i]
                        if p.valids[i] is not None
                        else np.ones(p.row_count, dtype=bool)
                        for p in pages
                    ]
                )
            )
        else:
            valids.append(None)
    return Page(types, cols, valids, dicts, sum(p.row_count for p in pages))
