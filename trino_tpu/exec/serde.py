"""Page wire format: host-side serialized batches for remote exchange.

Analogue of Trino's serialized-page format (main/execution/buffer/
PagesSerdeUtil.java:53 — length-prefixed header with positionCount +
codec markers, per-block encodings; PageSerializer.java:18 adds LZ4;
SURVEY.md §2.8). TPU-first delta: the wire unit is a host ``Page`` —
compacted numpy SoA columns — because pages cross process/host
boundaries only after leaving the device. Compression is zlib (the
stdlib stand-in for airlift LZ4; the native C++ serde plugs in behind
the same two functions).

Framing:  [u8 codec] [u32 raw_len] [body]
  codec: 0 = raw pickle-v5 body, 1 = zlib-compressed body.
The body is a pickle of the Page's schema descriptor + numpy buffers —
protocol 5 keeps the bulk column bytes as contiguous buffers, which is
what the C++ path mmaps/compresses without copies.
"""

from __future__ import annotations

import dataclasses
import pickle
import struct
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch, bucket_capacity

_HEADER = struct.Struct("<BI")
COMPRESS_MIN_BYTES = 1 << 13  # below this, compression costs more than it saves


@dataclasses.dataclass
class Page:
    """Host-side compacted batch: the unit of exchange between tasks.

    `columns[i]` has exactly `row_count` entries (no capacity padding —
    dead rows never cross the wire, like Page.compact before serialize).
    """

    types: List[T.DataType]
    columns: List[np.ndarray]
    valids: List[Optional[np.ndarray]]
    dictionaries: List[Optional[Tuple[str, ...]]]
    row_count: int

    @property
    def width(self) -> int:
        return len(self.columns)

    def size_bytes(self) -> int:
        n = 0
        for c in self.columns:
            n += c.nbytes
        for v in self.valids:
            if v is not None:
                n += v.nbytes
        return n

    @staticmethod
    def from_batch(batch: RelBatch) -> "Page":
        """Device batch -> compacted host page (one device->host copy;
        live-row extraction via the native mask_gather sweep)."""
        import jax

        from trino_tpu import native

        host = jax.device_get(batch)
        live = (
            np.asarray(host.live).astype(bool)
            if host.live is not None
            else np.ones(batch.capacity, dtype=bool)
        )
        flat: List[np.ndarray] = []
        valid_idx: List[Optional[int]] = []
        for c in host.columns:
            flat.append(np.asarray(c.data))
            if c.valid is not None:
                valid_idx.append(len(flat))
                flat.append(np.asarray(c.valid))
            else:
                valid_idx.append(None)
        compacted = native.mask_compact(flat, live)
        cols, valids, dicts, typs = [], [], [], []
        i = 0
        for c, vi in zip(host.columns, valid_idx):
            cols.append(compacted[i])
            i += 1
            if vi is not None:
                valids.append(compacted[i])
                i += 1
            else:
                valids.append(None)
            dicts.append(c.dictionary.values if c.dictionary is not None else None)
            typs.append(c.type)
        return Page(typs, cols, valids, dicts, int(live.sum()))

    def to_batch(self, capacity: Optional[int] = None) -> RelBatch:
        """Host page -> device batch (padded back to bucketed capacity)."""
        import jax.numpy as jnp

        cap = capacity if capacity is not None else bucket_capacity(self.row_count)
        out = []
        for t, data, valid, dvals in zip(
            self.types, self.columns, self.valids, self.dictionaries
        ):
            d = Dictionary(dvals) if dvals is not None else None
            # Dictionary values are sorted + deduped on construction; wire
            # pages are encoded against the exact tuple, so re-encode codes
            # if sorting changed positions (it never does for tables whose
            # dictionaries were built by Dictionary itself).
            if d is not None and d.values != tuple(dvals):
                remap = np.asarray([d.code(v) for v in dvals], dtype=np.int32)
                data = remap[data]
            out.append(Column.from_numpy(t, data, valid, d, capacity=cap))
        live = None
        if self.row_count != cap:
            lv = np.zeros(cap, dtype=bool)
            lv[: self.row_count] = True
            live = jnp.asarray(lv)
        return RelBatch(out, live)


def serialize_page(page: Page, compress: Optional[bool] = None) -> bytes:
    desc = (
        page.types,
        page.dictionaries,
        page.row_count,
        [c.dtype.str for c in page.columns],
        [c.tobytes() for c in page.columns],
        [None if v is None else v.tobytes() for v in page.valids],
    )
    body = pickle.dumps(desc, protocol=5)
    if compress is None:
        compress = len(body) >= COMPRESS_MIN_BYTES
    if compress:
        packed = zlib.compress(body, 1)
        return _HEADER.pack(1, len(body)) + packed
    return _HEADER.pack(0, len(body)) + body


def deserialize_page(data: bytes) -> Page:
    codec, raw_len = _HEADER.unpack_from(data, 0)
    body = data[_HEADER.size :]
    if codec == 1:
        body = zlib.decompress(body)
        assert len(body) == raw_len
    types, dicts, rows, dtypes, col_bufs, valid_bufs = pickle.loads(body)
    cols = [
        np.frombuffer(b, dtype=np.dtype(ds)).copy()
        for ds, b in zip(dtypes, col_bufs)
    ]
    valids = [
        None if b is None else np.frombuffer(b, dtype=bool).copy()
        for b in valid_bufs
    ]
    return Page(list(types), cols, valids, list(dicts), rows)


def serialize_batch(batch: RelBatch, compress: Optional[bool] = None) -> bytes:
    return serialize_page(Page.from_batch(batch), compress)


def deserialize_batch(data: bytes) -> RelBatch:
    return deserialize_page(data).to_batch()


def concat_pages(pages: Sequence[Page]) -> Page:
    """Merge wire pages into one (consumer-side consolidation). String
    columns are re-encoded onto a unified dictionary."""
    pages = [p for p in pages if p.row_count > 0] or list(pages[:1])
    if len(pages) == 1:
        return pages[0]
    width = pages[0].width
    types = pages[0].types
    cols, valids, dicts = [], [], []
    for i in range(width):
        dvals = [p.dictionaries[i] for p in pages]
        if any(d is not None for d in dvals):
            merged = Dictionary([v for d in dvals if d is not None for v in d])
            parts = []
            for p, d in zip(pages, dvals):
                remap = np.asarray(
                    [merged.code(v) for v in (d or ())], dtype=np.int32
                )
                c = p.columns[i]
                parts.append(remap[c] if len(remap) else c)
            cols.append(np.concatenate(parts))
            dicts.append(merged.values)
        else:
            cols.append(np.concatenate([p.columns[i] for p in pages]))
            dicts.append(None)
        if any(p.valids[i] is not None for p in pages):
            valids.append(
                np.concatenate(
                    [
                        p.valids[i]
                        if p.valids[i] is not None
                        else np.ones(p.row_count, dtype=bool)
                        for p in pages
                    ]
                )
            )
        else:
            valids.append(None)
    return Page(types, cols, valids, dicts, sum(p.row_count for p in pages))
