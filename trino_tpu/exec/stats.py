"""Operator/driver statistics.

Analogue of OperatorStats/OperationTimer (main/operator/ — per-operator
CPU/wall recorded on every getOutput/addInput, Driver.java:403/408,
aggregated Driver->Pipeline->Task->Query and rendered by EXPLAIN ANALYZE
— SURVEY.md §5.1). Two timing modes:

- default (pipelined): wall time measures HOST dispatch; XLA executes
  asynchronously, so device time surfaces only at host-sync points and
  the final sync lands on the sink that forces it.
- device_sync (EXPLAIN ANALYZE): a device barrier closes every timed
  section, so each operator's wall INCLUDES the device time of the
  work it dispatched — true per-operator device attribution at the
  cost of the async pipeline (the profile-run trade every engine's
  ANALYZE makes; OperatorStats' added CPU accounting overhead is the
  reference's version of the same).
"""

from __future__ import annotations

import dataclasses
import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
import time
from typing import List, Optional, Set, Tuple


@dataclasses.dataclass
class OperatorStats:
    operator: str = ""
    add_input_calls: int = 0
    get_output_calls: int = 0
    input_batches: int = 0
    output_batches: int = 0
    input_rows: int = 0
    output_rows: int = 0
    add_input_s: float = 0.0
    get_output_s: float = 0.0
    finish_s: float = 0.0
    # True when the timings above CLOSE with a device barrier (device-
    # inclusive attribution); False = host dispatch only
    device_synced: bool = False

    @property
    def total_s(self) -> float:
        return self.add_input_s + self.get_output_s + self.finish_s

    def line(self) -> str:
        return (
            f"{self.operator}: in={self.input_rows} rows/"
            f"{self.input_batches} batches, out={self.output_rows} rows/"
            f"{self.output_batches} batches, "
            f"wall={self.total_s * 1000:.1f}ms "
            f"(add={self.add_input_s * 1000:.1f} "
            f"get={self.get_output_s * 1000:.1f} "
            f"finish={self.finish_s * 1000:.1f})"
        )


def _device_barrier() -> None:
    """Block until every dispatched device computation has finished
    (same-device programs run in dispatch order, so blocking on a
    freshly enqueued trivial program drains the queue)."""
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()


class InstrumentedOperator:
    """Transparent timing wrapper around one operator — the
    OperationTimer discipline without touching operator code.

    Two observability hooks piggyback on the recording path:

    - `heartbeat` (zero-arg callable) fires at ENTRY and EXIT of every
      add_input/get_output/finish — operator-internal liveness at
      tens-of-ms granularity, vs. the Driver's batch-boundary beats
      (~1 s under compile/datagen): the worker watchdog's tightened
      stuck-task threshold keys off these.
    - `span` (runtime/tracing.py Span) gets its start re-anchored at
      the operator's first activity, its end stamped at finish, and the
      final OperatorStats attached as attributes — one operator span
      per task in the query trace.
    """

    def __init__(self, inner, stats: OperatorStats, count_rows: bool,
                 device_sync: bool = False,
                 shape_ledger: Optional[Set[Tuple]] = None,
                 heartbeat=None, span=None):
        self.inner = inner
        self.stats = stats
        self.stats.operator = type(inner).__name__
        self.stats.device_synced = device_sync
        self._count_rows = count_rows
        self._device_sync = device_sync
        # observed (operator, capacity, dtype-signature) classes — the
        # same vocabulary sql/validate.py's shape census predicts over,
        # so EXPLAIN ANALYZE can print expected vs observed side by side
        self._shape_ledger = shape_ledger
        self._heartbeat = heartbeat
        self._span = span
        self._span_anchored = False
        # deferred row counts: masked batches enqueue a device-side
        # jnp.sum scalar instead of forcing a host sync per batch (a
        # round trip on a real accelerator); flush_counts() resolves
        # them at pipeline completion / terminal status
        self._pending_counts: list = []
        self._pending_lock = named_lock("InstrumentedOperator._pending_lock")

    def _beat(self) -> None:
        if self._heartbeat is not None:
            self._heartbeat()
        if self._span is not None and not self._span_anchored:
            # span start = first activity, not wrap time: operators
            # deep in a pipeline idle until data reaches them, and the
            # trace should show WHEN each operator ran, not task setup
            self._span_anchored = True
            self._span.start_s = time.time()

    def _count(self, attr: str, batch) -> None:
        live = getattr(batch, "live", None)
        if live is None:
            setattr(self.stats, attr, getattr(self.stats, attr)
                    + batch.capacity)
            return
        import jax.numpy as jnp

        with self._pending_lock:
            self._pending_counts.append((attr, jnp.sum(live)))

    def flush_counts(self) -> None:
        """Resolve deferred row counts into the stats (one host sync
        for the whole backlog instead of one per batch)."""
        with self._pending_lock:
            pending, self._pending_counts = self._pending_counts, []
        for attr, v in pending:
            setattr(self.stats, attr, getattr(self.stats, attr) + int(v))

    def close_span(self) -> None:
        """Finalize stats (flush deferred row counts), then end the
        operator span with them attached (called by the task when the
        pipeline completes — finish() can run long before the last
        get_output drains)."""
        self.flush_counts()
        if self._span is None:
            return
        self._span.set(**{
            k: v for k, v in dataclasses.asdict(self.stats).items()
            if k != "operator"
        })
        self._span.end()

    def _record_shape(self, batch) -> None:
        if self._shape_ledger is None:
            return
        try:
            self._shape_ledger.add((
                type(self.inner).__name__,
                batch.capacity,
                tuple(str(c.type) for c in batch.columns),
            ))
        except Exception:
            pass  # ledger must never break execution

    def needs_input(self) -> bool:
        return self.inner.needs_input()

    def add_input(self, batch) -> None:
        self._beat()
        t0 = time.monotonic()
        self.inner.add_input(batch)
        if self._device_sync:
            _device_barrier()
        self.stats.add_input_s += time.monotonic() - t0
        self.stats.add_input_calls += 1
        self.stats.input_batches += 1
        if self._count_rows:
            self._count("input_rows", batch)
        self._beat()

    def get_output(self):
        self._beat()
        t0 = time.monotonic()
        out = self.inner.get_output()
        if self._device_sync and out is not None:
            # a None poll dispatched nothing — a barrier there would
            # charge one device round trip per idle poll
            _device_barrier()
        self.stats.get_output_s += time.monotonic() - t0
        self.stats.get_output_calls += 1
        if out is not None:
            self.stats.output_batches += 1
            if self._count_rows:
                self._count("output_rows", out)
            self._record_shape(out)
            self._beat()
        return out

    def finish(self) -> None:
        self._beat()
        t0 = time.monotonic()
        self.inner.finish()
        if self._device_sync:
            _device_barrier()
        self.stats.finish_s += time.monotonic() - t0
        self._beat()

    def is_finished(self) -> bool:
        return self.inner.is_finished()

    def is_blocked(self) -> bool:
        return self.inner.is_blocked()

    def __getattr__(self, name):
        # pass through operator-specific surface (e.g. CollectorSink.rows)
        return getattr(self.inner, name)


def instrument(operators, count_rows: bool = True,
               device_sync: bool = False,
               shape_ledger: Optional[Set[Tuple]] = None,
               heartbeat=None, span_factory=None):
    """Wrap a pipeline's operators; returns (wrapped, [OperatorStats]).
    `device_sync=True` closes every timed section with a device barrier
    (EXPLAIN ANALYZE's per-operator device attribution). Pass a shared
    `shape_ledger` set to collect observed output shape classes,
    `heartbeat` for operator-internal watchdog beats, and
    `span_factory(operator_name) -> Span` to open one trace span per
    operator (ended with stats attached via close_span)."""
    stats = [OperatorStats() for _ in operators]
    wrapped = [
        InstrumentedOperator(
            op, st, count_rows, device_sync, shape_ledger,
            heartbeat=heartbeat,
            span=(span_factory(type(op).__name__)
                  if span_factory is not None else None),
        )
        for op, st in zip(operators, stats)
    ]
    return wrapped, stats


ENGINE_COUNTERS = (
    "rows_scanned",
    "bytes_scanned",
    "rows_shuffled",
    "exchanges_elided",
    "xla_compiles",
)


def engine_counters_delta(before: dict, after: dict) -> dict:
    """Per-query view of the METRICS singleton's cumulative engine
    counters: snapshot() before and after the run, subtract."""
    return {
        k: after.get(k, 0.0) - before.get(k, 0.0) for k in ENGINE_COUNTERS
    }


def render_stats(
    groups: List[List[OperatorStats]],
    counters: Optional[dict] = None,
) -> str:
    lines = []
    synced = any(st.device_synced for g in groups for st in g)
    if synced:
        lines.append(
            "Timings are DEVICE-INCLUSIVE (each operator section "
            "closed by a device barrier; async pipelining disabled "
            "for attribution)"
        )
    for i, group in enumerate(groups):
        lines.append(f"Pipeline {i}:")
        for st in group:
            lines.append("  " + st.line())
    if counters is not None:
        lines.append(
            "Engine counters: "
            + " ".join(f"{k}={counters.get(k, 0.0):.0f}" for k in ENGINE_COUNTERS)
        )
    return "\n".join(lines)
