"""Intra-task local exchange.

Analogue of main/operator/exchange/LocalExchange.java:67 (+
LocalExchangeSinkOperator / LocalExchangeSourceOperator): a bounded
in-memory crossing between drivers of ONE task, so pipelines overlap —
host-side work (remote-page deserialization, spool reads) runs on one
thread while the device-compute pipeline consumes on another, and
independent hash-build pipelines run concurrently.

TPU-first framing: there is one device, so this is NOT about parallel
device compute — XLA serializes kernels anyway. The win is overlapping
the HOST phases (serde, HTTP pulls, split decoding) with device
execution, which the reference gets from its multi-driver pipelines
(Trino runs ~N drivers per pipeline per task; here the device pipeline
stays single-driver and the host-side producers fan in).

Modes: "arbitrary" (any consumer takes the next batch — the
least-loaded-queue policy doubles as the SkewedPartitionRebalancer's
local form), "broadcast" (every consumer sees every batch),
"round_robin" (strict rotation).
"""

from __future__ import annotations

import threading
from trino_tpu.analysis.witness import named_condition, named_lock, named_rlock
from collections import deque
from typing import List, Optional


class LocalExchange:
    def __init__(
        self,
        n_consumers: int = 1,
        mode: str = "arbitrary",
        max_buffered_batches: int = 4,
    ):
        assert mode in ("arbitrary", "broadcast", "round_robin")
        self.mode = mode
        self._queues: List[deque] = [deque() for _ in range(n_consumers)]
        self._lock = named_lock("LocalExchange._lock")
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._max = max_buffered_batches
        self._producers = 0
        self._producers_done = False
        self._aborted = False
        self._error: Optional[BaseException] = None
        self._rr = 0

    def abort(self) -> None:
        """Tear down (consumer failed): drop buffered batches, unblock
        producers (put becomes a no-op), finish consumers."""
        with self._lock:
            self._aborted = True
            self._producers_done = True
            for q in self._queues:
                q.clear()
            self._not_full.notify_all()
            self._not_empty.notify_all()

    # -- producer side --
    def add_producer(self) -> None:
        with self._lock:
            self._producers += 1

    def producer_finished(self) -> None:
        with self._lock:
            self._producers -= 1
            if self._producers <= 0:
                self._producers_done = True
                self._not_empty.notify_all()

    def producer_failed(self, error: BaseException) -> None:
        """A producer pipeline died mid-stream: latch its error so
        consumers RAISE instead of reading the truncated stream as a
        clean end-of-input. Without the latch, a killed upstream lets
        the consumer half finish the task's sink normally and the task
        publishes an empty 'complete' result — a wrong answer, not a
        failure."""
        with self._lock:
            if self._error is None:
                self._error = error
            self._producers_done = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def put(self, batch) -> None:
        with self._not_full:
            # the gate must watch the queue(s) this put will grow:
            # broadcast appends to EVERY queue (bound = fullest);
            # round_robin appends to a specific queue (bound = that one);
            # arbitrary appends to the shortest (bound = min). Gating
            # everything on min would let a slow consumer's queue grow
            # without limit while a fast consumer keeps the min small.
            def _level() -> int:
                if self.mode == "broadcast":
                    return max(len(q) for q in self._queues)
                if self.mode == "round_robin":
                    return len(self._queues[self._rr % len(self._queues)])
                return min(len(q) for q in self._queues)

            while not self._aborted and _level() >= self._max:
                self._not_full.wait(0.1)
            if self._aborted:
                return
            if self.mode == "broadcast":
                for q in self._queues:
                    q.append(batch)
            elif self.mode == "round_robin":
                self._queues[self._rr % len(self._queues)].append(batch)
                self._rr += 1
            else:  # arbitrary: least-loaded queue (local skew rebalance)
                target = min(
                    range(len(self._queues)), key=lambda i: len(self._queues[i])
                )
                self._queues[target].append(batch)
            self._not_empty.notify_all()

    # -- consumer side --
    def get(self, consumer: int, timeout: float = 0.1):
        """(batch | None, done). done=True only when producers finished
        AND this consumer's queue drained."""
        with self._not_empty:
            if self._error is not None:
                raise RuntimeError(
                    "local exchange producer failed"
                ) from self._error
            q = self._queues[consumer]
            if not q and not self._producers_done:
                self._not_empty.wait(timeout)
            if self._error is not None:
                raise RuntimeError(
                    "local exchange producer failed"
                ) from self._error
            if q:
                batch = q.popleft()
                self._not_full.notify_all()
                return batch, False
            return None, self._producers_done


class LocalExchangeSinkOperator:
    """Terminal operator of a producer pipeline: pushes into the
    exchange (LocalExchangeSinkOperator.java)."""

    def __init__(self, exchange: LocalExchange):
        self._ex = exchange
        self._finished = False
        exchange.add_producer()

    def needs_input(self) -> bool:
        return not self._finished

    def add_input(self, batch) -> None:
        self._ex.put(batch)

    def get_output(self):
        return None

    def finish(self) -> None:
        if not self._finished:
            self._finished = True
            self._ex.producer_finished()

    def is_finished(self) -> bool:
        return self._finished

    def is_blocked(self) -> bool:
        return False


class LocalExchangeSourceOperator:
    """Leaf operator of a consumer pipeline: pulls from the exchange
    (LocalExchangeSourceOperator.java)."""

    def __init__(self, exchange: LocalExchange, consumer: int = 0):
        self._ex = exchange
        self._consumer = consumer
        self._done = False
        self._pending = None

    def needs_input(self) -> bool:
        return False

    def add_input(self, batch) -> None:
        raise RuntimeError("source operator takes no input")

    def get_output(self):
        if self._pending is not None:
            out, self._pending = self._pending, None
            return out
        if self._done:
            return None
        batch, done = self._ex.get(self._consumer, timeout=0.05)
        if done:
            self._done = True
        return batch

    def finish(self) -> None:
        pass

    def is_finished(self) -> bool:
        return self._done and self._pending is None

    def is_blocked(self) -> bool:
        # blocked while waiting for producers (lets the Driver yield)
        if self._done or self._pending is not None:
            return False
        batch, done = self._ex.get(self._consumer, timeout=0.0)
        if done:
            self._done = True
            return False
        if batch is not None:
            self._pending = batch
            return False
        return True
