"""Regenerate the committed EXPLAIN-diff corpus (PR 1 plan-quality passes).

Run from the repo root:

    JAX_PLATFORMS=cpu python explain_corpus/generate.py

Each emitted file pairs an EXPLAIN with the relevant pass disabled (or
the plan before the rule fires) against the same query with it enabled,
so reviewers can see exactly what each pass buys:

    01_transitive_predicate.txt   EqualityInference derives a join-key
                                  bound for the unfiltered side
    02_scan_pushdown.txt          conjuncts + column list land on the
                                  scan node (TPC-H Q6)
    03_partial_agg_exchange.txt   partial aggregation placed below the
                                  repartition exchange
    04_elided_exchange.txt        co-bucketed join/agg plan drops its
                                  repartition exchanges

The corpus is deterministic (fixed seeds, tiny inputs) — diffs in a
future PR mean the planner actually changed.
"""

import os

# corpus 11 exercises the chunked mesh plane, whose chunk count depends
# on the per-shard extent — force the same virtual 8-device CPU mesh the
# test suite runs under (tests/conftest.py) so standalone regeneration
# matches the corpus-diff gate
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import CatalogManager, ColumnMetadata
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.sql import plan as P
from trino_tpu.sql.analyzer import Analyzer
from trino_tpu.sql.fragmenter import (
    explain_distributed,
    plan_distributed,
    push_partial_aggregation_through_exchange,
)
from trino_tpu.sql.parser import parse

HERE = os.path.dirname(os.path.abspath(__file__))
# write_all() retargets this so the corpus-diff test can regenerate into
# a tmp dir and diff against the committed files
_OUT_DIR = [HERE]


def emit(name: str, *sections, out_dir: str = None):
    path = os.path.join(out_dir or _OUT_DIR[0], name)
    body = []
    for title, text in sections:
        body.append("=" * 72)
        body.append(title)
        body.append("=" * 72)
        body.append(text.rstrip())
        body.append("")
    with open(path, "w") as fh:
        fh.write("\n".join(body))
    print(f"wrote {path}")


def _mem_runner():
    r = LocalQueryRunner(Session(catalog="memory", schema="s"))
    r.register_catalog("memory", create_memory_connector())
    mem = r.catalogs.get("memory")
    rng = np.random.default_rng(7)
    n = 1000
    mem.load_table(
        "s", "a",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [np.arange(n, dtype=np.int64), rng.integers(0, 9, n, dtype=np.int64)],
    )
    mem.load_table(
        "s", "b",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
        [np.arange(n, dtype=np.int64), rng.integers(0, 9, n, dtype=np.int64)],
    )
    return r


def explain(runner, sql):
    return runner.execute("explain " + sql).rows[0][0]


def corpus_01_transitive():
    r = _mem_runner()
    # the subquery keeps `ak < 100` ABOVE the join at analysis time —
    # exactly the Filter(Join) shape InferTransitivePredicates rewrites
    sql = (
        "select v, w from (select a.k as ak, b.k as bk, a.v as v, "
        "b.w as w from a join b on a.k = b.k) j where ak < 100"
    )
    r.execute("SET SESSION enable_optimizer = false")
    off = explain(r, sql)
    r.execute("SET SESSION enable_optimizer = true")
    on = explain(r, sql)
    emit(
        "01_transitive_predicate.txt",
        (f"QUERY\n{sql}", ""),
        ("enable_optimizer = false  (bound stays on the filter above "
         "the join; both\ntables scanned in full)", off),
        ("enable_optimizer = true   (EqualityInference derives k < 100 "
         "for b via the\njoin equivalence ak = bk; BOTH scans now carry "
         "pushed=[k lt 100])", on),
    )


def corpus_02_scan_pushdown():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    sql = (
        "select sum(l_extendedprice * l_discount) as revenue from lineitem "
        "where l_shipdate >= date '1994-01-01' "
        "and l_shipdate < date '1995-01-01' "
        "and l_discount between 0.05 and 0.07 and l_quantity < 24"
    )
    r.execute("SET SESSION enable_pushdown = false")
    off = explain(r, sql)
    r.execute("SET SESSION enable_pushdown = true")
    on = explain(r, sql)
    emit(
        "02_scan_pushdown.txt",
        (f"QUERY (TPC-H Q6)\n{sql}", ""),
        ("enable_pushdown = false  (FilterNode above a full-width scan)",
         off),
        ("enable_pushdown = true   (conjuncts in `pushed=[...]` on the "
         "scan, column list\nnarrowed to the four referenced columns, "
         "no residual Filter)", on),
    )


def corpus_03_partial_agg():
    c = CatalogManager()
    c.register("tpch", create_tpch_connector())
    sql = (
        "select l_returnflag, sum(l_quantity) from lineitem "
        "group by l_returnflag"
    )
    output = Analyzer(c, "tpch", "tiny").plan(parse(sql))
    # the rule's input: a single-step aggregate above the repartition
    # exchange that AddExchanges inserted
    scan = _scan_of(output)
    ex = P.ExchangeNode(scan, "repartition", (0,), scan.fields)
    naive = P.AggregateNode(
        ex, (0,), (P.AggCall("sum", 1, T.BIGINT),),
        (P.Field("l_returnflag", scan.fields[0].type),
         P.Field("sum", T.BIGINT)),
        step="single",
    )
    pushed = push_partial_aggregation_through_exchange(naive)
    sp = plan_distributed(output, c)
    # catalogs=... annotates each fragment header with its compile-churn
    # census (expected_xla_lowerings — sql/validate.py)
    distributed = explain_distributed(sp, catalogs=c)
    emit(
        "03_partial_agg_exchange.txt",
        (f"QUERY\n{sql}", ""),
        ("before push_partial_aggregation_through_exchange\n"
         "(single-step aggregate consumes the repartition exchange: "
         "every input row\ncrosses the wire)", P.explain_text(naive)),
        ("after push_partial_aggregation_through_exchange\n"
         "(partial aggregate runs scan-side below the exchange; only "
         "one row per\ngroup per producer is shuffled; final step "
         "merges)", P.explain_text(pushed)),
        ("full distributed plan (plan_distributed applies the rule; "
         "Aggregate[partial]\nsits in the scan fragment, "
         "Aggregate[final] above the remote source; each\nfragment "
         "header carries its compile-churn census)",
         distributed),
    )


def _scan_of(node):
    if isinstance(node, P.ScanNode):
        return node
    for ch in node.children():
        s = _scan_of(ch)
        if s is not None:
            return s
    return None


def corpus_04_elided_exchange():
    rng = np.random.default_rng(11)
    ka = rng.integers(0, 50, 300).astype(np.int64)
    va = rng.integers(0, 9, 300).astype(np.int64)
    kb = rng.integers(0, 50, 200).astype(np.int64)
    wb = rng.integers(0, 9, 200).astype(np.int64)
    sql = (
        "select ta.k, sum(ta.v + tb.w) from ta join tb on ta.k = tb.k "
        "group by ta.k"
    )

    def distributed_explain(bucketed):
        mem = create_memory_connector()
        bb = ("k",) if bucketed else None
        mem.load_table(
            "d", "ta",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
            [ka, va], bucketed_by=bb,
        )
        mem.load_table(
            "d", "tb",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
            [kb, wb], bucketed_by=bb,
        )
        c = CatalogManager()
        c.register("memory", mem)
        output = Analyzer(c, "memory", "d").plan(parse(sql))
        before = METRICS.snapshot().get("exchanges_elided", 0.0)
        sp = plan_distributed(output, c, broadcast_threshold=0)
        elided = METRICS.snapshot().get("exchanges_elided", 0.0) - before
        return explain_distributed(sp, catalogs=c), elided

    plain, e_plain = distributed_explain(False)
    bucketed, e_bucketed = distributed_explain(True)
    emit(
        "04_elided_exchange.txt",
        (f"QUERY\n{sql}", ""),
        (f"unbucketed tables  (exchanges_elided +{e_plain:.0f}: the "
         "final aggregate reuses the\njoin's hash distribution, but "
         "both join inputs still repartition)", plain),
        (f"bucketed_by=('k') on both tables  (exchanges_elided "
         f"+{e_bucketed:.0f}: declared\nco-bucketing satisfies the "
         "join and aggregate distribution requirements,\nso the "
         "repartition exchanges disappear and fragments collapse)",
         bucketed),
    )


def corpus_05_plan_validation():
    from trino_tpu.expr import ir
    from trino_tpu.sql.validate import (
        PlanValidationError,
        census_text,
        shape_census,
        validate_logical,
    )

    # a rule mis-shifting a Ref — the error names checker + node path
    vals = P.ValuesNode((P.Field("a", T.BIGINT),), ((0,),))
    bad_ref = P.ProjectNode(
        vals, (ir.InputRef(5, T.BIGINT),), (P.Field("x", T.BIGINT),)
    )
    try:
        validate_logical(bad_ref, stage="optimizer", rule="example_rule")
        ref_err = "NOT CAUGHT"
    except PlanValidationError as e:
        ref_err = str(e)
    # an un-canonicalized tstz repartition key (zone bits would reach
    # the hash) — the regression canonicalize_tstz_keys exists to stop
    tvals = P.ValuesNode((P.Field("ts", T.TIMESTAMP_TZ),), ((0,),))
    bad_tstz = P.ExchangeNode(tvals, "repartition", (0,), tvals.fields)
    try:
        validate_logical(bad_tstz)
        tstz_err = "NOT CAUGHT"
    except PlanValidationError as e:
        tstz_err = str(e)
    # census over a join plan: the dynamic filter's retry-variant class
    c = CatalogManager()
    c.register("tpch", create_tpch_connector())
    sql = (
        "select n_name, count(*) from supplier, nation "
        "where s_nationkey = n_nationkey group by n_name"
    )
    output = Analyzer(c, "tpch", "tiny").plan(parse(sql))
    census = census_text(shape_census(output, c), warn_threshold=32)
    emit(
        "05_plan_validation.txt",
        ("corrupted plan: Project ref outside input width\n"
         "(PlanValidationError names the checker, node path, stage and "
         "last rule)", ref_err),
        ("corrupted plan: repartition on a raw TIMESTAMP_TZ key\n"
         "(exchange_keys checker demands the $utc zone-masked "
         "projection)", tstz_err),
        (f"QUERY\n{sql}", ""),
        ("compile-churn census (logical plan): one line per expected "
         "(operator,\ncapacity, dtype) XLA lowering; the "
         "DynamicFilterOperator class is marked\nretry-variant — its "
         "pruned probe capacity depends on which retry attempt's\n"
         "build side survives, so it compiles fresh shapes no warm run "
         "covers", census),
    )


def corpus_06_compile_regime():
    from trino_tpu.compile.shapes import CapacityLadder, ShapeStabilizer
    from trino_tpu.compile.warmup import WarmupService
    from trino_tpu.sql.validate import census_text, shape_census

    # 1. the capacity ladder: how pruned spans snap onto stable rungs
    lines = []
    for base in (2, 4):
        lad = CapacityLadder(base=base)
        rungs = ", ".join(str(r) for r in lad.rungs(1 << 20))
        lines.append(f"base={base}: {rungs}")
    stab = ShapeStabilizer(CapacityLadder(base=2))
    demo = []
    for span, pruned in ((60175, 60175), (60175, 1732), (60175, 0)):
        cap = stab.chunk_capacity(span)
        demo.append(
            f"span={span} rows_after_pruning={pruned} -> capacity={cap}"
        )
    ladder_text = (
        "\n".join(lines)
        + "\n\nchunk capacity is a function of the PRE-pruning span, so "
        "pushdown- or\ndynamic-filter-pruned chunks land on the same "
        "class as the unpruned scan:\n" + "\n".join(demo)
    )

    # 2. census with tail classes: a table larger than batch_rows scans
    # in batch_rows chunks plus one smaller tail chunk
    c = CatalogManager()
    c.register("tpch", create_tpch_connector())
    sql_tail = (
        "select l_returnflag, sum(l_quantity) from lineitem "
        "group by l_returnflag"
    )
    output = Analyzer(c, "tpch", "tiny").plan(parse(sql_tail))
    census = census_text(
        shape_census(
            output, c, batch_rows=49152, ladder=CapacityLadder(base=2)
        ),
        warn_threshold=32,
    )

    # 3. the census-driven warmup plan: the fused filter/project stages
    # the planner registered for AOT compilation, with their predicted
    # capacity classes (plan-time artifact — no runtime counters)
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    sql_warm = (
        "select l_orderkey + 1 from lineitem where l_quantity * 2 < 10"
    )
    stmt = parse(sql_warm)
    q = stmt.query if hasattr(stmt, "query") else stmt
    _, physical = r._plan(q, sql_key=None)
    svc = WarmupService(physical.warmup_entries, mode="block")
    emit(
        "06_compile_regime.txt",
        ("capacity ladder (compile/shapes.py): geometric rungs pruned "
         "scan chunks,\nspill re-reads and exchange pages pad up to; "
         "base is the session property\ncapacity_ladder_base",
         ladder_text),
        (f"QUERY\n{sql_tail}", ""),
        ("stabilized shape census at batch_rows=49152 (lineitem tiny = "
         "60175 rows\n> batch_rows, so the scan and its consumers carry "
         "a tail capacity class\nbeside the main one)", census),
        (f"QUERY\n{sql_warm}", ""),
        ("warmup plan (compile/warmup.py): the fused FilterProject "
         "stage the planner\nregistered, warmed once per predicted "
         "capacity on an all-dead zero batch\nbefore (block) or while "
         "(background) the query runs", svc.plan_text()),
    )


def corpus_07_distributed_analyze():
    """Distributed EXPLAIN ANALYZE through the TaskInfo aggregation
    path (runtime/queryinfo.py): merged per-stage operator lines,
    expected-vs-observed lowering counts, and per-task-attempt summary
    lines. Wall/cpu timings and the process-global query counter are
    nondeterministic, so they are redacted to `#` — the corpus pins the
    structure (fragments, operators, row/batch counts, lowerings), not
    the clock."""
    import re

    from trino_tpu.runtime import DistributedQueryRunner, Worker

    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [Worker(f"corpus-w{i}", cats) for i in range(2)]
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny"),
        worker_handles=workers,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    sql = (
        "select n_regionkey, count(*) from nation group by n_regionkey"
    )
    out = r.execute("EXPLAIN ANALYZE " + sql).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"replicas= .*", "replicas= #", text)
        # process-global resident/recovery-tier counters depend on what
        # ran before this corpus fn — corpora 09 and 11 pin the real
        # numbers
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "07_distributed_analyze.txt",
        (f"QUERY\n{sql}", ""),
        ("distributed EXPLAIN ANALYZE (runtime/queryinfo.py rollup: "
         "Driver -> Task ->\nStage; merged operator lines per fragment "
         "through the shared OperatorStats\nformatter, "
         "expected-vs-observed XLA lowerings from the census ledger,\n"
         "one summary line per task attempt; wall-clock values "
         "redacted to `#`)", redact(out)),
    )


def corpus_08_mesh_analyze():
    """Distributed EXPLAIN ANALYZE on the chunked mesh plane
    (parallel/mesh_plan.py + mesh_chunk.py): a colocated in-process
    cluster reports `data_plane=mesh` with the statically counted ICI
    collectives (all_to_all per hash exchange, all_gather per broadcast
    / single-row enforcement) and the session's chunk granularity; an
    ineligible plan reports the fallback reason instead. Timings
    redacted as in corpus 07."""
    import re

    from trino_tpu.runtime import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny"),
        n_workers=2,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    r.session.mesh_chunk_rows = 256
    sql = (
        "select o_orderpriority, count(*) from orders join customer "
        "on o_custkey = c_custkey group by o_orderpriority"
    )
    out = r.execute("EXPLAIN ANALYZE " + sql).rows[0][0]
    sql_single = "select 1"
    out_single = r.execute("EXPLAIN ANALYZE " + sql_single).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"replicas= .*", "replicas= #", text)
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "08_mesh_analyze.txt",
        (f"QUERY\n{sql}", ""),
        ("mesh-eligible EXPLAIN ANALYZE: the trailing data_plane line "
         "shows where\nthe query's data plane runs — here the mesh, "
         "with the static collective\ncounts (the broadcast join rides "
         "all_gather, the partial->final agg\nexchange rides "
         "all_to_all) and mesh_chunk_rows=256 preemptible chunking\n"
         "(wall-clock values redacted to `#`)", redact(out)),
        (f"QUERY\n{sql_single}", ""),
        ("ineligible plan: a single-fragment query never reaches the "
         "mesh — the\ndata_plane line carries the static refusal "
         "reason", redact(out_single)),
    )


def corpus_09_resident_analyze():
    """The resident state tier (trino_tpu/resident/): a point lookup
    over a table named in `resident_tables` builds and pins a
    device-resident hash table on first touch (miss), probes it with a
    shape-stable jitted program thereafter (hit, zero rebuild), rides
    an INSERT on the append-only delta side (the pin survives under the
    table's NEW generation), and is evicted by non-append DML
    (generation bump -> rebuild on next touch, oracle-equal). The
    trailing `resident=` line of distributed EXPLAIN ANALYZE reports
    the pin population and lifetime counters; device byte counts are
    layout-dependent and redacted to `#`."""
    import re

    from trino_tpu.resident import GENERATIONS, RESIDENT
    from trino_tpu.resident.fastlane import (
        drain_compactions,
        try_resident_lookup,
    )
    from trino_tpu.runtime import DistributedQueryRunner

    RESIDENT.evict_all()
    RESIDENT.reset_stats()
    r = LocalQueryRunner(
        Session(catalog="memory", schema="s", resident_tables="s.kv")
    )
    r.register_catalog("memory", create_memory_connector())
    mem = r.catalogs.get("memory")
    n = 64
    mem.load_table(
        "s", "kv",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [np.arange(n, dtype=np.int64), np.arange(n, dtype=np.int64) * 10],
    )
    events = []

    def look(k):
        res = try_resident_lookup(r, f"select v from kv where k = {k}")
        return None if res is None else res.rows

    events.append(f"lookup k=7        -> {look(7)}   (miss: build + pin)")
    events.append(f"lookup k=7        -> {look(7)}   (hit: device probe)")
    r.execute("insert into kv values (1000, 12345)")
    events.append(
        f"insert (1000, 12345); lookup k=1000 -> {look(1000)}   "
        "(delta append: pin survived re-keyed)"
    )
    drain_compactions()
    r.execute("update kv set v = 0 where k = 7")
    events.append(
        f"update k=7 -> v=0; lookup k=7       -> {look(7)}   "
        "(generation bump evicted the pin; rebuild, oracle-equal)"
    )
    stats = RESIDENT.stats()
    events.append(
        "counters: hits={hits} misses={misses} pins={pins} "
        "evictions={evictions} compactions={compactions}".format(**stats)
    )

    # the resident= line on a distributed EXPLAIN ANALYZE (stats are
    # process-global; the distributed runner reports the same tier)
    dr = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny"), n_workers=2,
        hash_partitions=2,
    )
    dr.register_catalog("tpch", create_tpch_connector())
    out = dr.execute(
        "EXPLAIN ANALYZE select count(*) from nation"
    ).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"replicas= .*", "replicas= #", text)
        text = re.sub(r"pinned_bytes=\d+", "pinned_bytes=#", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "09_resident_analyze.txt",
        ("resident fast-lane lifecycle (miss -> hit -> delta append -> "
         "DML eviction\n-> rebuild); every lookup answer is "
         "oracle-equal to the cold path", "\n".join(events)),
        ("distributed EXPLAIN ANALYZE: the trailing resident= line "
         "(process-global\npin population + lifetime counters; byte "
         "counts redacted to `#`)", redact(out)),
    )


def corpus_10_adaptive_analyze():
    """The adaptive execution tier (trino_tpu/adaptive/): the same
    distributed query analyzed with adaptive execution OFF (baseline —
    no estimate/observation deltas reported) and ON with a permissive
    re-plan threshold. The build side's modulo filter is exactly the
    shape the stats heuristics misestimate, so the adaptive run crosses
    the divergence gate at the build barrier, re-plans the remainder
    seeded with observed stats, and reports: per-fragment
    estimated_vs_observed lines in the stage rollup, the adaptive
    counters line, and the per-barrier observation that triggered the
    re-plan. Wall-clock values and the content-addressed spool key are
    redacted to `#`."""
    import re

    from trino_tpu.adaptive import SPOOL
    from trino_tpu.runtime import DistributedQueryRunner, Worker

    # the spool is process-wide; a leftover entry from an earlier run in
    # the same process would flip spool_stores=1 to spool_hits=1
    SPOOL.clear()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [Worker(f"corpus-aw{i}", cats) for i in range(2)]
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny"),
        worker_handles=workers,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    sql = (
        "select count(*) from supplier s "
        "join nation n on s_nationkey = n_nationkey "
        "where n_nationkey % 2 = 0"
    )
    off = r.execute("EXPLAIN ANALYZE " + sql).rows[0][0]

    workers_on = [Worker(f"corpus-aw{i+2}", cats) for i in range(2)]
    r_on = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            adaptive_execution=True,
            adaptive_replan_threshold=1.3,
        ),
        worker_handles=workers_on,
        hash_partitions=2,
    )
    r_on.register_catalog("tpch", create_tpch_connector())
    on = r_on.execute("EXPLAIN ANALYZE " + sql).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"replicas= .*", "replicas= #", text)
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        text = re.sub(r"spool=[0-9a-f]+", "spool=#", text)
        return text

    emit(
        "10_adaptive_analyze.txt",
        (f"QUERY\n{sql}", ""),
        ("adaptive_execution = off  (estimates never checked against "
         "observations;\nthe misestimated build side rides through "
         "silently)", redact(off)),
        ("adaptive_execution = on, adaptive_replan_threshold = 1.3  "
         "(the build\nbarrier observes 13 rows against an estimate of "
         "8.25, crosses the\nthreshold, and re-plans the remainder with "
         "the completed build spooled\nas a literal source; "
         "per-fragment estimated_vs_observed lines land in\nthe stage "
         "rollup and the adaptive section closes the report)",
         redact(on)),
    )


def corpus_11_recovery_analyze():
    """The recovery tier (trino_tpu/recovery/): a chunked mesh query
    with `mesh_checkpoint_interval_chunks` set snapshots its device
    carries at checkpoint boundaries; an injected MeshDeviceLost
    mid-run resumes from the last checkpoint instead of chunk 0 (the
    already-accumulated chunks are never re-executed and the resumed
    stretch lands on the same warm ladder rungs), oracle-equal to the
    uninterrupted run. The trailing `recovery=` line of EXPLAIN ANALYZE
    pins the lifetime counters and the `resumed_from_chunk=k/K`
    position of the most recent mesh run (ANALYZE itself executes the
    task plane to collect per-operator stats, so the faulted run comes
    first). Counters are reset up front so the numbers are exact;
    timings redacted as in corpus 07."""
    import re

    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.runtime import DistributedQueryRunner

    CHECKPOINTS.clear()
    CHECKPOINTS.reset_stats()
    METRICS.remove("recovery.spooled_stage_hits")
    r = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            mesh_chunk_rows=1024, mesh_checkpoint_interval_chunks=2,
        ),
        n_workers=2,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    sql = (
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag"
    )
    # one clean run to learn the chunk geometry (and warm the ladder)
    clean = r.execute(sql).rows
    clean_taken = CHECKPOINTS.taken
    n_chunks = mesh_chunk.LAST_RUN_INFO["chunks"]
    target = n_chunks - 2  # fault late: most chunks already settled
    state = {"fired": False}

    def fault_once(k, K):
        if not state["fired"] and k == target:
            state["fired"] = True
            raise mesh_chunk.MeshDeviceLost(
                f"injected device loss at chunk {k}/{K}"
            )

    mesh_chunk.MESH_FAULT_HOOK = fault_once
    try:
        faulted = r.execute(sql).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert state["fired"], "fault hook never reached its target chunk"
    info = mesh_chunk.LAST_RUN_INFO
    events = [
        f"clean run: chunks={n_chunks} "
        f"checkpoints_taken={clean_taken}",
        f"device loss injected at chunk {target}/{n_chunks}",
        f"resumed_from_chunk={info['resumed_from_chunk']} "
        f"resumes={info['resumes']} "
        f"executed_chunk_steps={info['executed_chunk_steps']} "
        "(completed chunks never re-executed)",
        f"rows oracle-equal to uninterrupted run: {faulted == clean}",
    ]
    out = r.execute("EXPLAIN ANALYZE " + sql).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"replicas= .*", "replicas= #", text)
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "11_recovery_analyze.txt",
        (f"QUERY\n{sql}", ""),
        ("checkpointed mesh run under an injected device loss "
         "(mesh_chunk_rows=1024,\nmesh_checkpoint_interval_chunks=2): "
         "the run resumes from the last checkpoint\ninstead of chunk 0 "
         "and stays on the mesh plane", "\n".join(events)),
        ("EXPLAIN ANALYZE after the faulted run: the trailing "
         "recovery= line reports\nthe lifetime checkpoint/resume "
         "counters plus the resume position of the\nmost recent mesh "
         "run (wall-clock values redacted to `#`)", redact(out)),
    )


def corpus_12_skew_analyze():
    """The skew-aware join plane (ISSUE 16): a build side whose modal
    key holds 40% of its rows crosses skew_hot_key_threshold at the
    adaptive build barrier — the controller classifies the heavy hitter
    from OBSERVED stats (never estimates), annotates the join with
    skew_hot_keys (salted repartition on the mesh plane: hot build rows
    replicate over all_gather, hot probe rows salt across shards), and
    the adaptive report grows a `skew:` line. Separately the MXU
    join-project kernel (ops/mxu_join.py) takes a high-fanout
    agg-over-join on the local path without ever expanding the pair
    batch. The trailing `skew=` line of distributed EXPLAIN ANALYZE
    pins the lifetime counters; they are reset up front so the numbers
    are exact. Timings redacted as in corpus 07."""
    import re

    from trino_tpu.adaptive import SPOOL
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime import DistributedQueryRunner, Worker

    SPOOL.clear()
    for c in ("heavy_hitters_detected", "salted_exchanges",
              "mxu_join_selected", "spill_mode_replans"):
        METRICS.remove(f"skew.{c}")

    def load(conn):
        rng = np.random.default_rng(23)
        n, nk = 2000, 40
        conn.load_table(
            "s", "facts",
            [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
            [rng.integers(0, nk, n).astype(np.int64),
             rng.integers(0, 100, n).astype(np.int64)],
        )
        # build side with a 40% modal key (key 0): the heavy hitter
        bk = np.concatenate([
            np.zeros(160, dtype=np.int64),
            rng.integers(1, nk, 240).astype(np.int64),
        ])
        conn.load_table(
            "s", "hot_dim",
            [ColumnMetadata("k", T.BIGINT), ColumnMetadata("name", T.VARCHAR)],
            [bk, np.array([f"g{i % 6}" for i in range(bk.size)],
                          dtype=object)],
        )
        return conn

    sql = (
        "select d.name, sum(f.v), count(*) from facts f "
        "join hot_dim d on f.k1 = d.k group by d.name order by 1"
    )

    # 1. MXU join-project on the local path (fanout 10 x ndv 40)
    lr = LocalQueryRunner(Session(
        catalog="memory", schema="s",
        mxu_join_enabled=True, mxu_join_min_work=16.0,
    ))
    lr.register_catalog("memory", load(MemoryConnector()))
    mxu_rows = lr.execute(sql).rows
    events = [
        f"local MXU join-project: {len(mxu_rows)} groups, "
        f"mxu_join_selected="
        f"{int(METRICS.snapshot().get('skew.mxu_join_selected', 0.0))}",
    ]

    # 2. heavy-hitter classification at the adaptive build barrier
    cats = CatalogManager()
    cats.register("memory", load(MemoryConnector()))
    workers = [Worker(f"corpus-sw{i}", cats) for i in range(2)]
    r = DistributedQueryRunner(
        Session(
            catalog="memory", schema="s",
            adaptive_execution=True,
            skewed_join_salting=True,
            skew_hot_key_threshold=0.2,
        ),
        worker_handles=workers,
        hash_partitions=2,
    )
    r.register_catalog("memory", load(MemoryConnector()))
    out = r.execute("EXPLAIN ANALYZE " + sql).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"replicas= .*", "replicas= #", text)
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"spool=[0-9a-f]+", "spool=#", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "12_skew_analyze.txt",
        (f"QUERY\n{sql}", ""),
        ("MXU join-project selection (mxu_join_enabled=true): the "
         "grouped aggregate\nover the inner join lowers to the "
         "indicator-matmul kernel — per-key sums\non the systolic "
         "array, no pair expansion", "\n".join(events)),
        ("distributed EXPLAIN ANALYZE with adaptive_execution=true, "
         "skewed_join_salting\n=true (hot_dim's modal key holds 40% of "
         "build rows > skew_hot_key_threshold\n=0.2: the build barrier "
         "classifies it from observed stats, the adaptive\nsection "
         "grows its skew: line, and the join is annotated for salted "
         "mesh\nrepartition; the trailing skew= line pins the lifetime "
         "counters)", redact(out)),
    )


def corpus_13_replica_analyze():
    """The replicated serving plane (trino_tpu/runtime/replicas.py): the
    8-device corpus mesh carved into two 4-wide sub-meshes. Two warm
    runs alternate across the replicas (round-robin placement — each
    sub-mesh pays its device-set lowering once); an injected
    MeshDeviceLost on the replica serving the third run fails the query
    over to its sibling, which resumes from the host-portable
    checkpoint. The trailing `replicas=` line of EXPLAIN ANALYZE pins
    the grid shape, per-replica lifecycle states and THIS runner's
    placement/failover counters — instance-scoped, so the numbers are
    exact. Timings redacted as in corpus 07."""
    import re

    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.runtime import DistributedQueryRunner

    CHECKPOINTS.clear()
    r = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            mesh_replicas=2, mesh_chunk_rows=1024,
            mesh_checkpoint_interval_chunks=1, mesh_resume_attempts=0,
        ),
        n_workers=2,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    sql = (
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag"
    )
    # two warm runs: sequential placements alternate replicas, so both
    # sub-meshes hold warm programs before the fault
    clean = r.execute(sql).rows
    r.execute(sql)
    n_chunks = mesh_chunk.LAST_RUN_INFO["chunks"]
    target = n_chunks - 2
    state = {"victim": None, "fired": False}

    def kill_victim(k, K):
        rep = mesh_chunk.active_replica()
        if rep is None:
            return
        if state["victim"] is None:
            state["victim"] = rep
        if not state["fired"] and rep == state["victim"] and k >= target:
            state["fired"] = True
            raise mesh_chunk.MeshDeviceLost(
                f"injected: replica {rep} lost at chunk {k}/{K}"
            )

    mesh_chunk.MESH_FAULT_HOOK = kill_victim
    try:
        faulted = r.execute(sql).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert state["fired"], "fault hook never reached its target chunk"
    info = mesh_chunk.LAST_RUN_INFO
    rm = r._replicas
    events = [
        f"grid: {rm.n_replicas} replicas x {rm.partition_width} devices "
        "(two 4-wide sub-meshes of the 8-device corpus mesh)",
        f"replica {state['victim']} lost at chunk "
        f"{target}/{n_chunks}",
        f"failover: resumed_from_chunk={info['resumed_from_chunk']} "
        f"on the sibling sub-mesh (failovers={rm.failovers})",
        f"rows oracle-equal to the uninterrupted run: {faulted == clean}",
    ]
    out = r.execute("EXPLAIN ANALYZE " + sql).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "13_replica_analyze.txt",
        (f"QUERY\n{sql}", ""),
        ("replica failover under an injected device loss "
         "(mesh_replicas=2): the\nquery resumes on the sibling sub-mesh "
         "from the host-portable checkpoint\ninstead of restarting at "
         "chunk 0", "\n".join(events)),
        ("EXPLAIN ANALYZE after the failover: the trailing replicas= "
         "line reports\nthe grid shape, per-replica lifecycle states "
         "(a=active) and this runner's\ninstance-scoped "
         "placement/failover counters (wall-clock values redacted\nto "
         "`#`)", redact(out)),
    )


def corpus_14_scheduler_analyze():
    """The preemptive mesh scheduler (trino_tpu/runtime/scheduler.py):
    a chunked analytic streams chunk-steps on the full-width mesh; a
    fast-lane point lookup (dimension-decorated, serving/admission.py
    `is_fast_lane`) arrives mid-stream and PREEMPTS it — the analytic
    parks (device carries snapshot to the host checkpoint store, device
    memory released), the lookup runs, and the analytic resumes from
    chunk k on the same warm rungs: zero re-executed chunk-steps,
    byte-identical rows. The trailing `scheduler=` line of EXPLAIN
    ANALYZE pins the park/resume/preemption counters — instance-scoped,
    so the numbers are exact. Timings redacted as in corpus 07."""
    import re
    import threading
    import time

    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.runtime import DistributedQueryRunner

    CHECKPOINTS.clear()
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", mesh_chunk_rows=1024),
        n_workers=2,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    analytic = (
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag"
    )
    point = (
        "select n_name, r_name from nation join region "
        "on n_regionkey = r_regionkey where n_nationkey = 3"
    )
    # warm both shapes solo: every program below re-dispatches cached
    # rungs, so the preempted run demonstrably mints zero new lowerings
    clean = r.execute(analytic).rows
    n_chunks = mesh_chunk.LAST_RUN_INFO["chunks"]
    point_clean = r.execute(point).rows
    state = {"fired": False, "point_rows": None}
    main_thread = threading.current_thread()

    def inject_point(k, K):
        # fire once, on the analytic's chunk loop only (the point
        # lookup is single-chunk, and its run is on another thread)
        if threading.current_thread() is not main_thread:
            return
        if state["fired"] or k < 1 or K < 3:
            return
        state["fired"] = True

        def run_point():
            state["point_rows"] = r.execute(point).rows

        threading.Thread(target=run_point, daemon=True).start()
        # hold this boundary until the fast submission reaches the run
        # queue, so the NEXT boundary deterministically parks
        sched = r._mesh_scheduler
        deadline = time.monotonic() + 10.0
        while (
            sched.waiting_count(fast=True) < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.002)

    mesh_chunk.MESH_FAULT_HOOK = inject_point
    try:
        parked_rows = r.execute(analytic).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert state["fired"], "preempt hook never fired"
    info = mesh_chunk.LAST_RUN_INFO
    assert info["parks"] == 1, f"expected exactly one park: {info}"
    deadline = time.monotonic() + 10.0
    while state["point_rows"] is None and time.monotonic() < deadline:
        time.sleep(0.002)
    events = [
        f"analytic: {n_chunks} chunk-steps on the full-width mesh; a "
        "fast-lane point lookup arrived at chunk 1",
        f"park: parks={info['parks']} — carries snapshotted to the "
        "host checkpoint store, device memory released, lookup granted "
        "the mesh",
        f"point lookup rows == warm solo run: "
        f"{state['point_rows'] == point_clean}",
        f"resume: unparks={info['unparks']}, "
        f"executed_chunk_steps={info['executed_chunk_steps']} "
        f"(== {n_chunks}: zero re-executed chunk-steps)",
        f"rows byte-identical to the uninterrupted run: "
        f"{parked_rows == clean}",
    ]
    out = r.execute("EXPLAIN ANALYZE " + analytic).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        # corpus 15 pins the real membership= line
        text = re.sub(r"membership= .*", "membership= #", text)
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "14_scheduler_analyze.txt",
        (f"QUERY\n{analytic}", ""),
        ("checkpoint-backed preemption on one mesh: a fast-lane point "
         "lookup\narriving mid-stream parks the running analytic at the "
         "next chunk boundary\nand the analytic resumes from chunk k "
         "warm — zero re-executed chunk-steps,\nbyte-identical rows",
         "\n".join(events)),
        ("EXPLAIN ANALYZE after the park/resume cycle: the trailing "
         "scheduler=\nline reports this runner's instance-scoped "
         "park/resume/preemption\ncounters (wall-clock values redacted "
         "to `#`)", redact(out)),
    )


def corpus_15_fabric_analyze():
    """The multi-host replica fabric (trino_tpu/runtime/fabric.py).
    Two legs. Transport: a loopback FabricServer fronting a peer
    HostFabric takes a framed checkpoint push, serves it back
    byte-identical, and refuses a corrupted payload typed on its
    sha256 digest — instance-scoped endpoint counters pin the
    exchange. Membership: a replicated runner suffers a sibling
    membership flap (leave + rejoin, each bumping the monotonic
    epoch) immediately followed by a device loss on the serving
    replica; failover resumes on the rejoined sibling because its
    join_epoch equals the fault epoch, while a resume context
    captured BEFORE the flap is refused typed (MembershipEpochError).
    The trailing `membership=` line of EXPLAIN ANALYZE pins the epoch
    and join/leave/fence counters — instance-scoped, so the numbers
    are exact. Timings redacted as in corpus 07."""
    import re

    from trino_tpu.parallel import mesh_chunk
    from trino_tpu.recovery import CHECKPOINTS
    from trino_tpu.recovery.checkpoint import (
        MeshCheckpoint,
        MeshCheckpointStore,
    )
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.fabric import (
        HostFabric,
        MembershipEpochError,
        checkpoint_digest,
    )
    from trino_tpu.runtime.http import FabricClient, FabricServer

    # -- transport leg: push / pull / corrupt over a loopback endpoint
    peer_store = MeshCheckpointStore()
    peer = HostFabric(store=peer_store, host_id="peer")
    srv = FabricServer(peer, internal_secret=None, require_secret=False)
    client = FabricClient(srv.uri, internal_secret=None)
    key = ("corpus15", "fabric", 0)
    data = MeshCheckpoint(
        next_chunk=3, n_chunks=8, chunk_cap=64,
        resolved_caps={"rows": 64},
        carries_host=(
            np.arange(64, dtype=np.int64),
            np.linspace(0.0, 1.0, 64),
        ),
        tables=(), generations=(),
    ).to_bytes()
    pushed = client.push_checkpoint(key, data)
    back, digest = client.pull_checkpoint(key)
    corrupt = bytearray(data)
    corrupt[len(corrupt) // 2] ^= 0xFF
    # original digest over corrupted bytes: the endpoint must refuse
    rejected = client.push_checkpoint(
        key, bytes(corrupt), digest=checkpoint_digest(data)
    )
    stored = peer_store.export_bytes(key)
    srv.stop()
    transport = [
        "peer endpoint: HostFabric behind a loopback FabricServer "
        "(single-process\nembedding, require_secret=False; a networked "
        "fabric refuses to start\nwithout TRINO_TPU_INTERNAL_SECRET)",
        f"push accepted: imported={pushed.get('imported')} — the "
        "encoded checkpoint key\ntravels length-prefixed in the request "
        "BODY, never the request line",
        f"pull round-trip byte-identical: {back == data} (digest "
        f"match: {digest == checkpoint_digest(data)})",
        "corrupted payload under the original digest refused typed: "
        f"imported={rejected.get('imported')} "
        f"reason={rejected.get('reason')}",
        f"stored entry unpoisoned by the refused push: {stored == data}",
        f"endpoint counters: received={peer.received} "
        f"served={peer.served} digest_rejects={peer.digest_rejects}",
    ]

    # -- membership leg: flap + host loss on a replicated runner ------
    CHECKPOINTS.clear()
    r = DistributedQueryRunner(
        Session(
            catalog="tpch", schema="tiny",
            mesh_replicas=2, mesh_chunk_rows=1024,
            mesh_checkpoint_interval_chunks=1, mesh_resume_attempts=0,
        ),
        n_workers=2,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    sql = (
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag"
    )
    # two warm runs: round-robin placement warms both sub-meshes
    clean = r.execute(sql).rows
    r.execute(sql)
    n_chunks = mesh_chunk.LAST_RUN_INFO["chunks"]
    target = n_chunks - 2
    state = {"victim": None, "fired": False, "pre_epoch": None}

    def flap_then_kill(k, K):
        rep = mesh_chunk.active_replica()
        if rep is None:
            return
        if state["victim"] is None:
            state["victim"] = rep
        if not state["fired"] and rep == state["victim"] and k >= target:
            state["fired"] = True
            rm_ = r._replicas
            state["pre_epoch"] = rm_.membership_epoch
            # sibling flaps (heartbeat loss + recovery) just before the
            # serving replica dies: two epoch bumps, then the fault
            rm_.leave(1 - rep)
            rm_.join(1 - rep)
            raise mesh_chunk.MeshDeviceLost(
                f"injected: replica {rep} lost at chunk {k}/{K} "
                "after a sibling membership flap"
            )

    mesh_chunk.MESH_FAULT_HOOK = flap_then_kill
    try:
        faulted = r.execute(sql).rows
    finally:
        mesh_chunk.MESH_FAULT_HOOK = None
    assert state["fired"], "fault hook never reached its target chunk"
    info = mesh_chunk.LAST_RUN_INFO
    rm = r._replicas
    sib = 1 - state["victim"]
    # a resume context captured BEFORE the flap is stale: the sibling's
    # join_epoch has moved past it, so the fence refuses it typed
    try:
        rm.require_epoch(rm.replicas[sib], state["pre_epoch"])
        fenced = False
    except MembershipEpochError:
        fenced = True
    events = [
        f"grid: {rm.n_replicas} replicas x {rm.partition_width} "
        f"devices; membership epoch starts at {state['pre_epoch']}",
        f"flap: replica {sib} left and rejoined mid-run (epoch "
        f"{state['pre_epoch']} -> {rm.membership_epoch}: every leave "
        "and join bumps it)",
        f"replica {state['victim']} lost at chunk {target}/{n_chunks}; "
        f"failover resumed_from_chunk={info['resumed_from_chunk']} on "
        "the rejoined sibling\n(its join_epoch equals the fault epoch, "
        "so the resume is admitted)",
        f"rows oracle-equal to the uninterrupted run: {faulted == clean}",
        f"stale resume context (epoch {state['pre_epoch']}, captured "
        "before the flap)\nrefused typed with MembershipEpochError: "
        f"{fenced}",
    ]
    out = r.execute("EXPLAIN ANALYZE " + sql).rows[0][0]

    def redact(text):
        text = re.sub(r"\b(wall|cpu)=\d+(\.\d+)?ms", r"\1=#ms", text)
        text = re.sub(r"\b(add|get|finish)=\d+(\.\d+)?", r"\1=#", text)
        text = re.sub(r"\btask q\d+\.", "task q#.", text)
        text = re.sub(r"replicas= .*", "replicas= #", text)
        text = re.sub(r"resident= .*", "resident= #", text)
        text = re.sub(r"recovery= .*", "recovery= #", text)
        text = re.sub(r"skew= .*", "skew= #", text)
        # process-global witness registry: lock/thread counts depend
        # on what ran before — corpus 16 pins the analyzer itself
        text = re.sub(r"concurrency= .*", "concurrency= #", text)
        return text

    emit(
        "15_fabric_analyze.txt",
        (f"QUERY\n{sql}", ""),
        ("checkpoint transport across the host boundary: framed "
         "push/pull with\nsha256 content digests; a corrupted payload "
         "is refused typed and never\npoisons the receiving store",
         "\n".join(transport)),
        ("heartbeat-driven membership under a flap + host loss "
         "(mesh_replicas=2):\nthe rejoined sibling resumes from the "
         "host-portable checkpoint; a\npre-flap resume context is "
         "fenced on the membership epoch",
         "\n".join(events)),
        ("EXPLAIN ANALYZE after the flap + failover: the trailing "
         "membership=\nline reports the monotonic epoch and this "
         "runner's instance-scoped\njoin/leave/fence counters "
         "(wall-clock values redacted to `#`)", redact(out)),
    )


# deliberately-broken fixture modules for corpus 16: a two-lock order
# cycle and a bare write to a guarded_by-annotated global. Analyzed
# in-memory (never imported), so the file:line coordinates are stable.
_CYCLE_FIXTURE = """\
from trino_tpu.analysis.witness import named_lock

_lock_a = named_lock("deadlock_fixture._lock_a")
_lock_b = named_lock("deadlock_fixture._lock_b")


def forward():
    with _lock_a:
        with _lock_b:
            pass


def backward():
    with _lock_b:
        with _lock_a:
            pass
"""

_BARE_WRITE_FIXTURE = """\
from trino_tpu.analysis.witness import named_lock

_cache_lock = named_lock("bare_write_fixture._cache_lock")
CACHE = {}  # guarded_by: _cache_lock


def bad_write(key, value):
    CACHE[key] = value
"""


def corpus_16_concurrency_analyze():
    """The concurrency soundness plane (trino_tpu/analysis/): the pinned
    output of the static lock-order / shared-state analyzer over the
    whole package — the lock inventory, the may-hold-while-acquiring
    order, and zero findings — plus the analyzer's findings on two
    deliberately broken fixture modules, showing what a violation report
    looks like (cycle with both witness paths; bare guarded write)."""
    from trino_tpu.analysis import analyze_package, analyze_sources

    rep = analyze_package()
    s = rep.summary()
    summary = "\n".join(f"{k}={v}" for k, v in s.items())
    order = "\n".join(
        f"{a} -> {b}" for a, b in sorted(rep.graph.edges)
    ) or "(no lock is ever acquired while another is held)"

    bad = analyze_sources({
        "deadlock_fixture": (
            "fixtures/deadlock_fixture.py", _CYCLE_FIXTURE),
        "bare_write_fixture": (
            "fixtures/bare_write_fixture.py", _BARE_WRITE_FIXTURE),
    })
    findings = "\n".join(
        f"[{f.kind}] {f.file}:{f.line}\n  {f.message}"
        for f in bad.findings
    )

    emit(
        "16_concurrency_analyze.txt",
        ("QUERY\nbench.py --analyze  (trino_tpu/analysis/ static passes)",
         ""),
        ("whole-package summary (the CI gate's JSON, one key per line; "
         "a diff\nhere means the engine's locking structure actually "
         "changed)", summary),
        ("the may-hold-while-acquiring order — every (held, acquired) "
         "pair the\nstatic pass can prove, including through call "
         "edges; the runtime\nwitness seeds its partial order from "
         "these", order),
        ("analyzer findings on two deliberately broken fixture modules "
         "(the\nsame fixtures tests/test_concurrency_analysis.py "
         "asserts on): a\ntwo-lock acquisition cycle reported with "
         "both witness paths, and a\nbare write to a guarded_by-"
         "annotated global", findings),
    )


def write_all(out_dir=None):
    """Regenerate every corpus file (into `out_dir` when given — used
    by tests/test_explain_corpus.py to diff against committed files)."""
    if out_dir is not None:
        _OUT_DIR[0] = out_dir
    try:
        corpus_01_transitive()
        corpus_02_scan_pushdown()
        corpus_03_partial_agg()
        corpus_04_elided_exchange()
        corpus_05_plan_validation()
        corpus_06_compile_regime()
        corpus_07_distributed_analyze()
        corpus_08_mesh_analyze()
        corpus_09_resident_analyze()
        corpus_10_adaptive_analyze()
        corpus_11_recovery_analyze()
        corpus_12_skew_analyze()
        corpus_13_replica_analyze()
        corpus_14_scheduler_analyze()
        corpus_15_fabric_analyze()
        corpus_16_concurrency_analyze()
    finally:
        _OUT_DIR[0] = HERE


if __name__ == "__main__":
    write_all()
