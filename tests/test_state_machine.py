"""StateMachine listeners/latching (runtime/state_machine.py —
StateMachine.java:44 analogue) and its task-lifecycle integration."""

import threading

from trino_tpu.runtime.state_machine import (
    StateMachine,
    query_state_machine,
    task_state_machine,
)


def test_transitions_and_listeners():
    sm = StateMachine("q1", "queued", ("finished", "failed"))
    seen = []
    sm.add_listener(seen.append)
    assert seen == ["queued"]  # immediate fire with current state
    assert sm.set("running")
    assert sm.set("finished")
    assert seen == ["queued", "running", "finished"]


def test_terminal_latches():
    sm = StateMachine("t", "running", ("finished", "failed"))
    assert sm.set("failed")
    assert not sm.set("finished")  # terminal latched
    assert sm.get() == "failed"
    assert sm.is_terminal()


def test_compare_and_set():
    sm = StateMachine("t", "a", ())
    assert not sm.compare_and_set("b", "c")
    assert sm.compare_and_set("a", "b")
    assert sm.get() == "b"


def test_wait_for_unblocks():
    sm = query_state_machine("q")
    done = []

    def waiter():
        done.append(sm.wait_for(lambda s: s == "finished", timeout=5))

    t = threading.Thread(target=waiter)
    t.start()
    sm.set("running")
    sm.set("finished")
    t.join(5)
    assert done == ["finished"]


def test_wait_for_timeout():
    sm = StateMachine("t", "a", ())
    assert sm.wait_for(lambda s: s == "never", timeout=0.05) == "a"


def test_listener_may_reenter():
    # listeners fire outside the lock: re-entrant calls must not deadlock
    sm = StateMachine("t", "a", ("z",))
    calls = []

    def listener(s):
        calls.append(s)
        if s == "b":
            sm.set("z")

    sm.add_listener(listener)
    sm.set("b")
    assert sm.get() == "z"
    assert calls == ["a", "b", "z"]


def test_task_execution_uses_state_machine():
    from trino_tpu.runtime.state_machine import TASK_TERMINAL
    from trino_tpu.runtime.task import TaskExecution, TaskId, TaskSpec
    from trino_tpu.sql.fragmenter import PlanFragment
    from trino_tpu.sql.plan import Field, ValuesNode
    from trino_tpu import types as T

    node = ValuesNode((Field("a", T.BIGINT),), ((1,), (2,)))
    frag = PlanFragment(0, node, "single", "single")
    spec = TaskSpec(
        task_id=TaskId("q0", 0, 0),
        fragment=frag,
        n_output_partitions=1,
        remote_schemas={},
        scan_slice=None,
        input_locations={},
    )
    t = TaskExecution(spec, None)
    states = []
    t.add_state_listener(states.append)
    t.start()
    t.join(10)
    assert t.state == "finished"
    assert states[0] == "planned" and states[-1] in TASK_TERMINAL
    # terminal latch: abort after finish keeps the verdict
    t.abort()
    assert t.state == "finished"


# -- metrics registry (runtime/metrics.py, JMX surface analogue) --


def test_metrics_registry():
    from trino_tpu.runtime.metrics import MetricsRegistry

    m = MetricsRegistry()
    m.increment("a")
    m.increment("a", 2)
    m.register_gauge("g", lambda: 7.5)
    m.register_gauge("bad", lambda: 1 / 0)  # must not poison snapshots
    snap = m.snapshot()
    assert snap["a"] == 3.0 and snap["g"] == 7.5 and "bad" not in snap


def test_metrics_endpoint():
    import json
    import urllib.request

    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import LocalQueryRunner, Session
    from trino_tpu.runtime.metrics import METRICS
    from trino_tpu.runtime.server import CoordinatorServer
    from trino_tpu.client import Client

    lq = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    lq.register_catalog("tpch", create_tpch_connector())
    srv = CoordinatorServer(lq)
    try:
        before = METRICS.counter("queries.finished")
        Client(srv.uri).execute("select 1")
        snap = json.loads(
            urllib.request.urlopen(f"{srv.uri}/v1/metrics").read()
        )
        assert snap["queries.submitted"] >= 1
        assert snap["queries.finished"] >= before + 1
    finally:
        srv.stop()
