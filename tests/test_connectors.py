"""Connector tests: TPC-H generator invariants, memory store round-trip,
blackhole sink — tier-1 analogue of the reference's per-plugin tests."""

import sqlite3

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.block import RelBatch
from trino_tpu.connectors.blackhole import create_blackhole_connector
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.connectors.tpch import (
    TABLES,
    base_row_count,
    create_tpch_connector,
    generate_column,
    lineitem_row_count,
    order_index_to_key,
)

SF = 0.01  # tiny


def test_row_counts_tiny():
    assert base_row_count("region", SF) == 5
    assert base_row_count("nation", SF) == 25
    assert base_row_count("customer", SF) == 1500
    assert base_row_count("orders", SF) == 15000
    assert base_row_count("supplier", SF) == 100
    assert base_row_count("part", SF) == 2000
    assert base_row_count("partsupp", SF) == 8000
    # lineitem ~4x orders
    n = lineitem_row_count(SF)
    assert 15000 * 3 < n < 15000 * 5


def test_determinism_and_split_independence():
    full, _ = generate_column("orders", "o_custkey", SF, 0, 1000)
    again, _ = generate_column("orders", "o_custkey", SF, 0, 1000)
    np.testing.assert_array_equal(full, again)
    a, _ = generate_column("orders", "o_custkey", SF, 0, 400)
    b, _ = generate_column("orders", "o_custkey", SF, 400, 1000)
    np.testing.assert_array_equal(full, np.concatenate([a, b]))


def test_lineitem_split_independence():
    full, _ = generate_column("lineitem", "l_extendedprice", SF, 0, 500)
    a, _ = generate_column("lineitem", "l_extendedprice", SF, 0, 123)
    b, _ = generate_column("lineitem", "l_extendedprice", SF, 123, 500)
    np.testing.assert_array_equal(full, np.concatenate([a, b]))


def test_custkey_never_divisible_by_3():
    ck, _ = generate_column("orders", "o_custkey", SF, 0, 15000)
    assert (ck % 3 != 0).all()
    assert ck.min() >= 1
    assert ck.max() <= 1500


def test_referential_integrity_lineitem_orders():
    lk, _ = generate_column("lineitem", "l_orderkey", SF, 0, 15000)
    ok, _ = generate_column("orders", "o_orderkey", SF, 0, 15000)
    assert set(np.unique(lk)) <= set(ok.tolist())


def test_partsupp_covers_lineitem_pairs():
    lp, _ = generate_column("lineitem", "l_partkey", SF, 0, 2000)
    ls, _ = generate_column("lineitem", "l_suppkey", SF, 0, 2000)
    pp, _ = generate_column("partsupp", "ps_partkey", SF, 0, 8000)
    ps, _ = generate_column("partsupp", "ps_suppkey", SF, 0, 8000)
    pairs = set(zip(pp.tolist(), ps.tolist()))
    lpairs = set(zip(lp.tolist(), ls.tolist()))
    assert lpairs <= pairs


def test_sparse_orderkeys():
    idx = np.arange(16, dtype=np.int64)
    keys = order_index_to_key(idx)
    assert keys[:8].tolist() == [1, 2, 3, 4, 5, 6, 7, 8]
    assert keys[8:16].tolist() == [33, 34, 35, 36, 37, 38, 39, 40]


def test_string_dictionaries_decode():
    data, d = generate_column("lineitem", "l_returnflag", SF, 0, 100)
    vals = {d.values[c] for c in data}
    assert vals <= {"A", "N", "R"}
    data, d = generate_column("orders", "o_orderpriority", SF, 0, 100)
    assert all(d.values[c][0] in "12345" for c in data)


def test_comment_like_targets_exist():
    data, d = generate_column("orders", "o_comment", SF, 0, 15000)
    import re

    rx = re.compile("^.*special.*requests.*$")
    frac = np.mean([bool(rx.match(d.values[c])) for c in data])
    assert 0.001 < frac < 0.1


def test_dates_in_range():
    od, _ = generate_column("orders", "o_orderdate", SF, 0, 15000)
    import datetime

    lo = (datetime.date(1992, 1, 1) - datetime.date(1970, 1, 1)).days
    hi = (datetime.date(1998, 8, 2) - datetime.date(1970, 1, 1)).days
    assert od.min() >= lo and od.max() <= hi
    ship, _ = generate_column("lineitem", "l_shipdate", SF, 0, 100)
    commit, _ = generate_column("lineitem", "l_commitdate", SF, 0, 100)
    receipt, _ = generate_column("lineitem", "l_receiptdate", SF, 0, 100)
    assert (receipt > ship).all()


def test_page_source_batches():
    conn = create_tpch_connector()
    h = conn.metadata.get_table_handle("tiny", "customer")
    splits = conn.split_manager.get_splits(h, 4)
    assert len(splits) == 4
    total = 0
    for s in splits:
        for batch in conn.page_source.batches(s, ["c_custkey", "c_mktsegment"], 512):
            total += batch.row_count()
            assert batch.width == 2
    assert total == 1500


def test_tpch_table_stats():
    conn = create_tpch_connector()
    h = conn.metadata.get_table_handle("tiny", "lineitem")
    st = conn.metadata.get_table_statistics(h)
    assert st.row_count == lineitem_row_count(SF)


def test_sqlite_oracle_loads():
    from tests.oracle import load_tpch_sqlite, sqlite_rows

    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF, tables=["region", "nation", "customer"])
    assert sqlite_rows(conn, "SELECT count(*) FROM customer") == [(1500,)]
    rows = sqlite_rows(conn, "SELECT n_name FROM nation ORDER BY n_nationkey LIMIT 1")
    assert rows == [("ALGERIA",)]


# ---- memory connector ----


def test_memory_roundtrip():
    conn = create_memory_connector()
    h = conn.metadata.create_table(
        "default", "t",
        [ColumnMetadata("id", T.BIGINT), ColumnMetadata("name", T.VARCHAR)],
    )
    sink = conn.page_sink(h)
    sink.append(RelBatch.from_pydict(
        [("id", T.BIGINT), ("name", T.VARCHAR)],
        {"id": [1, 2, None], "name": ["x", None, "z"]},
    ))
    sink.append(RelBatch.from_pydict(
        [("id", T.BIGINT), ("name", T.VARCHAR)],
        {"id": [4], "name": ["a"]},
    ))
    assert sink.finish() == 4
    splits = conn.split_manager.get_splits(h, 1)
    rows = []
    for s in splits:
        for b in conn.page_source.batches(s, ["id", "name"], 1024):
            rows.extend(b.to_pylists())
    assert rows == [[1, "x"], [2, None], [None, "z"], [4, "a"]]


def test_memory_dictionary_grows_across_inserts():
    conn = create_memory_connector()
    h = conn.metadata.create_table("default", "t", [ColumnMetadata("s", T.VARCHAR)])
    sink = conn.page_sink(h)
    sink.append(RelBatch.from_pydict([("s", T.VARCHAR)], {"s": ["m", "z"]}))
    sink.append(RelBatch.from_pydict([("s", T.VARCHAR)], {"s": ["a"]}))
    d = conn.metadata.column_dictionary(h, "s")
    assert d.values == ("a", "m", "z")
    (split,) = conn.split_manager.get_splits(h, 1)
    rows = []
    for b in conn.page_source.batches(split, ["s"], 64):
        rows.extend(b.to_pylists())
    assert [r[0] for r in rows] == ["m", "z", "a"]


def test_blackhole():
    conn = create_blackhole_connector()
    h = conn.metadata.create_table("default", "sink", [ColumnMetadata("x", T.BIGINT)])
    sink = conn.page_sink(h)
    sink.append(RelBatch.from_pydict([("x", T.BIGINT)], {"x": [1, 2, 3]}))
    assert sink.finish() == 3
    (split,) = conn.split_manager.get_splits(h, 8)
    batches = list(conn.page_source.batches(split, ["x"], 64))
    assert sum(b.row_count() for b in batches) == 0
