"""Security (authenticators + access control) and transactions —
SURVEY.md §2.10 'Security' and 'Transactions' rows: the reference's
main/server/security/ authenticators, the AccessControl SPI with
file-based rules, and main/transaction/'s engine transaction manager
coordinating connector handles."""

import urllib.error
import urllib.request

import pytest

from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.security import (
    AccessDeniedError,
    AuthenticationError,
    FileBasedAccessControl,
    Identity,
    InsecureAuthenticator,
    JwtAuthenticator,
    PasswordAuthenticator,
)
from trino_tpu import types as T


def make_runner(access_control=None, user="alice"):
    r = LocalQueryRunner(
        Session(catalog="tpch", schema="tiny", user=user),
        access_control=access_control,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


class TestAccessControl:
    RULES = [
        {"user": "admin", "privileges": ["OWNERSHIP"]},
        {"user": "alice", "table": "nation|region", "privileges": ["SELECT"]},
        {"user": "bob", "privileges": ["SELECT", "INSERT"]},
    ]

    def test_allowed_select(self):
        r = make_runner(FileBasedAccessControl(self.RULES), user="alice")
        assert r.execute("SELECT count(*) FROM nation").only_value() == 25

    def test_denied_table(self):
        r = make_runner(FileBasedAccessControl(self.RULES), user="alice")
        with pytest.raises(AccessDeniedError):
            r.execute("SELECT count(*) FROM orders")

    def test_denied_join_partner(self):
        # every scanned table is checked, not just the first
        r = make_runner(FileBasedAccessControl(self.RULES), user="alice")
        with pytest.raises(AccessDeniedError):
            r.execute(
                "SELECT count(*) FROM nation, orders WHERE o_custkey = n_nationkey"
            )

    def test_no_rule_denies(self):
        r = make_runner(FileBasedAccessControl(self.RULES), user="mallory")
        with pytest.raises(AccessDeniedError):
            r.execute("SELECT 1 FROM nation")

    def test_plan_cache_rechecks(self):
        """The same SQL must re-check on every execution even when the
        plan is cached (a cached plan is not an authz grant)."""
        rules = FileBasedAccessControl(self.RULES)
        r = make_runner(rules, user="alice")
        sql = "SELECT count(*) FROM nation"
        assert r.execute(sql).only_value() == 25
        r.session.user = "mallory"
        with pytest.raises(AccessDeniedError):
            r.execute(sql)

    def test_ownership_gates_ddl(self):
        rules = FileBasedAccessControl(self.RULES)
        r = LocalQueryRunner(
            Session(catalog="memory", schema="s", user="bob"),
            access_control=rules,
        )
        r.register_catalog("memory", create_memory_connector())
        with pytest.raises(AccessDeniedError):
            r.execute("CREATE TABLE t (x bigint)")
        r.session.user = "admin"
        r.execute("CREATE TABLE t (x bigint)")
        r.session.user = "bob"  # INSERT granted, DROP not
        r.execute("INSERT INTO t VALUES (1)")
        with pytest.raises(AccessDeniedError):
            r.execute("DROP TABLE t")


class TestAuthenticators:
    def test_insecure_header(self):
        ident = InsecureAuthenticator().authenticate({"X-Trino-User": "zoe"})
        assert ident.user == "zoe"

    def test_password_roundtrip(self):
        import base64

        auth = PasswordAuthenticator(
            {"alice": PasswordAuthenticator.hash_password("secret")}
        )
        hdr = {
            "Authorization": "Basic "
            + base64.b64encode(b"alice:secret").decode()
        }
        assert auth.authenticate(hdr).user == "alice"
        bad = {
            "Authorization": "Basic "
            + base64.b64encode(b"alice:wrong").decode()
        }
        with pytest.raises(AuthenticationError):
            auth.authenticate(bad)

    def test_jwt_roundtrip_and_tamper(self):
        auth = JwtAuthenticator("sekrit")
        token = auth.issue("carol")
        assert (
            auth.authenticate({"Authorization": f"Bearer {token}"}).user
            == "carol"
        )
        tampered = token[:-2] + ("AA" if token[-2:] != "AA" else "BB")
        with pytest.raises(AuthenticationError):
            auth.authenticate({"Authorization": f"Bearer {tampered}"})
        with pytest.raises(AuthenticationError):
            JwtAuthenticator("other").authenticate(
                {"Authorization": f"Bearer {token}"}
            )

    def test_jwt_expiry(self):
        auth = JwtAuthenticator("sekrit")
        token = auth.issue("carol", ttl_seconds=-10)
        with pytest.raises(AuthenticationError):
            auth.authenticate({"Authorization": f"Bearer {token}"})

    def test_server_401(self):
        from trino_tpu.runtime.server import CoordinatorServer

        r = make_runner()
        srv = CoordinatorServer(
            r, authenticator=PasswordAuthenticator(
                {"alice": PasswordAuthenticator.hash_password("pw")}
            ),
        )
        try:
            req = urllib.request.Request(
                srv.uri + "/v1/statement", data=b"SELECT 1", method="POST"
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 401
            import base64

            req = urllib.request.Request(
                srv.uri + "/v1/statement", data=b"SELECT 1", method="POST",
                headers={
                    "Authorization": "Basic "
                    + base64.b64encode(b"alice:pw").decode()
                },
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
        finally:
            srv.stop()


class TestTransactions:
    def _memory_runner(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="s", user="u"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("CREATE TABLE t (x bigint)")
        return r

    def test_commit_publishes(self):
        r = self._memory_runner()
        r.execute("START TRANSACTION")
        r.execute("INSERT INTO t VALUES (1), (2)")
        # read-committed: pending writes not visible before commit
        assert r.execute("SELECT count(*) FROM t").only_value() == 0
        r.execute("COMMIT")
        assert r.execute("SELECT count(*) FROM t").only_value() == 2

    def test_rollback_discards(self):
        r = self._memory_runner()
        r.execute("START TRANSACTION")
        r.execute("INSERT INTO t VALUES (1)")
        r.execute("ROLLBACK")
        assert r.execute("SELECT count(*) FROM t").only_value() == 0

    def test_multi_statement_transaction(self):
        r = self._memory_runner()
        r.execute("START TRANSACTION")
        r.execute("INSERT INTO t VALUES (1)")
        r.execute("INSERT INTO t VALUES (2), (3)")
        r.execute("COMMIT")
        assert r.execute("SELECT count(*) FROM t").only_value() == 3

    def test_autocommit_without_transaction(self):
        r = self._memory_runner()
        r.execute("INSERT INTO t VALUES (7)")
        assert r.execute("SELECT count(*) FROM t").only_value() == 1

    def test_nested_begin_rejected(self):
        from trino_tpu.transaction import TransactionError

        r = self._memory_runner()
        r.execute("START TRANSACTION")
        with pytest.raises(TransactionError):
            r.execute("START TRANSACTION")

    def test_start_transaction_modifiers_parse(self):
        r = self._memory_runner()
        r.execute("START TRANSACTION ISOLATION LEVEL SERIALIZABLE, READ WRITE")
        r.execute("COMMIT")
        r.execute("START TRANSACTION READ ONLY")
        r.execute("ROLLBACK")


class TestReviewRegressions:
    def test_http_identity_drives_access_control(self):
        """The HTTP-authenticated principal, not the runner's static
        session user, decides access."""
        import base64
        import json
        import time

        from trino_tpu.runtime.server import CoordinatorServer

        rules = FileBasedAccessControl(
            [{"user": "alice", "privileges": ["SELECT"]}]
        )
        r = make_runner(rules, user="alice")  # static session user allowed
        srv = CoordinatorServer(
            r,
            authenticator=PasswordAuthenticator({
                "alice": PasswordAuthenticator.hash_password("a"),
                "mallory": PasswordAuthenticator.hash_password("m"),
            }),
        )
        try:
            def run_as(user, pw):
                hdr = {
                    "Authorization": "Basic "
                    + base64.b64encode(f"{user}:{pw}".encode()).decode()
                }
                req = urllib.request.Request(
                    srv.uri + "/v1/statement",
                    data=b"SELECT count(*) FROM nation",
                    method="POST", headers=hdr,
                )
                resp = json.load(urllib.request.urlopen(req, timeout=60))
                for _ in range(300):
                    if resp["stats"]["state"] in ("FINISHED", "FAILED"):
                        break
                    nxt = urllib.request.Request(resp["nextUri"], headers=hdr)
                    resp = json.load(urllib.request.urlopen(nxt, timeout=60))
                    time.sleep(0.05)
                return resp

            ok = run_as("alice", "a")
            assert ok["stats"]["state"] == "FINISHED", ok
            denied = run_as("mallory", "m")
            assert denied["stats"]["state"] == "FAILED"
            assert "Access Denied" in denied["error"]["message"]
        finally:
            srv.stop()

    def test_failed_commit_does_not_wedge_session(self):
        from trino_tpu.transaction import TransactionError

        r = LocalQueryRunner(Session(catalog="memory", schema="s", user="u"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("CREATE TABLE t (x bigint)")
        r.execute("START TRANSACTION")
        r.execute("INSERT INTO t VALUES (1)")
        r.execute("DROP TABLE t")  # makes the staged replay fail
        with pytest.raises(TransactionError):
            r.execute("COMMIT")
        # the session is usable again: a new transaction can start
        r.execute("START TRANSACTION")
        r.execute("ROLLBACK")

    def test_read_only_transaction_rejects_writes(self):
        from trino_tpu.transaction import TransactionError

        r = LocalQueryRunner(Session(catalog="memory", schema="s", user="u"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("CREATE TABLE t (x bigint)")
        r.execute("START TRANSACTION READ ONLY")
        with pytest.raises(TransactionError):
            r.execute("INSERT INTO t VALUES (1)")
        r.execute("ROLLBACK")
        assert r.execute("SELECT count(*) FROM t").only_value() == 0

    def test_commit_outside_transaction_raises(self):
        from trino_tpu.transaction import TransactionError

        r = make_runner()
        with pytest.raises(TransactionError):
            r.execute("COMMIT")
        with pytest.raises(TransactionError):
            r.execute("ROLLBACK")

    def test_isolation_level_two_word_forms(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="s", user="u"))
        r.register_catalog("memory", create_memory_connector())
        for level in (
            "READ COMMITTED", "READ UNCOMMITTED", "REPEATABLE READ",
            "SERIALIZABLE",
        ):
            r.execute(f"START TRANSACTION ISOLATION LEVEL {level}")
            r.execute("ROLLBACK")

    def test_unauthenticated_delete_rejected(self):
        from trino_tpu.runtime.server import CoordinatorServer

        r = make_runner()
        srv = CoordinatorServer(
            r, authenticator=PasswordAuthenticator(
                {"alice": PasswordAuthenticator.hash_password("pw")}
            ),
        )
        try:
            req = urllib.request.Request(
                srv.uri + "/v1/statement/executing/deadbeef", method="DELETE"
            )
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 401
        finally:
            srv.stop()
