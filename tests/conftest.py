"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's tier-3 strategy (SURVEY.md §4): Trino boots a
multi-node cluster inside one JVM (DistributedQueryRunner); we boot a
multi-device mesh inside one process via XLA's host-platform device
partitioning. Real-TPU runs use bench.py, not the test suite.

NOTE: this environment injects a sitecustomize that imports jax at
interpreter startup with JAX_PLATFORMS=axon already in the env, so
setting os.environ here is too late for jax's config default — we must
force the platform through jax.config *after* import, before any
backend is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """The full suite compiles 1000+ XLA programs in one process; this
    environment's XLA CPU compiler segfaults under that accumulated
    load (re-confirmed in r3: disabling this clearing crashed the run
    inside backend_compile — it is NOT the associative_scan issue,
    which r3 removed separately). Dropping compiled executables between
    modules bounds compiler state at the cost of per-module recompiles;
    TRINO_TPU_NO_CLEAR_CACHES=1 disables it for experiments."""
    yield
    if os.environ.get("TRINO_TPU_NO_CLEAR_CACHES") != "1":
        jax.clear_caches()
