"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's tier-3 strategy (SURVEY.md §4): Trino boots a
multi-node cluster inside one JVM (DistributedQueryRunner); we boot a
multi-device mesh inside one process via XLA's host-platform device
partitioning. Real-TPU runs use bench.py, not the test suite.

NOTE: this environment injects a sitecustomize that imports jax at
interpreter startup with JAX_PLATFORMS=axon already in the env, so
setting os.environ here is too late for jax's config default — we must
force the platform through jax.config *after* import, before any
backend is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older JAX: no jax_num_cpu_devices option. The XLA_FLAGS
    # host-platform device-count fallback set above covers it.
    pass

# Persistent XLA compilation cache: the suite compiles 1000+ programs
# and the per-module clear_caches() below (segfault workaround) forces
# recompiles of shared kernels — with the disk cache those recompiles
# become cache hits (keyed by HLO hash, so code changes invalidate
# naturally). TRINO_TPU_NO_COMPILE_CACHE=1 disables for experiments.
if os.environ.get("TRINO_TPU_NO_COMPILE_CACHE") != "1":
    import tempfile

    _cache_dir = os.environ.get(
        "TRINO_TPU_COMPILE_CACHE",
        os.path.join(
            tempfile.gettempdir(),
            f"trino_tpu_test_xla_cache_{os.getuid()}",  # per-user
        ),
    )
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # bound the on-disk cache (LRU-evicted by jax past this size)
    jax.config.update("jax_compilation_cache_max_size", 2 * 1024**3)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long soaks (chaos soak, full mesh TPC-H sweep) excluded "
        "from the tier-1 run (-m 'not slow'); run in the dev loop",
    )


# -- shared read-only runners (tier-1 wall trim) -----------------------
# Many modules used to build identical tpch/tpcds-tiny runners — and
# 2-worker distributed clusters — once per module, or even once per
# parametrized case. These session-scoped fixtures build each exactly
# once per run. Tests using them MUST be read-only: no DML/DDL, no SET
# SESSION, no session-attribute mutation; a test that mutates state
# builds its own runner.


@pytest.fixture(scope="session")
def tpch_local():
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import LocalQueryRunner, Session

    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(scope="session")
def tpcds_local():
    from trino_tpu.connectors.tpcds import create_tpcds_connector
    from trino_tpu.engine import LocalQueryRunner, Session

    r = LocalQueryRunner(Session(catalog="tpcds", schema="tiny"))
    r.register_catalog("tpcds", create_tpcds_connector())
    return r


@pytest.fixture(scope="session")
def tpch_cluster():
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny"),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(scope="session")
def tpcds_cluster():
    from trino_tpu.connectors.tpcds import create_tpcds_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="tpcds", schema="tiny"),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpcds", create_tpcds_connector())
    return r


@pytest.fixture(scope="session")
def tpch_cluster_mesh_off():
    """Page-plane (mesh_execution=False) 2-worker cluster. The chunk /
    recovery / replica modules each need the page plane's answers as a
    byte-identity oracle, and test_local_exchange needs a
    task_concurrency=2 cluster (2 is the session default) — one shared
    runner serves all of them."""
    from trino_tpu.connectors.tpch import create_tpch_connector
    from trino_tpu.engine import Session
    from trino_tpu.runtime import DistributedQueryRunner

    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", mesh_execution=False),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(autouse=True, scope="module")
def _concurrency_sanitizer(request):
    """Thread-leak and held-lock sanitizer: after each module, every
    registered background thread must have exited (or be daemon) and no
    witness lock may still be held. Session-scoped servers (statement
    server, proxy, worker HTTP) are daemon threads, so they pass; a test
    that forgets to stop a non-daemon worker fails its module here with
    the thread's registered name and owner."""
    yield
    import time as _time

    from trino_tpu.analysis import threadreg, witness

    _t0 = _time.monotonic()
    leaks = threadreg.THREADS.non_daemon_leaks()
    if leaks:
        # grace for threads mid-exit (target returned, join pending)
        deadline = _time.monotonic() + 2.0
        while leaks and _time.monotonic() < deadline:
            _time.sleep(0.02)
            leaks = threadreg.THREADS.non_daemon_leaks()
    assert not leaks, (
        "non-daemon threads leaked by this module: " + ", ".join(leaks)
    )

    held = witness.held_locks()
    if held:
        # a background daemon may transiently hold a lock; retry briefly
        deadline = _time.monotonic() + 1.0
        while held and _time.monotonic() < deadline:
            _time.sleep(0.01)
            held = witness.held_locks()
    assert not held, f"locks still held after module: {held}"
    assert witness.violation_count() == 0, (
        f"{witness.violation_count()} lock-witness violations recorded "
        "(a LockOrderError was raised and swallowed somewhere)"
    )
    dbg = os.environ.get("TRINO_TPU_SANITIZER_DEBUG")
    if dbg:
        with open(dbg, "a") as fh:
            fh.write(
                "[sanitizer] %s teardown=%.3fs locks=%d threads=%d t=%.1f\n"
                % (request.module.__name__, _time.monotonic() - _t0,
                   witness.lock_count(), threadreg.THREADS.spawned_total,
                   _time.monotonic())
            )


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """The full suite compiles 1000+ XLA programs in one process; this
    environment's XLA CPU compiler segfaults under that accumulated
    load (re-confirmed in r3: disabling this clearing crashed the run
    inside backend_compile — it is NOT the associative_scan issue,
    which r3 removed separately). Dropping compiled executables between
    modules bounds compiler state at the cost of per-module recompiles;
    TRINO_TPU_NO_CLEAR_CACHES=1 disables it for experiments."""
    yield
    if os.environ.get("TRINO_TPU_NO_CLEAR_CACHES") != "1":
        jax.clear_caches()
