"""r4 function-breadth batch 2: collect-path aggregates (array_agg,
map_agg, histogram, ...), moment-sum composites (regr_* family, entropy,
checksum), and nth_value. Oracles: pandas/python recomputation.

Reference seats: ArrayAggregationFunction, MapAggregationFunction,
Histogram, NumericHistogramAggregation (Ben-Haim/Tom-Tov),
DoubleRegressionAggregation, EntropyAggregation,
ChecksumAggregationFunction, NthValueFunction."""

import math

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session

G = np.array([1, 1, 1, 2, 2, 3], dtype=np.int64)
K = ["a", "b", "a", "c", "c", None]
V = np.array([10, 20, 30, 40, 50, 60], dtype=np.int64)


@pytest.fixture(scope="module")
def runner():
    conn = MemoryConnector()
    conn.load_table(
        "default", "t",
        [ColumnMetadata("g", T.BIGINT), ColumnMetadata("k", T.VARCHAR),
         ColumnMetadata("v", T.BIGINT)],
        [G, K, V],
        valids=[None, np.array([1, 1, 1, 1, 1, 0], bool), None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", conn)
    return r


def one(runner, sql):
    return runner.execute(sql).rows[0][0]


class TestCollectAggregates:
    def test_array_agg_grouped(self, runner):
        rows = runner.execute(
            "select g, array_agg(v) from t group by g order by g").rows
        assert [sorted(r[1]) for r in rows] == [
            [10, 20, 30], [40, 50], [60]]

    def test_array_agg_keeps_nulls(self, runner):
        got = one(runner, "select array_agg(k) from t where g = 3")
        assert got == [None]

    def test_array_agg_empty_group_is_null(self, runner):
        assert one(runner,
                   "select array_agg(v) from t where g > 99") is None

    def test_map_agg(self, runner):
        rows = runner.execute(
            "select g, map_agg(k, v) from t group by g order by g").rows
        assert rows[0][1] == {"a": 30, "b": 20}  # later key wins
        assert rows[1][1] == {"c": 50}
        assert rows[2][1] is None  # only a NULL key

    def test_multimap_agg(self, runner):
        rows = runner.execute(
            "select g, multimap_agg(k, v) from t group by g order by g"
        ).rows
        assert rows[0][1] == {"a": [10, 30], "b": [20]}

    def test_histogram(self, runner):
        assert one(runner, "select histogram(k) from t") == {
            "a": 2, "b": 1, "c": 2}

    def test_map_union(self, runner):
        got = one(runner, "select map_union(m) from ("
                          "select map_agg(k, v) m from t group by g)")
        assert got == {"a": 30, "b": 20, "c": 50}

    def test_numeric_histogram_bucket_count(self, runner):
        h = one(runner, "select numeric_histogram(2, v) from t")
        assert len(h) == 2
        assert sum(h.values()) == 6  # weights preserve row count
        # centroid means partition the sorted values
        assert h == {25.0: 4.0, 55.0: 2.0}

    def test_approx_most_frequent(self, runner):
        assert one(runner,
                   "select approx_most_frequent(1, k, 10) from t") == {"a": 2}

    def test_bitwise_aggs(self, runner):
        rows = runner.execute(
            "select g, bitwise_or_agg(v), bitwise_and_agg(v), "
            "bitwise_xor_agg(v) from t group by g order by g").rows
        assert rows[0][1:] == [10 | 20 | 30, 10 & 20 & 30, 10 ^ 20 ^ 30]
        assert rows[2][1:] == [60, 60, 60]


class TestCompositeAggregates:
    def test_regr_family_vs_numpy(self, runner):
        y, x = V.astype(float), G.astype(float)
        n = len(x)
        got = runner.execute(
            "select regr_count(v, g), regr_avgx(v, g), regr_avgy(v, g), "
            "regr_sxx(v, g), regr_sxy(v, g), regr_syy(v, g), "
            "regr_r2(v, g) from t").rows[0]
        sxx = float(np.sum((x - x.mean()) ** 2))
        sxy = float(np.sum((x - x.mean()) * (y - y.mean())))
        syy = float(np.sum((y - y.mean()) ** 2))
        r2 = sxy * sxy / (sxx * syy)
        want = [n, x.mean(), y.mean(), sxx, sxy, syy, r2]
        for g, w in zip(got, want):
            assert abs(g - w) < 1e-9 * max(1.0, abs(w))

    def test_regr_r2_constant_x_is_null(self, runner):
        assert one(runner,
                   "select regr_r2(v, 1) from t") is None

    def test_entropy(self, runner):
        got = one(runner, "select entropy(v) from t where g = 1")
        c = np.array([10.0, 20.0, 30.0])
        p = c / c.sum()
        want = float(-(p * np.log2(p)).sum())
        assert abs(got - want) < 1e-12

    def test_entropy_empty_is_zero(self, runner):
        assert one(runner, "select entropy(v) from t where g > 99") == 0.0

    def test_checksum_order_insensitive(self, runner):
        a = one(runner, "select checksum(v) from t")
        b = one(runner, "select checksum(v) from "
                        "(select v from t order by v desc)")
        assert a == b and a is not None

    def test_checksum_detects_difference(self, runner):
        a = one(runner, "select checksum(v) from t")
        b = one(runner, "select checksum(v + 1) from t")
        assert a != b

    def test_checksum_strings_and_empty(self, runner):
        assert one(runner, "select checksum(k) from t") is not None
        assert one(runner,
                   "select checksum(v) from t where g > 99") is None

    def test_geometric_mean(self, runner):
        got = one(runner, "select geometric_mean(v) from t")
        want = float(np.exp(np.mean(np.log(V.astype(float)))))
        assert abs(got - want) < 1e-9


class TestDistributedPlanes:
    """The collect aggregates are holistic (single-step after gather);
    the composites ride the partial/final wire. Both must agree with
    the local runner through the DistributedQueryRunner."""

    @pytest.fixture(scope="class")
    def dist(self):
        from trino_tpu.runtime import DistributedQueryRunner

        conn = MemoryConnector()
        conn.load_table(
            "default", "t",
            [ColumnMetadata("g", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
            [G, V],
        )
        r = DistributedQueryRunner(
            Session(catalog="memory", schema="default"),
            n_workers=2, hash_partitions=2,
        )
        r.register_catalog("memory", conn)
        return r

    def test_distributed_array_agg(self, dist):
        rows = dist.execute(
            "select g, array_agg(v) from t group by g order by g").rows
        assert [sorted(r[1]) for r in rows] == [
            [10, 20, 30], [40, 50], [60]]

    def test_distributed_checksum_is_plane_independent(self, dist, runner):
        a = dist.execute("select checksum(v) from t").rows
        b = dist.execute(
            "select checksum(v) from (select v from t order by v)").rows
        assert a == b
        # and agrees with the LOCAL runner over the same data — a
        # partial/final merge bug identical in both distributed plans
        # would pass the pair above but not this
        assert a == runner.execute("select checksum(v) from t").rows

    def test_distributed_sketch(self, dist):
        got = dist.execute(
            "select cardinality(approx_set(v)) from t").rows[0][0]
        assert got == 6  # tiny input: HLL is exact here


class TestNthValue:
    def test_nth_value_default_frame(self, runner):
        rows = runner.execute(
            "select g, v, nth_value(v, 2) over "
            "(partition by g order by v) from t order by g, v").rows
        # default RANGE frame: row 1 of each partition sees < 2 rows
        assert [r[2] for r in rows] == [None, 20, 20, None, 50, None]

    def test_nth_value_one_is_first_value(self, runner):
        rows = runner.execute(
            "select nth_value(v, 1) over (partition by g order by v), "
            "first_value(v) over (partition by g order by v) "
            "from t").rows
        assert all(r[0] == r[1] for r in rows)
