"""Connector-declared (bucketed) table partitioning.

VERDICT r3 item 10: when both sides of a join are bucketed on the join
key, the plan must run exchange-free — counter-asserted on the mesh
plane. The contract chain under test:

  spi.ConnectorMetadata.table_partitioning  (NodePartitioningManager seat)
    -> fragmenter._make_scan_partitioning    (AddExchanges uses the
       declared property instead of SOURCE)
    -> memory connector bucket splits        (ops/hashing.hash32_np, the
       bit-for-bit host replica of the exchange hash)
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.parallel import mesh_plan
from trino_tpu.runtime import DistributedQueryRunner

N_A, N_B = 5_000, 3_000


def _load(conn, bucketed):
    rng = np.random.default_rng(7)
    ka = rng.integers(0, 1_000, N_A).astype(np.int64)
    va = rng.integers(0, 100, N_A).astype(np.int64)
    kb = rng.integers(0, 1_000, N_B).astype(np.int64)
    wb = rng.integers(0, 100, N_B).astype(np.int64)
    conn.load_table(
        "default", "ta",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [ka, va], bucketed_by=("k",) if bucketed else None,
    )
    conn.load_table(
        "default", "tb",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
        [kb, wb], bucketed_by=("k",) if bucketed else None,
    )
    return ka, va, kb, wb


def _expected_join_sum(ka, va, kb, wb):
    import pandas as pd

    a = pd.DataFrame({"k": ka, "v": va})
    b = pd.DataFrame({"k": kb, "w": wb})
    j = a.merge(b, on="k")
    g = (j.v + j.w).groupby(j.k).sum().reset_index()
    return sorted((int(k), int(s)) for k, s in zip(g.k, g[0]))


def _runner(bucketed):
    s = Session(catalog="memory", schema="default",
                broadcast_join_threshold=0)
    r = DistributedQueryRunner(s, n_workers=2, hash_partitions=2)
    conn = MemoryConnector()
    data = _load(conn, bucketed)
    r.register_catalog("memory", conn)
    return r, data


# the join tests below are read-only — share one cluster per layout
# instead of rebuilding runner + data per test (tier-1 wall)
@pytest.fixture(scope="module")
def bucketed_cluster():
    return _runner(bucketed=True)


@pytest.fixture(scope="module")
def unbucketed_cluster():
    return _runner(bucketed=False)


SQL = ("select a.k, sum(a.v + b.w) from ta a join tb b on a.k = b.k "
       "group by a.k")


def test_np_hash_is_lockstep_with_device_hash():
    """hash32_np/partition_of_np MUST match hash32/partition_of bit for
    bit — a drift silently mis-buckets rows under a cancelled exchange."""
    import jax.numpy as jnp

    from trino_tpu.ops.hashing import (
        dictionary_code_hashes, hash32, hash32_np, partition_of,
        partition_of_np,
    )

    rng = np.random.default_rng(0)
    a = rng.integers(-2**62, 2**62, 4096, dtype=np.int64)
    b = rng.integers(0, 50, 4096, dtype=np.int64)
    v = rng.random(4096) < 0.9
    hj = np.asarray(hash32([jnp.asarray(a), jnp.asarray(b)],
                           [jnp.asarray(v), None]))
    hn = hash32_np([a, b], [v, None])
    assert np.array_equal(hj, hn)
    # dictionary-string lane (value-hash LUT) parity
    lut = dictionary_code_hashes(["x", "y", "zebra", "w"])
    codes = rng.integers(0, 4, 512, dtype=np.int32)
    lane = jnp.take(jnp.asarray(lut), jnp.asarray(codes)).astype(jnp.uint32)
    assert np.array_equal(np.asarray(hash32([lane])), hash32_np([lut[codes]]))
    for n in (8, 7, 16, 3):
        assert np.array_equal(
            np.asarray(partition_of(jnp.asarray(hj), n)),
            partition_of_np(hn, n),
        )


def test_bucket_splits_partition_and_cover_the_table():
    conn = MemoryConnector()
    ka, va, kb, wb = _load(conn, bucketed=True)
    h = conn.metadata.get_table_handle("default", "ta")
    assert conn.metadata.table_partitioning(h) == ("k",)
    for nb in (1, 4, 5):
        splits = conn.split_manager.get_splits(h, nb)
        assert len(splits) == nb
        seen = []
        for sp in splits:
            for b in conn.page_source.batches(sp, ["k", "v"], 1 << 14):
                seen.extend((r[0], r[1]) for r in b.to_pylists())
        assert sorted(seen) == sorted(zip(ka.tolist(), va.tolist()))


def test_cobucketed_plan_has_no_repartition(
    bucketed_cluster, unbucketed_cluster
):
    from trino_tpu.sql.fragmenter import plan_distributed
    from trino_tpu.sql.parser import parse

    def n_hash_fragments(runner):
        out = runner._analyze(parse(SQL))
        sub = plan_distributed(
            out, runner.catalogs, broadcast_threshold=0, target_splits=1
        )
        return sum(1 for f in sub.all_fragments() if f.output_kind == "hash")

    rb, _ = bucketed_cluster
    ru, _ = unbucketed_cluster
    assert n_hash_fragments(rb) == 0
    assert n_hash_fragments(ru) >= 1


def test_cobucketed_join_runs_exchange_free_on_mesh(bucketed_cluster):
    r, (ka, va, kb, wb) = bucketed_cluster
    before = dict(mesh_plan.MESH_COUNTERS)
    res = r.execute(SQL)
    after = mesh_plan.MESH_COUNTERS
    assert after["queries"] == before["queries"] + 1, "fell back to HTTP"
    assert after["all_to_all"] == before["all_to_all"], (
        "co-bucketed join still repartitioned"
    )
    assert sorted((int(a), int(b)) for a, b in res.rows) == \
        _expected_join_sum(ka, va, kb, wb)


def test_unbucketed_join_does_repartition(unbucketed_cluster):
    """The exchange-free assert above is meaningful: the same query over
    unbucketed tables DOES ride all_to_all."""
    r, (ka, va, kb, wb) = unbucketed_cluster
    before = dict(mesh_plan.MESH_COUNTERS)
    res = r.execute(SQL)
    after = mesh_plan.MESH_COUNTERS
    assert after["queries"] == before["queries"] + 1
    assert after["all_to_all"] > before["all_to_all"]
    assert sorted((int(a), int(b)) for a, b in res.rows) == \
        _expected_join_sum(ka, va, kb, wb)


def test_bucketed_join_against_repartitioned_side(bucketed_cluster):
    """Mixed case: a bucketed scan joined with a DERIVED (runtime
    repartitioned) side must still align bucket i with partition i —
    this is exactly the np/device hash parity contract."""
    r, (ka, va, kb, wb) = bucketed_cluster
    sql = ("select a.k, sum(a.v + d.mw) from ta a join "
           "(select k, max(w) mw from tb group by k) d on a.k = d.k "
           "group by a.k")
    res = r.execute(sql)
    import pandas as pd

    b = pd.DataFrame({"k": kb, "w": wb}).groupby("k").w.max().reset_index()
    a = pd.DataFrame({"k": ka, "v": va})
    j = a.merge(b, on="k")
    g = (j.v + j.w).groupby(j.k).sum().reset_index()
    exp = sorted((int(k), int(s)) for k, s in zip(g.k, g[0]))
    assert sorted((int(x), int(y)) for x, y in res.rows) == exp


def test_bucketed_with_nulls_and_strings():
    """NULL keys and dictionary-string bucket columns route like the
    runtime exchange (NULL lane = the exchange's NULL sentinel)."""
    conn = MemoryConnector()
    k = np.array([1, 2, 3, 4, 5, 6, 7, 8], dtype=np.int64)
    kv = np.array([True, True, False, True, False, True, True, True])
    s = ["ab", "cd", "ab", None, "ef", "cd", "ab", "gh"]
    sv = np.array([v is not None for v in s])
    conn.load_table(
        "default", "tn",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("s", T.VARCHAR)],
        [k, s], valids=[kv, sv], bucketed_by=("k", "s"),
    )
    h = conn.metadata.get_table_handle("default", "tn")
    splits = conn.split_manager.get_splits(h, 4)
    got = []
    for sp in splits:
        for b in conn.page_source.batches(sp, ["k", "s"], 16):
            got.extend((r[0], r[1]) for r in b.to_pylists())
    exp = [(int(kk) if vv else None, ss) for kk, vv, ss in zip(k, kv, s)]
    assert sorted(got, key=repr) == sorted(exp, key=repr)


def test_bucketed_rejects_float_keys():
    conn = MemoryConnector()
    with pytest.raises(ValueError, match="integer-family"):
        conn.load_table(
            "default", "tf", [ColumnMetadata("x", T.DOUBLE)],
            [np.zeros(4)], bucketed_by=("x",),
        )


def test_bucketed_local_runner_sees_all_rows():
    conn = MemoryConnector()
    ka, va, kb, wb = _load(conn, bucketed=True)
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", conn)
    res = r.execute("select count(*), sum(v) from ta")
    assert res.rows[0] == [N_A, int(va.sum())]
