"""r4 function-breadth batch 1: binary/digest, string remainder,
datetime parse family, math remainder, session functions.

Every function asserts a REFERENCE-DERIVED expected value (published
test vectors for the digests; python stdlib oracles for parse/encode),
per SURVEY.md §4's per-function oracle-test strategy."""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def runner():
    conn = MemoryConnector()
    conn.load_table(
        "default", "t",
        [ColumnMetadata("s", T.VARCHAR), ColumnMetadata("n", T.BIGINT)],
        [["hello", "world", "abc", None],
         np.array([1, 2, 3, 4], dtype=np.int64)],
        valids=[np.array([1, 1, 1, 0], bool), None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", conn)
    return r


def one(runner, sql):
    return runner.execute(sql).rows[0][0]


class TestDigests:
    def test_sha512_empty_vector(self, runner):
        # FIPS 180-4 test vector
        assert one(runner, "select sha512('')").startswith("cf83e1357eefb8bd")

    def test_xxhash64_vectors(self, runner):
        # xxHash reference vectors (XXH64, seed 0)
        assert one(runner, "select xxhash64('')") == "ef46db3751d8e999"
        assert one(runner, "select xxhash64('abc')") == "44bc2cf5ad770999"

    def test_murmur3_vector(self, runner):
        # smhasher MurmurHash3_x64_128("abc", 0)
        assert one(runner, "select murmur3('abc')") == (
            "6778ad3f3f3f96b4522dca264174a23b"
        )

    def test_hmac_sha256(self, runner):
        # RFC 4231-style: hmac('hello', 'key') cross-checked with hashlib
        import hashlib
        import hmac

        want = hmac.new(b"key", b"hello", "sha256").hexdigest()
        assert one(runner, "select hmac_sha256('hello', 'key')") == want

    def test_hmac_on_column_skips_null(self, runner):
        rows = runner.execute("select hmac_md5(s, 'k') from t").rows
        assert rows[3][0] is None and rows[0][0] is not None

    def test_crc32_matches_zlib(self, runner):
        import zlib

        assert one(runner, "select crc32('hello')") == zlib.crc32(b"hello")


class TestEncodings:
    def test_base32_roundtrip(self, runner):
        assert one(runner, "select to_base32('hello')") == "NBSWY3DP"
        assert one(runner, "select from_base32(to_base32(s)) from t") == "hello"

    def test_base64url_roundtrip(self, runner):
        got = one(runner, "select to_base64url('h?>llo')")
        assert "+" not in got and "/" not in got
        assert one(runner,
                   "select from_base64url(to_base64url('h?>llo'))") == "h?>llo"

    def test_big_endian_roundtrip(self, runner):
        assert one(runner,
                   "select from_big_endian_64(to_big_endian_64(258))") == 258
        assert one(runner,
                   "select from_big_endian_32(to_big_endian_32(77))") == 77

    def test_big_endian_wrong_width_is_null(self, runner):
        assert one(runner, "select from_big_endian_64('abc')") is None

    def test_ieee754_roundtrip(self, runner):
        assert one(runner,
                   "select from_ieee754_64(to_ieee754_64(2.5))") == 2.5

    def test_char2hexint(self, runner):
        # Teradata renders UTF-16BE code units: 'AB' -> 00410042
        assert one(runner, "select char2hexint('AB')") == "00410042"


class TestStringRemainder:
    def test_luhn(self, runner):
        assert one(runner, "select luhn_check('79927398713')") is True
        assert one(runner, "select luhn_check('79927398710')") is False

    def test_strrpos_vs_strpos(self, runner):
        assert one(runner, "select strrpos('ababab', 'ab')") == 5
        assert one(runner, "select strpos('ababab', 'ab')") == 1
        assert one(runner, "select index('ababab', 'ba')") == 2

    def test_position(self, runner):
        assert one(runner, "select position('lo', 'hello')") == 4

    def test_word_stem(self, runner):
        assert one(runner, "select word_stem('running')") == "run"
        assert one(runner, "select word_stem(s) from t") == "hello"

    def test_utf8_identity_on_carrier(self, runner):
        assert one(runner, "select from_utf8(to_utf8('héllo'))") == "héllo"

    def test_concat_ws_skips_nulls(self, runner):
        assert one(runner,
                   "select concat_ws('-', 'a', null, 'b', 'c')") == "a-b-c"
        assert one(runner, "select concat_ws('-', null, 'b')") == "b"
        assert one(runner, "select concat_ws('-', null, null)") == ""
        assert one(runner, "select concat_ws('-', 'x', '', 'y')") == "x--y"
        rows = runner.execute(
            "select concat_ws('-', s, 'z') from t").rows
        assert [r[0] for r in rows] == ["a-z"[0:3].replace("a", "hello"),
                                        "world-z", "abc-z", "z"]


class TestDatetimeParse:
    def test_from_iso8601_timestamp(self, runner):
        import datetime as dt

        want = int((dt.datetime(2020, 1, 1, 12, 30)
                    - dt.datetime(1970, 1, 1)).total_seconds() * 1e6)
        got = one(runner,
                  "select from_iso8601_timestamp('2020-01-01T12:30:00Z')")
        assert got == want
        # offsets normalize to UTC
        off = one(runner,
                  "select from_iso8601_timestamp('2020-01-01T13:30:00+01:00')")
        assert off == want

    def test_from_iso8601_timestamp_nanos_truncates(self, runner):
        a = one(runner, "select from_iso8601_timestamp_nanos("
                        "'2020-01-01T00:00:00.123456789Z')")
        assert a % 1_000_000 == 123456

    def test_parse_datetime_joda(self, runner):
        got = one(runner, "select parse_datetime("
                          "'10/05/2020 11:22', 'dd/MM/yyyy HH:mm')")
        import datetime as dt

        want = int((dt.datetime(2020, 5, 10, 11, 22)
                    - dt.datetime(1970, 1, 1)).total_seconds() * 1e6)
        assert got == want

    def test_parse_datetime_month_name(self, runner):
        # regression: 'MM' listed before 'MMM' shadowed month names
        got = one(runner, "select parse_datetime("
                          "'01 Jan 2020', 'dd MMM yyyy')")
        assert got == 1577836800000000

    def test_to_date_oracle_format(self, runner):
        import datetime as dt

        got = one(runner, "select to_date('2021-03-04', 'yyyy-mm-dd')")
        assert got == (dt.date(2021, 3, 4) - dt.date(1970, 1, 1)).days

    def test_parse_failure_is_null(self, runner):
        assert one(runner,
                   "select to_date('bogus', 'yyyy-mm-dd')") is None

    def test_from_unixtime_nanos_floor(self, runner):
        assert one(runner,
                   "select from_unixtime_nanos(1500000000123456789)") == \
            1500000000123456
        assert one(runner, "select from_unixtime_nanos(-1)") == -1

    def test_timezone_offsets_are_utc(self, runner):
        assert one(runner,
                   "select timezone_hour(from_unixtime(0))") == 0
        assert one(runner,
                   "select timezone_minute(from_unixtime(0))") == 0

    def test_timestamp_literal(self, runner):
        got = one(runner, "select timestamp '2020-01-01 00:30:00'")
        assert got == 1577838600000000

    def test_date_fn_and_cast(self, runner):
        assert one(runner, "select date('2021-05-06')") == 18753
        assert one(runner, "select cast('2021-05-06' as date)") == 18753
        assert one(runner, "select cast('bad' as date)") is None

    def test_to_iso8601(self, runner):
        assert one(runner,
                   "select to_iso8601(date '2020-02-29')") == "2020-02-29"


class TestMathSession:
    def test_from_base_and_to_base(self, runner):
        assert one(runner, "select from_base('1010', 2)") == 10
        assert one(runner, "select from_base('ff', 16)") == 255
        assert one(runner, "select to_base(255, 16)") == "ff"
        assert one(runner, "select to_base(-8, 2)") == "-1000"

    def test_from_base_invalid_is_null(self, runner):
        assert one(runner, "select from_base('zz', 8)") is None

    def test_inverse_beta_cdf_roundtrip(self, runner):
        # beta_cdf(a, b, inverse_beta_cdf(a, b, p)) == p
        got = one(runner, "select beta_cdf(2.0, 3.0, "
                          "inverse_beta_cdf(2.0, 3.0, 0.37))")
        assert abs(got - 0.37) < 1e-9

    def test_rand_bounds(self, runner):
        rows = runner.execute(
            "select rand(), rand(10), random(5, 8) from t").rows
        for u, a, b in rows:
            assert 0.0 <= u < 1.0 and 0 <= a < 10 and 5 <= b < 8

    def test_session_constants(self, runner):
        assert one(runner, "select current_timezone()") == "UTC"
        assert "trino_tpu" in one(runner, "select version()")
        # now() is TIMESTAMP WITH TIME ZONE at the session zone (r5;
        # DateTimeFunctions.java currentTimestamp) — rendered with zone
        v = one(runner, "select now()")
        assert isinstance(v, str) and v.endswith("UTC") and v >= "2025"

    def test_uuid_shape(self, runner):
        u = one(runner, "select uuid()")
        assert len(u) == 36 and u.count("-") == 4

    def test_human_readable_seconds(self, runner):
        assert one(runner, "select human_readable_seconds(93784)") == (
            "1 day, 2 hours, 3 minutes, 4 seconds"
        )

    def test_parse_duration_to_milliseconds(self, runner):
        assert one(runner,
                   "select to_milliseconds(parse_duration('3.5m'))") == 210000

    def test_parse_data_size(self, runner):
        assert int(one(runner, "select parse_data_size('2.3MB')")) == 2411724

    def test_format_number(self, runner):
        assert one(runner, "select format_number(1234567)") == "1.23M"
        assert one(runner, "select format_number(531)") == "531"

    def test_color_functions(self, runner):
        assert one(runner, "select rgb(255, 0, 0)") == 0xFF0000
        assert one(runner, "select color('#0f0')") == 0x00FF00
        assert "x" in one(runner, "select render('x', color('red'))")
        bar = one(runner, "select bar(0.5, 10)")
        assert bar.count("█") == 5
