"""Compile regime (trino_tpu/compile/): capacity ladder, shape
stabilization, census-driven warmup, program/persistent caches, and the
zero-recompile guarantees the regime exists to provide — dynamic-filter
retries, FTE re-attempts, and simulated worker restarts must all re-land
on already-compiled (operator, capacity, dtype-sig) lowerings."""

import os
from types import SimpleNamespace

import pytest

from trino_tpu import types as T
from trino_tpu.block import RelBatch
from trino_tpu.compile.cache import PersistentCompileCache
from trino_tpu.compile.shapes import CapacityLadder, ShapeStabilizer
from trino_tpu.compile.warmup import (
    WarmupEntry,
    WarmupService,
    classes_warm,
    note_classes_warm,
    reset_warm_classes,
    zeros_batch,
)
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime.metrics import METRICS


# ---------------------------------------------------------------------------
# capacity ladder (compile/shapes.py)
# ---------------------------------------------------------------------------


def test_ladder_rungs_monotone_and_idempotent():
    ladder = CapacityLadder()
    prev = 0
    for n in [1, 2, 15, 16, 17, 100, 1000, 65535, 65536, 65537, 1 << 20]:
        r = ladder.rung(n)
        assert r >= n
        assert r >= prev  # nondecreasing in n
        assert ladder.rung(r) == r  # rungs are fixed points
        prev = r


def test_ladder_base4_coarsens_base2():
    b2, b4 = CapacityLadder(base=2), CapacityLadder(base=4)
    # every base-4 rung is a base-2 rung (stays on the pow2 grid) ...
    assert set(b4.rungs(1 << 20)) <= set(b2.rungs(1 << 20))
    # ... and there are fewer of them (coarser = fewer distinct classes)
    assert len(b4.rungs(1 << 20)) < len(b2.rungs(1 << 20))
    assert b4.rung(100) == 256  # 16, 64, 256, ...
    assert b2.rung(100) == 128


def test_ladder_validation():
    with pytest.raises(ValueError):
        CapacityLadder(base=3)  # not a power of two
    with pytest.raises(ValueError):
        CapacityLadder(base=1)  # degenerate: every n its own class
    with pytest.raises(ValueError):
        CapacityLadder(min_capacity=24)


def test_scan_classes_main_and_tail():
    st = ShapeStabilizer(CapacityLadder(), batch_rows=49152)
    # tpch tiny lineitem: 60175 rows at batch_rows=49152 → one full
    # chunk (rung 65536) plus an 11023-row tail (rung 16384)
    assert st.scan_classes(60175) == (65536, 16384)
    assert st.scan_classes(1000) == (1024,)  # fits in one chunk: no tail
    assert st.scan_classes(2 * 49152) == (65536,)  # even split: no tail
    # pruned chunks re-land on the unpruned span's class
    assert st.chunk_capacity(60175) == st.chunk_capacity(60175) == 65536


# ---------------------------------------------------------------------------
# warmup service (compile/warmup.py)
# ---------------------------------------------------------------------------


def test_warmup_failure_degrades_not_fails():
    def boom(batch):
        raise RuntimeError("lowering exploded")

    entry = WarmupEntry(
        operator="FilterProjectOperator",
        fn=boom,
        in_schema=[(T.BIGINT, None)],
        out_dtypes=("bigint",),
        capacities=(16,),
    )
    svc = WarmupService([entry], mode="block").start()
    assert svc.wait(timeout=30.0)  # service completes despite the raise
    assert entry.status == "failed"
    assert "exploded" in entry.detail
    assert svc.warmed_keys() == set()
    line = svc.report_line()
    assert "failed=1" in line and "compiled=0" in line


def test_warmup_nested_schema_skipped():
    nested = SimpleNamespace(is_nested=True)
    with pytest.raises(NotImplementedError):
        zeros_batch([(nested, None)], 16)
    entry = WarmupEntry(
        operator="FilterProjectOperator",
        fn=lambda b: b,
        in_schema=[(nested, None)],
        out_dtypes=("array(bigint)",),
        capacities=(16,),
    )
    svc = WarmupService([entry], mode="block").start()
    svc.wait(timeout=30.0)
    assert entry.status == "skipped"


def test_warmup_success_marks_classes_warm():
    reset_warm_classes()
    try:
        keys = {("FilterProjectOperator", c, ("bigint",)) for c in (16, 64)}
        assert not classes_warm(keys)
        assert not classes_warm(set())  # vacuous truth is not warmth
        entry = WarmupEntry(
            operator="FilterProjectOperator",
            fn=lambda b: b,
            in_schema=[(T.BIGINT, None)],
            out_dtypes=("bigint",),
            capacities=(16, 64),
        )
        svc = WarmupService([entry], mode="block").start()
        svc.wait(timeout=30.0)
        assert entry.status == "compiled"
        assert svc.warmed_keys() == keys
        assert classes_warm(keys)
        # a superset with an un-warmed class is not all-warm
        assert not classes_warm(keys | {("HashAggregationOperator", 16, ("bigint",))})
    finally:
        reset_warm_classes()


def test_warmup_off_mode_is_immediate():
    svc = WarmupService([], mode="off").start()
    assert svc.wait(timeout=0)


# ---------------------------------------------------------------------------
# persistent cache management (compile/cache.py)
# ---------------------------------------------------------------------------


def test_persistent_cache_scrub_and_evict(tmp_path):
    cache = PersistentCompileCache(root=str(tmp_path), max_bytes=250)
    os.makedirs(cache.dir, exist_ok=True)

    def put(name, size, mtime):
        p = os.path.join(cache.dir, name)
        with open(p, "wb") as f:
            f.write(b"x" * size)
        os.utime(p, (mtime, mtime))
        return p

    put("dead", 0, 100)  # zero-byte: writer died pre-write
    put("entry.tmp", 50, 100)  # orphaned temp: writer died mid-rename
    put("tmp_orphan", 50, 100)
    oldest = put("xla_a", 100, 100)
    put("xla_b", 100, 200)
    put("xla_c", 100, 300)

    cache.prepare()  # scrub + evict, as a restarted worker would
    assert cache.scrubbed == 3
    # 300 bytes of real entries > max_bytes=250: oldest mtime goes first
    assert cache.evicted == 1
    assert not os.path.exists(oldest)
    assert cache.entry_count() == 2
    assert cache.total_bytes() == 200
    stats = cache.stats()
    assert stats["scrubbed"] == 3 and stats["evicted"] == 1
    # the salt dir is versioned: a jax upgrade or schema rev change must
    # not serve stale executables
    assert "jax" in cache.salt and "schema" in cache.salt
    assert cache.dir.endswith(cache.salt)


def test_persistent_cache_prepare_is_idempotent(tmp_path):
    cache = PersistentCompileCache(root=str(tmp_path), max_bytes=1 << 20)
    cache.prepare()
    cache.prepare()  # fresh dir, nothing to scrub or evict
    assert cache.scrubbed == 0 and cache.evicted == 0


# ---------------------------------------------------------------------------
# spill re-read capacity restore (exec/spill.py)
# ---------------------------------------------------------------------------


def test_spiller_restores_spill_time_capacity():
    from trino_tpu.exec.spill import FileSpiller

    b = RelBatch.from_pydict(
        [("a", T.BIGINT)], {"a": [1, 2, 3, 4, 5]}, capacity=64
    )
    assert b.capacity == 64
    sp = FileSpiller()
    try:
        sp.spill(b)
        (out,) = list(sp.unspill())
        # serialization compacts to live rows; the re-read must re-enter
        # the operator on the class it was first compiled for
        assert out.capacity == 64
        assert out.to_pylists() == b.to_pylists()
    finally:
        sp.close()


# ---------------------------------------------------------------------------
# warm watchdog threshold (runtime/worker.py)
# ---------------------------------------------------------------------------


class _FakeTask:
    def __init__(self, warm):
        self.shapes_warm = warm
        self.state = "running"
        self.seen = []
        self.spec = SimpleNamespace(task_id=f"t-{warm}")

    def interrupt_if_stuck(self, timeout, now=None):
        self.seen.append(timeout)
        return None


def _worker(**kw):
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime.worker import Worker

    return Worker("w-watchdog", CatalogManager(), **kw)


def test_watchdog_warm_threshold_selection():
    w = _worker(stuck_task_interrupt_s=5.0, stuck_task_interrupt_warm_s=0.5)
    warm, cold = _FakeTask(True), _FakeTask(False)
    w._tasks = {"a": warm, "b": cold}
    w.watchdog_once()
    assert warm.seen == [0.5]  # all predicted classes warm → tight leash
    assert cold.seen == [5.0]  # cold compiles still get the slow path


def test_watchdog_warm_only_skips_cold_tasks():
    w = _worker(stuck_task_interrupt_warm_s=0.5)  # no conservative limit
    warm, cold = _FakeTask(True), _FakeTask(False)
    w._tasks = {"a": warm, "b": cold}
    w.watchdog_once()
    assert warm.seen == [0.5]
    assert cold.seen == []  # no threshold applies → never interrupted


def test_watchdog_disabled_without_thresholds():
    w = _worker()
    w._tasks = {"a": _FakeTask(True)}
    assert w.watchdog_once() == []


# ---------------------------------------------------------------------------
# end-to-end: stabilized execution, zero-recompile replay, warmup modes
# ---------------------------------------------------------------------------


FP_Q = "select l_orderkey + 1 from lineitem where l_quantity * 2 < 10"
AGG_Q = (
    "select l_returnflag, sum(l_quantity), count(*) from lineitem"
    " group by l_returnflag order by l_returnflag"
)
JOIN_Q = (
    "select count(*) from lineitem, orders"
    " where l_orderkey = o_orderkey and o_totalprice < 50000"
)
REPLAY_QUERIES = (FP_Q, AGG_Q, JOIN_Q)


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


def _compiles_this_query(runner, sql):
    text = runner.execute("explain analyze " + sql).rows[0][0]
    assert "xla_compiles_this_query=" in text, text
    return int(text.split("xla_compiles_this_query=")[1].split()[0])


def test_stabilized_results_match_unstabilized_oracle(runner):
    oracle = {}
    runner.execute("SET SESSION shape_stabilization = false")
    try:
        for q in REPLAY_QUERIES:
            oracle[q] = runner.execute(q).rows
    finally:
        runner.execute("SET SESSION shape_stabilization = true")
    for q in REPLAY_QUERIES:
        assert runner.execute(q).rows == oracle[q]
    # a coarser ladder pads harder but must not change results
    runner.execute("SET SESSION capacity_ladder_base = 4")
    try:
        for q in REPLAY_QUERIES:
            assert runner.execute(q).rows == oracle[q]
    finally:
        runner.execute("SET SESSION capacity_ladder_base = 2")


def test_second_execution_compiles_nothing(runner):
    """The regime's core guarantee: once a query shape has executed,
    re-running it (dynamic-filter pruned re-scans included — JOIN_Q
    plans a dynamic filter) mints zero new XLA lowerings."""
    for q in REPLAY_QUERIES:
        first = _compiles_this_query(runner, q)
        second = _compiles_this_query(runner, q)
        assert second == 0, f"{q!r}: first={first} second={second}"


def test_restarted_runner_replays_warm(runner):
    """Simulated worker restart: a fresh runner (fresh plan cache,
    fresh shape ledger) replaying queries this process already executed
    reports zero compiles — program cache and jitted kernels are
    process-global, standing in for the persistent cache on TPU."""
    baseline = {}
    for q in REPLAY_QUERIES:  # ensure this process is warm
        baseline[q] = runner.execute(q).rows
    fresh = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    fresh.register_catalog("tpch", create_tpch_connector())
    for q in REPLAY_QUERIES:
        assert _compiles_this_query(fresh, q) == 0, q
        assert fresh.execute(q).rows == baseline[q]


def test_warmup_modes(runner):
    try:
        runner.execute("SET SESSION warmup_mode = off")
        text = runner.execute("explain analyze " + FP_Q).rows[0][0]
        assert "warmup:" not in text

        runner.execute("SET SESSION warmup_mode = block")
        text = runner.execute("explain analyze " + FP_Q).rows[0][0]
        assert "warmup: mode=block" in text, text
        tail = text.split("warmup: mode=block ")[1].splitlines()[0]
        stats = dict(kv.split("=") for kv in tail.split())
        assert int(stats["entries"]) >= 1
        assert int(stats["failed"]) == 0, text
        # the FP stage was warmed and then executed → counted as a hit
        assert int(stats["hits"]) >= 1, text

        runner.execute("SET SESSION warmup_mode = background")
        text = runner.execute("explain analyze " + FP_Q).rows[0][0]
        assert "warmup: mode=background" in text, text
    finally:
        runner.execute("SET SESSION warmup_mode = off")


def test_warmup_mode_validated(runner):
    with pytest.raises(Exception, match="warmup_mode"):
        runner.execute("SET SESSION warmup_mode = sideways")


# ---------------------------------------------------------------------------
# FTE re-attempt: retries re-land on compiled classes
# ---------------------------------------------------------------------------


FTE_Q = (
    "SELECT l_returnflag, sum(l_quantity), count(*) FROM lineitem"
    " GROUP BY l_returnflag ORDER BY l_returnflag"
)


def test_fte_reattempt_compiles_nothing():
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.failure import FailureInjector
    from trino_tpu.runtime.worker import Worker

    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [Worker(f"w{i}", cats, failure_injector=inj) for i in range(2)]
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="task"),
        worker_handles=workers,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())

    baseline = r.execute(FTE_Q).rows  # clean run compiles everything
    before = METRICS.counter("xla_compiles")
    inj.inject(fragment_id=0, partition=0, attempts=(0,), where="start")
    assert r.execute(FTE_Q).rows == baseline
    delta = METRICS.counter("xla_compiles") - before
    assert delta == 0, f"FTE re-attempt minted {delta} new lowerings"
