"""ARRAY-typed columns (block.py ArrayColumn — spi/block/ArrayBlock
analogue) and lateral UNNEST over them (exec/unnest.py), plus
vectorized cardinality."""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.block import ArrayColumn
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def runner():
    mem = create_memory_connector()
    mem.load_table(
        "default", "orders_tags",
        [
            ColumnMetadata("id", T.BIGINT),
            ColumnMetadata("name", T.VARCHAR),
            ColumnMetadata("tags", T.array_of(T.VARCHAR)),
            ColumnMetadata("scores", T.array_of(T.BIGINT)),
        ],
        [
            np.asarray([1, 2, 3, 4], dtype=np.int64),
            ["ann", "bob", "cid", "dee"],
            [["red", "blue"], ["green"], [], ["red", "green", "blue"]],
            [[10, 20], [30], [], [1, 2, 3]],
        ],
        None,
        [None, None, None, None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    return r


def test_array_column_roundtrip():
    col = ArrayColumn.from_pylists(T.BIGINT, [[1, 2], [], None, [3]])
    assert col.to_pylist(count=4) == [[1, 2], [], None, [3]]


def test_cardinality_on_column(runner):
    rows = runner.execute(
        "select id, cardinality(tags) from orders_tags order by id"
    ).rows
    assert rows == [[1, 2], [2, 1], [3, 0], [4, 3]]


def test_unnest_array_column(runner):
    rows = runner.execute(
        "select id, t from orders_tags, UNNEST(tags) as u(t)"
        " order by id, t"
    ).rows
    assert rows == [
        [1, "blue"], [1, "red"], [2, "green"],
        [4, "blue"], [4, "green"], [4, "red"],
    ]


def test_unnest_empty_arrays_produce_no_rows(runner):
    rows = runner.execute(
        "select count(*) from orders_tags, UNNEST(scores) as u(s)"
        " where id = 3"
    ).rows
    assert rows == [[0]]


def test_unnest_with_ordinality(runner):
    rows = runner.execute(
        "select id, s, o from orders_tags, UNNEST(scores)"
        " WITH ORDINALITY as u(s, o) where id = 4 order by o"
    ).rows
    assert rows == [[4, 1, 1], [4, 2, 2], [4, 3, 3]]


def test_unnest_multi_array_zip(runner):
    # tags has 2/1/0/3 elements, scores 2/1/0/3: zip aligns
    rows = runner.execute(
        "select id, t, s from orders_tags, UNNEST(tags, scores)"
        " as u(t, s) where id = 1 order by s"
    ).rows
    assert rows == [[1, "red", 10], [1, "blue", 20]]


def test_unnest_aggregation(runner):
    rows = runner.execute(
        "select t, count(*) c from orders_tags, UNNEST(tags) as u(t)"
        " group by t order by t"
    ).rows
    assert rows == [["blue", 2], ["green", 2], ["red", 2]]


def test_unnest_filter_on_source(runner):
    rows = runner.execute(
        "select name, s from orders_tags, UNNEST(scores) as u(s)"
        " where id >= 2 and s > 1 order by s"
    ).rows
    assert rows == [["dee", 2], ["dee", 3], ["bob", 30]]


def test_constant_unnest_still_works(runner):
    rows = runner.execute(
        "select * from UNNEST(ARRAY[7, 8]) as u(v) order by v"
    ).rows
    assert rows == [[7], [8]]


def test_array_type_rendering(runner):
    rows = runner.execute("SHOW COLUMNS FROM orders_tags").rows
    d = dict(rows)
    assert d["tags"] == "array(varchar)"
    assert d["scores"] == "array(bigint)"


def test_array_crosses_exchange():
    """r2 raised here ("ARRAY columns cannot cross an exchange"); the
    TPG2 nested encodings made arrays first-class on the wire — see
    test_nested_types.py for the full matrix."""
    from trino_tpu.block import RelBatch
    from trino_tpu.exec.serde import Page, deserialize_page, serialize_page

    col = ArrayColumn.from_pylists(T.BIGINT, [[1], [2, 3]])
    page = Page.from_batch(RelBatch([col]))
    back = deserialize_page(serialize_page(page)).to_batch()
    assert back.columns[0].to_pylist(count=2) == [[1], [2, 3]]


def test_select_array_column_directly(runner):
    rows = runner.execute(
        "select id, scores from orders_tags order by id"
    ).rows
    assert rows == [
        [1, [10, 20]], [2, [30]], [3, []], [4, [1, 2, 3]],
    ]


def test_ctas_and_insert_arrays(runner):
    runner.execute(
        "create table arr_copy as select id, scores from orders_tags"
        " where id <= 2"
    )
    assert runner.execute(
        "select id, scores from arr_copy order by id"
    ).rows == [[1, [10, 20]], [2, [30]]]
    runner.execute(
        "insert into arr_copy select id, scores from orders_tags"
        " where id = 4"
    )
    assert runner.execute(
        "select s from arr_copy, UNNEST(scores) u(s) where id = 4"
        " order by s"
    ).rows == [[1], [2], [3]]


def test_ctas_string_arrays(runner):
    runner.execute(
        "create table tag_copy as select id, tags from orders_tags"
    )
    rows = runner.execute(
        "select t, count(*) from tag_copy, UNNEST(tags) u(t)"
        " group by t order by t"
    ).rows
    assert rows == [["blue", 2], ["green", 2], ["red", 2]]


def test_unnest_empty_table():
    mem = create_memory_connector()
    mem.load_table(
        "d", "empty",
        [ColumnMetadata("id", T.BIGINT),
         ColumnMetadata("arr", T.array_of(T.BIGINT))],
        [np.zeros(0, dtype=np.int64), []], None, [None, None],
    )
    r = LocalQueryRunner(Session(catalog="m", schema="d"))
    r.register_catalog("m", mem)
    assert r.execute(
        "select id, x from empty, UNNEST(arr) as u(x)"
    ).rows == []


def test_nested_arrays_roundtrip():
    inner = T.array_of(T.BIGINT)
    col = ArrayColumn.from_pylists(inner, [[[1, 2], [3]], [[4]]])
    assert col.to_pylist(count=2) == [[[1, 2], [3]], [[4]]]


def test_unnest_nested_arrays():
    mem = create_memory_connector()
    mem.load_table(
        "d", "nested",
        [ColumnMetadata("id", T.BIGINT),
         ColumnMetadata("nest", T.array_of(T.array_of(T.BIGINT)))],
        [np.asarray([1, 2], dtype=np.int64), [[[1, 2], [3]], [[4]]]],
        None, [None, None],
    )
    r = LocalQueryRunner(Session(catalog="m", schema="d"))
    r.register_catalog("m", mem)
    rows = r.execute(
        "select id, x from nested, UNNEST(nest) as u(x) order by id"
    ).rows
    assert rows == [[1, [1, 2]], [1, [3]], [2, [4]]]


def test_arrays_rejected_as_keys(runner):
    for sql, where in [
        ("select tags, count(*) from orders_tags group by tags",
         "grouping"),
        ("select id from orders_tags order by tags", "sort"),
        ("select a.id from orders_tags a, orders_tags b"
         " where a.tags = b.tags", ""),
        ("select distinct tags from orders_tags", "grouping"),
    ]:
        with pytest.raises(Exception, match="ARRAY|array"):
            runner.execute(sql)
