"""MATCH_RECOGNIZE (exec/match_recognize.py + parser/analyzer wiring —
main/operator/window/pattern/ analogue): the classic stock V/W-shape
patterns, quantifiers, PREV/NEXT navigation, measures, AFTER MATCH
SKIP, partitioning, and NULL/boundary behavior."""

import pytest

from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", create_memory_connector())
    # classic ticker data: two symbols, price V-shapes
    r.execute(
        "create table stock as select * from (values"
        " ('a', 1, 90), ('a', 2, 80), ('a', 3, 70), ('a', 4, 85),"
        " ('a', 5, 95), ('a', 6, 60), ('a', 7, 50), ('a', 8, 80),"
        " ('b', 1, 20), ('b', 2, 10), ('b', 3, 30), ('b', 4, 40)"
        ") as t(symbol, day, price)"
    )
    return r


MR_V = """
select * from stock MATCH_RECOGNIZE (
  PARTITION BY symbol
  ORDER BY day
  MEASURES
    first(down.day) as start_day,
    last(down.price) as bottom_price,
    last(up.day) as end_day,
    match_number() as mno
  ONE ROW PER MATCH
  AFTER MATCH SKIP PAST LAST ROW
  PATTERN (down+ up+)
  DEFINE
    down AS price < PREV(price),
    up AS price > PREV(price)
)
order by symbol, start_day
"""


def test_v_shape(runner):
    rows = runner.execute(MR_V).rows
    # symbol a: V at days 2-5 (90>80>70, up 85,95), V at 6-8 (60,50 up 80)
    # symbol b: V at days 2-3..4 (20>10, up 30,40)
    assert rows == [
        ["a", 2, 70, 5, 1],
        ["a", 6, 50, 8, 2],
        ["b", 2, 10, 4, 1],
    ]


def test_skip_to_next_row(runner):
    rows = runner.execute(
        """
        select * from stock MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY day
          MEASURES first(down.day) as d, match_number() as m
          ONE ROW PER MATCH
          AFTER MATCH SKIP TO NEXT ROW
          PATTERN (down down)
          DEFINE down AS price < PREV(price)
        ) where symbol = 'a' order by d
        """
    ).rows
    # 'a' falls at days 2,3 then 6,7: consecutive-fall pairs with
    # overlap allowed = (2,3), (6,7)
    assert rows == [["a", 2, 1], ["a", 6, 2]]


def test_alternation_and_classifier(runner):
    rows = runner.execute(
        """
        select * from stock MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY day
          MEASURES classifier() as which, first(up.day) as ud,
                   first(down.day) as dd
          ONE ROW PER MATCH
          PATTERN (up | down)
          DEFINE up AS price > PREV(price),
                 down AS price < PREV(price)
        ) where symbol = 'b' order by coalesce(ud, dd)
        """
    ).rows
    # b: day2 down, day3 up, day4 up (each its own 1-row match;
    # classifier reports the matched variable; alternation prefers up)
    assert rows == [["b", "down", None, 2], ["b", "up", 3, None]] or rows == [
        ["b", None, 2, "down"],
    ] or len(rows) == 3


def test_optional_and_repetition(runner):
    rows = runner.execute(
        """
        select * from stock MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY day
          MEASURES first(down.day) as s, last(down.day) as e
          ONE ROW PER MATCH
          PATTERN (down{2})
          DEFINE down AS price < PREV(price)
        ) order by symbol, s
        """
    ).rows
    assert rows == [["a", 2, 3], ["a", 6, 7]]


def test_undefined_variable_matches_all(runner):
    # B undefined -> TRUE for every row (standard semantics)
    rows = runner.execute(
        """
        select * from stock MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY day
          MEASURES first(down.day) as s, last(b.day) as nxt
          ONE ROW PER MATCH
          PATTERN (down b)
          DEFINE down AS price < PREV(price)
        ) where symbol = 'b' order by s
        """
    ).rows
    assert rows == [["b", 2, 3]]


def test_partition_boundary_isolates_prev(runner):
    # first row of each partition: PREV(price) is NULL -> no match can
    # start there; symbols never bleed into each other
    rows = runner.execute(
        """
        select * from stock MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY day
          MEASURES first(down.day) as s
          ONE ROW PER MATCH
          PATTERN (down)
          DEFINE down AS price < PREV(price)
        ) order by symbol, s
        """
    ).rows
    assert rows == [
        ["a", 2], ["a", 3], ["a", 6], ["a", 7], ["b", 2],
    ]


def test_next_navigation(runner):
    rows = runner.execute(
        """
        select * from stock MATCH_RECOGNIZE (
          PARTITION BY symbol ORDER BY day
          MEASURES first(peak.day) as d, first(peak.price) as p
          ONE ROW PER MATCH
          PATTERN (peak)
          DEFINE peak AS price > PREV(price) AND price > NEXT(price)
        ) order by symbol, d
        """
    ).rows
    assert rows == [["a", 5, 95], ["b", 3, 30]] or rows == [["a", 5, 95]]


def test_measures_without_partition(runner):
    rows = runner.execute(
        """
        select * from stock MATCH_RECOGNIZE (
          ORDER BY symbol, day
          MEASURES match_number() as m, first(r.price) as p
          ONE ROW PER MATCH
          PATTERN (r{3})
          DEFINE r AS price >= 0
        )
        """
    ).rows
    assert len(rows) == 4  # 12 rows / 3 per match


def test_errors(runner):
    with pytest.raises(Exception, match="ONE ROW PER MATCH"):
        runner.execute(
            "select * from stock MATCH_RECOGNIZE (ORDER BY day"
            " MEASURES match_number() as m ALL ROWS PER MATCH"
            " PATTERN (x) DEFINE x AS price > 0)"
        )
    with pytest.raises(Exception, match="does not appear in PATTERN"):
        runner.execute(
            "select * from stock MATCH_RECOGNIZE (ORDER BY day"
            " MEASURES match_number() as m PATTERN (x)"
            " DEFINE y AS price > 0)"
        )
    with pytest.raises(Exception, match="other"):
        runner.execute(
            "select * from stock MATCH_RECOGNIZE (ORDER BY day"
            " MEASURES match_number() as m PATTERN (x y)"
            " DEFINE x AS price > 0, y AS price > x.price)"
        )


def test_formatter_roundtrip():
    from trino_tpu.sql.formatter import format_statement
    from trino_tpu.sql.parser import parse

    tree = parse(MR_V)
    assert parse(format_statement(tree)) == tree
