"""TIMESTAMP WITH TIME ZONE — VERDICT r4 item #4 (second half).

Packed (instant_millis << 12 | zone_id) int64 encoding — the
reference's short tstz form (spi/type/DateTimeEncoding.java,
spi/type/TimeZoneKey.java). Oracle: Python zoneinfo, including DST
spring-forward/fall-back boundaries. Covers literals, session-zone
parsing, AT TIME ZONE, casts both ways, zone-aware extract, interval
arithmetic across the DST gap, and aggregation/grouping/filtering on
tstz columns through the engine."""

import datetime as dt
from zoneinfo import ZoneInfo

import pytest

from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.ops import tz as TZ

NY = "America/New_York"


@pytest.fixture(scope="module")
def r():
    r = LocalQueryRunner(
        Session(catalog="memory", schema="t", timezone=NY)
    )
    r.register_catalog("memory", create_memory_connector())
    return r


def q1(r, sql):
    return r.execute(sql).rows[0][0]


class TestZoneDb:
    def test_offsets_match_zoneinfo_incl_dst(self):
        zid = TZ.zone_id(NY)
        for iso in [
            "2024-03-10 06:59:59", "2024-03-10 07:00:00",  # spring fwd
            "2024-11-03 05:59:59", "2024-11-03 06:00:00",  # fall back
            "1975-06-01 00:00:00", "2035-12-25 12:00:00",
        ]:
            d = dt.datetime.fromisoformat(iso).replace(
                tzinfo=dt.timezone.utc
            )
            ms = int(d.timestamp() * 1000)
            exp = int(
                d.astimezone(ZoneInfo(NY)).utcoffset().total_seconds() * 1000
            )
            assert TZ.offset_millis_py(zid, ms) == exp, iso

    def test_fixed_offset_and_registry_roundtrip(self):
        for name in ["UTC", "+05:30", "-08:00", NY, "Europe/London"]:
            assert TZ.zone_name(TZ.zone_id(name)) in (name, "UTC")


class TestLiteralsAndCasts:
    def test_literal_with_zone(self, r):
        assert q1(
            r, f"select timestamp '2024-07-04 12:30:15.250 {NY}'"
        ) == "2024-07-04 12:30:15.250 America/New_York"

    def test_literal_offset_same_instant(self, r):
        a = q1(r, "select cast(timestamp '2024-07-04 16:30:00 UTC' as timestamp)")
        b = q1(r, "select cast(timestamp '2024-07-04 12:30:00 -04:00' as timestamp)")
        # both name the same instant; wall clocks differ by the offsets
        assert a - b == 4 * 3600 * 1_000_000

    def test_cast_string_session_zone(self, r):
        # zone-less string takes the session zone (America/New_York)
        got = q1(
            r, "select cast('2024-01-15 12:00:00' as timestamp with time zone)"
        )
        assert got == "2024-01-15 12:00:00.000 America/New_York"

    def test_cast_timestamp_to_tstz_dst(self, r):
        # wall 2024-03-10 03:00 EDT = 07:00 UTC (after spring-forward)
        got = q1(
            r,
            "select cast(cast(timestamp '2024-03-10 03:00:00' as timestamp "
            "with time zone) as timestamp) ",
        )
        wall = dt.datetime(2024, 3, 10, 3, 0)
        assert got == int(
            (wall - dt.datetime(1970, 1, 1)).total_seconds() * 1e6
        )

    def test_cast_tstz_to_date(self, r):
        got = q1(
            r,
            "select cast(timestamp '2024-01-15 23:30:00 -05:00' as date)",
        )
        assert got == (dt.date(2024, 1, 15) - dt.date(1970, 1, 1)).days


class TestAtTimeZone:
    def test_instant_preserved(self, r):
        got = q1(
            r,
            "select timestamp '2024-07-04 12:00:00 UTC' "
            "at time zone 'Asia/Tokyo'",
        )
        assert got == "2024-07-04 21:00:00.000 Asia/Tokyo"

    def test_at_timezone_function(self, r):
        got = q1(
            r,
            "select at_timezone(timestamp '2024-07-04 12:00:00 UTC', "
            "'+05:30')",
        )
        assert got == "2024-07-04 17:30:00.000 +05:30"

    def test_with_timezone(self, r):
        got = q1(
            r,
            "select with_timezone(timestamp '2024-07-04 12:00:00', "
            "'Asia/Tokyo')",
        )
        assert got == "2024-07-04 12:00:00.000 Asia/Tokyo"


class TestExtract:
    def test_civil_fields_in_value_zone(self, r):
        rows = r.execute(
            "select extract(year from ts), extract(month from ts), "
            "extract(day from ts), extract(hour from ts), "
            "extract(minute from ts) from (select timestamp "
            "'2024-12-31 23:45:00 -05:00' as ts)"
        ).rows[0]
        assert rows == [2024, 12, 31, 23, 45]

    def test_timezone_hour_minute(self, r):
        rows = r.execute(
            "select extract(timezone_hour from ts), "
            "extract(timezone_minute from ts) from (select timestamp "
            "'2024-06-01 00:00:00 +05:30' as ts)"
        ).rows[0]
        assert rows == [5, 30]

    def test_timezone_hour_negative(self, r):
        rows = r.execute(
            "select extract(timezone_hour from ts) from (select "
            f"timestamp '2024-01-15 12:00:00 {NY}' as ts)"
        ).rows[0]
        assert rows == [-5]


class TestArithmetic:
    def test_add_day_across_spring_forward(self, r):
        # +24 exact hours over the DST gap: wall clock jumps to 13:00
        got = q1(
            r,
            f"select timestamp '2024-03-09 12:00:00 {NY}' "
            "+ interval '1' day",
        )
        assert got == "2024-03-10 13:00:00.000 America/New_York"

    def test_sub_hour_across_fall_back(self, r):
        got = q1(
            r,
            f"select timestamp '2024-11-03 01:30:00 {NY}' "
            "- interval '2' hour",
        )
        # 01:30 EST (the second 01:30) minus 2h = 00:30 EDT
        assert got.endswith("America/New_York")

    def test_comparison_and_between(self, r):
        assert q1(
            r,
            "select timestamp '2024-01-01 00:00:00 UTC' < "
            "timestamp '2024-01-01 00:00:01 UTC'",
        ) is True


class TestEngineIntegration:
    @pytest.fixture(scope="class")
    def rt(self, r):
        r.execute(
            "create table memory.t.ev (ts timestamp with time zone, v bigint)"
        )
        r.execute(
            "insert into ev values "
            f"(timestamp '2024-03-10 01:59:00 {NY}', 1), "
            f"(timestamp '2024-03-10 03:00:00 {NY}', 2), "
            f"(timestamp '2024-03-10 03:00:00 {NY}', 3), "
            "(null, 4)"
        )
        return r

    def test_group_order_minmax(self, rt):
        rows = rt.execute(
            "select ts, count(*) from ev group by ts order by ts"
        ).rows
        assert rows[0] == ["2024-03-10 01:59:00.000 America/New_York", 1]
        assert rows[1] == ["2024-03-10 03:00:00.000 America/New_York", 2]
        assert rows[2] == [None, 1]

    def test_filter_on_literal(self, rt):
        assert q1(
            rt,
            "select count(*) from ev where ts >= "
            f"timestamp '2024-03-10 03:00:00 {NY}'",
        ) == 2

    def test_min_max(self, rt):
        rows = rt.execute("select min(ts), max(ts) from ev").rows[0]
        assert rows[0].startswith("2024-03-10 01:59:00.000")
        assert rows[1].startswith("2024-03-10 03:00:00.000")

    def test_now_is_tstz(self, rt):
        got = q1(rt, "select now()")
        assert got.endswith("America/New_York")
        assert q1(rt, "select current_timezone()") == NY


class TestCoercionAndFunctions:
    """Review-hardening matrix: mixed-type comparison coercion,
    date_trunc/date_add/date_diff over tstz, AT TIME ZONE precedence."""

    def test_mixed_timestamp_tstz_comparison(self, r):
        # zone-less side coerces to tstz at the session zone (NY):
        # wall 07:00 NY == 12:00 UTC in July (EDT, -04:00)... actually
        # 08:00 EDT == 12:00 UTC
        assert q1(
            r,
            "select timestamp '2024-07-04 08:00:00' = "
            "timestamp '2024-07-04 12:00:00 UTC'",
        ) is True
        assert q1(
            r,
            "select timestamp '2024-07-04 07:59:00' < "
            "timestamp '2024-07-04 12:00:00 UTC'",
        ) is True

    def test_at_time_zone_binds_tighter_than_plus(self, r):
        got = q1(
            r,
            "select timestamp '2024-07-04 12:00:00 UTC' "
            "at time zone 'Asia/Tokyo' + interval '1' hour",
        )
        assert got == "2024-07-04 22:00:00.000 Asia/Tokyo"

    def test_date_trunc_in_value_zone(self, r):
        got = q1(
            r,
            "select date_trunc('day', timestamp "
            "'2024-07-04 01:30:00 Asia/Tokyo')",
        )
        # midnight TOKYO wall clock, zone preserved
        assert got == "2024-07-04 00:00:00.000 Asia/Tokyo"

    def test_date_add_hour_exact_instant(self, r):
        # +3 exact hours across the NY spring-forward gap
        got = q1(
            r,
            "select date_add('hour', 3, timestamp "
            f"'2024-03-10 00:30:00 {NY}')",
        )
        assert got == "2024-03-10 04:30:00.000 America/New_York"

    def test_date_add_day_calendar(self, r):
        # +1 calendar day keeps the WALL clock across the transition
        got = q1(
            r,
            "select date_add('day', 1, timestamp "
            f"'2024-03-09 12:00:00 {NY}')",
        )
        assert got == "2024-03-10 12:00:00.000 America/New_York"

    def test_date_diff_hours_instant(self, r):
        # spring-forward day has 23 wall hours but the instants differ
        # by 23 exact hours between equal wall times
        got = q1(
            r,
            "select date_diff('hour', "
            f"timestamp '2024-03-10 00:00:00 {NY}', "
            f"timestamp '2024-03-11 00:00:00 {NY}')",
        )
        assert got == 23

    def test_extract_hour_from_date_rejected(self, r):
        from trino_tpu.sql.analyzer import AnalysisError

        with pytest.raises(Exception):
            r.execute("select extract(hour from date '2024-01-01')")

    def test_year_month_functions_on_tstz(self, r):
        rows = r.execute(
            "select year(ts), month(ts), hour(ts) from (select "
            "timestamp '2024-12-31 23:00:00 -05:00' as ts)"
        ).rows[0]
        assert rows == [2024, 12, 23]


class TestMixedZoneKeys:
    """Equal instants in DIFFERENT zones must group/join/distinct as one
    key (canonicalize_tstz_keys, sql/optimizer.py): 01:59 America/New_York
    == 06:59 UTC on 2024-03-10."""

    @pytest.fixture(scope="class")
    def rz(self):
        r = LocalQueryRunner(Session(catalog="memory", schema="z"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("create table mz (ts timestamp with time zone, v bigint)")
        r.execute(
            "insert into mz values"
            " (TIMESTAMP '2024-03-10 01:59:00 America/New_York', 1),"
            " (TIMESTAMP '2024-03-10 06:59:00 UTC', 2),"
            " (TIMESTAMP '2024-03-10 07:59:00 UTC', 5)"
        )
        return r

    def test_group_by_merges_equal_instants(self, rz):
        rows = rz.execute(
            "select ts, sum(v) from mz group by ts order by 2"
        ).rows
        assert len(rows) == 2
        assert sorted(x[1] for x in rows) == [3, 5]
        # representative keeps an ORIGINAL zone from the group
        assert rows[0][0] in (
            "2024-03-10 01:59:00.000 America/New_York",
            "2024-03-10 06:59:00.000 UTC",
        )

    def test_count_distinct_and_select_distinct(self, rz):
        assert rz.execute("select count(distinct ts) from mz").rows[0][0] == 2
        assert len(rz.execute("select distinct ts from mz").rows) == 2

    def test_join_matches_across_zones(self, rz):
        rz.execute("create table mu (ts timestamp with time zone, w bigint)")
        rz.execute(
            "insert into mu values (TIMESTAMP '2024-03-10 06:59:00 UTC', 77)"
        )
        rows = rz.execute(
            "select mz.v, mu.w from mz join mu on mz.ts = mu.ts order by 1"
        ).rows
        assert rows == [[1, 77], [2, 77]]
        semi = rz.execute(
            "select v from mz where ts in (select ts from mu) order by 1"
        ).rows
        assert semi == [[1], [2]]

    def test_window_partition_by_merges_equal_instants(self, rz):
        # window PARTITION BY must key on the instant, not the packed
        # (millis, zone) value: rows 1 and 2 are the same instant in
        # different zones and land in ONE partition
        rows = rz.execute(
            "select v, count(*) over (partition by ts) c, "
            "sum(v) over (partition by ts) s from mz order by v"
        ).rows
        assert rows == [[1, 2, 3], [2, 2, 3], [5, 1, 5]]

    def test_window_partition_by_tstz_rank_order(self, rz):
        # ordered frame inside a tstz partition; the appended masked key
        # must not shift the function's arg/order channels
        rows = rz.execute(
            "select v, row_number() over (partition by ts order by v) r "
            "from mz order by v"
        ).rows
        assert rows == [[1, 1], [2, 2], [5, 1]]

    def test_optimizer_off_same_answers(self, rz):
        rz.execute("SET SESSION enable_optimizer = false")
        try:
            rows = rz.execute(
                "select ts, sum(v) from mz group by ts order by 2"
            ).rows
        finally:
            rz.execute("SET SESSION enable_optimizer = true")
        assert len(rows) == 2 and sorted(x[1] for x in rows) == [3, 5]
