"""Plan sanity-checker pipeline (sql/validate.py).

Each corrupted-plan case asserts the RIGHT checker fires and names the
RIGHT node — a validator that trips on the wrong checker would mask the
actual invariant. Plus: plan determinism over the full TPC-H suite, the
rules-mode regression (a rule mutated to mis-shift refs is caught and
NAMED), and the cost-based partial-aggregation gate.
"""

import dataclasses

import pytest

from trino_tpu import types as T
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.expr import ir
from trino_tpu.sql import plan as P
from trino_tpu.sql.fragmenter import (
    PlanFragment,
    SubPlan,
    push_partial_aggregation_through_exchange,
)
from trino_tpu.sql.optimizer import IterativeOptimizer, Rule
from trino_tpu.sql.parser import parse
from trino_tpu.sql.validate import (
    Lowering,
    PlanValidationError,
    check_plan_determinism,
    check_sql_stability,
    shape_census,
    validate_logical,
    validate_subplan,
)
from tests.tpch_queries import QUERIES


def _values(*fields):
    fs = tuple(P.Field(n, t) for n, t in fields)
    return P.ValuesNode(fs, ((0,) * len(fs),))


def _err(fn) -> PlanValidationError:
    with pytest.raises(PlanValidationError) as e:
        fn()
    return e.value


# -- corrupted plans: one per checker -----------------------------------------


def test_bad_ref_index_names_refs_checker():
    vals = _values(("a", T.BIGINT))
    bad = P.ProjectNode(
        vals, (ir.InputRef(5, T.BIGINT),), (P.Field("x", T.BIGINT),)
    )
    e = _err(lambda: validate_logical(bad))
    assert e.checker == "refs"
    assert "Project" in e.node_path
    assert "5" in str(e)


def test_wrong_field_dtype_names_types_checker():
    vals = _values(("a", T.BIGINT))
    bad = P.ProjectNode(
        vals, (ir.InputRef(0, T.BIGINT),), (P.Field("x", T.DOUBLE),)
    )
    e = _err(lambda: validate_logical(bad))
    assert e.checker == "types"
    assert "Project" in e.node_path


def test_duplicate_node_object_names_structure_checker():
    vals = _values(("a", T.BIGINT))
    proj = P.ProjectNode(
        vals, (ir.InputRef(0, T.BIGINT),), (P.Field("x", T.BIGINT),)
    )
    bad = P.UnionAllNode((proj, proj), proj.fields)
    e = _err(lambda: validate_logical(bad))
    assert e.checker == "structure"
    assert "duplicate" in str(e)
    assert "Project" in e.node_path


def test_mismatched_exchange_keys_names_exchange_checker():
    left_in = _values(("a", T.BIGINT))
    right_in = _values(("b", T.BIGINT), ("s", T.VARCHAR))
    left = P.ExchangeNode(left_in, "repartition", (0,), left_in.fields)
    # join keys agree (both bigint) but the right side repartitions on
    # the VARCHAR column — rows land on different tasks
    right = P.ExchangeNode(right_in, "repartition", (1,), right_in.fields)
    bad = P.JoinNode(
        "inner", left, right, (0,), (0,), None, left.fields + right.fields
    )
    e = _err(lambda: validate_logical(bad))
    assert e.checker == "exchange_keys"
    assert "Join" in e.node_path


def test_uncanonicalized_tstz_key_names_exchange_checker():
    vals = _values(("ts", T.TIMESTAMP_TZ))
    bad = P.ExchangeNode(vals, "repartition", (0,), vals.fields)
    e = _err(lambda: validate_logical(bad))
    assert e.checker == "exchange_keys"
    assert "Exchange" in e.node_path
    assert "$utc" in str(e)


def test_canonicalized_tstz_key_passes():
    vals = _values(("ts$utc", T.TIMESTAMP_TZ))
    ok = P.ExchangeNode(vals, "repartition", (0,), vals.fields)
    validate_logical(ok)


def test_dangling_remote_source_names_structure_checker():
    remote = P.RemoteSourceNode((99,), (P.Field("a", T.BIGINT),))
    frag = PlanFragment(0, remote, "single", "single")
    e = _err(lambda: validate_subplan(SubPlan(frag, [])))
    assert e.checker == "structure"
    assert "RemoteSource" in e.node_path
    assert "99" in str(e)


def test_remote_source_schema_disagreement():
    producer = PlanFragment(
        1, _values(("a", T.VARCHAR)), "single", "single"
    )
    remote = P.RemoteSourceNode((1,), (P.Field("a", T.BIGINT),))
    consumer = PlanFragment(0, remote, "single", "single")
    e = _err(
        lambda: validate_subplan(SubPlan(consumer, [SubPlan(producer, [])]))
    )
    assert e.checker == "structure"
    assert "producer" in str(e)


def test_aggregate_width_mismatch_names_refs_checker():
    vals = _values(("k", T.BIGINT), ("v", T.BIGINT))
    bad = P.AggregateNode(
        vals, (0,), (P.AggCall("sum", 1, T.BIGINT),),
        (P.Field("k", T.BIGINT),),  # missing the agg output field
    )
    e = _err(lambda: validate_logical(bad))
    assert e.checker == "refs"
    assert "Aggregate" in e.node_path


# -- determinism over the full TPC-H suite ------------------------------------


@pytest.fixture(scope="module")
def tpch_runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


def test_tpch_planning_is_deterministic(tpch_runner):
    for qid, sql in sorted(QUERIES.items()):
        stmt = parse(sql)
        q = stmt.query if hasattr(stmt, "query") else stmt
        check_plan_determinism(
            lambda: tpch_runner._analyze(q), what=f"tpch q{qid}"
        )


def test_tpch_sql_formatting_is_stable():
    # formatted text keys the prepared-statement plan cache, so
    # formatting must be a fixpoint
    for qid, sql in sorted(QUERIES.items()):
        check_sql_stability(sql, what=f"tpch q{qid}")


def test_tpch_q3_validates_in_rules_mode(tpch_runner):
    tpch_runner.session.plan_validation = "rules"
    try:
        stmt = parse(QUERIES[3])
        tpch_runner._analyze(stmt.query if hasattr(stmt, "query") else stmt)
    finally:
        tpch_runner.session.plan_validation = "passes"


# -- rules mode catches a mutated optimizer rule ------------------------------


class MisshiftProjectRefs(Rule):
    """A deliberately broken rewrite: shifts every Project InputRef up
    by one — the classic off-by-one a real pushdown rule can make."""

    name = "misshift_project_refs"

    def apply(self, node, ctx):
        if isinstance(node, P.ProjectNode):
            shifted = tuple(
                ir.InputRef(e.index + 1, e.type)
                if isinstance(e, ir.InputRef) else e
                for e in node.exprs
            )
            if shifted != node.exprs:
                return dataclasses.replace(node, exprs=shifted)
        return None


def test_rules_mode_catches_misshifted_rule():
    vals = _values(("a", T.BIGINT))
    root = P.ProjectNode(
        vals, (ir.InputRef(0, T.BIGINT),), (P.Field("x", T.BIGINT),)
    )
    opt = IterativeOptimizer((MisshiftProjectRefs(),))
    with pytest.raises(PlanValidationError) as e:
        opt.optimize(
            root,
            validator=lambda plan, rule: validate_logical(
                plan, stage="optimizer", rule=rule
            ),
        )
    assert e.value.checker == "refs"
    assert e.value.rule == "misshift_project_refs"


# -- cost-based partial aggregation (satellite: ROADMAP open item) ------------


class _FakeStats:
    """Stats stub with a KNOWN per-column NDV — the gate only trusts
    confident estimates (unknown NDV keeps the structural split)."""

    def __init__(self, in_rows, ndv):
        self._in, self._ndv = in_rows, ndv

    def stats(self, node):
        col = dataclasses.make_dataclass("C", ["ndv"])(float(self._ndv))
        return dataclasses.make_dataclass("S", ["row_count", "col"])(
            float(self._in), lambda ch: col
        )


def _agg_over_exchange():
    vals = _values(("k", T.BIGINT), ("v", T.BIGINT))
    ex = P.ExchangeNode(vals, "repartition", (0,), vals.fields)
    return P.AggregateNode(
        ex, (0,), (P.AggCall("sum", 1, T.BIGINT),),
        (P.Field("k", T.BIGINT), P.Field("s", T.BIGINT)),
    )


def test_partial_agg_fires_when_groups_reduce():
    # 1000 rows, NDV(k)=10 -> ~10 groups: the partial step shrinks the
    # wire 100x
    root = push_partial_aggregation_through_exchange(
        _agg_over_exchange(), _FakeStats(1000, 10)
    )
    assert isinstance(root, P.AggregateNode) and root.step == "final"
    assert isinstance(root.child, P.ExchangeNode)
    assert root.child.child.step == "partial"


def test_partial_agg_skips_when_keys_nearly_unique():
    # NDV(group keys) ~= input rows: pre-aggregation cannot reduce wire
    # volume, so the split is skipped
    root = push_partial_aggregation_through_exchange(
        _agg_over_exchange(), _FakeStats(1000, 990)
    )
    assert isinstance(root, P.AggregateNode) and root.step == "single"


def test_partial_agg_fires_when_ndv_unknown():
    # unknown NDV must NOT suppress the split — the structural
    # behaviour is the safe default (TPC-DS q72 regression)
    class _UnknownNdv(_FakeStats):
        def stats(self, node):
            s = super().stats(node)
            return dataclasses.make_dataclass("S", ["row_count", "col"])(
                s.row_count,
                lambda ch: dataclasses.make_dataclass("C", ["ndv"])(None),
            )

    root = push_partial_aggregation_through_exchange(
        _agg_over_exchange(), _UnknownNdv(1000, 0)
    )
    assert root.step == "final"


def test_partial_agg_stays_structural_without_stats():
    root = push_partial_aggregation_through_exchange(_agg_over_exchange())
    assert root.step == "final"


# -- compile-churn census -----------------------------------------------------


def test_shape_census_simple_aggregation(tpch_runner):
    stmt = parse(
        "select l_returnflag, sum(l_quantity) from lineitem "
        "group by l_returnflag"
    )
    out = tpch_runner._analyze(stmt.query if hasattr(stmt, "query") else stmt)
    classes = shape_census(out, tpch_runner.catalogs)
    ops = {c.operator for c in classes}
    assert "TableScanOperator" in ops
    assert "HashAggregationOperator" in ops
    # no joins -> no retry-variant (dynamic filter) classes
    assert not any(c.retry_variant for c in classes)


def test_shape_census_join_marks_retry_variant(tpch_runner):
    stmt = parse(
        "select n_name, count(*) from supplier, nation "
        "where s_nationkey = n_nationkey group by n_name"
    )
    out = tpch_runner._analyze(stmt.query if hasattr(stmt, "query") else stmt)
    classes = shape_census(out, tpch_runner.catalogs)
    variants = [c for c in classes if c.retry_variant]
    assert variants and all(
        c.operator == "DynamicFilterOperator" for c in variants
    )
    assert shape_census(
        out, tpch_runner.catalogs, dynamic_filtering=False
    ) == [c for c in classes if not c.retry_variant]


def test_explain_analyze_census_matches_observed(tpch_runner):
    res = tpch_runner.execute(
        "explain analyze select l_returnflag, sum(l_quantity) "
        "from lineitem group by l_returnflag"
    )
    text = res.rows[0][0]
    assert "expected_xla_lowerings=" in text
    assert "observed_shape_classes=" in text
    expected = int(
        text.split("expected_xla_lowerings=")[1].split()[0].rstrip(";")
    )
    observed = int(
        text.split("observed_shape_classes=")[1].split()[0].rstrip(";")
    )
    # the acceptance bound: static census within +-1 of what actually
    # ran (sinks compile no output program; estimate jitter rounds away
    # inside the power-of-two capacity classes)
    assert abs(expected - observed) <= 1, text


def test_explain_analyze_census_tail_classes(tpch_runner):
    """Tables larger than batch_rows scan in batch_rows chunks plus one
    smaller tail chunk; the census must count the tail capacity class
    (PR 5 carried a known miss here) and the ±1 acceptance bound must
    hold through it. batch_rows=49152 puts lineitem tiny (60175 rows)
    at main class 65536 + tail class 16384."""
    tpch_runner.execute("SET SESSION batch_rows = 49152")
    try:
        res = tpch_runner.execute(
            "explain analyze select l_returnflag, sum(l_quantity) "
            "from lineitem group by l_returnflag"
        )
        text = res.rows[0][0]
        expected = int(
            text.split("expected_xla_lowerings=")[1].split()[0].rstrip(";")
        )
        observed = int(
            text.split("observed_shape_classes=")[1].split()[0].rstrip(";")
        )
        assert abs(expected - observed) <= 1, text
        # both the main and the tail scan class are predicted
        assert "TableScanOperator cap=65536" in text, text
        assert "TableScanOperator cap=16384" in text, text
    finally:
        tpch_runner.execute(f"SET SESSION batch_rows = {1 << 20}")


def test_census_warns_above_threshold():
    classes = [
        Lowering(f"Op{i}", 16, ("bigint",)) for i in range(5)
    ]
    from trino_tpu.sql.validate import census_line

    assert "WARNING" in census_line(classes, warn_threshold=3)
    assert "WARNING" not in census_line(classes, warn_threshold=10)
