"""Window functions + set operations vs the sqlite oracle
(AbstractTestWindowQueries analogue, SURVEY.md §4.3)."""

import sqlite3

import pytest

from tests.oracle import assert_rows_match, load_tpch_sqlite, sqlite_rows
from tests.test_tpch import to_sqlite

SF = 0.01


@pytest.fixture(scope="module")
def oracle():
    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def runner(tpch_local):
    return tpch_local


WINDOW_QUERIES = [
    # ranking family
    "select n_regionkey, n_name, row_number() over (partition by n_regionkey order by n_name) rn"
    " from nation order by n_regionkey, n_name",
    "select n_regionkey, n_name,"
    " rank() over (partition by n_regionkey order by substr(n_name,1,1)) r,"
    " dense_rank() over (partition by n_regionkey order by substr(n_name,1,1)) dr"
    " from nation order by n_regionkey, n_name",
    "select n_regionkey, n_name, ntile(3) over (partition by n_regionkey order by n_name) b"
    " from nation order by n_regionkey, n_name",
    # whole-partition aggregates
    "select s_nationkey, s_name, sum(s_acctbal) over (partition by s_nationkey) tot,"
    " count(*) over (partition by s_nationkey) c"
    " from supplier order by s_nationkey, s_name",
    "select o_orderkey, avg(o_totalprice) over (partition by o_orderpriority) a"
    " from orders where o_orderkey < 100 order by o_orderkey",
    # running frames
    "select s_nationkey, s_name, s_acctbal, sum(s_acctbal) over"
    " (partition by s_nationkey order by s_name rows between unbounded preceding and current row) run"
    " from supplier order by s_nationkey, s_name",
    "select s_name, min(s_acctbal) over"
    " (order by s_suppkey rows between unbounded preceding and current row) m"
    " from supplier order by s_suppkey",
    # default RANGE frame with peers (sum over order-by with duplicates)
    "select o_custkey, o_orderkey, sum(o_orderkey) over"
    " (partition by o_custkey order by o_orderdate) s"
    " from orders where o_custkey < 30 order by o_custkey, o_orderkey",
    # navigation
    "select o_custkey, o_orderkey, lag(o_orderkey) over (partition by o_custkey order by o_orderkey) prev,"
    " lead(o_orderkey, 2) over (partition by o_custkey order by o_orderkey) nxt2"
    " from orders where o_custkey < 20 order by o_custkey, o_orderkey",
    "select n_name, first_value(n_name) over (partition by n_regionkey order by n_name) f,"
    " last_value(n_name) over (partition by n_regionkey) l"
    " from nation order by n_name",
    # window over aggregated input
    "select n_regionkey, count(*) c, rank() over (order by count(*) desc) r"
    " from nation group by n_regionkey order by r, n_regionkey",
]


@pytest.mark.parametrize("sql", WINDOW_QUERIES)
def test_window_query(sql, runner, oracle):
    got = runner.execute(sql).rows
    want = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(got, want, ordered=True, abs_tol=1e-2)


SET_QUERIES = [
    "select c_custkey from customer where c_custkey < 100 intersect"
    " select o_custkey from orders order by 1",
    "select c_custkey from customer where c_custkey < 100 except"
    " select o_custkey from orders order by c_custkey limit 5",
    "select n_regionkey from nation intersect select r_regionkey from region order by 1 desc",
    "select o_orderstatus from orders except select 'O' order by 1",
    "select c_mktsegment from customer intersect select 'BUILDING'",
    "select n_name from nation where n_regionkey = 0 union"
    " select n_name from nation where n_regionkey = 1 order by 1 limit 4",
]


@pytest.mark.parametrize("sql", SET_QUERIES)
def test_set_operation(sql, runner, oracle):
    got = runner.execute(sql).rows
    want = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(got, want, ordered="order by" in sql, abs_tol=1e-2)


def test_unsupported_frame_rejected(runner):
    from trino_tpu.sql.parser import ParsingError

    with pytest.raises(ParsingError):
        runner.execute(
            "select sum(n_nationkey) over (order by n_name"
            " rows between 2 preceding and current row) from nation"
        )


def test_window_distributed(oracle, tpch_cluster):
    """Window functions through the fragmenter: repartition on the
    PARTITION BY keys, window per task."""
    r = tpch_cluster
    sql = (
        "select s_nationkey, s_name, sum(s_acctbal) over (partition by s_nationkey) t,"
        " row_number() over (partition by s_nationkey order by s_name) rn"
        " from supplier order by s_nationkey, s_name"
    )
    got = r.execute(sql).rows
    want = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(got, want, ordered=True, abs_tol=1e-2)


def test_percent_rank_and_cume_dist(runner):
    """percent_rank/cume_dist vs hand-computed oracle."""
    rows = runner.execute(
        "SELECT n_regionkey, n_nationkey,"
        " percent_rank() OVER (PARTITION BY n_regionkey ORDER BY n_nationkey),"
        " cume_dist() OVER (PARTITION BY n_regionkey ORDER BY n_nationkey)"
        " FROM nation ORDER BY n_regionkey, n_nationkey"
    ).rows
    by_rk = {}
    for rk, nk, pr, cd in rows:
        by_rk.setdefault(rk, []).append((nk, pr, cd))
    for rk, items in by_rk.items():
        n = len(items)
        for i, (nk, pr, cd) in enumerate(items):
            want_pr = 0.0 if n == 1 else i / (n - 1)
            want_cd = (i + 1) / n
            assert abs(pr - want_pr) < 1e-12, (rk, nk)
            assert abs(cd - want_cd) < 1e-12, (rk, nk)


def test_cume_dist_with_peers(runner):
    # ties share the peer group: cume_dist counts through the group end
    rows = runner.execute(
        "SELECT x, cume_dist() OVER (ORDER BY x) FROM"
        " (VALUES (1), (2), (2), (3)) t(x) ORDER BY x"
    ).rows
    assert [r[1] for r in rows] == [0.25, 0.75, 0.75, 1.0]
    rows2 = runner.execute(
        "SELECT x, percent_rank() OVER (ORDER BY x) FROM"
        " (VALUES (1), (2), (2), (3)) t(x) ORDER BY x"
    ).rows
    assert [r[1] for r in rows2] == [0.0, 1 / 3, 1 / 3, 1.0]
