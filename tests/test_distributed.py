"""Distributed execution tests: serde, buffers, exchange client,
fragmenter, and the multi-worker DistributedQueryRunner vs the sqlite
oracle (the tier-3 strategy, SURVEY.md §4.3)."""

import threading

import numpy as np
import pytest

from tests.oracle import assert_rows_match, sqlite_rows
from tests.test_tpch import to_sqlite
from tests.tpch_queries import QUERIES
from trino_tpu import types as T
from trino_tpu.block import Column, Dictionary, RelBatch
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import Session
from trino_tpu.exec.serde import (
    Page,
    concat_pages,
    deserialize_batch,
    deserialize_page,
    serialize_batch,
    serialize_page,
)
from trino_tpu.runtime import DistributedQueryRunner
from trino_tpu.runtime.buffers import OutputBuffer
from trino_tpu.runtime.exchange import DirectExchangeClient, ExchangeLocation
from trino_tpu.sql.fragmenter import plan_distributed
from trino_tpu.sql.analyzer import Analyzer
from trino_tpu.sql.parser import parse
from trino_tpu.sql import plan as P


# ---------------------------------------------------------------------------
# serde
# ---------------------------------------------------------------------------


def _sample_batch():
    return RelBatch.from_pydict(
        [("a", T.BIGINT), ("b", T.VARCHAR), ("c", T.DOUBLE)],
        {
            "a": [1, 2, None, 4, 5],
            "b": ["x", "y", "x", None, "zz"],
            "c": [1.5, -2.25, 0.0, 3.75, None],
        },
    )


def test_serde_roundtrip():
    b = _sample_batch()
    out = deserialize_batch(serialize_batch(b))
    assert out.to_pylists() == b.to_pylists()


def test_serde_compression_roundtrip():
    b = _sample_batch()
    raw = serialize_batch(b, compress=False)
    packed = serialize_batch(b, compress=True)
    assert raw[0] == 0 and packed[0] == 1
    assert deserialize_batch(raw).to_pylists() == deserialize_batch(packed).to_pylists()


def test_serde_wire_is_not_pickle():
    """The page body must be the self-describing binary layout; wire
    bytes from a worker port must never reach an object deserializer
    (RCE surface — VERDICT r1 weak #4)."""
    import inspect
    import pickle

    import trino_tpu.exec.serde as S

    src = inspect.getsource(S)
    assert "import pickle" not in src and "pickle.loads" not in src
    blob = serialize_batch(_sample_batch(), compress=False)
    body = blob[5:]
    with pytest.raises(Exception):
        pickle.loads(body)
    # magic marker present
    import struct

    assert struct.unpack_from("<I", body, 0)[0] == 0x54504732  # TPG2


def test_serde_all_types_roundtrip():
    b = RelBatch.from_pydict(
        [
            ("i", T.BIGINT),
            ("s", T.VARCHAR),
            ("d", T.decimal(12, 2)),
            ("f", T.DOUBLE),
            ("t", T.DATE),
            ("bo", T.BOOLEAN),
        ],
        {
            "i": [1, None, 3],
            "s": ["a", None, "b"],
            "d": [1.25, 2.5, None],
            "f": [0.5, None, -1.5],
            "t": [1, 2, None],
            "bo": [True, False, None],
        },
    )
    out = deserialize_batch(serialize_batch(b))
    assert out.to_pylists() == b.to_pylists()
    for c1, c2 in zip(b.columns, out.columns):
        assert c1.type == c2.type


def test_serde_rejects_corrupt_frames():
    blob = serialize_batch(_sample_batch(), compress=False)
    with pytest.raises(Exception):
        deserialize_page(b"\x00" + blob[1:5] + b"garbage-not-a-page")


def test_page_concat_unifies_dictionaries():
    p1 = Page.from_batch(
        RelBatch.from_pydict([("s", T.VARCHAR)], {"s": ["a", "b"]})
    )
    p2 = Page.from_batch(
        RelBatch.from_pydict([("s", T.VARCHAR)], {"s": ["c", "a"]})
    )
    merged = concat_pages([p1, p2])
    assert merged.row_count == 4
    batch = merged.to_batch()
    assert [r[0] for r in batch.to_pylists()] == ["a", "b", "c", "a"]


# ---------------------------------------------------------------------------
# buffers + exchange client (pull + ack)
# ---------------------------------------------------------------------------


def _page_of(values):
    return Page.from_batch(
        RelBatch.from_pydict([("v", T.BIGINT)], {"v": values})
    )


def test_output_buffer_token_ack():
    buf = OutputBuffer(1)
    buf.enqueue(0, _page_of([1]))
    buf.enqueue(0, _page_of([2]))
    pages, token, complete = buf.get_pages(0, 0)
    assert len(pages) == 2 and token == 2 and not complete
    # re-request same token: at-least-once redelivery
    pages2, token2, _ = buf.get_pages(0, 0)
    assert len(pages2) == 2 and token2 == 2
    buf.set_no_more_pages()
    # advancing to token 2 acks both and reports completion
    pages3, token3, complete3 = buf.get_pages(0, 2)
    assert pages3 == [] and complete3
    assert buf.is_fully_consumed()


def test_output_buffer_backpressure_unblocks():
    buf = OutputBuffer(1, max_bytes=8)
    buf.enqueue(0, _page_of([1, 2, 3]))
    done = threading.Event()

    def producer():
        buf.enqueue(0, _page_of([4]))  # blocks until consumer acks
        done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    assert not done.wait(0.1)
    _, token, _ = buf.get_pages(0, 0)
    buf.get_pages(0, token)  # ack
    assert done.wait(2.0)


def test_exchange_client_pulls_all_locations():
    bufs = [OutputBuffer(1), OutputBuffer(1)]
    bufs[0].enqueue(0, _page_of([1, 2]))
    bufs[1].enqueue(0, _page_of([3]))
    for b in bufs:
        b.set_no_more_pages()
    client = DirectExchangeClient(
        [ExchangeLocation(b.get_pages, 0) for b in bufs], long_poll_s=0.05
    )
    got = []
    while not client.is_finished():
        p = client.poll()
        if p is not None:
            got.extend(int(x) for x in p.columns[0])
    assert sorted(got) == [1, 2, 3]


def test_aborted_buffer_fails_consumer():
    buf = OutputBuffer(1)
    buf.abort()
    with pytest.raises(RuntimeError):
        buf.get_pages(0, 0)


# ---------------------------------------------------------------------------
# fragmenter
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def catalogs():
    from trino_tpu.connectors.spi import CatalogManager

    c = CatalogManager()
    c.register("tpch", create_tpch_connector())
    return c


def _fragments(catalogs, sql):
    analyzer = Analyzer(catalogs, "tpch", "tiny")
    output = analyzer.plan(parse(sql))
    return plan_distributed(output, catalogs)


def test_fragmenter_groupby_shape(catalogs):
    sp = _fragments(
        catalogs, "select l_returnflag, sum(l_quantity) from lineitem group by l_returnflag"
    )
    frags = {f.id: f for f in sp.all_fragments()}
    assert len(frags) == 3
    # leaf: source-partitioned partial agg with hash output
    leaf = [f for f in frags.values() if f.partitioning == "source"]
    assert len(leaf) == 1 and leaf[0].output_kind == "hash"
    # middle: hash-partitioned final agg
    mid = [f for f in frags.values() if f.partitioning == "hash"]
    assert len(mid) == 1

    def find_steps(n, acc):
        if isinstance(n, P.AggregateNode):
            acc.append(n.step)
        for c in n.children():
            find_steps(c, acc)
        return acc

    steps = []
    for f in frags.values():
        find_steps(f.root, steps)
    assert sorted(steps) == ["final", "partial"]


def test_fragmenter_broadcast_join(catalogs):
    # nation (25 rows) broadcasts under the default threshold
    sp = _fragments(
        catalogs,
        "select n_name, s_name from supplier, nation where s_nationkey = n_nationkey",
    )
    kinds = {f.output_kind for f in sp.all_fragments()}
    assert "broadcast" in kinds


def test_fragmenter_distributed_sort(catalogs):
    sp = _fragments(
        catalogs, "select o_orderkey from orders order by o_orderkey"
    )
    # local sort lives in the source fragment; the gather merges
    src = [f for f in sp.all_fragments() if f.partitioning == "source"][0]
    assert src.output_merge_keys

    def has_sort(n):
        return isinstance(n, P.SortNode) or any(has_sort(c) for c in n.children())

    assert has_sort(src.root)


# ---------------------------------------------------------------------------
# end-to-end vs the sqlite oracle
# ---------------------------------------------------------------------------

SF = 0.01
# ALL 22 TPC-H queries through the page-exchange path at 4 workers
# (AbstractTestQueries breadth; the mesh plane covers its own subset in
# tests/test_mesh.py)
DIST_QUERIES = sorted(QUERIES)


@pytest.fixture(scope="module")
def oracle():
    import sqlite3

    from tests.oracle import load_tpch_sqlite

    conn = sqlite3.connect(":memory:")
    load_tpch_sqlite(conn, SF)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def runner():
    # mesh_execution off: this suite covers the HTTP page-exchange data
    # plane (workers/tasks/buffers); tests/test_mesh.py covers the
    # collective data plane
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", mesh_execution=False),
        n_workers=4, hash_partitions=4,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.mark.parametrize("qid", DIST_QUERIES)
def test_distributed_tpch(qid, runner, oracle):
    sql = QUERIES[qid]
    res = runner.execute(sql)
    expected = sqlite_rows(oracle, to_sqlite(sql))
    assert_rows_match(
        res.rows, expected, ordered=("order by" in sql), abs_tol=1e-2
    )


def test_distributed_explain(runner):
    plan = runner.execute(
        "EXPLAIN SELECT count(*) FROM orders"
    ).only_value()
    assert "Fragment" in plan and "RemoteSource" in plan


# ---------------------------------------------------------------------------
# HTTP worker topology + discovery
# ---------------------------------------------------------------------------


def test_http_worker_topology():
    """Coordinator schedules over workers behind real HTTP servers; pages
    stream over the wire with token/ack pulls."""
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime.http import HttpWorkerClient, WorkerServer
    from trino_tpu.runtime.worker import Worker

    servers, handles = [], []
    try:
        for i in range(2):
            cats = CatalogManager()
            cats.register("tpch", create_tpch_connector())
            servers.append(
                WorkerServer(Worker(f"w{i}", cats), require_secret=False)
            )
            handles.append(HttpWorkerClient(servers[-1].uri))
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny"),
            worker_handles=handles,
            hash_partitions=2,
        )
        r.register_catalog("tpch", create_tpch_connector())
        res = r.execute(
            "SELECT l_returnflag, count(*) FROM lineitem"
            " GROUP BY l_returnflag ORDER BY l_returnflag"
        )
        assert [row[0] for row in res.rows] == ["A", "N", "R"]
        assert sum(row[1] for row in res.rows) == 60064
        # worker status + graceful shutdown surface
        st = handles[0].status()
        assert st["state"] == "active"
        handles[0].shutdown_gracefully()
        assert handles[0].status()["state"] == "shutting_down"
    finally:
        for s in servers:
            s.stop()


def test_internal_auth_rejects_unauthenticated(monkeypatch):
    """With a shared secret, every worker endpoint answers 401 to
    requests without a valid internal bearer; an authenticated client
    works end to end (InternalAuthenticationManager analogue)."""
    import urllib.error
    import urllib.request

    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime.http import HttpWorkerClient, WorkerServer
    from trino_tpu.runtime.worker import Worker

    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    srv = WorkerServer(Worker("w0", cats), internal_secret="s3cret")
    # worker-side page pulls (http_fetch) read the cluster secret from
    # the environment, like etc/config.properties cluster config
    monkeypatch.setenv("TRINO_TPU_INTERNAL_SECRET", "s3cret")
    try:
        # no bearer -> 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.uri + "/v1/status", timeout=5)
        assert ei.value.code == 401
        # wrong secret -> 401
        bad = HttpWorkerClient(srv.uri, internal_secret="wrong")
        with pytest.raises(urllib.error.HTTPError) as ei:
            bad.status()
        assert ei.value.code == 401
        # right secret -> full task protocol works
        ok = HttpWorkerClient(srv.uri, internal_secret="s3cret")
        assert ok.status()["state"] == "active"
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny"),
            worker_handles=[ok],
        )
        r.register_catalog("tpch", create_tpch_connector())
        res = r.execute("SELECT count(*) FROM region")
        assert res.rows == [[5]]
    finally:
        srv.stop()


def test_http_task_failure_reported():
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime.http import HttpWorkerClient, WorkerServer
    from trino_tpu.runtime.worker import Worker

    # worker with NO catalogs: tasks fail at plan time
    srv = WorkerServer(Worker("w0", CatalogManager()), require_secret=False)
    try:
        handle = HttpWorkerClient(srv.uri)
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema="tiny"), worker_handles=[handle]
        )
        r.register_catalog("tpch", create_tpch_connector())
        with pytest.raises(RuntimeError, match="query failed"):
            r.execute("SELECT count(*) FROM orders")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# fault-tolerant execution (BaseFailureRecoveryTest analogue, SURVEY §4.4)
# ---------------------------------------------------------------------------


FTE_QUERY = (
    "SELECT l_returnflag, sum(l_quantity), count(*) FROM lineitem"
    " GROUP BY l_returnflag ORDER BY l_returnflag"
)


@pytest.fixture()
def fte_cluster():
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime.failure import FailureInjector
    from trino_tpu.runtime.worker import Worker

    inj = FailureInjector()
    cats = CatalogManager()
    cats.register("tpch", create_tpch_connector())
    workers = [Worker(f"w{i}", cats, failure_injector=inj) for i in range(2)]
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="task"),
        worker_handles=workers,
        hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r, inj


def test_fte_survives_task_failure_at_start(fte_cluster):
    r, inj = fte_cluster
    baseline = r.execute(FTE_QUERY).rows
    inj.inject(fragment_id=0, partition=0, attempts=(0,), where="start")
    assert r.execute(FTE_QUERY).rows == baseline


def test_fte_survives_task_failure_after_output(fte_cluster):
    r, inj = fte_cluster
    baseline = r.execute(FTE_QUERY).rows
    inj.inject(fragment_id=1, partition=1, attempts=(0, 1), where="mid")
    assert r.execute(FTE_QUERY).rows == baseline


FTE_SHAPES = {
    # each shape exercises a different stage topology under retry:
    # plain partial->final aggregation; partitioned join + aggregation;
    # distributed sort with a MERGE-SORTED exchange; semi join + TopN
    "agg": FTE_QUERY,
    "join_agg": (
        "SELECT o_orderpriority, count(*) FROM orders, lineitem"
        " WHERE o_orderkey = l_orderkey AND l_shipmode = 'MAIL'"
        " GROUP BY o_orderpriority ORDER BY o_orderpriority"
    ),
    "sort_merge": (
        "SELECT l_orderkey, l_extendedprice FROM lineitem"
        " WHERE l_suppkey < 3 ORDER BY l_extendedprice DESC, l_orderkey"
    ),
    "semi_topn": (
        "SELECT o_orderkey, o_totalprice FROM orders WHERE o_orderkey IN"
        " (SELECT l_orderkey FROM lineitem WHERE l_quantity > 49)"
        " ORDER BY o_totalprice DESC, o_orderkey LIMIT 20"
    ),
}


@pytest.mark.parametrize("where", ["start", "mid"])
@pytest.mark.parametrize("shape", sorted(FTE_SHAPES))
def test_fte_injection_matrix(fte_cluster, shape, where):
    """Every task's FIRST attempt dies (wildcard rule over all
    fragments) at `where`; the FTE scheduler must retry each and still
    produce exact results — including the merge-sorted exchange stage
    (BaseFailureRecoveryTest.java:78 breadth)."""
    r, inj = fte_cluster
    sql = FTE_SHAPES[shape]
    baseline = r.execute(sql).rows
    inj.clear()
    inj.inject(attempts=(0,), where=where)
    assert r.execute(sql).rows == baseline


def test_fte_retries_exhausted(fte_cluster):
    from trino_tpu.runtime.fte import TaskRetriesExceeded

    r, inj = fte_cluster
    inj.inject(fragment_id=0, attempts=tuple(range(10)), where="start")
    with pytest.raises(TaskRetriesExceeded):
        r.execute(FTE_QUERY)


def test_query_retry_policy(fte_cluster):
    r, inj = fte_cluster
    baseline = r.execute(FTE_QUERY).rows
    r2 = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", retry_policy="query"),
        worker_handles=r.workers,
        hash_partitions=2,
    )
    r2.catalogs = r.catalogs
    inj.inject(fragment_id=0, partition=0, attempts=(0,), where="start", max_hits=1)
    assert r2.execute(FTE_QUERY).rows == baseline


def test_spool_commit_roundtrip(tmp_path):
    from trino_tpu.runtime.spool import (
        SpoolingExchangeSink,
        is_committed,
        read_spool,
    )

    sink = SpoolingExchangeSink(str(tmp_path), "q1.0.0", 2)
    sink.enqueue(0, _page_of([1, 2]))
    sink.enqueue(1, _page_of([3]))
    sink.enqueue(0, _page_of([4]))
    assert not is_committed(str(tmp_path), "q1.0.0")
    sink.set_no_more_pages()
    assert is_committed(str(tmp_path), "q1.0.0")
    pages, token, complete = read_spool(str(tmp_path / "q1.0.0"), 0, 0)
    assert complete and token == 2
    assert [int(x) for p in pages for x in p.columns[0]] == [1, 2, 4]


def test_discovery_heartbeat_marks_failed_worker():
    from trino_tpu.runtime.discovery import NodeManager

    class FlakyHandle:
        worker_id = "flaky"
        alive = True

        def status(self):
            if not self.alive:
                raise ConnectionError("down")
            return {"state": "active"}

    nm = NodeManager()
    h = FlakyHandle()
    nm.register(h)
    nm.ping_once()
    assert nm.all_states()["flaky"] == "active"
    h.alive = False
    for _ in range(8):
        nm.ping_once()
    assert nm.all_states()["flaky"] == "failed"
    assert nm.active_workers() == []


def test_distributed_explain_analyze(runner):
    """Operator stats cross the wire: every fragment reports per-operator
    rows/batches summed over its tasks (TaskInfo stats path)."""
    out = runner.execute(
        "EXPLAIN ANALYZE select o_orderstatus, count(*) from orders"
        " group by o_orderstatus"
    ).rows[0][0]
    assert "Fragment" in out and "tasks]" in out
    assert "Pipeline" in out
    # scan operators in the source fragment must report real row counts
    assert "in=15000 rows" in out or "out=15000 rows" in out, out


def test_worker_refuses_to_start_without_secret(monkeypatch):
    """A networked worker must not come up without internal auth — its
    task endpoint accepts plan specs (VERDICT r2 weak #7: a default-config
    worker decoded arbitrary posted bytes)."""
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime.http import WorkerServer
    from trino_tpu.runtime.worker import Worker

    monkeypatch.delenv("TRINO_TPU_INTERNAL_SECRET", raising=False)
    with pytest.raises(RuntimeError, match="internal secret"):
        WorkerServer(Worker("w0", CatalogManager()))


def test_task_spec_wire_is_typed_json_not_pickle():
    """Task specs cross the wire via the allowlisted codec: the bytes are
    JSON (auditable), decode refuses unregistered classes, and a full
    TaskSpec with a real fragment round-trips."""
    import dataclasses as _dc
    import json as _json

    from trino_tpu.runtime import codec
    from trino_tpu.runtime.task import TaskId, TaskSpec
    from trino_tpu.sql.fragmenter import plan_distributed
    from trino_tpu.sql.parser import parse

    from trino_tpu.engine import LocalQueryRunner

    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    plan = r._analyze(
        parse("SELECT l_returnflag, count(*) FROM lineitem GROUP BY 1")
    )
    sub = plan_distributed(plan, r.catalogs)
    frag = sub.all_fragments()[-1]
    spec = TaskSpec(
        task_id=TaskId("q1", frag.id, 0),
        fragment=frag,
        n_output_partitions=2,
        remote_schemas={},
        scan_slice=(0, 2),
        input_locations={0: [("http", "http://127.0.0.1:1", "q1.0.0")]},
    )
    wire = codec.dumps(spec)
    _json.loads(wire)  # plain JSON, not a binary object stream
    back = codec.loads(wire)
    assert back.task_id == spec.task_id
    assert back.fragment == frag
    assert back.input_locations == {0: [("http", "http://127.0.0.1:1", "q1.0.0")]}

    # allowlist: a class outside the registry must not decode
    with pytest.raises(codec.CodecError):
        codec.decode({"$": "os.system", "f": {}})
    # and encode refuses arbitrary objects (e.g. callables)
    with pytest.raises(codec.CodecError):
        codec.dumps({"fetch": lambda: None})
