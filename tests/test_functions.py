"""Scalar function library through the SQL surface (the
main/operator/scalar/ coverage tier, SURVEY.md §2.10)."""

import pytest



@pytest.fixture(scope="module")
def runner(tpch_local):
    return tpch_local


CASES = [
    ("SELECT 'a' || 'b' || 'c'", "abc"),
    ("SELECT concat(n_name, '_x') FROM nation WHERE n_nationkey = 0", "ALGERIA_x"),
    (
        "SELECT n_name || '-' || r_name FROM nation, region"
        " WHERE n_regionkey = r_regionkey AND n_nationkey = 0",
        "ALGERIA-AFRICA",
    ),
    ("SELECT trim('  hi  ')", "hi"),
    ("SELECT ltrim('  hi  ')", "hi  "),
    ("SELECT rtrim('  hi  ')", "  hi"),
    ("SELECT replace('banana', 'na', 'NA')", "baNANA"),
    ("SELECT reverse('abc')", "cba"),
    ("SELECT nullif(1, 1)", None),
    ("SELECT nullif(2, 1)", 2),
    ("SELECT greatest(1, 5, 3)", 5),
    ("SELECT least(1.5, 0.5)", 0.5),
    ("SELECT power(2, 10)", 1024.0),
    ("SELECT sign(-5)", -1),
    ("SELECT sign(2.5)", 1.0),
    ("SELECT mod(10, 3)", 1),
    ("SELECT year(date '1995-03-15')", 1995),
    ("SELECT month(date '1995-03-15')", 3),
    ("SELECT day(date '1995-03-15')", 15),
    ("SELECT if(1 > 2, 'yes', 'no')", "no"),
    ("SELECT if(1 < 2, 'yes', 'no')", "yes"),
    ("SELECT starts_with(n_name, 'AL') FROM nation WHERE n_nationkey = 0", True),
    ("SELECT log10(100)", 2.0),
    ("SELECT log2(8)", 3.0),
    ("SELECT greatest(1, NULL, 3)", None),
]


@pytest.mark.parametrize("sql,want", CASES)
def test_scalar_function(sql, want, runner):
    got = runner.execute(sql).only_value()
    if isinstance(want, float):
        assert got is not None and abs(got - want) < 1e-9
    else:
        assert got == want


MATH_CASES = [
    ("SELECT sin(0)", 0.0),
    ("SELECT cos(0)", 1.0),
    ("SELECT tan(0)", 0.0),
    ("SELECT asin(1)", 1.5707963267948966),
    ("SELECT acos(1)", 0.0),
    ("SELECT atan(1)", 0.7853981633974483),
    ("SELECT atan2(1, 1)", 0.7853981633974483),
    ("SELECT tanh(0)", 0.0),
    ("SELECT cbrt(27)", 3.0),
    ("SELECT degrees(pi())", 180.0),
    ("SELECT radians(180) - pi()", 0.0),
    ("SELECT log(2, 8)", 3.0),
    ("SELECT truncate(3.78)", 3.0),
    ("SELECT truncate(-3.78)", -3.0),
    ("SELECT is_nan(nan())", True),
    ("SELECT is_infinite(infinity())", True),
    ("SELECT is_finite(1.0)", True),
    ("SELECT bitwise_and(12, 10)", 8),
    ("SELECT bitwise_or(12, 10)", 14),
    ("SELECT bitwise_xor(12, 10)", 6),
    ("SELECT bitwise_not(0)", -1),
    ("SELECT bitwise_left_shift(1, 4)", 16),
    ("SELECT bitwise_right_shift(16, 2)", 4),
    ("SELECT chr(65)", "A"),
    ("SELECT e()", 2.718281828459045),
]


@pytest.mark.parametrize("sql,want", MATH_CASES)
def test_math_function(sql, want, runner):
    got = runner.execute(sql).only_value()
    if isinstance(want, float):
        assert got is not None and abs(got - want) < 1e-12
    else:
        assert got == want


STRING_CASES = [
    ("SELECT strpos(n_name, 'GER') FROM nation WHERE n_nationkey = 0", 3),
    ("SELECT strpos(n_name, 'ZZZ') FROM nation WHERE n_nationkey = 0", 0),
    ("SELECT ends_with(n_name, 'RIA') FROM nation WHERE n_nationkey = 0", True),
    ("SELECT codepoint(n_name) FROM nation WHERE n_nationkey = 0", ord("A")),
    ("SELECT split_part(n_comment, ' ', 1) IS NOT NULL FROM nation WHERE n_nationkey = 0", True),
    ("SELECT lpad(n_name, 10, '.') FROM nation WHERE n_nationkey = 0", "...ALGERIA"),
    ("SELECT rpad(n_name, 9, '!') FROM nation WHERE n_nationkey = 0", "ALGERIA!!"),
    ("SELECT lpad(n_name, 3, '.') FROM nation WHERE n_nationkey = 0", "ALG"),
    ("SELECT translate(n_name, 'AL', 'al') FROM nation WHERE n_nationkey = 0", "alGERIa"),
    ("SELECT regexp_like(n_name, '^AL') FROM nation WHERE n_nationkey = 0", True),
    ("SELECT regexp_like(n_name, '^XX') FROM nation WHERE n_nationkey = 0", False),
    ("SELECT regexp_extract(n_name, '([A-Z]+)IA$', 1) FROM nation WHERE n_nationkey = 0", "ALGER"),
    ("SELECT regexp_extract(n_name, 'XYZ') FROM nation WHERE n_nationkey = 0", None),
    ("SELECT regexp_replace(n_name, 'A', 'x') FROM nation WHERE n_nationkey = 0", "xLGERIx"),
    ("SELECT regexp_count(n_name, 'A') FROM nation WHERE n_nationkey = 0", 2),
    ("SELECT typeof(1)", "bigint"),
]


@pytest.mark.parametrize("sql,want", STRING_CASES)
def test_string_function(sql, want, runner):
    assert runner.execute(sql).only_value() == want


DATE_CASES = [
    ("SELECT quarter(date '1995-05-15')", 2),
    ("SELECT week(date '2026-01-01')", 1),
    ("SELECT day_of_week(date '2026-07-30')", 4),   # Thursday
    ("SELECT day_of_year(date '1995-02-01')", 32),
    ("SELECT extract(quarter from date '1995-11-15')", 4),
    ("SELECT extract(dow from date '2026-07-27')", 1),  # Monday
    ("SELECT date_trunc('month', date '1995-05-15') = date '1995-05-01'", True),
    ("SELECT date_trunc('year', date '1995-05-15') = date '1995-01-01'", True),
    ("SELECT date_trunc('quarter', date '1995-05-15') = date '1995-04-01'", True),
    ("SELECT date_trunc('week', date '2026-07-30') = date '2026-07-27'", True),
    ("SELECT date_add('day', 17, date '1995-12-20') = date '1996-01-06'", True),
    ("SELECT date_add('month', 1, date '1996-01-31') = date '1996-02-29'", True),
    ("SELECT date_add('year', -4, date '2000-02-29') = date '1996-02-29'", True),
    ("SELECT date_diff('day', date '1995-12-20', date '1996-01-06')", 17),
    ("SELECT date_diff('month', date '1995-01-31', date '1995-03-01')", 1),
    ("SELECT date_diff('year', date '1995-06-01', date '1997-05-31')", 1),
    ("SELECT date_diff('week', date '1995-01-01', date '1995-01-15')", 2),
    ("SELECT last_day_of_month(date '1996-02-10') = date '1996-02-29'", True),
    ("SELECT last_day_of_month(date '1995-04-10') = date '1995-04-30'", True),
]


@pytest.mark.parametrize("sql,want", DATE_CASES)
def test_date_function(sql, want, runner):
    assert runner.execute(sql).only_value() == want


def test_date_functions_on_columns(runner):
    """Vectorized paths over a real date column vs python datetime."""
    import datetime

    rows = runner.execute(
        "SELECT o_orderdate, quarter(o_orderdate), week(o_orderdate),"
        " day_of_week(o_orderdate), day_of_year(o_orderdate),"
        " date_trunc('month', o_orderdate),"
        " date_add('month', 2, o_orderdate),"
        " date_diff('day', o_orderdate, date '1998-01-01')"
        " FROM orders LIMIT 500"
    ).rows
    assert len(rows) == 500
    epoch = datetime.date(1970, 1, 1)
    for d, q, w, dw, dy, tm, am, dd in rows:
        # DATE values surface as epoch-day integers (tests/oracle.py
        # convention)
        d = epoch + datetime.timedelta(days=d)
        tm = epoch + datetime.timedelta(days=tm)
        am = epoch + datetime.timedelta(days=am)
        assert q == (d.month - 1) // 3 + 1
        assert w == d.isocalendar()[1]
        assert dw == d.isoweekday()
        assert dy == d.timetuple().tm_yday
        assert tm == d.replace(day=1)
        y = d.year + (d.month + 1) // 12
        m = (d.month + 1) % 12 + 1
        import calendar

        want_am = datetime.date(y, m, min(d.day, calendar.monthrange(y, m)[1]))
        assert am == want_am, (d, am, want_am)
        assert dd == (datetime.date(1998, 1, 1) - d).days


def test_review_regressions(runner):
    # logical (zero-fill) right shift, not arithmetic
    assert (
        runner.execute("SELECT bitwise_right_shift(-8, 2)").only_value()
        == (-8 % (1 << 64)) >> 2
    )
    # \$ escapes the dollar in regexp_replace templates
    assert (
        runner.execute(
            "SELECT regexp_replace(n_name, '^ALGERIA$', '\\$1')"
            " FROM nation WHERE n_nationkey = 0"
        ).only_value()
        == "$1"
    )
    # codepoint of the empty string is NULL, not 0
    assert (
        runner.execute(
            "SELECT codepoint(ltrim(' ')) FROM nation WHERE n_nationkey = 0"
        ).only_value()
        is None
    )


# -- registry-resolved breadth (expr/registry.py): hashing, encoding,
# URL, JSON, string distances, ISO-week year --

REGISTRY_CASES = [
    ("SELECT md5('abc')", "900150983cd24fb0d6963f7d28e17f72"),
    ("SELECT sha1('abc')", "a9993e364706816aba3e25717850c26c9cd0d89d"),
    ("SELECT sha256('abc')",
     "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"),
    ("SELECT crc32('abc')", 891568578),
    ("SELECT to_hex('AZ')", "415A"),
    ("SELECT from_hex('415a')", "AZ"),
    ("SELECT to_base64('abc')", "YWJj"),
    ("SELECT from_base64('YWJj')", "abc"),
    ("SELECT levenshtein_distance('kitten', 'sitting')", 3),
    ("SELECT hamming_distance('karolin', 'kathrin')", 3),
    ("SELECT url_extract_protocol('https://example.com:8080/p?q=1#f')",
     "https"),
    ("SELECT url_extract_host('https://example.com:8080/p?q=1#f')",
     "example.com"),
    ("SELECT url_extract_port('https://example.com:8080/p')", 8080),
    ("SELECT url_extract_port('https://example.com/p')", None),
    ("SELECT url_extract_port('https://example.com:abc/p')", None),
    ("SELECT url_extract_path('https://example.com/a/b?q=1')", "/a/b"),
    ("SELECT url_extract_query('https://example.com/p?q=1&r=2')", "q=1&r=2"),
    ("SELECT url_extract_fragment('https://example.com/p#frag')", "frag"),
    ("SELECT url_extract_parameter('https://e.com/p?a=1&b=2', 'b')", "2"),
    ("SELECT url_extract_parameter('https://e.com/p?a=1', 'zz')", None),
    ("SELECT url_encode('a b&c')", "a%20b%26c"),
    ("SELECT url_decode('a%20b%26c')", "a b&c"),
    ("SELECT json_extract_scalar('{\"a\": {\"b\": 7}}', '$.a.b')", "7"),
    ("SELECT json_extract_scalar('{\"a\": [1, \"x\"]}', '$.a[1]')", "x"),
    ("SELECT json_extract_scalar('{\"a\": true}', '$.a')", "true"),
    # numbers render as their literal document tokens
    ("SELECT json_extract_scalar('{\"a\": 7.0}', '$.a')", "7.0"),
    ("SELECT json_extract_scalar('{\"a\": 7.50}', '$.a')", "7.50"),
    ("SELECT json_extract_scalar('{\"a\": {}}', '$.a')", None),
    ("SELECT json_extract_scalar('{\"a\": 1}', '$.missing')", None),
    ("SELECT json_array_length('[1, 2, 3]')", 3),
    ("SELECT json_array_length('{\"a\": 1}')", None),
    ("SELECT json_size('{\"a\": {\"b\": 1, \"c\": 2}}', '$.a')", 2),
    ("SELECT json_size('{\"a\": 7}', '$.a')", 0),
    ("SELECT year_of_week(date '2005-01-02')", 2004),
    ("SELECT yow(date '2005-01-02')", 2004),
    # DATE materializes as epoch days engine-wide
    ("SELECT from_iso8601_date('1995-03-15')",
     (__import__("datetime").date(1995, 3, 15)
      - __import__("datetime").date(1970, 1, 1)).days),
]


@pytest.mark.parametrize("sql,expected", REGISTRY_CASES)
def test_registry_scalar(runner, sql, expected):
    rows = runner.execute(sql).rows
    assert rows[0][0] == expected


def test_registry_functions_over_table(runner):
    # dictionary-wise evaluation over a real column
    rows = runner.execute(
        "SELECT n_name, md5(n_name) FROM nation WHERE n_nationkey < 2"
        " ORDER BY n_nationkey"
    ).rows
    import hashlib

    for name, digest in rows:
        assert digest == hashlib.md5(name.encode()).hexdigest()


def test_registry_arity_error(runner):
    with pytest.raises(Exception, match="argument"):
        runner.execute("SELECT md5('a', 'b')")


def test_unknown_function_still_fails(runner):
    with pytest.raises(Exception, match="unknown function"):
        runner.execute("SELECT definitely_not_a_function(1)")


def test_show_functions(runner):
    rows = runner.execute("SHOW FUNCTIONS").rows
    names = {r[0] for r in rows}
    # breadth probes across categories
    assert {"md5", "url_extract_host", "json_extract_scalar", "sum",
            "row_number", "approx_distinct"} <= names
    assert len(rows) > 140
    cats = {r[3] for r in rows}
    assert cats == {"scalar", "aggregate", "window"}


def test_const_arg_enforced_at_analysis(runner):
    # column where a constant is required -> AnalysisError, not a
    # binder assertion mid-execution
    with pytest.raises(Exception, match="must be a constant"):
        runner.execute(
            "select levenshtein_distance(n_name, n_comment) from nation"
        )
