"""Scalar function library through the SQL surface (the
main/operator/scalar/ coverage tier, SURVEY.md §2.10)."""

import pytest

from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    r.register_catalog("tpch", create_tpch_connector())
    return r


CASES = [
    ("SELECT 'a' || 'b' || 'c'", "abc"),
    ("SELECT concat(n_name, '_x') FROM nation WHERE n_nationkey = 0", "ALGERIA_x"),
    (
        "SELECT n_name || '-' || r_name FROM nation, region"
        " WHERE n_regionkey = r_regionkey AND n_nationkey = 0",
        "ALGERIA-AFRICA",
    ),
    ("SELECT trim('  hi  ')", "hi"),
    ("SELECT ltrim('  hi  ')", "hi  "),
    ("SELECT rtrim('  hi  ')", "  hi"),
    ("SELECT replace('banana', 'na', 'NA')", "baNANA"),
    ("SELECT reverse('abc')", "cba"),
    ("SELECT nullif(1, 1)", None),
    ("SELECT nullif(2, 1)", 2),
    ("SELECT greatest(1, 5, 3)", 5),
    ("SELECT least(1.5, 0.5)", 0.5),
    ("SELECT power(2, 10)", 1024.0),
    ("SELECT sign(-5)", -1),
    ("SELECT sign(2.5)", 1.0),
    ("SELECT mod(10, 3)", 1),
    ("SELECT year(date '1995-03-15')", 1995),
    ("SELECT month(date '1995-03-15')", 3),
    ("SELECT day(date '1995-03-15')", 15),
    ("SELECT if(1 > 2, 'yes', 'no')", "no"),
    ("SELECT if(1 < 2, 'yes', 'no')", "yes"),
    ("SELECT starts_with(n_name, 'AL') FROM nation WHERE n_nationkey = 0", True),
    ("SELECT log10(100)", 2.0),
    ("SELECT log2(8)", 3.0),
    ("SELECT greatest(1, NULL, 3)", None),
]


@pytest.mark.parametrize("sql,want", CASES)
def test_scalar_function(sql, want, runner):
    got = runner.execute(sql).only_value()
    if isinstance(want, float):
        assert got is not None and abs(got - want) < 1e-9
    else:
        assert got == want
