"""General-position subqueries + r5 optimizer rules — VERDICT r4 #7.

Mark joins (SemiJoinNode's semiJoinOutput analogue) carry EXISTS/IN
into disjunctions and the SELECT list with exact three-valued IN
semantics on the validity lane; correlated scalar subqueries project
into the SELECT list through the existing decorrelated LEFT join.
Oracle: hand-computed matrices (sqlite lacks the same NULL-handling
corners, so expectations are derived from the SQL spec directly)."""

import pytest

from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.sql.optimizer import (
    FlattenUnion,
    PushAggregationThroughOuterJoin,
    PushFilterThroughAggregation,
    PushFilterThroughUnion,
    PushFilterThroughWindow,
    RemoveRedundantDistinct,
)


@pytest.fixture(scope="module")
def r():
    r = LocalQueryRunner(Session(catalog="memory", schema="t"))
    r.register_catalog("memory", create_memory_connector())
    r.execute("create table memory.t.a (x bigint, k bigint)")
    r.execute("insert into a values (1, 1), (2, 1), (3, 2), (4, 3)")
    r.execute("create table memory.t.b (y bigint, k bigint)")
    r.execute("insert into b values (10, 1), (20, 2), (30, 9)")
    r.execute("create table memory.t.nb (k bigint)")
    r.execute("insert into nb values (1), (null)")
    r.execute("create table memory.t.empty (k bigint)")
    return r


class TestMarkJoins:
    def test_exists_in_disjunction(self, r):
        rows = r.execute(
            "select x from a where x = 4 or exists "
            "(select 1 from b where b.k = a.k) order by x"
        ).rows
        assert rows == [[1], [2], [3], [4]]

    def test_not_exists_in_disjunction(self, r):
        rows = r.execute(
            "select x from a where x = 1 or not exists "
            "(select 1 from b where b.k = a.k) order by x"
        ).rows
        assert rows == [[1], [4]]

    def test_exists_in_select_list(self, r):
        rows = r.execute(
            "select x, exists (select 1 from b where b.k = a.k) "
            "from a order by x"
        ).rows
        assert rows == [[1, True], [2, True], [3, True], [4, False]]

    def test_uncorrelated_in_under_or(self, r):
        rows = r.execute(
            "select x from a where x = 4 or k in (select k from b) "
            "order by x"
        ).rows
        assert rows == [[1], [2], [3], [4]]

    def test_correlated_in_under_or(self, r):
        rows = r.execute(
            "select x from a where x = 4 or k in "
            "(select k from b where b.y < 25) order by x"
        ).rows
        assert rows == [[1], [2], [3], [4]]

    def test_in_projection_three_valued(self, r):
        # k IN {1, NULL}: k=1 TRUE; k=2,3 UNKNOWN (NULL in set)
        rows = r.execute(
            "select x, k in (select k from nb) from a order by x"
        ).rows
        assert rows == [
            [1, True], [2, True], [3, None], [4, None]
        ]

    def test_not_in_under_or_null_set(self, r):
        # NOT IN over a set containing NULL: never TRUE
        rows = r.execute(
            "select x from a where false or k not in (select k from nb)"
        ).rows
        assert rows == []

    def test_in_empty_set(self, r):
        rows = r.execute(
            "select x from a where false or k in (select k from empty)"
        ).rows
        assert rows == []
        rows = r.execute(
            "select x from a where false or k not in "
            "(select k from empty) order by x"
        ).rows
        assert rows == [[1], [2], [3], [4]]


class TestScalarSubqueryPositions:
    def test_correlated_scalar_in_select(self, r):
        rows = r.execute(
            "select x, (select max(y) from b where b.k = a.k) "
            "from a order by x"
        ).rows
        assert rows == [[1, 10], [2, 10], [3, 20], [4, None]]

    def test_uncorrelated_scalar_in_select(self, r):
        rows = r.execute(
            "select x, (select max(y) from b) from a order by x"
        ).rows
        assert rows == [[1, 30], [2, 30], [3, 30], [4, 30]]

    def test_scalar_in_select_over_join(self, r):
        # VERDICT matrix: scalar in SELECT-list over a join
        rows = r.execute(
            "select a.x, (select max(y) from b where b.k = a.k) "
            "from a join b on a.k = b.k order by a.x"
        ).rows
        assert rows == [[1, 10], [2, 10], [3, 20]]


class TestNewRules:
    """Each rule asserted to FIRE (plan shape) and preserve results."""

    def _plan(self, r, sql):
        return "\n".join(
            str(row[0]) for row in r.execute("explain " + sql).rows
        )

    def test_push_filter_through_aggregation(self, r):
        sql = (
            "select * from (select k, sum(x) s from a group by k) "
            "where k > 1"
        )
        plan = self._plan(r, sql)
        # the filter must sit BELOW the aggregate (scan side) — either as
        # a residual FilterNode or fully absorbed into the scan's pushed
        # constraints once it reaches the scan
        agg_pos = plan.lower().find("aggregate")
        flt_pos = plan.lower().find("filter")
        pushed = "pushed=[k gt 1]" in plan
        assert pushed or flt_pos > agg_pos >= 0, plan
        assert sorted(r.execute(sql).rows) == [[2, 3], [3, 4]]

    def test_push_filter_through_window(self, r):
        sql = (
            "select * from (select x, k, row_number() over "
            "(partition by k order by x) rn from a) where k = 1"
        )
        plan = self._plan(r, sql)
        win_pos = plan.lower().find("window")
        flt_pos = plan.lower().find("filter")
        pushed = "pushed=[k eq 1]" in plan
        assert pushed or flt_pos > win_pos >= 0, plan
        rows = sorted(r.execute(sql).rows)
        assert rows == [[1, 1, 1], [2, 1, 2]]

    def test_flatten_union_and_push_filter(self, r):
        sql = (
            "select * from (select x from a union all "
            "(select x + 10 from a union all select x + 100 from a)) "
            "where x > 100"
        )
        rows = sorted(r.execute(sql).rows)
        assert rows == [[101], [102], [103], [104]]

    def test_remove_redundant_distinct(self, r):
        sql = "select distinct k from (select distinct k from a)"
        plan = self._plan(r, sql)
        assert plan.lower().count("aggregate") == 1, plan
        assert sorted(r.execute(sql).rows) == [[1], [2], [3]]

    def test_push_aggregation_through_outer_join(self, r):
        sql = (
            "select d.k, sum(a.x), count(a.x) from "
            "(select distinct k from a) d left join a on d.k = a.k "
            "group by d.k"
        )
        plan = self._plan(r, sql)
        # after the push, the aggregate sits BELOW the join
        join_pos = plan.lower().find("join")
        # the pushed aggregate appears after the join in tree print
        assert "join" in plan.lower()
        rows = sorted(r.execute(sql).rows)
        assert rows == [[1, 3, 2], [2, 3, 1], [3, 4, 1]]

    def test_rule_count_floor(self):
        from trino_tpu.sql.optimizer import SIMPLIFICATION_RULES

        assert len(SIMPLIFICATION_RULES) >= 18


class TestReviewHardening:
    """Scenarios from the r5 adversarial review, kept as regressions."""

    def test_outer_only_exists_preserves_cardinality(self, r):
        r.execute("create table memory.t.b5 (y bigint)")
        r.execute("insert into b5 values (1)")
        rows = r.execute(
            "select x, exists(select 1 from b5 where a.x > 2) "
            "from a order by x"
        ).rows
        assert rows == [
            [1, False], [2, False], [3, True], [4, True]
        ]

    def test_correlated_in_three_valued_under_not(self, r):
        r.execute("create table memory.t.c3 (g bigint, v bigint)")
        r.execute("insert into c3 values (1, null), (2, 2)")
        r.execute("create table memory.t.a3 (x bigint, k bigint)")
        r.execute("insert into a3 values (1, 1), (2, 2)")
        # k IN {NULL} is UNKNOWN; NOT UNKNOWN is UNKNOWN -> excluded
        rows = r.execute(
            "select x from a3 where not "
            "(k in (select v from c3 where c3.g = a3.k))"
        ).rows
        assert rows == []
        rows = r.execute(
            "select x, k in (select v from c3 where c3.g = a3.k) "
            "from a3 order by x"
        ).rows
        assert rows == [[1, None], [2, True]]

    def test_nondeterministic_having_not_pushed(self, r):
        r.execute("create table memory.t.t8 (k bigint, v bigint)")
        r.execute(
            "insert into t8 values (1,1),(1,1),(1,1),(1,1),"
            "(1,1),(1,1),(1,1),(1,1)"
        )
        rows = r.execute(
            "select k, sum(v) from t8 group by k "
            "having k + rand() < 1.5"
        ).rows
        # rand() evaluates ONCE per group: all 8 rows or none
        assert rows == [] or rows == [[1, 8]]
