"""SQL formatter round-trip: parse(format(parse(sql))) == parse(sql).

Mirrors the reference's TestSqlFormatter strategy (format each tree
shape and assert the rendered text re-parses to the identical AST) but
drives it with the whole TPC-H suite plus targeted statement shapes —
the strongest cheap property the formatter can promise.
"""

import pytest

from tests.tpch_queries import QUERIES
from trino_tpu.sql.formatter import format_expression, format_statement
from trino_tpu.sql.parser import parse


def roundtrip(sql: str):
    tree = parse(sql)
    text = format_statement(tree)
    assert parse(text) == tree, f"round-trip changed the tree:\n{text}"
    return text


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_roundtrip(qid):
    roundtrip(QUERIES[qid])


@pytest.mark.parametrize(
    "sql",
    [
        "SELECT 1 + 2 * 3, (1 + 2) * 3",
        "SELECT -x, NOT a AND b, NOT (a AND b) FROM t",
        "SELECT a FROM t WHERE x BETWEEN 1 AND 10 AND y NOT IN (1, 2)",
        "SELECT a FROM t WHERE s LIKE 'a%' ESCAPE '\\'",
        "SELECT CASE WHEN a = 1 THEN 'x' ELSE 'y' END FROM t",
        "SELECT CASE a WHEN 1 THEN 'x' WHEN 2 THEN 'z' END FROM t",
        "SELECT CAST(x AS decimal(12, 2)) FROM t",
        "SELECT count(DISTINCT x), sum(y) FROM t",
        "SELECT rank() OVER (PARTITION BY a ORDER BY b DESC) FROM t",
        "SELECT sum(x) OVER (ORDER BY b "
        "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) FROM t",
        "SELECT EXTRACT(YEAR FROM d) FROM t",
        "SELECT a FROM t1 LEFT JOIN t2 ON t1.x = t2.y",
        "SELECT a FROM t1 CROSS JOIN t2",
        "SELECT a FROM t1 INNER JOIN t2 USING (k)",
        "SELECT a FROM (SELECT b AS a FROM t) AS s(a)",
        "SELECT * FROM UNNEST(ARRAY[1, 2]) WITH ORDINALITY AS u(v, o)",
        "WITH c(x) AS (SELECT a FROM t) SELECT x FROM c",
        "SELECT a FROM t GROUP BY ROLLUP(a, b)",
        "SELECT a FROM t GROUP BY GROUPING SETS ((a), (a, b), ())",
        "SELECT a FROM t UNION ALL SELECT b FROM u",
        "SELECT a FROM t INTERSECT SELECT b FROM u",
        "SELECT a FROM t EXCEPT SELECT b FROM u ORDER BY 1 LIMIT 3",
        "VALUES (1, 'a'), (2, 'b')",
        "SELECT a FROM t ORDER BY a DESC NULLS FIRST OFFSET 2 LIMIT 5",
        "SELECT a FROM t WHERE EXISTS (SELECT 1 FROM u WHERE u.k = t.k)",
        "SELECT a FROM t WHERE x NOT IN (SELECT y FROM u)",
        "SELECT (SELECT max(y) FROM u) FROM t",
        "SELECT a FROM t WHERE x IS NOT NULL",
        "SELECT DATE '1998-12-01' - INTERVAL '90' DAY",
        "SELECT ARRAY[1, 2, 3]",
        "EXPLAIN SELECT a FROM t",
        "EXPLAIN ANALYZE SELECT a FROM t",
        "CREATE TABLE s.t (a bigint, b varchar)",
        "CREATE TABLE s.t2 AS SELECT a FROM t",
        "INSERT INTO t (a, b) SELECT x, y FROM u",
        "INSERT INTO t VALUES (1, 2)",
        "DELETE FROM t WHERE a = 1",
        "UPDATE t SET a = a + 1, b = 'z' WHERE c > 0",
        "DROP TABLE t",
        "START TRANSACTION",
        "COMMIT",
        "ROLLBACK",
        "SHOW TABLES",
        "SHOW SCHEMAS",
        "SHOW COLUMNS FROM t",
        "SHOW SESSION",
    ],
)
def test_statement_roundtrip(sql):
    roundtrip(sql)


def test_quoted_identifier():
    text = roundtrip('SELECT "Weird Name" FROM "T!"')
    assert '"Weird Name"' in text and '"T!"' in text


def test_string_escaping():
    text = roundtrip("SELECT 'it''s'")
    assert "'it''s'" in text


def test_expression_formatting():
    from trino_tpu.sql import ast

    e = ast.BinaryOp(
        "mul",
        ast.BinaryOp("add", ast.NumberLiteral("1"), ast.NumberLiteral("2")),
        ast.NumberLiteral("3"),
    )
    assert format_expression(e) == "(1 + 2) * 3"


def test_canonical_is_stable():
    # formatting is idempotent: format(parse(format(tree))) == format(tree)
    for qid in (1, 3, 18, 21):
        text = format_statement(parse(QUERIES[qid]))
        assert format_statement(parse(text)) == text
