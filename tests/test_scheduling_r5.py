"""Weighted-fair resource queues + topology-aware node selection —
VERDICT r4 Missing #10 / Weak #8 (WeightedFairQueue.java,
TopologyAwareNodeSelector.java)."""

import threading

from trino_tpu.runtime.node_scheduler import TopologyAwareNodeSelector
from trino_tpu.runtime.resource_groups import (
    ResourceGroupManager,
    ResourceGroupSpec,
    Selector,
)


class _FakeWorker:
    def __init__(self, name):
        self.name = name

    def status(self):
        return {"tasks": 0}


class TestWeightedFairness:
    def test_weighted_share_under_contention(self):
        root = ResourceGroupSpec(
            "root", max_concurrency=1, max_queued=100,
            sub_groups=[
                ResourceGroupSpec("heavy", max_concurrency=10,
                                  scheduling_weight=3, max_queued=100),
                ResourceGroupSpec("light", max_concurrency=10,
                                  scheduling_weight=1, max_queued=100),
            ],
        )
        mgr = ResourceGroupManager(root, [
            Selector(("root", "heavy"), user_pattern="h.*"),
            Selector(("root", "light"), user_pattern="l.*"),
        ])
        admitted = []

        def worker(user):
            for _ in range(20):
                lease = mgr.acquire(user=user, timeout=30)
                admitted.append(user[0])
                mgr.release(lease)

        ts = [
            threading.Thread(target=worker, args=("heavy",)),
            threading.Thread(target=worker, args=("light",)),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        # with weight 3:1 under a shared 1-slot parent, the heavy group
        # should win clearly more admissions in any window
        h = admitted.count("h")
        l = admitted.count("l")
        assert h + l == 40
        # long-run ratio approximates 3:1; allow slack for thread timing
        first = admitted[:24]
        assert first.count("h") > first.count("l"), admitted

    def test_fifo_within_group(self):
        mgr = ResourceGroupManager(
            ResourceGroupSpec("root", max_concurrency=1, max_queued=10)
        )
        lease = mgr.acquire()
        order = []

        def waiter(tag):
            t = mgr.acquire(timeout=30)
            order.append(tag)
            mgr.release(t)

        ts = []
        for tag in ("a", "b", "c"):
            t = threading.Thread(target=waiter, args=(tag,))
            t.start()
            ts.append(t)
            import time

            time.sleep(0.05)  # establish arrival order
        mgr.release(lease)
        for t in ts:
            t.join(timeout=30)
        assert order == ["a", "b", "c"]

    def test_queue_cap_still_enforced(self):
        from trino_tpu.runtime.resource_groups import QueryQueueFullError

        mgr = ResourceGroupManager(
            ResourceGroupSpec("root", max_concurrency=1, max_queued=0)
        )
        lease = mgr.acquire()
        try:
            try:
                mgr.acquire(timeout=0.2)
                assert False, "queue cap not enforced"
            except QueryQueueFullError:
                pass
        finally:
            mgr.release(lease)


class TestTopologyAwareSelection:
    def test_tiered_locality(self):
        w = {name: _FakeWorker(name) for name in
             ("r1h1", "r1h2", "r2h1", "r2h2")}
        locs = {
            id(w["r1h1"]): "rack1/h1", id(w["r1h2"]): "rack1/h2",
            id(w["r2h1"]): "rack2/h1", id(w["r2h2"]): "rack2/h2",
        }
        sel = TopologyAwareNodeSelector(locs)
        active = list(w.values())
        # exact host match wins
        assert sel.select(active, location="rack1/h2").name == "r1h2"
        # no host match -> same rack (least-loaded within the rack)
        got = sel.select(active, location="rack2/h9")
        assert got.name in ("r2h1", "r2h2")
        # unknown rack -> falls back to least-loaded overall
        got = sel.select(active, location="rack9/h9")
        assert got.name in w

    def test_no_location_degrades_to_uniform(self):
        a, b = _FakeWorker("a"), _FakeWorker("b")
        sel = TopologyAwareNodeSelector({})
        picks = {sel.select([a, b]).name for _ in range(2)}
        assert picks == {"a", "b"}  # least-loaded spreads


class TestTieredStrictness:
    def test_host_tier_beats_loaded_rack(self):
        """A below-cap same-host node wins even when a same-rack node
        is emptier (r5 review: tiers must be strict)."""
        h = {n: _FakeWorker(n) for n in ("r1h1", "r1h2")}
        locs = {id(h["r1h1"]): "rack1/h1", id(h["r1h2"]): "rack1/h2"}
        sel = TopologyAwareNodeSelector(locs, max_tasks_per_node=4)
        active = list(h.values())
        # load the host-tier node first
        assert sel.select(active, location="rack1/h2").name == "r1h2"
        # still picks the same host while below cap, despite load
        assert sel.select(active, location="rack1/h2").name == "r1h2"
        # at cap the rack tier takes over
        sel2 = TopologyAwareNodeSelector(locs, max_tasks_per_node=1)
        assert sel2.select(active, location="rack1/h2").name == "r1h2"
        assert sel2.select(active, location="rack1/h2").name == "r1h1"


class TestFragmentCoLocation:
    def test_distributed_tasks_colocate_per_fragment(self):
        """Workers carrying locations co-schedule each fragment's tasks
        on one island (counter-asserted via task placement)."""
        from trino_tpu.connectors.memory import create_memory_connector
        from trino_tpu.engine import Session
        from trino_tpu.runtime import DistributedQueryRunner
        from trino_tpu.runtime.worker import Worker
        from trino_tpu.connectors.spi import CatalogManager

        catalogs = CatalogManager()
        workers = [
            Worker(f"w{i}", catalogs, location=loc)
            for i, loc in enumerate(
                ["podA/h0", "podA/h1", "podB/h0", "podB/h1"]
            )
        ]
        r = DistributedQueryRunner(
            Session(catalog="memory", schema="t", mesh_execution=False),
            worker_handles=workers, hash_partitions=2,
        )
        # in-process handles share the coordinator catalogs object
        r.catalogs = catalogs
        mem = create_memory_connector()
        catalogs.register("memory", mem)
        import numpy as np
        from trino_tpu.connectors.spi import ColumnMetadata
        from trino_tpu import types as T

        mem.load_table(
            "t", "v", [ColumnMetadata("x", T.BIGINT)],
            [np.arange(500)], None, [None],
        )
        res = r.execute(
            "select x % 7 as g, count(*) from v group by 1"
        )
        assert res.rows and res.data_plane == "http"


class TestStrideNoStarvation:
    def test_idle_history_is_not_credit(self):
        """A group that ran for a long time must not be starved when a
        new sibling arrives (stride rejoin at the current pass)."""
        root = ResourceGroupSpec(
            "root", max_concurrency=1,
            sub_groups=[
                ResourceGroupSpec("old", scheduling_weight=1),
                ResourceGroupSpec("new", scheduling_weight=1),
            ],
        )
        mgr = ResourceGroupManager(root, [
            Selector(("root", "old"), user_pattern="o.*"),
            Selector(("root", "new"), user_pattern="n.*"),
        ])
        # age the old group far ahead
        for _ in range(50):
            mgr.release(mgr.acquire(user="old"))
        admitted = []

        def worker(user, count):
            for _ in range(count):
                lease = mgr.acquire(user=user, timeout=30)
                admitted.append(user[0])
                mgr.release(lease)

        ts = [
            threading.Thread(target=worker, args=("old", 10)),
            threading.Thread(target=worker, args=("new", 10)),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        # the new group must not monopolize the first admissions
        first8 = admitted[:8]
        assert first8.count("o") >= 2, admitted
