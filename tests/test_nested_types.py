"""MAP / ROW column types and nested wire encodings.

Reference parity: spi/block/MapBlock.java, RowBlock.java,
ArrayBlockEncoding.java (nested columns on the wire), MapType/RowType
operators (subscript, cardinality, field reference). VERDICT r2
missing #3: arrays could not cross an exchange and MAP/ROW did not
exist.
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.block import Column, MapColumn, RelBatch, RowColumn
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.exec.serde import Page, deserialize_page, serialize_page


@pytest.fixture(scope="module")
def runner():
    mem = create_memory_connector()
    mt = T.map_of(T.VARCHAR, T.BIGINT)
    rt = T.row_of(("x", T.BIGINT), ("y", T.VARCHAR))
    mem.load_table(
        "default", "t",
        [
            ColumnMetadata("id", T.BIGINT),
            ColumnMetadata("m", mt),
            ColumnMetadata("r", rt),
        ],
        [
            np.asarray([1, 2, 3], dtype=np.int64),
            [{"a": 10, "b": 20}, {"a": 30}, None],
            [(7, "p"), (8, "q"), None],
        ],
        None,
        [None, None, None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    return r


# ---------------------------------------------------------------------------
# wire format (TPG2 nested encodings)
# ---------------------------------------------------------------------------


def test_serde_nested_roundtrip_array_of_map():
    amap = T.array_of(T.map_of(T.VARCHAR, T.BIGINT))
    vals = [[{"a": 1, "b": 2}, {}], None, [{"c": 3}], []]
    c1 = Column.from_pylist(amap, vals)
    c2 = Column.from_pylist(
        T.row_of(("x", T.BIGINT), ("y", T.VARCHAR)),
        [(1, "p"), None, (3, "q"), (4, None)],
    )
    c3 = Column.from_pylist(T.BIGINT, [10, None, 30, 40])
    page = Page.from_batch(RelBatch([c1, c2, c3], None))
    back = deserialize_page(serialize_page(page)).to_batch()
    assert back.columns[0].to_pylist(count=4) == vals
    assert back.columns[1].to_pylist(count=4) == [
        (1, "p"), None, (3, "q"), (4, None)
    ]
    assert back.columns[2].to_pylist(count=4) == [10, None, 30, 40]


def test_serde_nested_respects_live_mask():
    """Dead rows (and their element slices) must not cross the wire."""
    import jax.numpy as jnp

    c = Column.from_pylist(
        T.array_of(T.BIGINT), [[1, 2], [3], [4, 5, 6], [7]]
    )
    live = jnp.asarray(np.array([True, False, True, False]
                                + [False] * (c.capacity - 4)))
    page = Page.from_batch(RelBatch([c], live))
    assert page.row_count == 2
    back = deserialize_page(serialize_page(page)).to_batch()
    assert back.columns[0].to_pylist(count=2) == [[1, 2], [4, 5, 6]]
    # the flat store shrank to exactly the live rows' elements
    assert int(np.asarray(back.columns[0].data)[:2].sum()) == 5


def test_serde_type_tree_survives():
    t = T.array_of(T.map_of(T.VARCHAR, T.array_of(T.BIGINT)))
    c = Column.from_pylist(t, [[{"k": [1, 2]}], []])
    page = Page.from_batch(RelBatch([c], None))
    back = deserialize_page(serialize_page(page))
    assert back.types[0] == t
    assert back.to_batch().columns[0].to_pylist(count=2) == [[{"k": [1, 2]}], []]


# ---------------------------------------------------------------------------
# SQL surface
# ---------------------------------------------------------------------------


def test_map_cardinality_and_subscript(runner):
    res = runner.execute(
        "select id, cardinality(m), m['a'], element_at(m, 'b') from t"
    )
    assert res.rows == [
        [1, 2, 10, 20],
        [2, 1, 30, None],
        [3, None, None, None],
    ]


def test_map_subscript_in_where(runner):
    assert runner.execute("select id from t where m['a'] = 30").rows == [[2]]


def test_row_field_access(runner):
    res = runner.execute("select id, r.x, r.y from t")
    assert res.rows == [[1, 7, "p"], [2, 8, "q"], [3, None, None]]


def test_map_keys_values(runner):
    res = runner.execute("select map_keys(m), map_values(m) from t")
    assert res.rows == [
        [["a", "b"], [10, 20]],
        [["a"], [30]],
        [None, None],
    ]


def test_row_constructor(runner):
    res = runner.execute("select row(id, 5) from t")
    assert res.rows == [[(1, 5)], [(2, 5)], [(3, 5)]]


def test_nested_type_ddl_parses(runner):
    runner.execute(
        "create table nested_ddl (a array(bigint), m map(varchar, bigint),"
        " r row(x bigint, y varchar))"
    )
    cols = runner.execute("show columns from nested_ddl").rows
    assert cols == [
        ["a", "array(bigint)"],
        ["m", "map(varchar, bigint)"],
        ["r", "row(x bigint, y varchar)"],
    ]


def test_array_subscript_column():
    mem = create_memory_connector()
    mem.load_table(
        "default", "arr",
        [ColumnMetadata("a", T.array_of(T.BIGINT))],
        [[[10, 20, 30], [40], None, []]],
        None, [None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    res = r.execute("select a[1], a[3], element_at(a, -1) from arr")
    assert res.rows == [
        [10, 30, 30],
        [40, None, 40],
        [None, None, None],
        [None, None, None],
    ]


# ---------------------------------------------------------------------------
# distributed: arrays cross a real HTTP exchange (VERDICT r2 missing #3)
# ---------------------------------------------------------------------------


def test_arrays_cross_http_exchange():
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.http import HttpWorkerClient, WorkerServer
    from trino_tpu.runtime.worker import Worker

    mem = create_memory_connector()
    mem.load_table(
        "default", "tagged",
        [
            ColumnMetadata("id", T.BIGINT),
            ColumnMetadata("tags", T.array_of(T.VARCHAR)),
        ],
        [
            np.asarray([1, 2, 3], dtype=np.int64),
            [["red", "blue"], [], ["green"]],
        ],
        None, [None, None],
    )
    cats = CatalogManager()
    cats.register("memory", mem)

    srv = WorkerServer(Worker("w0", cats), require_secret=False)
    try:
        r = DistributedQueryRunner(
            Session(catalog="memory", schema="default"),
            worker_handles=[HttpWorkerClient(srv.uri)],
        )
        r.register_catalog("memory", mem)
        res = r.execute("select id, tags from tagged order by id")
        assert res.rows == [
            [1, ["red", "blue"]],
            [2, []],
            [3, ["green"]],
        ]
    finally:
        srv.stop()


def test_nested_subscript_of_nested():
    """a[i] / m[k] returning NESTED values must keep the child layout
    (review r3: a bare data-gather returned inner LENGTHS as values)."""
    mem = create_memory_connector()
    mem.load_table(
        "default", "nn",
        [
            ColumnMetadata("aa", T.array_of(T.array_of(T.BIGINT))),
            ColumnMetadata("ma", T.map_of(T.VARCHAR, T.array_of(T.BIGINT))),
        ],
        [
            [[[1, 2], [3]], [[4, 5, 6]]],
            [{"p": [7, 8]}, {"q": [9]}],
        ],
        None, [None, None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    assert r.execute("select aa[1], aa[2] from nn").rows == [
        [[1, 2], [3]],
        [[4, 5, 6], None],
    ]
    assert r.execute("select ma['p'], ma['q'] from nn").rows == [
        [[7, 8], None],
        [None, [9]],
    ]


def test_nested_crosses_hash_partitioned_exchange():
    """Hash-partitioned exchanges must carry nested columns (review r3:
    split_page assumed flat ndarrays)."""
    import numpy as np

    from trino_tpu.runtime import DistributedQueryRunner

    mem = create_memory_connector()
    n = 64
    mem.load_table(
        "default", "big",
        [
            ColumnMetadata("id", T.BIGINT),
            ColumnMetadata("tags", T.array_of(T.BIGINT)),
        ],
        [
            np.arange(n, dtype=np.int64),
            [[i, i + 1] if i % 3 else [] for i in range(n)],
        ],
        None, [None, None],
    )
    r = DistributedQueryRunner(
        Session(catalog="memory", schema="default", mesh_execution=False),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("memory", mem)
    # the join forces a hash repartition of `big` carrying `tags`
    res = r.execute(
        "select b.id, b.tags from big b join big c on b.id = c.id"
        " where b.id in (5, 6) order by b.id"
    )
    assert res.rows == [[5, [5, 6]], [6, []]]


def test_full_join_with_nested_payload():
    mem = create_memory_connector()
    mem.load_table(
        "default", "fa2",
        [ColumnMetadata("x", T.BIGINT), ColumnMetadata("t", T.array_of(T.BIGINT))],
        [__import__("numpy").asarray([1, 2], dtype="int64"), [[10], [20, 21]]],
        None, [None, None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    r.execute("create table fb2 (y bigint)")
    r.execute("insert into fb2 values (2), (3)")
    rows = r.execute(
        "select x, t, y from fa2 full join fb2 on x = y"
    ).rows
    key = lambda r_: (r_[0] is None, r_[0] or 0, r_[2] or 0)
    assert sorted(rows, key=key) == [
        [1, [10], None],
        [2, [20, 21], 2],
        [None, None, 3],
    ]
