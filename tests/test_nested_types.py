"""MAP / ROW column types and nested wire encodings.

Reference parity: spi/block/MapBlock.java, RowBlock.java,
ArrayBlockEncoding.java (nested columns on the wire), MapType/RowType
operators (subscript, cardinality, field reference). VERDICT r2
missing #3: arrays could not cross an exchange and MAP/ROW did not
exist.
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.block import Column, MapColumn, RelBatch, RowColumn
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.exec.serde import Page, deserialize_page, serialize_page


@pytest.fixture(scope="module")
def runner():
    mem = create_memory_connector()
    mt = T.map_of(T.VARCHAR, T.BIGINT)
    rt = T.row_of(("x", T.BIGINT), ("y", T.VARCHAR))
    mem.load_table(
        "default", "t",
        [
            ColumnMetadata("id", T.BIGINT),
            ColumnMetadata("m", mt),
            ColumnMetadata("r", rt),
        ],
        [
            np.asarray([1, 2, 3], dtype=np.int64),
            [{"a": 10, "b": 20}, {"a": 30}, None],
            [(7, "p"), (8, "q"), None],
        ],
        None,
        [None, None, None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    return r


# ---------------------------------------------------------------------------
# wire format (TPG2 nested encodings)
# ---------------------------------------------------------------------------


def test_serde_nested_roundtrip_array_of_map():
    amap = T.array_of(T.map_of(T.VARCHAR, T.BIGINT))
    vals = [[{"a": 1, "b": 2}, {}], None, [{"c": 3}], []]
    c1 = Column.from_pylist(amap, vals)
    c2 = Column.from_pylist(
        T.row_of(("x", T.BIGINT), ("y", T.VARCHAR)),
        [(1, "p"), None, (3, "q"), (4, None)],
    )
    c3 = Column.from_pylist(T.BIGINT, [10, None, 30, 40])
    page = Page.from_batch(RelBatch([c1, c2, c3], None))
    back = deserialize_page(serialize_page(page)).to_batch()
    assert back.columns[0].to_pylist(count=4) == vals
    assert back.columns[1].to_pylist(count=4) == [
        (1, "p"), None, (3, "q"), (4, None)
    ]
    assert back.columns[2].to_pylist(count=4) == [10, None, 30, 40]


def test_serde_nested_respects_live_mask():
    """Dead rows (and their element slices) must not cross the wire."""
    import jax.numpy as jnp

    c = Column.from_pylist(
        T.array_of(T.BIGINT), [[1, 2], [3], [4, 5, 6], [7]]
    )
    live = jnp.asarray(np.array([True, False, True, False]
                                + [False] * (c.capacity - 4)))
    page = Page.from_batch(RelBatch([c], live))
    assert page.row_count == 2
    back = deserialize_page(serialize_page(page)).to_batch()
    assert back.columns[0].to_pylist(count=2) == [[1, 2], [4, 5, 6]]
    # the flat store shrank to exactly the live rows' elements
    assert int(np.asarray(back.columns[0].data)[:2].sum()) == 5


def test_serde_type_tree_survives():
    t = T.array_of(T.map_of(T.VARCHAR, T.array_of(T.BIGINT)))
    c = Column.from_pylist(t, [[{"k": [1, 2]}], []])
    page = Page.from_batch(RelBatch([c], None))
    back = deserialize_page(serialize_page(page))
    assert back.types[0] == t
    assert back.to_batch().columns[0].to_pylist(count=2) == [[{"k": [1, 2]}], []]


# ---------------------------------------------------------------------------
# SQL surface
# ---------------------------------------------------------------------------


def test_map_cardinality_and_subscript(runner):
    res = runner.execute(
        "select id, cardinality(m), m['a'], element_at(m, 'b') from t"
    )
    assert res.rows == [
        [1, 2, 10, 20],
        [2, 1, 30, None],
        [3, None, None, None],
    ]


def test_map_subscript_in_where(runner):
    assert runner.execute("select id from t where m['a'] = 30").rows == [[2]]


def test_row_field_access(runner):
    res = runner.execute("select id, r.x, r.y from t")
    assert res.rows == [[1, 7, "p"], [2, 8, "q"], [3, None, None]]


def test_map_keys_values(runner):
    res = runner.execute("select map_keys(m), map_values(m) from t")
    assert res.rows == [
        [["a", "b"], [10, 20]],
        [["a"], [30]],
        [None, None],
    ]


def test_row_constructor(runner):
    res = runner.execute("select row(id, 5) from t")
    assert res.rows == [[(1, 5)], [(2, 5)], [(3, 5)]]


def test_nested_type_ddl_parses(runner):
    runner.execute(
        "create table nested_ddl (a array(bigint), m map(varchar, bigint),"
        " r row(x bigint, y varchar))"
    )
    cols = runner.execute("show columns from nested_ddl").rows
    assert cols == [
        ["a", "array(bigint)"],
        ["m", "map(varchar, bigint)"],
        ["r", "row(x bigint, y varchar)"],
    ]


def test_array_subscript_column():
    mem = create_memory_connector()
    mem.load_table(
        "default", "arr",
        [ColumnMetadata("a", T.array_of(T.BIGINT))],
        [[[10, 20, 30], [40], None, []]],
        None, [None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    res = r.execute("select a[1], a[3], element_at(a, -1) from arr")
    assert res.rows == [
        [10, 30, 30],
        [40, None, 40],
        [None, None, None],
        [None, None, None],
    ]


# ---------------------------------------------------------------------------
# distributed: arrays cross a real HTTP exchange (VERDICT r2 missing #3)
# ---------------------------------------------------------------------------


def test_arrays_cross_http_exchange():
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime import DistributedQueryRunner
    from trino_tpu.runtime.http import HttpWorkerClient, WorkerServer
    from trino_tpu.runtime.worker import Worker

    mem = create_memory_connector()
    mem.load_table(
        "default", "tagged",
        [
            ColumnMetadata("id", T.BIGINT),
            ColumnMetadata("tags", T.array_of(T.VARCHAR)),
        ],
        [
            np.asarray([1, 2, 3], dtype=np.int64),
            [["red", "blue"], [], ["green"]],
        ],
        None, [None, None],
    )
    cats = CatalogManager()
    cats.register("memory", mem)

    srv = WorkerServer(Worker("w0", cats), require_secret=False)
    try:
        r = DistributedQueryRunner(
            Session(catalog="memory", schema="default"),
            worker_handles=[HttpWorkerClient(srv.uri)],
        )
        r.register_catalog("memory", mem)
        res = r.execute("select id, tags from tagged order by id")
        assert res.rows == [
            [1, ["red", "blue"]],
            [2, []],
            [3, ["green"]],
        ]
    finally:
        srv.stop()
