"""Property tests for ops/floatbits.f64_lanes (ADVICE r3): the 4-lane
key must be a total order matching SQL double semantics and INJECTIVE
over normal doubles — including the f32-saturation boundary region
where the r3 windows collapsed distinct values."""

import numpy as np
import pytest

import jax.numpy as jnp

from trino_tpu.ops.floatbits import f64_lanes


def keys_of(vals):
    lanes = f64_lanes(jnp.asarray(vals, jnp.float64))
    arrs = [np.asarray(l, dtype=np.uint64) for l in lanes]
    return [tuple(int(a[i]) for a in arrs) for i in range(len(vals))]


MAXF32 = float(np.finfo(np.float32).max)


def _interesting_values():
    rng = np.random.default_rng(7)
    vals = []
    # saturation boundary: the r3 regression pair plus a dense sweep
    vals += [MAXF32 * (1 + 1e-9), MAXF32 * (1 + 2e-9)]
    vals += list(MAXF32 * (1 + rng.uniform(0, 1e3, 50)))
    vals += [MAXF32, np.nextafter(MAXF32, np.inf), 2.0 ** 128, 2.0 ** 200]
    # huge normals through the top window
    vals += list(rng.uniform(1, 2, 30) * 2.0 ** rng.integers(120, 1023, 30))
    # tiny normals
    vals += list(rng.uniform(1, 2, 30) * 2.0 ** -rng.integers(100, 1021, 30).astype(float))
    # window boundaries +- ulps
    for e in (-630, -378, -126, 126, 378, 630, 882):
        b = 2.0 ** e
        vals += [np.nextafter(b, 0), b, np.nextafter(b, np.inf)]
    # ordinary values
    vals += list(rng.standard_normal(100) * 10 ** rng.integers(-10, 10, 100).astype(float))
    vals += [0.0, -0.0, 1.0, -1.0]
    out = []
    for v in vals:
        f = float(v)
        if np.isfinite(f) and f != 0 and abs(f) >= 2.2250738585072014e-308:
            out.append(f)
        elif f == 0:
            out.append(f)
    # negatives of everything
    return out + [-v for v in out]


def test_injective_over_normals():
    vals = _interesting_values()
    ks = keys_of(vals)
    seen = {}
    for v, k in zip(vals, ks):
        canon = 0.0 if v == 0 else v
        if k in seen:
            assert seen[k] == canon, (
                f"collision: {seen[k]!r} and {v!r} share key {k}"
            )
        seen[k] = canon


def test_order_matches_double_order():
    vals = sorted(set(v for v in _interesting_values()))
    ks = keys_of(vals)
    for i in range(len(vals) - 1):
        if vals[i] == vals[i + 1]:
            continue
        assert ks[i] < ks[i + 1], (vals[i], vals[i + 1], ks[i], ks[i + 1])


def test_specials():
    vals = [float("-inf"), -1.0, -0.0, 0.0, 1.0, float("inf"), float("nan")]
    ks = keys_of(vals)
    assert ks[2] == ks[3]  # -0.0 == +0.0
    assert ks[0] < ks[1] < ks[2] < ks[4] < ks[5]
    assert ks[6] > ks[5]  # NaN largest (SQL/Double.compare order)
