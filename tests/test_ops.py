"""Kernel unit tests vs numpy oracles — analogue of Trino's operator
unit tests (TestGroupByHash, TestHashJoinOperator etc., SURVEY.md §4.1)."""

import numpy as np
import jax.numpy as jnp
import pytest

from trino_tpu.ops import groupby, join, sort
from trino_tpu.ops.hashing import hash32, hash64, partition_of


def test_hash_deterministic_and_spread():
    x = jnp.arange(1000, dtype=jnp.int64)
    h1 = np.asarray(hash32([x], [jnp.ones(1000, bool)]))
    h2 = np.asarray(hash32([x], [jnp.ones(1000, bool)]))
    assert (h1 == h2).all()
    # good spread into 8 partitions
    parts = np.asarray(partition_of(jnp.asarray(h1), 8))
    counts = np.bincount(parts, minlength=8)
    assert counts.min() > 60  # roughly uniform

    h64 = np.asarray(hash64([x], [jnp.ones(1000, bool)]))
    assert len(np.unique(h64)) == 1000
    assert (h64 >= 0).all()


def _group_oracle(keys, mask):
    seen = {}
    gids = []
    for i in range(len(mask)):
        if not mask[i]:
            gids.append(None)
            continue
        k = tuple(col[i] for col in keys)
        gids.append(seen.setdefault(k, len(seen)))
    return gids, len(seen)


@pytest.mark.parametrize("n,card", [(64, 4), (512, 100), (256, 256)])
def test_assign_group_ids_matches_oracle(n, card):
    rng = np.random.default_rng(7)
    k1 = rng.integers(0, card, n).astype(np.int64)
    k2 = rng.integers(0, 3, n).astype(np.int32)
    mask = rng.random(n) > 0.1
    C = 1024
    gid, table, overflow = groupby.assign_group_ids(
        [jnp.asarray(k1), jnp.asarray(k2)],
        [jnp.ones(n, bool), jnp.ones(n, bool)],
        jnp.asarray(mask),
        C,
    )
    assert not bool(overflow)
    gid = np.asarray(gid)
    oracle_gids, n_groups = _group_oracle([k1, k2], mask)
    assert int(table.num_groups()) == n_groups
    # same key -> same gid; different keys -> different gid
    remap = {}
    for i in range(n):
        if not mask[i]:
            assert gid[i] == C
            continue
        og = oracle_gids[i]
        if og in remap:
            assert gid[i] == remap[og], f"row {i}"
        else:
            assert gid[i] not in remap.values()
            remap[og] = gid[i]
    # table stores the right keys at each slot
    sk1 = np.asarray(table.slot_keys[0])
    for i in range(n):
        if mask[i]:
            assert sk1[gid[i]] == k1[i]


def test_group_ids_null_is_its_own_group():
    k = jnp.asarray([1, 1, 1, 5], dtype=jnp.int64)
    v = jnp.asarray([True, False, False, True])
    gid, table, _ = groupby.assign_group_ids(
        [k], [v], jnp.ones(4, bool), 16
    )
    gid = np.asarray(gid)
    assert gid[1] == gid[2]  # NULL == NULL for grouping
    assert gid[0] != gid[1] and gid[0] != gid[3]
    assert int(table.num_groups()) == 3


def test_group_overflow_flag():
    n = 64
    k = jnp.arange(n, dtype=jnp.int64)
    gid, table, overflow = groupby.assign_group_ids(
        [k], [jnp.ones(n, bool)], jnp.ones(n, bool), 32
    )
    assert bool(overflow)


def test_segment_aggregates():
    gid = jnp.asarray([0, 1, 0, 2, 16, 1], dtype=jnp.int32)  # 16 = dead
    vals = jnp.asarray([1.0, 2.0, 3.0, 4.0, 100.0, 6.0])
    w = jnp.asarray([True, True, True, True, False, True])
    s = np.asarray(groupby.seg_sum(gid, vals, w, 16))
    assert s[0] == 4.0 and s[1] == 8.0 and s[2] == 4.0
    c = np.asarray(groupby.seg_count(gid, w, 16))
    assert c[0] == 2 and c[1] == 2 and c[2] == 1
    mn = np.asarray(groupby.seg_min(gid, vals, w, 16))
    mx = np.asarray(groupby.seg_max(gid, vals, w, 16))
    assert mn[0] == 1.0 and mx[1] == 6.0


def _join_oracle(bkeys, blive, pkeys, plive):
    out = set()
    for i, (pk, pl) in enumerate(zip(pkeys, plive)):
        if not pl:
            continue
        for j, (bk, bl) in enumerate(zip(bkeys, blive)):
            if bl and bk == pk:
                out.add((i, j))
    return out


@pytest.mark.parametrize("nb,np_,card", [(32, 32, 8), (128, 256, 20), (64, 64, 1000)])
def test_join_probe_matches_oracle(nb, np_, card):
    rng = np.random.default_rng(3)
    bk = rng.integers(0, card, nb).astype(np.int64)
    pk = rng.integers(0, card, np_).astype(np.int64)
    blive = rng.random(nb) > 0.2
    plive = rng.random(np_) > 0.2
    ls = join.build_lookup(
        [jnp.asarray(bk)], [jnp.ones(nb, bool)], jnp.asarray(blive)
    )
    lo, counts, total = join.probe_counts(
        ls, [jnp.asarray(pk)], [jnp.ones(np_, bool)], jnp.asarray(plive)
    )
    cap = max(16, 1 << int(np.ceil(np.log2(max(1, int(total))))))
    pi, bi, ok = join.expand_matches(
        ls, [jnp.asarray(pk)], [jnp.ones(np_, bool)], lo, counts, cap
    )
    got = {
        (int(p), int(b))
        for p, b, o in zip(np.asarray(pi), np.asarray(bi), np.asarray(ok))
        if o
    }
    assert got == _join_oracle(bk, blive, pk, plive)


def test_join_null_keys_never_match():
    bk = jnp.asarray([1, 2], dtype=jnp.int64)
    bv = jnp.asarray([True, False])
    pk = jnp.asarray([1, 2], dtype=jnp.int64)
    pv = jnp.asarray([False, True])
    ls = join.build_lookup([bk], [bv], jnp.ones(2, bool))
    lo, counts, total = join.probe_counts(ls, [pk], [pv], jnp.ones(2, bool))
    assert int(total) == 0


def test_semi_and_outer_flags():
    bk = jnp.asarray([1, 1, 3], dtype=jnp.int64)
    pk = jnp.asarray([1, 2, 3, 4], dtype=jnp.int64)
    ls = join.build_lookup([bk], [jnp.ones(3, bool)], jnp.ones(3, bool))
    lo, counts, total = join.probe_counts(
        ls, [pk], [jnp.ones(4, bool)], jnp.ones(4, bool)
    )
    pi, bi, ok = join.expand_matches(ls, [pk], [jnp.ones(4, bool)], lo, counts, 16)
    pm = np.asarray(join.probe_matched_flags(4, pi, ok))
    assert list(pm) == [True, False, True, False]
    bm = np.asarray(join.build_matched_flags(3, bi, ok))
    assert list(bm) == [True, True, True]


def test_sort_multi_key_with_nulls_and_desc():
    a = jnp.asarray([3, 1, 2, 1, 2], dtype=jnp.int64)
    av = jnp.asarray([True, True, False, True, True])
    b = jnp.asarray([1.0, 9.0, 5.0, 7.0, 2.0])
    live = jnp.asarray([True, True, True, True, True])
    order = sort.sort_order(
        [a, b], [av, None], [False, True], [False, False], live
    )
    # a asc nulls last, then b desc: rows (1,b9),(3,b7),(4,b2),(0,b1),(2=null)
    assert list(np.asarray(order)) == [1, 3, 4, 0, 2]


def test_sort_dead_rows_last():
    a = jnp.asarray([5, 4, 3, 2], dtype=jnp.int64)
    live = jnp.asarray([True, False, True, True])
    order = sort.sort_order([a], [None], [False], [False], live)
    assert list(np.asarray(order)) == [3, 2, 0, 1]


def test_sort_nan_is_largest_both_directions():
    x = jnp.asarray([1.0, float("nan"), 2.0])
    live = jnp.ones(3, bool)
    asc = sort.sort_order([x], [None], [False], [False], live)
    assert list(np.asarray(asc)) == [0, 2, 1]
    desc = sort.sort_order([x], [None], [True], [False], live)
    assert list(np.asarray(desc)) == [1, 2, 0]


def test_temporal_coercion():
    from trino_tpu import types as T

    assert T.common_super_type(T.DATE, T.TIMESTAMP) == T.TIMESTAMP
    assert T.common_super_type(T.DATE, T.INTERVAL_DAY) is None
    assert T.common_super_type(T.DATE, T.BIGINT) is None
    assert T.arithmetic_result_type("+", T.DATE, T.INTERVAL_DAY) == T.DATE


def test_decimal_supertype_widens_to_int128():
    from trino_tpu import types as T

    # r4: wide operand pairs widen into the Int128 carrier (capped at
    # 38) instead of raising — spi/type/Decimals MAX_PRECISION
    wide = T.common_super_type(T.decimal(18, 0), T.decimal(18, 18))
    assert wide == T.decimal(36, 18) and wide.is_long_decimal
    assert T.common_super_type(T.decimal(12, 2), T.decimal(10, 4)) == T.decimal(14, 4)


class TestMxuGroupby:
    """Pallas MXU one-hot contraction kernel (ops/mxu_groupby.py) — the
    GroupByHash+accumulate hot loop on the systolic array (SURVEY.md
    §3.3). Interpret mode on CPU computes the identical program."""

    def _check(self, n, c, n_vals, seed, live_frac=1.0):
        import jax
        import numpy as np
        import jax.numpy as jnp
        from trino_tpu.ops.mxu_groupby import (
            grouped_sum_mxu, grouped_sum_reference,
        )

        rng = np.random.default_rng(seed)
        gid = jnp.asarray(rng.integers(0, c, n, dtype=np.int32))
        live = jnp.asarray(rng.random(n) < live_frac)
        vals = tuple(
            jnp.asarray(rng.integers(-(10**12), 10**12, n).astype(np.int64))
            for _ in range(n_vals)
        )
        interp = jax.default_backend() != "tpu"
        got = grouped_sum_mxu(gid, vals, live, c, interpret=interp)
        want = grouped_sum_reference(gid, vals, live, c)
        for g, w in zip(got, want):
            assert np.array_equal(np.asarray(g), np.asarray(w))

    def test_exact_int64_sums(self):
        self._check(n=3000, c=300, n_vals=2, seed=1)

    def test_masked_rows_and_row_padding(self):
        # n not a multiple of the 256-row tile; 30% dead rows
        self._check(n=1001, c=17, n_vals=1, seed=2, live_frac=0.7)

    def test_many_values_multi_sublane_tile(self):
        # >7 value columns forces a8 > 8 (two sublane tiles of planes)
        self._check(n=2048, c=100, n_vals=9, seed=3)

    def test_mxu_group_reduce_contract(self):
        """mxu_group_reduce matches dense_group_reduce on the same
        bounded-domain inputs (sum/count reducers)."""
        import numpy as np
        import jax.numpy as jnp
        from trino_tpu.ops.groupby import dense_group_reduce, mxu_group_reduce

        rng = np.random.default_rng(4)
        n, d0, d1 = 5000, 5, 7
        keys = [
            jnp.asarray(rng.integers(0, d0, n).astype(np.int64)),
            jnp.asarray(rng.integers(0, d1, n).astype(np.int64)),
        ]
        valids = [
            jnp.asarray(rng.random(n) < 0.9),
            jnp.ones(n, dtype=jnp.bool_),
        ]
        mask = jnp.asarray(rng.random(n) < 0.8)
        values = [
            jnp.asarray(rng.integers(-1000, 1000, n).astype(np.int64)),
            jnp.ones(n, dtype=jnp.int64),
        ]
        vvalids = [jnp.asarray(rng.random(n) < 0.95), None]
        args = (keys, valids, mask, values, tuple(vvalids),
                ("sum", "count"), (d0, d1), 64)
        want = dense_group_reduce(*args)
        got = mxu_group_reduce(*args)
        for g, w in zip(got[:5], want[:5]):
            for ga, wa in zip(
                (g if isinstance(g, (list, tuple)) else [g]),
                (w if isinstance(w, (list, tuple)) else [w]),
            ):
                assert np.array_equal(np.asarray(ga), np.asarray(wa))
        assert int(got[5]) == int(want[5])

    def test_engine_routes_through_mxu(self, monkeypatch):
        """A bounded-dictionary GROUP BY in the (64, 2048] band runs
        through the Pallas path and matches the sort-path answer."""
        monkeypatch.setenv("TRINO_TPU_FORCE_MXU", "1")
        from trino_tpu.connectors.tpch import create_tpch_connector
        from trino_tpu.engine import LocalQueryRunner, Session

        sql = (
            "SELECT s_name, count(*), sum(ps_availqty)"
            " FROM partsupp, supplier WHERE ps_suppkey = s_suppkey"
            " GROUP BY s_name ORDER BY s_name"
        )
        r = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
        r.register_catalog("tpch", create_tpch_connector())
        forced = r.execute(sql).rows
        monkeypatch.setenv("TRINO_TPU_FORCE_MXU", "0")
        r2 = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
        r2.register_catalog("tpch", create_tpch_connector())
        assert forced == r2.execute(sql).rows
        assert len(forced) == 100  # one row per supplier
