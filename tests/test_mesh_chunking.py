"""Chunked mesh-plane tests: preemptible ICI execution (PR 10).

The mesh plane (parallel/mesh_chunk.py) splits the driver scan into
per-chunk jit steps with host preemption checks at every chunk
boundary, so deadline kills, client abandonment and the stuck-task
watchdog fire mid-query WITHOUT leaving the mesh. These tests pin the
contract:

  - results are identical across chunk settings (unchunked, K=1, K=2,
    K=many) — the carry/flush machinery must not change answers;
  - a wall deadline preempts BETWEEN chunks with the typed
    EXCEEDED_TIME_LIMIT error and no page-plane fallback;
  - abandonment (cancel) and the watchdog (MeshStuck -> retryable page
    fallback) take their distinct paths;
  - second execution of a chunked query lowers ZERO new XLA programs
    (the record cache + deterministic capacity ladder);
  - chunk capacities land on capacity-ladder rungs and the programs
    register WarmupEntrys / warm classes with the compile regime;
  - a mid-execution MeshUnsupported falls back observably (reason in
    QueryInfo, mesh_fallback trace event) and still answers correctly.
"""

import pytest

from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import Session
from trino_tpu.parallel import mesh_chunk, mesh_plan
from trino_tpu.runtime import DistributedQueryRunner
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_TIME_LIMIT,
    QueryAbandonedError,
    QueryDeadlineError,
)

# exact-valued aggregates only: chunked accumulation changes float
# merge ORDER, so byte-identity asserts stick to ints and
# integral-valued decimal columns
Q_GROUP = (
    "select l_returnflag, l_linestatus, count(*) c, "
    "sum(l_quantity) q, min(l_orderkey) mn, max(l_orderkey) mx "
    "from lineitem group by l_returnflag, l_linestatus "
    "order by l_returnflag, l_linestatus"
)
Q_JOIN = (
    "select o_orderpriority, count(*) c from orders join customer "
    "on o_custkey = c_custkey group by o_orderpriority "
    "order by o_orderpriority"
)


def mk_runner(**session_kw):
    r = DistributedQueryRunner(
        Session(catalog="tpch", schema="tiny", **session_kw),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("tpch", create_tpch_connector())
    return r


@pytest.fixture(scope="module")
def baseline_rows(tpch_cluster_mesh_off):
    """Page-plane answers — the oracle every chunk setting must hit.
    Read-only queries on the shared session cluster (tier-1 wall)."""
    return {
        "group": tpch_cluster_mesh_off.execute(Q_GROUP).rows,
        "join": tpch_cluster_mesh_off.execute(Q_JOIN).rows,
    }


# tiny-SF lineitem holds ~7.5k rows per shard on the 8-device mesh:
# 8192 -> one chunk, 4096 -> two, 512 -> many
@pytest.mark.parametrize("chunk_rows", [0, 8192, 4096, 512])
def test_chunked_results_identical(chunk_rows, baseline_rows):
    r = mk_runner(mesh_chunk_rows=chunk_rows)
    before = mesh_plan.MESH_COUNTERS["queries"]
    assert r.execute(Q_GROUP).rows == baseline_rows["group"]
    assert r.execute(Q_JOIN).rows == baseline_rows["join"]
    assert mesh_plan.MESH_COUNTERS["queries"] == before + 2, \
        f"fell back to HTTP: {r.last_mesh_fallback}"
    if chunk_rows:
        assert mesh_chunk.LAST_RUN_INFO["chunked"] is True
    else:
        assert mesh_chunk.LAST_RUN_INFO["chunked"] is False


def test_deadline_preempts_between_chunks(baseline_rows):
    """A wall deadline kills a WARM chunked query at a chunk boundary:
    typed, coded, and WITHOUT falling back to the page plane (the
    pre-PR-10 behavior was to refuse the mesh whenever limits were
    set)."""
    r = mk_runner(mesh_chunk_rows=128)
    assert r.execute(Q_GROUP).rows == baseline_rows["group"]  # warm
    # slow the tracker tick so the chunk-boundary wall check — not the
    # background enforcement thread — is what kills the query
    r.query_tracker.tick_interval_s = 60.0
    r.session.query_max_execution_time_s = 0.05
    with pytest.raises(QueryDeadlineError) as ei:
        r.execute(Q_GROUP)
    msg = str(ei.value)
    assert EXCEEDED_TIME_LIMIT in msg
    assert "mesh chunk" in msg
    assert r.last_mesh_fallback is None, "deadline kill must not fall back"


def test_abandonment_preempts_between_chunks():
    r = mk_runner(mesh_chunk_rows=512)
    r.execute(Q_GROUP)  # warm
    with pytest.raises(QueryAbandonedError, match="abandoned"):
        r.execute(Q_GROUP, cancel=lambda: True)
    assert r.last_mesh_fallback is None


def test_watchdog_falls_back_to_page_plane(baseline_rows):
    """A chunk step slower than stuck_task_interrupt_s raises MeshStuck
    — RETRYABLE, unlike deadline kills — and the coordinator retries
    the query on the page plane: correct answer, reason recorded. The
    property is set after worker construction so the page-plane workers
    keep their 0 (disabled) watchdog."""
    r = mk_runner(mesh_chunk_rows=256)
    r.session.stuck_task_interrupt_s = 1e-9
    before = mesh_plan.MESH_COUNTERS["fallbacks"]
    assert r.execute(Q_GROUP).rows == baseline_rows["group"]
    assert mesh_plan.MESH_COUNTERS["fallbacks"] == before + 1
    assert "stuck" in (r.last_mesh_fallback or "").lower()


def test_second_execution_zero_relowerings(baseline_rows):
    """The program-cache records + deterministic capacity ladder mean a
    repeated chunked query replays entirely from cache: zero new XLA
    lowerings."""
    r = mk_runner(mesh_chunk_rows=512)
    assert r.execute(Q_JOIN).rows == baseline_rows["join"]
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    assert r.execute(Q_JOIN).rows == baseline_rows["join"]
    delta = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    assert delta == 0, f"second execution lowered {delta:g} XLA programs"


def test_chunk_capacity_lands_on_ladder_rung():
    """mesh_chunk_rows is rounded to a capacity-ladder rung so chunk
    programs land on census-predicted shape classes (ladder base 2:
    100 -> 128)."""
    r = mk_runner(mesh_chunk_rows=100)
    r.execute(Q_GROUP)
    assert mesh_chunk.LAST_RUN_INFO["chunk_cap"] == 128


def test_warmup_registration():
    """Successful chunked programs register WarmupEntrys and mark their
    shape classes warm for the compile regime (PR 6)."""
    from trino_tpu.compile.warmup import WARM_CLASSES

    r = mk_runner(mesh_chunk_rows=512)
    r.execute(Q_GROUP)
    entries = mesh_chunk.mesh_warmup_entries()
    assert entries, "no mesh WarmupEntrys registered"
    ops = {e.operator for e in entries}
    assert ops <= {"MeshPrelude", "MeshChunkStep", "MeshFlush"}
    assert "MeshChunkStep" in ops
    for e in entries:
        assert e.keys() <= WARM_CLASSES


def test_mid_execution_unsupported_falls_back_observably(
    baseline_rows, monkeypatch
):
    """Regression (PR 10 satellite): a MeshUnsupported raised DURING
    execution used to fall back silently. It must now record the reason
    in QueryInfo, bump the per-reason counter, and drop a mesh_fallback
    instant event on the query span — while still answering via the
    page plane."""
    reason = "synthetic mid-execution refusal"

    def boom(self, preempt=None, query_span=None):
        raise mesh_plan.MeshUnsupported(reason)

    monkeypatch.setattr(mesh_chunk.ChunkedMeshRunner, "run", boom)
    r = mk_runner(query_trace="on")
    before = METRICS.snapshot()
    res = r.execute(Q_JOIN)
    assert res.rows == baseline_rows["join"]
    assert r.last_mesh_fallback == reason
    qi = r.query_info(r.last_query_id)
    assert qi["data_plane"] == "http"
    assert qi["mesh_fallback"] == reason
    after = METRICS.snapshot()
    slug = "mesh_fallbacks.synthetic_mid_execution_refusal"
    assert after.get(slug, 0) == before.get(slug, 0) + 1
    export = r.query_trace_export(r.last_query_id)
    events = [
        e for s in export["spans"] for e in s.get("events", [])
        if e["name"] == "mesh_fallback"
    ]
    assert events and events[0]["attributes"]["reason"] == reason


def test_chunked_span_tree_valid():
    """A chunked mesh query under query_trace=on exports a complete
    span tree: stage/task/operator mesh spans, per-chunk events, and no
    invariant violations."""
    from trino_tpu.runtime.tracing import check_span_invariants

    r = mk_runner(mesh_chunk_rows=512, query_trace="on")
    r.execute(Q_GROUP)
    export = r.query_trace_export(r.last_query_id)
    assert check_span_invariants(export) == []
    names = [s["name"] for s in export["spans"]]
    assert any(n.startswith("stage mesh") for n in names)
    assert any(n.startswith("task mesh") for n in names)
    assert "MeshChunkStep" in names
    chunk_events = [
        e for s in export["spans"] for e in s.get("events", [])
        if e["name"] == "chunk"
    ]
    assert len(chunk_events) >= 2, "expected per-chunk trace events"
