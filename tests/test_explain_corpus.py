"""Corpus-diff gate for explain_corpus/.

Regenerates every corpus file into a tmp dir and diffs it against the
committed copy. The corpus is deterministic (fixed seeds, tiny inputs),
so a mismatch means the planner, the validator messages, or the census
actually changed — rerun `JAX_PLATFORMS=cpu PYTHONPATH=. python
explain_corpus/generate.py` and review the diff.
"""

import difflib
import importlib.util
import os

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
CORPUS = os.path.join(HERE, os.pardir, "explain_corpus")


@pytest.fixture(scope="module")
def generate():
    spec = importlib.util.spec_from_file_location(
        "explain_corpus_generate", os.path.join(CORPUS, "generate.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_corpus_matches_committed(generate, tmp_path):
    generate.write_all(str(tmp_path))
    names = sorted(
        n for n in os.listdir(CORPUS) if n.endswith(".txt")
    )
    assert names, "no committed corpus files found"
    regenerated = sorted(os.listdir(tmp_path))
    assert regenerated == names, (
        f"generate.py emits {regenerated}, committed corpus has {names}"
    )
    for name in names:
        with open(os.path.join(CORPUS, name)) as fh:
            committed = fh.read()
        with open(tmp_path / name) as fh:
            fresh = fh.read()
        if committed != fresh:
            diff = "\n".join(difflib.unified_diff(
                committed.splitlines(), fresh.splitlines(),
                f"committed/{name}", f"regenerated/{name}", lineterm="",
            ))
            pytest.fail(f"{name} drifted from committed corpus:\n{diff}")


def test_corpus_carries_validation_annotations():
    with open(os.path.join(CORPUS, "05_plan_validation.txt")) as fh:
        body = fh.read()
    assert "[refs] at Project" in body
    assert "[exchange_keys] at Exchange" in body
    assert "expected_xla_lowerings=" in body
    assert "retry-variant" in body
    with open(os.path.join(CORPUS, "03_partial_agg_exchange.txt")) as fh:
        assert "expected_xla_lowerings=" in fh.read()
