"""Client protocol, CLI, session properties, config, resource groups
(SURVEY.md §2.11, §5.6, §2.3)."""

import threading
import time

import pytest

from trino_tpu.client import Client, QueryError
from trino_tpu.cli import format_table
from trino_tpu.config import SYSTEM_PROPERTIES, load_properties_file
from trino_tpu.connectors.tpch import create_tpch_connector
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime.resource_groups import (
    QueryQueueFullError,
    ResourceGroupManager,
    ResourceGroupSpec,
    Selector,
)
from trino_tpu.runtime.server import CoordinatorServer


@pytest.fixture(scope="module")
def server():
    lq = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    lq.register_catalog("tpch", create_tpch_connector())
    srv = CoordinatorServer(lq)
    yield srv
    srv.stop()


def test_client_roundtrip(server):
    c = Client(server.uri)
    r = c.execute(
        "select n_regionkey, count(*) c from nation group by n_regionkey order by 1"
    )
    assert r.column_names == ["n_regionkey", "c"]
    assert r.rows == [[i, 5] for i in range(5)]


def test_client_error_propagates(server):
    c = Client(server.uri)
    with pytest.raises(QueryError, match="does not exist"):
        c.execute("select * from tpch.tiny.nope")


def test_client_pagination(server):
    c = Client(server.uri)
    r = c.execute("select o_orderkey from orders order by o_orderkey")
    assert len(r.rows) == 15000
    assert r.rows[0] == [1]


def test_cli_format_table():
    out = format_table(["a", "bb"], [[1, None], [22, "x"]])
    lines = out.splitlines()
    assert lines[0].split("|")[0].strip() == "a"
    assert "NULL" in out
    assert "(2 rows)" in out


# -- session properties / config --


def test_set_show_session():
    lq = LocalQueryRunner(Session(catalog="tpch", schema="tiny"))
    lq.register_catalog("tpch", create_tpch_connector())
    lq.execute("SET SESSION batch_rows = 8192")
    assert lq.session.batch_rows == 8192
    lq.execute("SET SESSION enable_dynamic_filtering = false")
    assert lq.session.enable_dynamic_filtering is False
    rows = lq.execute("SHOW SESSION").rows
    names = [r[0] for r in rows]
    assert "batch_rows" in names and "retry_policy" in names
    with pytest.raises(Exception):
        lq.execute("SET SESSION no_such_prop = 1")


def test_property_registry_validation():
    assert SYSTEM_PROPERTIES.validate("batch_rows", "4096") == 4096
    assert SYSTEM_PROPERTIES.validate("enable_dynamic_filtering", "false") is False
    with pytest.raises(ValueError):
        SYSTEM_PROPERTIES.validate("retry_policy", 7)


def test_load_properties_file(tmp_path):
    p = tmp_path / "config.properties"
    p.write_text("# comment\nbatch_rows=1024\nretry_policy = task\n\n")
    props = load_properties_file(str(p))
    assert props == {"batch_rows": "1024", "retry_policy": "task"}


# -- resource groups --


def test_resource_group_concurrency_and_queue():
    mgr = ResourceGroupManager(
        ResourceGroupSpec("global", max_concurrency=1, max_queued=1)
    )
    lease1 = mgr.acquire()
    assert mgr.stats()["global"][0] == 1
    # second query queues; third is rejected (queue full)
    entered = threading.Event()
    released = []

    def second():
        entered.set()
        lease = mgr.acquire(timeout=10)
        released.append(lease)

    t = threading.Thread(target=second, daemon=True)
    t.start()
    entered.wait()
    time.sleep(0.05)  # let it enter the queue
    with pytest.raises(QueryQueueFullError):
        mgr.acquire(timeout=0.01)
    mgr.release(lease1)
    t.join(5)
    assert released
    mgr.release(released[0])
    assert mgr.stats()["global"] == (0, 0)


def test_resource_group_selectors():
    spec = ResourceGroupSpec(
        "global",
        max_concurrency=10,
        sub_groups=[ResourceGroupSpec("etl", max_concurrency=1)],
    )
    mgr = ResourceGroupManager(
        spec, [Selector(("global", "etl"), user_pattern="etl-.*")]
    )
    lease = mgr.acquire(user="etl-nightly")
    assert mgr.stats()["global.etl"][0] == 1
    # non-matching user routes to the root group
    lease2 = mgr.acquire(user="alice")
    assert mgr.stats()["global"][0] == 2
    mgr.release(lease)
    mgr.release(lease2)

# -- query TTL tracking (QueryTracker analogue) --


def test_abandoned_query_expires(server):
    import urllib.request

    # submit directly so we control polling
    req = urllib.request.Request(
        f"{server.uri}/v1/statement",
        data=b"select count(*) from nation",
        method="POST",
    )
    import json as _json

    resp = _json.loads(urllib.request.urlopen(req).read())
    qid = resp["id"]
    job = server._jobs[qid]
    # wait for it to finish but never drain the results
    for _ in range(100):
        if job.state == "finished":
            break
        time.sleep(0.05)
    assert job.state == "finished"
    # simulate client silence past the TTL, then trigger the sweep
    old = server.CLIENT_TTL_S
    server.CLIENT_TTL_S = 0.0
    try:
        time.sleep(0.01)
        server._evict_completed()
    finally:
        server.CLIENT_TTL_S = old
    assert job.abandoned and job.state == "failed"
    assert "abandoned" in job.error
    assert job.rows == []


def test_completed_job_evicted_after_ttl(server):
    c = Client(server.uri)
    c.execute("select 1")
    # every fully-drained job older than the completed TTL is evicted
    old = server.COMPLETED_TTL_S
    server.COMPLETED_TTL_S = 0.0
    try:
        time.sleep(0.01)
        server._evict_completed()
    finally:
        server.COMPLETED_TTL_S = old
    assert all(j.finished_at is None for j in server._jobs.values())


class TestPreparedStatements:
    """PREPARE/EXECUTE/DEALLOCATE + the prepared-statement protocol
    headers (VERDICT r3 item #8; tree/Prepare.java:25, StatementClientV1
    X-Trino-Prepared-Statement / addedPrepare threading)."""

    def test_prepare_execute_deallocate_roundtrip(self, server):
        c = Client(server.uri)
        c.execute("prepare q1 from select n_name from nation where n_nationkey = ?")
        # PREPARE travels back as addedPrepare and the client resends
        # it per request, so EXECUTE works on this stateless server
        assert "q1" in c.prepared
        r = c.execute("execute q1 using 3")
        assert r.rows == [["CANADA"]]
        r = c.execute("execute q1 using 0")
        assert r.rows == [["ALGERIA"]]
        c.execute("deallocate prepare q1")
        assert "q1" not in c.prepared

    def test_two_parameters(self, server):
        c = Client(server.uri)
        c.execute(
            "prepare q2 from select count(*) from nation "
            "where n_regionkey = ? and n_nationkey > ?"
        )
        r = c.execute("execute q2 using 1, 2")
        want = server.runner.execute(
            "select count(*) from nation where n_regionkey = 1 and n_nationkey > 2"
        ).rows
        assert r.rows == want

    def test_dbapi_server_side_binding(self, server):
        import trino_tpu.dbapi as dbapi

        conn = dbapi.Connection(Client(server.uri))
        cur = conn.cursor()
        cur.execute(
            "SELECT n_name FROM nation WHERE n_nationkey = ?", (3,)
        )
        assert cur.fetchall() == [["CANADA"]]
        # the statement body traveled via the prepared header, not by
        # splicing the parameter into the SQL text
        assert "stmt" in conn._client.prepared
        cur.execute(
            "SELECT count(*) FROM nation WHERE n_name = ?", ("CANADA",)
        )
        assert cur.fetchall() == [[1]]
