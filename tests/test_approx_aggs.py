"""Mergeable approx_distinct / approx_percentile (VERDICT r2 missing #1).

The optimizer rewrites both onto plain mergeable aggregations
(sql/optimizer.RewriteApproxDistinct / RewriteApproxPercentile) that
ride the existing partial->final wire, spill, and mesh paths — no raw
rows are gathered. Reference parity:
operator/aggregation/ApproximateCountDistinctAggregations.java (HLL
state) and ApproximateDoublePercentileAggregations.java (qdigest).

Documented error bounds: approx_distinct 2048 HLL registers, standard
error 1.04/sqrt(2048) = 2.3% (tests allow 3 sigma); approx_percentile
quantile buckets of <= 1.6% relative width (sign+exp+6 mantissa bits),
exact for single-valued buckets.
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session


def _load(mem, n=40000, seed=11):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 4, n).astype(np.int64)
    x = rng.integers(0, 2500, n).astype(np.int64)
    y = rng.normal(50.0, 10.0, n)
    xv = rng.random(n) >= 0.03  # a few NULLs
    mem.load_table(
        "default", "d",
        [
            ColumnMetadata("k", T.BIGINT),
            ColumnMetadata("x", T.BIGINT),
            ColumnMetadata("y", T.DOUBLE),
        ],
        [k, x, y],
        [None, xv, None],
        [None, None, None],
    )
    return k, x, y, xv


@pytest.fixture(scope="module")
def data_runner():
    mem = create_memory_connector()
    truth = _load(mem)
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    return r, truth


def test_approx_distinct_grouped_accuracy(data_runner):
    r, (k, x, _, xv) = data_runner
    res = r.execute(
        "select k, approx_distinct(x) from d group by k order by k"
    )
    for kk, est in res.rows:
        t = len(set(x[(k == kk) & xv]))
        assert abs(est - t) / t < 0.07, (kk, est, t)  # 3 sigma


def test_approx_distinct_mixed_and_global(data_runner):
    r, (k, x, y, xv) = data_runner
    res = r.execute(
        "select k, approx_distinct(x), count(x), sum(x), min(x), avg(y)"
        " from d group by k order by k"
    )
    for kk, est, cnt, s, mn, avg in res.rows:
        sel = k == kk
        assert cnt == int((sel & xv).sum())
        assert s == int(x[sel & xv].sum())
        assert mn == int(x[sel & xv].min())
        assert abs(avg - float(y[sel].mean())) < 1e-9
    g = r.execute("select approx_distinct(x) from d").rows[0][0]
    t = len(set(x[xv]))
    assert abs(g - t) / t < 0.07
    assert r.execute("select approx_distinct(x) from d where k > 9").rows \
        == [[0]]


def test_approx_distinct_all_null_group():
    mem = create_memory_connector()
    mem.load_table(
        "default", "nulls",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("x", T.BIGINT)],
        [np.asarray([1, 1, 2], dtype=np.int64),
         np.asarray([5, 6, 0], dtype=np.int64)],
        [None, np.asarray([True, True, False])],
        [None, None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    res = r.execute(
        "select k, approx_distinct(x) from nulls group by k order by k"
    )
    assert res.rows == [[1, 2], [2, 0]]  # all-NULL group stays, counts 0


def test_approx_percentile_accuracy(data_runner):
    r, (k, _, y, _) = data_runner
    res = r.execute(
        "select k, approx_percentile(y, 0.5), approx_percentile(y, 0.9),"
        " count(*) from d group by k order by k"
    )
    for kk, p50, p90, cnt in res.rows:
        sel = y[k == kk]
        assert cnt == len(sel)
        assert abs(p50 - np.median(sel)) / abs(np.median(sel)) < 0.02
        t90 = np.percentile(sel, 90)
        assert abs(p90 - t90) / abs(t90) < 0.02


def test_approx_aggs_are_mergeable_plans(data_runner):
    """The rewrite must eliminate the holistic single-step gather: the
    EXPLAIN'd plan contains two aggregation levels and the hll/pctl
    finishers, not an approx_distinct/approx_percentile holistic agg."""
    r, _ = data_runner
    plan = r.execute(
        "EXPLAIN select k, approx_distinct(x) from d group by k"
    ).rows[0][0]
    assert "approx_distinct" not in plan
    assert "hll_estimate" in plan
    plan2 = r.execute(
        "EXPLAIN select k, approx_percentile(y, 0.5) from d group by k"
    ).rows[0][0]
    assert "pctl_merge" in plan2


def test_approx_aggs_distributed_two_workers():
    """2-worker distributed run at inputs > one batch: states merge
    through the partial->final wire (the VERDICT done criterion)."""
    from trino_tpu.runtime import DistributedQueryRunner

    mem = create_memory_connector()
    k, x, y, xv = _load(mem, n=50000, seed=23)
    r = DistributedQueryRunner(
        Session(catalog="memory", schema="default", batch_rows=1 << 13),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("memory", mem)
    res = r.execute(
        "select k, approx_distinct(x), approx_percentile(y, 0.5)"
        " from d group by k order by k"
    )
    assert len(res.rows) == 4
    for kk, est, p50 in res.rows:
        t = len(set(x[(k == kk) & xv]))
        assert abs(est - t) / t < 0.07, (kk, est, t)
        med = float(np.median(y[k == kk]))
        assert abs(p50 - med) / abs(med) < 0.02


def test_approx_distinct_on_strings():
    mem = create_memory_connector()
    words = [f"w{i % 700}" for i in range(5000)]
    mem.load_table(
        "default", "s",
        [ColumnMetadata("w", T.VARCHAR)],
        [words], None, [None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    est = r.execute("select approx_distinct(w) from s").rows[0][0]
    assert abs(est - 700) / 700 < 0.07
