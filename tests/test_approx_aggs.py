"""Mergeable approx_distinct / approx_percentile (VERDICT r2 missing #1).

The optimizer rewrites both onto plain mergeable aggregations
(sql/optimizer.RewriteApproxDistinct / RewriteApproxPercentile) that
ride the existing partial->final wire, spill, and mesh paths — no raw
rows are gathered. Reference parity:
operator/aggregation/ApproximateCountDistinctAggregations.java (HLL
state) and ApproximateDoublePercentileAggregations.java (qdigest).

Documented error bounds: approx_distinct 2048 HLL registers, standard
error 1.04/sqrt(2048) = 2.3% (tests allow 3 sigma); approx_percentile
quantile buckets of <= 1.6% relative width (sign+exp+6 mantissa bits),
exact for single-valued buckets.
"""

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.connectors.memory import create_memory_connector
from trino_tpu.connectors.spi import ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session


def _load(mem, n=40000, seed=11):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 4, n).astype(np.int64)
    x = rng.integers(0, 2500, n).astype(np.int64)
    y = rng.normal(50.0, 10.0, n)
    xv = rng.random(n) >= 0.03  # a few NULLs
    mem.load_table(
        "default", "d",
        [
            ColumnMetadata("k", T.BIGINT),
            ColumnMetadata("x", T.BIGINT),
            ColumnMetadata("y", T.DOUBLE),
        ],
        [k, x, y],
        [None, xv, None],
        [None, None, None],
    )
    return k, x, y, xv


@pytest.fixture(scope="module")
def data_runner():
    mem = create_memory_connector()
    truth = _load(mem)
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    return r, truth


def test_approx_distinct_grouped_accuracy(data_runner):
    r, (k, x, _, xv) = data_runner
    res = r.execute(
        "select k, approx_distinct(x) from d group by k order by k"
    )
    for kk, est in res.rows:
        t = len(set(x[(k == kk) & xv]))
        assert abs(est - t) / t < 0.07, (kk, est, t)  # 3 sigma


def test_approx_distinct_mixed_and_global(data_runner):
    r, (k, x, y, xv) = data_runner
    res = r.execute(
        "select k, approx_distinct(x), count(x), sum(x), min(x), avg(y)"
        " from d group by k order by k"
    )
    for kk, est, cnt, s, mn, avg in res.rows:
        sel = k == kk
        assert cnt == int((sel & xv).sum())
        assert s == int(x[sel & xv].sum())
        assert mn == int(x[sel & xv].min())
        assert abs(avg - float(y[sel].mean())) < 1e-9
    g = r.execute("select approx_distinct(x) from d").rows[0][0]
    t = len(set(x[xv]))
    assert abs(g - t) / t < 0.07
    assert r.execute("select approx_distinct(x) from d where k > 9").rows \
        == [[0]]


def test_approx_distinct_all_null_group():
    mem = create_memory_connector()
    mem.load_table(
        "default", "nulls",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("x", T.BIGINT)],
        [np.asarray([1, 1, 2], dtype=np.int64),
         np.asarray([5, 6, 0], dtype=np.int64)],
        [None, np.asarray([True, True, False])],
        [None, None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    res = r.execute(
        "select k, approx_distinct(x) from nulls group by k order by k"
    )
    assert res.rows == [[1, 2], [2, 0]]  # all-NULL group stays, counts 0


def test_approx_percentile_accuracy(data_runner):
    r, (k, _, y, _) = data_runner
    res = r.execute(
        "select k, approx_percentile(y, 0.5), approx_percentile(y, 0.9),"
        " count(*) from d group by k order by k"
    )
    for kk, p50, p90, cnt in res.rows:
        sel = y[k == kk]
        assert cnt == len(sel)
        assert abs(p50 - np.median(sel)) / abs(np.median(sel)) < 0.02
        t90 = np.percentile(sel, 90)
        assert abs(p90 - t90) / abs(t90) < 0.02


def test_approx_aggs_are_mergeable_plans(data_runner):
    """The rewrite must eliminate the holistic single-step gather: the
    EXPLAIN'd plan contains two aggregation levels and the hll/pctl
    finishers, not an approx_distinct/approx_percentile holistic agg."""
    r, _ = data_runner
    plan = r.execute(
        "EXPLAIN select k, approx_distinct(x) from d group by k"
    ).rows[0][0]
    assert "approx_distinct" not in plan
    assert "hll_estimate" in plan
    plan2 = r.execute(
        "EXPLAIN select k, approx_percentile(y, 0.5) from d group by k"
    ).rows[0][0]
    assert "pctl_merge" in plan2


def test_approx_aggs_distributed_two_workers():
    """2-worker distributed run at inputs > one batch: states merge
    through the partial->final wire (the VERDICT done criterion)."""
    from trino_tpu.runtime import DistributedQueryRunner

    mem = create_memory_connector()
    k, x, y, xv = _load(mem, n=50000, seed=23)
    r = DistributedQueryRunner(
        Session(catalog="memory", schema="default", batch_rows=1 << 13),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("memory", mem)
    res = r.execute(
        "select k, approx_distinct(x), approx_percentile(y, 0.5)"
        " from d group by k order by k"
    )
    assert len(res.rows) == 4
    for kk, est, p50 in res.rows:
        t = len(set(x[(k == kk) & xv]))
        assert abs(est - t) / t < 0.07, (kk, est, t)
        med = float(np.median(y[k == kk]))
        assert abs(p50 - med) / abs(med) < 0.02


def test_approx_distinct_on_strings():
    mem = create_memory_connector()
    words = [f"w{i % 700}" for i in range(5000)]
    mem.load_table(
        "default", "s",
        [ColumnMetadata("w", T.VARCHAR)],
        [words], None, [None],
    )
    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", mem)
    est = r.execute("select approx_distinct(w) from s").rows[0][0]
    assert abs(est - 700) / 700 < 0.07


class TestMultiSketch:
    """N approx aggregates per node (VERDICT r3 item #3): the tagged
    UNION ALL rewrite (sql/optimizer.RewriteMultiSketch) keeps every
    combination mergeable — no holistic raw-row fallback."""

    def test_two_approx_distinct(self, data_runner):
        r, (k, x, y, xv) = data_runner
        rows = r.execute(
            "select k, approx_distinct(x), approx_distinct(y), count(*) "
            "from d group by k order by k"
        ).rows
        import numpy as np

        for row in rows:
            kk, ax, ay, cnt = row
            sel = k == kk
            true_x = len(np.unique(x[sel & xv]))
            true_y = len(np.unique(y[sel]))
            assert abs(ax - true_x) <= 3 * 0.023 * max(true_x, 1)
            assert abs(ay - true_y) <= 3 * 0.023 * max(true_y, 1)
            assert cnt == int(sel.sum())

    def test_distinct_plus_percentile_plus_avg(self, data_runner):
        r, (k, x, y, xv) = data_runner
        rows = r.execute(
            "select k, approx_distinct(x), approx_percentile(y, 0.5), "
            "avg(y), sum(x) from d group by k order by k"
        ).rows
        import numpy as np

        for row in rows:
            kk, ax, p50, avg_y, sum_x = row
            sel = k == kk
            true_x = len(np.unique(x[sel & xv]))
            med = float(np.quantile(y[sel], 0.5))
            assert abs(ax - true_x) <= 3 * 0.023 * max(true_x, 1)
            assert abs(p50 - med) <= 0.02 * max(abs(med), 1.0)
            assert abs(avg_y - float(y[sel].mean())) < 1e-6
            assert sum_x == int(x[sel & xv].sum())

    def test_global_two_sketches(self, data_runner):
        r, (k, x, y, xv) = data_runner
        (ax, p90) = r.execute(
            "select approx_distinct(x), approx_percentile(y, 0.9) from d"
        ).rows[0]
        import numpy as np

        true_x = len(np.unique(x[xv]))
        q90 = float(np.quantile(y, 0.9))
        assert abs(ax - true_x) <= 3 * 0.023 * true_x
        assert abs(p90 - q90) <= 0.02 * abs(q90)

    def test_avg_decimal_coexists(self):
        """avg over DECIMAL re-aggregates exactly through the rewrite
        (decimal(38,s) partial sums + HALF_UP division)."""
        r = LocalQueryRunner(Session(catalog="memory", schema="t"))
        r.register_catalog("memory", create_memory_connector())
        r.execute("create table memory.t.m (g bigint, d decimal(12,2), x bigint)")
        r.execute(
            "insert into m values (1, 10.10, 7), (1, 20.30, 8), "
            "(2, 5.55, 7), (2, 5.45, 9), (1, 0.02, 7)"
        )
        rows = r.execute(
            "select g, avg(d), approx_distinct(x), approx_distinct(d) "
            "from m group by g order by g"
        ).rows
        assert rows[0][0] == 1 and abs(rows[0][1] - 10.14) < 1e-9
        assert rows[1][0] == 2 and abs(rows[1][1] - 5.50) < 1e-9
        assert rows[0][2] == 2 and rows[0][3] == 3
        assert rows[1][2] == 2 and rows[1][3] == 2
