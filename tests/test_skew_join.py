"""Skew-aware join plane (ISSUE 16): heavy-hitter salted repartition
+ MXU matmul join-project.

The plane has three triggers and these tests pin all of them:

  - the heavy-hitter classifier (adaptive/observer.py hot_keys) names
    modal build keys from OBSERVED stats at the barrier — never from
    estimates — and only plain integer keys qualify;
  - a classified join is annotated skew_hot_keys and the mesh plane
    (parallel/mesh_chunk.py) runs its exchange salted: hot build rows
    replicate over all_gather, hot probe rows scatter across the
    all_to_all — byte-equal to the unsalted run across chunk settings,
    zero new XLA lowerings on a warm repeat, and a deadline kill lands
    typed at a chunk boundary mid-salted-exchange;
  - the MXU join-project kernel (ops/mxu_join.py) aggregates a
    high-fanout equi-join without expanding the pair batch —
    oracle-equal to the gather path including NULL keys, NULL values,
    NULL group keys and an empty build side;
  - a build overflow past the spool bound re-plans the join into
    hybrid-hash spill mode (DHHJ) instead of thrashing;
  - a no-skew plan is byte-identical with the salting feature on.
"""

import dataclasses

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.adaptive import SPOOL, AdaptiveController
from trino_tpu.adaptive.observer import observe_rows, hot_keys
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import CatalogManager, ColumnMetadata
from trino_tpu.engine import LocalQueryRunner, Session
from trino_tpu.runtime import DistributedQueryRunner
from trino_tpu.runtime.metrics import METRICS
from trino_tpu.runtime.query_tracker import (
    EXCEEDED_TIME_LIMIT,
    QueryDeadlineError,
)
from trino_tpu.sql import plan as P
from trino_tpu.sql.analyzer import Analyzer
from trino_tpu.sql.parser import parse


def _zipf(rng, n, n_keys, s):
    p = 1.0 / np.arange(1, n_keys + 1, dtype=np.float64) ** s
    p /= p.sum()
    return rng.choice(n_keys, size=n, p=p).astype(np.int64)


# ---------------------------------------------------------------- #
# heavy-hitter classifier                                          #
# ---------------------------------------------------------------- #


def test_classifier_names_hot_keys_from_observations():
    rows = (
        [(0, "a")] * 40
        + [(1, "b")] * 25
        + [(k + 100, "c") for k in range(35)]
    )
    obs = observe_rows(rows, channels=[0])
    assert obs.rows == 100
    assert obs.heavy_hitter[0] == 40
    assert hot_keys(obs, 0, 0.3) == (0,)
    assert set(hot_keys(obs, 0, 0.2)) == {0, 1}
    assert hot_keys(obs, 0, 0.5) == ()


def test_classifier_threshold_is_inclusive():
    rows = [(7,)] * 20 + [(i + 100,) for i in range(80)]
    obs = observe_rows(rows, channels=[0])
    assert hot_keys(obs, 0, 0.20) == (7,)   # 20/100 == threshold
    assert hot_keys(obs, 0, 0.21) == ()


def test_classifier_only_plain_integer_keys():
    rows = [("hot",)] * 60 + [(True,)] * 30 + [(None,)] * 10
    obs = observe_rows(rows, channels=[0])
    # strings and bools never qualify (they cannot be compared against
    # the device key column at trace time); NULLs are not keys at all
    assert hot_keys(obs, 0, 0.1) == ()
    assert hot_keys(obs, 0, 0.0) == ()  # degenerate threshold: off
    assert hot_keys(observe_rows([], channels=[0]), 0, 0.2) == ()


# ---------------------------------------------------------------- #
# salted repartition on the mesh plane                             #
# ---------------------------------------------------------------- #

# global partial aggregate above the join: placement-insensitive, so
# the salted exchange map accepts the plan. Integer sums only — the
# byte-equality assert must not depend on float merge order.
SALT_SQL = (
    "select sum(f.v + d.w), count(*) from facts f "
    "join dim d on f.k1 = d.k"
)


def _skewed_catalog():
    conn = MemoryConnector()
    rng = np.random.default_rng(29)
    n, nk = 4000, 64
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [_zipf(rng, n, nk, 1.4), rng.integers(0, 100, n).astype(np.int64)],
    )
    conn.load_table(
        "s", "dim",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
        [_zipf(rng, 1000, nk, 1.4),
         rng.integers(0, 10, 1000).astype(np.int64)],
    )
    return conn


def _mk_mesh(**session_kw):
    r = DistributedQueryRunner(
        Session(
            catalog="memory", schema="s", broadcast_join_threshold=0,
            **session_kw,
        ),
        n_workers=2, hash_partitions=2,
    )
    r.register_catalog("memory", _skewed_catalog())
    return r


def _mk_salted(**session_kw):
    return _mk_mesh(
        adaptive_execution=True, skewed_join_salting=True,
        skew_hot_key_threshold=0.2, **session_kw,
    )


@pytest.fixture(scope="module")
def salt_oracle():
    r = _mk_mesh(mesh_execution=False)
    return r.execute(SALT_SQL).rows


# per-shard extent is 4000/8 = 500 rows: 0 -> unchunked, 256 -> two
# chunks, 128 -> four (the extra chunk-count rung rides tier-2: each
# setting compiles its own program family)
@pytest.mark.parametrize(
    "chunk_rows", [0, 256, pytest.param(128, marks=pytest.mark.slow)]
)
def test_salted_byte_equality_across_chunk_counts(chunk_rows, salt_oracle):
    SPOOL.clear()
    r = _mk_salted(mesh_chunk_rows=chunk_rows)
    hh0 = METRICS.snapshot().get("skew.heavy_hitters_detected", 0.0)
    se0 = METRICS.snapshot().get("skew.salted_exchanges", 0.0)
    assert r.execute(SALT_SQL).rows == salt_oracle
    assert r._last_data_plane == "mesh", r.last_mesh_fallback
    assert METRICS.snapshot().get("skew.heavy_hitters_detected", 0.0) > hh0
    assert METRICS.snapshot().get("skew.salted_exchanges", 0.0) > se0
    rep = r._last_adaptive_report
    assert rep is not None and rep.heavy_hitters >= 1
    assert rep.salted_joins >= 1


def test_salted_warm_repeat_zero_relowerings(salt_oracle):
    SPOOL.clear()
    r = _mk_salted(mesh_chunk_rows=256)
    assert r.execute(SALT_SQL).rows == salt_oracle  # cold: compiles
    compiles0 = METRICS.snapshot().get("xla_compiles", 0.0)
    assert r.execute(SALT_SQL).rows == salt_oracle
    delta = METRICS.snapshot().get("xla_compiles", 0.0) - compiles0
    assert delta == 0, f"salted warm repeat lowered {delta:g} programs"
    assert r._last_data_plane == "mesh", r.last_mesh_fallback


def test_deadline_kill_mid_salted_exchange_stays_typed(salt_oracle):
    """A wall deadline expiring inside the salted chunk loop preempts
    at a chunk boundary: typed EXCEEDED_TIME_LIMIT, no page-plane
    fallback, exactly like the unsalted mesh contract."""
    SPOOL.clear()
    # chunk_rows=256 reuses the program family the equality test
    # already compiled (PROGRAM_CACHE is global), keeping this cheap
    r = _mk_salted(mesh_chunk_rows=256)
    assert r.execute(SALT_SQL).rows == salt_oracle  # warm
    r.query_tracker.tick_interval_s = 60.0
    r.session.query_max_execution_time_s = 0.05
    with pytest.raises(QueryDeadlineError) as ei:
        r.execute(SALT_SQL)
    msg = str(ei.value)
    assert EXCEEDED_TIME_LIMIT in msg
    assert "mesh chunk" in msg
    assert r.last_mesh_fallback is None, "deadline kill must not fall back"


def test_no_skew_plan_is_byte_identical():
    """A uniform-key catalog never crosses the hot-key threshold: the
    adaptive-transformed plan with salting ON renders byte-identically
    to salting OFF, and no skew counter moves."""
    conn = MemoryConnector()
    rng = np.random.default_rng(3)
    n, nk = 2000, 50
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [rng.integers(0, nk, n).astype(np.int64),
         rng.integers(0, 100, n).astype(np.int64)],
    )
    conn.load_table(
        "s", "dim",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("w", T.BIGINT)],
        [np.arange(nk, dtype=np.int64),
         rng.integers(0, 10, nk).astype(np.int64)],
    )
    cats = CatalogManager()
    cats.register("memory", conn)
    out = Analyzer(cats, "memory", "s").plan(parse(SALT_SQL))

    def prepared(salting):
        SPOOL.clear()
        sess = Session(
            catalog="memory", schema="s", adaptive_execution=True,
            skewed_join_salting=salting, skew_hot_key_threshold=0.2,
        )
        ctl = AdaptiveController(cats, sess)
        root = ctl.prepare(out)
        return P.explain_text(root), ctl.report

    se0 = METRICS.snapshot().get("skew.salted_exchanges", 0.0)
    off_text, off_rep = prepared(False)
    on_text, on_rep = prepared(True)
    assert on_text == off_text
    assert on_rep.heavy_hitters == 0 and on_rep.salted_joins == 0
    assert METRICS.snapshot().get("skew.salted_exchanges", 0.0) == se0


# ---------------------------------------------------------------- #
# MXU join-project                                                 #
# ---------------------------------------------------------------- #

MXU_SQL = (
    "select d.name, sum(f.v), count(f.v), count(*) from facts f "
    "join dim d on f.k1 = d.k group by d.name order by 1"
)


def _mk_local(conn, **session_kw):
    r = LocalQueryRunner(Session(catalog="memory", schema="s", **session_kw))
    r.register_catalog("memory", conn)
    return r


def _mxu_vs_gather(conn, sql=MXU_SQL):
    before = METRICS.snapshot().get("skew.mxu_join_selected", 0.0)
    on = _mk_local(
        conn, mxu_join_enabled=True, mxu_join_min_work=0.0
    ).execute(sql).rows
    selected = (
        METRICS.snapshot().get("skew.mxu_join_selected", 0.0) - before
    )
    off = _mk_local(conn).execute(sql).rows
    return on, off, selected


def test_mxu_oracle_equality_high_fanout():
    conn = MemoryConnector()
    rng = np.random.default_rng(5)
    n, nk, fan = 5000, 30, 3
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [_zipf(rng, n, nk, 1.2),
         rng.integers(-50, 100, n).astype(np.int64)],
    )
    bk = np.concatenate([np.arange(nk, dtype=np.int64)] * fan)
    conn.load_table(
        "s", "dim",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("name", T.VARCHAR)],
        [bk, np.array([f"g{i % 7}" for i in range(bk.size)], dtype=object)],
    )
    on, off, selected = _mxu_vs_gather(conn)
    assert selected >= 1, "MXU join-project was not selected"
    assert on == off


def test_mxu_null_keys_values_and_group_keys():
    conn = MemoryConnector()
    rng = np.random.default_rng(5)
    n, nk = 3000, 25
    k1 = rng.integers(0, nk, n).astype(np.int64)
    v = rng.integers(-50, 100, n).astype(np.int64)
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [k1, v],
        valids=[rng.random(n) >= 0.1, rng.random(n) >= 0.15],
    )
    bk = np.concatenate([np.arange(nk, dtype=np.int64)] * 2)
    bkval = np.ones(bk.size, dtype=bool)
    bkval[3] = False  # NULL build key: joins nothing
    conn.load_table(
        "s", "dim",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("name", T.VARCHAR)],
        [bk, np.array([f"g{i % 5}" for i in range(bk.size)], dtype=object)],
        valids=[bkval, np.array([bool(i % 11) for i in range(bk.size)])],
    )
    on, off, selected = _mxu_vs_gather(conn)
    assert selected >= 1
    assert on == off  # incl. the NULL group-key row and SUM-of-NULLs


def test_mxu_empty_build():
    conn = MemoryConnector()
    rng = np.random.default_rng(5)
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [rng.integers(0, 25, 3000).astype(np.int64),
         rng.integers(0, 100, 3000).astype(np.int64)],
    )
    conn.load_table(
        "s", "dim",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("name", T.VARCHAR)],
        [np.array([], dtype=np.int64), np.array([], dtype=object)],
    )
    on, off, selected = _mxu_vs_gather(conn)
    assert selected >= 1
    assert on == off == []


def test_mxu_not_selected_below_work_threshold():
    conn = MemoryConnector()
    rng = np.random.default_rng(5)
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [rng.integers(0, 10, 500).astype(np.int64),
         rng.integers(0, 100, 500).astype(np.int64)],
    )
    conn.load_table(
        "s", "dim",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("name", T.VARCHAR)],
        [np.arange(10, dtype=np.int64),
         np.array([f"g{i}" for i in range(10)], dtype=object)],
    )
    before = METRICS.snapshot().get("skew.mxu_join_selected", 0.0)
    on = _mk_local(
        conn, mxu_join_enabled=True, mxu_join_min_work=1e12
    ).execute(MXU_SQL).rows
    assert METRICS.snapshot().get("skew.mxu_join_selected", 0.0) == before
    assert on == _mk_local(conn).execute(MXU_SQL).rows


# ---------------------------------------------------------------- #
# DHHJ spill-mode re-plan                                          #
# ---------------------------------------------------------------- #


def test_spill_mode_replan_on_build_overflow(monkeypatch):
    """A build side that overflows the spool bound past the divergence
    threshold re-plans the join into hybrid-hash spill mode: the
    annotation reaches HashBuildSink as force_spill (grace partitions
    pre-opened) and the answer stays oracle-equal."""
    from trino_tpu.adaptive import controller as ctl_mod

    conn = MemoryConnector()
    rng = np.random.default_rng(17)
    n, keys, fan = 4000, 40, 20
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [rng.integers(0, keys, n).astype(np.int64),
         rng.integers(0, 100, n).astype(np.int64)],
    )
    conn.load_table(
        "s", "d1",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("tag", T.BIGINT)],
        [np.repeat(np.arange(keys, dtype=np.int64), fan),
         np.arange(keys * fan, dtype=np.int64)],
    )
    # the lie: d1 reported at 1/10th (est 80), true build is 800 rows —
    # past the shrunken spool bound below, so the barrier OVERFLOWS
    real = conn.metadata.get_table_statistics

    def lying(handle):
        ts = real(handle)
        if handle.table == "d1" and ts.row_count is not None:
            return dataclasses.replace(
                ts, row_count=ts.row_count / 10.0, columns={}
            )
        return ts

    conn.metadata.get_table_statistics = lying
    monkeypatch.setattr(ctl_mod, "MAX_SPOOL_ROWS", 100)

    sql = (
        "select count(*), sum(f.v + d1.tag) from facts f "
        "join d1 on f.k1 = d1.k"
    )
    SPOOL.clear()
    spills0 = METRICS.snapshot().get("skew.spill_mode_replans", 0.0)
    r = _mk_local(
        conn, adaptive_execution=True, adaptive_replan_threshold=2.0,
        skew_spill_min_rows=100,
    )
    rows = r.execute(sql).rows
    rep = r._last_adaptive_report
    assert rep is not None and rep.spill_builds == 1
    assert any(o.get("spill") for o in rep.observations)
    assert (
        METRICS.snapshot().get("skew.spill_mode_replans", 0.0)
        == spills0 + 1
    )
    assert rows == _mk_local(conn).execute(sql).rows


def test_spill_replan_respects_min_rows_floor(monkeypatch):
    """The same overflow below skew_spill_min_rows must NOT flip the
    join to spill mode — tiny builds never benefit from grace
    partitioning."""
    from trino_tpu.adaptive import controller as ctl_mod

    conn = MemoryConnector()
    rng = np.random.default_rng(17)
    conn.load_table(
        "s", "facts",
        [ColumnMetadata("k1", T.BIGINT), ColumnMetadata("v", T.BIGINT)],
        [rng.integers(0, 40, 2000).astype(np.int64),
         rng.integers(0, 100, 2000).astype(np.int64)],
    )
    conn.load_table(
        "s", "d1",
        [ColumnMetadata("k", T.BIGINT), ColumnMetadata("tag", T.BIGINT)],
        [np.repeat(np.arange(40, dtype=np.int64), 20),
         np.arange(800, dtype=np.int64)],
    )
    real = conn.metadata.get_table_statistics

    def lying(handle):
        ts = real(handle)
        if handle.table == "d1" and ts.row_count is not None:
            return dataclasses.replace(
                ts, row_count=ts.row_count / 10.0, columns={}
            )
        return ts

    conn.metadata.get_table_statistics = lying
    monkeypatch.setattr(ctl_mod, "MAX_SPOOL_ROWS", 100)
    sql = "select count(*) from facts f join d1 on f.k1 = d1.k"
    SPOOL.clear()
    r = _mk_local(
        conn, adaptive_execution=True, adaptive_replan_threshold=2.0,
        skew_spill_min_rows=1 << 18,  # the default floor: 800 << it
    )
    rows = r.execute(sql).rows
    rep = r._last_adaptive_report
    assert rep is not None and rep.spill_builds == 0
    assert rows == _mk_local(conn).execute(sql).rows
