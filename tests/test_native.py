"""Native (C++) host-runtime components: partition scatter + mask
compaction, with numpy-fallback equivalence (trino_tpu/native)."""

import numpy as np
import pytest

from trino_tpu import native
from trino_tpu import types as T
from trino_tpu.block import RelBatch
from trino_tpu.exec.exchange_ops import split_page
from trino_tpu.exec.serde import Page


def test_native_library_builds():
    assert native.get_lib() is not None, "g++ toolchain expected in CI image"


@pytest.mark.parametrize("dtype", [np.int64, np.int32, np.float64, np.bool_, np.int8])
def test_scatter_matches_numpy(dtype):
    rng = np.random.default_rng(7)
    n = 10_000
    pids = rng.integers(-1, 5, n).astype(np.int32)
    col = rng.integers(0, 100, n).astype(dtype)
    got = native.partition_scatter([col], pids, 5)
    for p in range(5):
        assert np.array_equal(got[p][0], col[pids == p])


def test_mask_compact_matches_numpy():
    rng = np.random.default_rng(3)
    n = 10_000
    mask = rng.integers(0, 2, n).astype(bool)
    cols = [rng.integers(0, 100, n).astype(np.int64), rng.random(n)]
    out = native.mask_compact(cols, mask)
    for c, o in zip(cols, out):
        assert np.array_equal(o, c[mask])


def test_split_page_with_nulls():
    b = RelBatch.from_pydict(
        [("a", T.BIGINT), ("s", T.VARCHAR)],
        {"a": [1, 2, 3, 4, 5], "s": ["x", "y", "x", None, "z"]},
    )
    page = Page.from_batch(b)
    parts = split_page(page, np.asarray([0, 1, 0, 1, -1], dtype=np.int32), 2)
    assert [p.row_count for p in parts] == [2, 2]
    assert [int(x) for x in parts[0].columns[0]] == [1, 3]
    # null flag for 's' row 4 landed in partition 1
    assert parts[1].valids[1] is not None and not parts[1].valids[1][1]


def test_fallback_equivalence():
    """Force the numpy fallback; results must match the native path."""
    rng = np.random.default_rng(1)
    n = 5000
    pids = rng.integers(-1, 3, n).astype(np.int32)
    cols = [rng.integers(0, 50, n).astype(np.int64)]
    native_out = native.partition_scatter(cols, pids, 3)
    saved_lib, saved_tried = native._lib, native._tried
    try:
        native._lib, native._tried = None, True
        fallback_out = native.partition_scatter(cols, pids, 3)
    finally:
        native._lib, native._tried = saved_lib, saved_tried
    for p in range(3):
        assert np.array_equal(native_out[p][0], fallback_out[p][0])
